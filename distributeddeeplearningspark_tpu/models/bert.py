"""BERT for MLM pretraining — BASELINE.json config 3.

The reference pretrains BERT-base MLM on Wikipedia text RDD partitions
(SURVEY.md §2 'Models: BERT-base MLM'); headline metric is tokens/sec/chip.

TPU-first decisions:

- bf16 activations/matmuls, f32 LayerNorm and softmax accumulation — the MXU
  mixed-precision recipe (no GPU-style loss scaling).
- BSHD attention layout via :mod:`..ops.attention` so batch sharding and the
  reserved ``seq`` mesh axis shard leading dims without transposes.
- Tied MLM decoder: output projection reuses the token-embedding table
  (one [vocab, hidden] matmul — MXU-friendly, halves embedding memory).
- Tensor-parallel ready: QKV/MLP kernels are plain Dense kernels whose
  sharding is assigned by path-regex rules
  (:data:`distributeddeeplearningspark_tpu.parallel.sharding.ShardingRules`) —
  the model code contains no parallelism logic.

Batch dict: ``input_ids`` [B,S] int32, ``attention_mask`` [B,S] 1/0,
optional ``token_type_ids`` [B,S]; returns MLM logits [B,S,vocab] f32.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from distributeddeeplearningspark_tpu.ops.attention import dot_product_attention, padding_mask


class BertConfig:
    """BERT-base defaults (Devlin et al.); override via kwargs."""

    def __init__(
        self,
        vocab_size: int = 30522,
        hidden_size: int = 768,
        num_layers: int = 12,
        num_heads: int = 12,
        intermediate_size: int = 3072,
        max_position: int = 512,
        type_vocab_size: int = 2,
        dropout_rate: float = 0.1,
        dtype: Any = jnp.bfloat16,
        attention_impl: str = "auto",
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_position = max_position
        self.type_vocab_size = type_vocab_size
        self.dropout_rate = dropout_rate
        self.dtype = dtype
        self.attention_impl = attention_impl

    @staticmethod
    def large(**kw) -> "BertConfig":
        """BERT-large geometry (Devlin et al. Table 1: 24 layers, 1024
        hidden, 16 heads, ~340M params) — the scale-up companion to the
        config-3 contract model; pair with ``optim.lamb`` at pod-scale
        batch."""
        base = dict(hidden_size=1024, num_layers=24, num_heads=16,
                    intermediate_size=4096)
        base.update(kw)
        return BertConfig(**base)

    @staticmethod
    def tiny(**kw) -> "BertConfig":
        """4-layer/128-wide config for CPU tests."""
        base = dict(vocab_size=1024, hidden_size=128, num_layers=4, num_heads=4,
                    intermediate_size=512, max_position=128, dtype=jnp.float32)
        base.update(kw)
        return BertConfig(**base)


class SelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array, *, train: bool,
                 segment_ids: jax.Array | None = None) -> jax.Array:
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.num_heads, head_dim), dtype=cfg.dtype, name=name
        )
        q = dense("query")(x)
        k = dense("key")(x)
        v = dense("value")(x)
        y = dot_product_attention(q, k, v, mask=mask, segment_ids=segment_ids,
                                  impl=cfg.attention_impl)
        y = nn.DenseGeneral(cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype, name="out")(y)
        return nn.Dropout(cfg.dropout_rate, deterministic=not train)(y)


class EncoderLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array, *, train: bool,
                 segment_ids: jax.Array | None = None) -> jax.Array:
        cfg = self.cfg
        # post-LN (original BERT): sublayer → residual → LayerNorm(f32)
        y = SelfAttention(cfg, name="attention")(x, mask, train=train,
                                                 segment_ids=segment_ids)
        x = nn.LayerNorm(dtype=jnp.float32, name="attention_ln")(x + y).astype(cfg.dtype)
        y = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype, name="mlp_in")(x)
        y = nn.gelu(y)
        y = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlp_out")(y)
        y = nn.Dropout(cfg.dropout_rate, deterministic=not train)(y)
        return nn.LayerNorm(dtype=jnp.float32, name="mlp_ln")(x + y).astype(cfg.dtype)


class BertEncoder(nn.Module):
    """Embeddings + N encoder layers; returns hidden states [B,S,H].

    ``tok_embed`` may be passed in by a head module that wants to tie the
    decoder to the token-embedding table (flax module sharing).
    """

    cfg: BertConfig
    tok_embed: nn.Module | None = None

    @nn.compact
    def __call__(self, batch: dict[str, jax.Array], *, train: bool = False) -> jax.Array:
        cfg = self.cfg
        ids = batch["input_ids"]
        if ids.shape[1] > cfg.max_position:
            raise ValueError(
                f"sequence length {ids.shape[1]} exceeds max_position "
                f"{cfg.max_position} (out-of-range positions would silently "
                f"clamp to the last embedding row)"
            )
        positions = jnp.arange(ids.shape[1])[None, :]
        types = batch.get("token_type_ids", jnp.zeros_like(ids))

        tok_emb = self.tok_embed or nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, name="token_embeddings"
        )
        x = tok_emb(ids)
        x = x + nn.Embed(cfg.max_position, cfg.hidden_size, dtype=cfg.dtype,
                         name="position_embeddings")(positions)
        x = x + nn.Embed(cfg.type_vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                         name="type_embeddings")(types)
        x = nn.LayerNorm(dtype=jnp.float32, name="embeddings_ln")(x).astype(cfg.dtype)
        x = nn.Dropout(cfg.dropout_rate, deterministic=not train)(x)

        mask = padding_mask(batch.get("attention_mask", jnp.ones_like(ids)))
        # packed sequences (VERDICT r2 #4): per-position document ids block
        # attention across packed-document boundaries; the flash kernel
        # streams them natively, the XLA path expands into the mask
        segment_ids = batch.get("segment_ids")
        for i in range(cfg.num_layers):
            x = EncoderLayer(cfg, name=f"layer_{i}")(x, mask, train=train,
                                                     segment_ids=segment_ids)
        return x


class BertForMLM(nn.Module):
    """Encoder + MLM head with tied decoder.

    Two head modes (the loss works with either, since labels/weights share
    the logits' leading shape):

    - full-length (default): logits ``[B, S, vocab]`` — every position pays
      the vocab projection.
    - **gathered** — when the batch carries ``mlm_positions`` ``[B, P]``
      (P = max predictions per sequence, the packed form produced by
      ``data.text.pack_mlm_predictions`` or
      ``data.text.mlm_dataset(max_predictions=...)``): hidden states are
      gathered at the masked positions BEFORE the transform head and tied
      decoder, so the [·, vocab] matmul runs on ~15% of positions — the
      original TPU BERT's ``masked_lm_positions`` design, worth ~2 of the
      ~12 TFLOP in a b=32/s=512 train step. Logits ``[B, P, vocab]``.
    """

    cfg: BertConfig

    @nn.compact
    def __call__(self, batch: dict[str, jax.Array], *, train: bool = False) -> jax.Array:
        cfg = self.cfg
        tok_emb = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                           name="token_embeddings")
        encoder = BertEncoder(cfg, tok_embed=tok_emb, name="encoder")
        x = encoder(batch, train=train)
        if "mlm_positions" in batch:
            # [B, S, H] → [B, P, H]: static P keeps the program shape fixed
            pos = batch["mlm_positions"].astype(jnp.int32)
            x = jnp.take_along_axis(x, pos[:, :, None], axis=1)
        # MLM transform head
        x = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlm_dense")(x)
        x = nn.gelu(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="mlm_ln")(x).astype(cfg.dtype)
        # tied decoder: logits = x @ E^T + b (Embed.attend is the tie)
        logits = tok_emb.attend(x).astype(jnp.float32)
        bias = self.param("mlm_bias", nn.initializers.zeros, (cfg.vocab_size,), jnp.float32)
        return logits + bias


def bert_base(**kw) -> BertForMLM:
    return BertForMLM(BertConfig(**kw))


def bert_large(**kw) -> BertForMLM:
    return BertForMLM(BertConfig.large(**kw))


def bert_tiny(**kw) -> BertForMLM:
    return BertForMLM(BertConfig.tiny(**kw))
