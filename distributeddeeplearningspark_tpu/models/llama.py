"""Llama-2 decoder + LoRA — BASELINE.json config 5.

The reference fine-tunes Llama-2 7B with LoRA adapters, FSDP-style sharded
"across Spark executors" on a v4-32 (SURVEY.md §2 'Models: Llama-2 7B + LoRA').
Architecture per Touvron et al. 2023: pre-norm RMSNorm, rotary position
embeddings, SwiGLU MLP, untied LM head; 7B = 32 layers x 4096 hidden,
32 heads, 11008 intermediate. GQA (separate ``num_kv_heads``) is supported so
the 70B-family configs load too.

TPU-first decisions:

- ``nn.scan`` over the layer stack (default on): one traced layer instead of
  32 unrolled copies — compile time and HLO size stay O(1) in depth, and the
  stacked [L, ...] params give FSDP a large, evenly divisible leading dim.
- ``nn.remat`` per layer (default on): rematerialize activations in backward —
  the HBM-for-FLOPs trade that makes 7B training fit (SURVEY.md 'HBM').
- bf16 matmuls, f32 RMSNorm/softmax/rotary — the MXU mixed-precision recipe.
- LoRA lives inside :class:`LoRADenseGeneral`: base kernel frozen via
  ``optax`` masking (see :func:`lora_trainable`), adapters are the only
  trained params. Adapter matmuls are rank-r — tiny — so they ride along the
  main matmul without a fused kernel.
- No parallelism logic in model code: FSDP/TP layouts come from
  :func:`llama_rules` path-regex shardings (GSPMD inserts the collectives).

Batch dict: ``input_ids`` [B,S] i32, optional ``attention_mask`` [B,S] 1/0,
optional ``loss_mask`` (consumed by the loss, not the model). Returns logits
[B,S,vocab] f32.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from distributeddeeplearningspark_tpu.ops.attention import (
    dot_product_attention,
    padding_mask,
)
from distributeddeeplearningspark_tpu.parallel.sharding import ShardingRules


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32          # < num_heads → grouped-query attention
    intermediate_size: int = 11008
    max_position: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # STORAGE dtype of the base weights (embed, attention/MLP kernels, LM
    # head). Default f32 — full-parameter training wants f32 masters, and HF
    # checkpoint interchange stays bit-faithful. Set "bfloat16" for frozen-
    # base LoRA fine-tuning: the base never takes an optimizer step, so f32
    # masters are pure waste — the r4 memval run measured f32 storage at
    # 25.2 GiB of arguments for the 7B (vs 12.6 analytic bf16), which alone
    # overflows a 16 GiB chip and doubles the v4-32 per-chip budget. LoRA
    # A/B adapters and RMSNorm scales stay f32 regardless (they train).
    param_dtype: Any = jnp.float32
    attention_impl: str = "auto"
    scan_layers: bool = True
    remat: bool = True
    # What the per-layer remat may keep instead of recomputing (names map to
    # jax.checkpoint_policies): None = save nothing (lowest memory, full
    # recompute); "dots" = dots_with_no_batch_dims_saveable — keep matmul
    # outputs so the backward pass skips recomputing the MXU-heavy ops and
    # only replays the cheap elementwise chain. Memory sits between remat-off
    # and full remat; the right default depends on whether the workload is
    # HBM-bound (7B FSDP: None) or compute-bound (sub-chip-sized: "dots").
    remat_policy: str | None = None
    # Fuse the LM-head matmul into the loss (train/fused_ce.py): the model
    # returns {"hidden", "lm_head"} instead of [B,S,V] f32 logits, so the
    # logits and their backward cotangent (~2×B·S·V f32 — 2.1 GB at the
    # config-5 bench shape) never materialize. Pair with
    # ``losses.causal_lm_fused``. Ignored in decode mode (generation needs
    # real logits).
    fused_head_loss: bool = False
    # Mixture-of-Experts FFN (models/moe.py; 0 = dense SwiGLU). When >0
    # every layer's MLP becomes a top-k-routed expert bank whose stacked
    # kernels shard over the `expert` mesh axis; the model returns
    # {"logits", "moe_aux"} in training so the load-balance loss reaches
    # the optimizer (losses.causal_lm/_fused add it).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Routing-group size (0 = per-sequence groups). Dispatch/combine cost
    # per token is linear in the group size, so shrinking it below S cuts
    # the GShard dense-dispatch overhead (the r4 1.33×-dense floor) at the
    # price of per-group capacity enforcement; must divide B·S.
    moe_group_size: int = 0
    # QLoRA-style int8 base storage ("int8" | None). One step below bf16:
    # every frozen projection/FFN base kernel is stored int8 with a per-
    # output-channel f32 scale (absmax), dequantized INTO the matmul (the
    # int8→bf16 convert+multiply fuses as a dot-operand read, so HBM sees
    # ~1 byte/weight). Frozen-base LoRA only — the base never takes an
    # optimizer step, so storage precision is a pure memory/bandwidth
    # knob: 7B base drops 12.6 → ~6.3 GiB (b=2 headroom on a 16 GiB
    # chip; decode's per-token weight reads halve). Embeddings, LM head
    # and norm scales stay at param_dtype/f32 (QLoRA convention —
    # quantizing the embedding hurts quality for no meaningful bytes).
    # Requires lora_rank > 0; rejected with MoE (experts train).
    base_quant: str | None = None
    # LoRA (rank 0 = disabled → plain full-parameter model)
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: Sequence[str] = ("wq", "wv")
    # Autoregressive decoding (models/llama_gen.py): static-config switch so
    # the scanned-layer call signature never changes. decode=True gives each
    # attention a KV cache ("cache" collection) of max_cache_len positions;
    # every call appends its tokens at the cache index and attends over the
    # cached prefix. Equal-length prompts per batch (prefill writes [0, T)).
    decode: bool = False
    max_cache_len: int | None = None
    # Keep weight-relayout copies INSIDE the layer scan. XLA's layout
    # assignment gives the scan-stacked projection kernels one entry layout,
    # but the forward dot (contract hidden) and the backward dx dot
    # (contract heads·head_dim) each prefer a different one; XLA then
    # commutes copy(dynamic_slice(W_stacked)) → dynamic_slice(copy(W_stacked))
    # and hoists WHOLE-STACK relayout copies out of the loop. Measured on the
    # r4 chip window (7B, b=1, s=1024): three 1.0 GiB copies of the stacked
    # wq/wk/wv — 3.0 of the 3.79 GiB program HBM — overflowing a 16 GiB chip
    # by 0.7 GiB that the weights themselves fit. An optimization_barrier on
    # each SLICED param blocks the commutation, so the (same total bytes of)
    # relayout runs per-layer inside the loop: peak temp drops by ~2× the
    # stack size at the cost of re-running slice-relayouts in the remat
    # replay. Default on; set False to let XLA hoist when HBM is plentiful.
    scan_param_barrier: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @staticmethod
    def llama2_7b(**kw) -> "LlamaConfig":
        # Frozen-base LoRA fine-tunes default to bf16 base-weight STORAGE
        # (see param_dtype docstring: the r4 memval run measured f32 masters
        # at 25.2 GiB for the 7B — unfittable on a 16 GiB chip and double
        # the v4-32 budget, for weights that never take an optimizer step).
        # Full-parameter 7B keeps f32 masters.
        if kw.get("lora_rank") and "param_dtype" not in kw:
            kw["param_dtype"] = jnp.bfloat16
        return LlamaConfig(**kw)

    @staticmethod
    def llama2_13b(**kw) -> "LlamaConfig":
        """Llama-2 13B geometry (MHA — 13B predates GQA): the pod-scale
        step-up of config 5. The analytic budget (utils/memory.py) places
        the LoRA fine-tune comfortably inside a v4-32 fsdp=8 layout
        (tests/test_memory.py::test_13b_count_and_v4_32_fsdp_layout_fits);
        delegates to llama2_7b so the LoRA-implies-bf16-storage policy
        lives in exactly one place."""
        base = dict(hidden_size=5120, num_layers=40, num_heads=40,
                    num_kv_heads=40, intermediate_size=13824)
        base.update(kw)
        return LlamaConfig.llama2_7b(**base)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """4-layer/128-wide config for CPU tests."""
        base = dict(vocab_size=512, hidden_size=128, num_layers=4, num_heads=4,
                    num_kv_heads=2, intermediate_size=256, max_position=128,
                    dtype=jnp.float32)
        base.update(kw)
        return LlamaConfig(**base)


def _remat_policy(name: str | None):
    """Map LlamaConfig.remat_policy to a jax.checkpoint policy (None = save
    nothing)."""
    if name is None:
        return None
    policies = {
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
    }
    if name not in policies:
        raise ValueError(
            f"unknown remat_policy {name!r}; use None, 'dots', or 'dots_saveable'")
    return policies[name]


_BARRIER_DIFFERENTIABLE: bool | None = None


def _barrier_differentiable() -> bool:
    """jax < 0.5 has no differentiation rule for optimization_barrier, so the
    scan_param_barrier memory optimization (a numerics no-op by contract)
    must quietly disable itself there instead of killing the backward pass.
    Probed once with a scalar trace, cached for the process."""
    global _BARRIER_DIFFERENTIABLE
    if _BARRIER_DIFFERENTIABLE is None:
        try:
            jax.grad(jax.lax.optimization_barrier)(0.0)
            _BARRIER_DIFFERENTIABLE = True
        except Exception:
            _BARRIER_DIFFERENTIABLE = False
    return _BARRIER_DIFFERENTIABLE


def rotary_embedding(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Apply RoPE to [B,S,H,D] in f32, half-split (rotate-half) convention."""
    d = x.shape[-1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq      # [B,S,D/2]
    cos = jnp.cos(angles)[:, :, None, :]                              # [B,S,1,D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    """Llama RMSNorm: f32 accumulation, learned scale, no bias."""

    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        xf = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        y = xf * jax.lax.rsqrt(jnp.mean(jnp.square(xf), -1, keepdims=True) + self.eps)
        return (y * scale).astype(self.dtype)


class LoRADenseGeneral(nn.Module):
    """DenseGeneral with an optional rank-r LoRA delta: y = xW + (alpha/r)·xAB.

    ``rank == 0`` → exactly ``nn.DenseGeneral`` (no extra params), so the same
    model class serves pretraining and adapter fine-tuning; the base ``kernel``
    is frozen by the optimizer mask, never by the module. A and B are stored
    f32 (tiny) and named ``lora_a``/``lora_b`` — the path fragment both
    :func:`lora_trainable` and :func:`llama_rules` key on. B starts at zero so
    step 0 matches the base model (Hu et al. 2021).
    """

    features: int | Sequence[int]
    axis: int | Sequence[int] = -1
    rank: int = 0
    alpha: float = 16.0
    use_bias: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32  # base-kernel STORAGE; A/B stay f32
    base_quant: str | None = None   # "int8": kernel int8 + per-out-channel scale

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        axes = (self.axis,) if isinstance(self.axis, int) else tuple(self.axis)
        axes = tuple(a % x.ndim for a in axes)
        feats = (self.features,) if isinstance(self.features, int) else tuple(self.features)
        in_dim = math.prod(x.shape[a] for a in axes)
        batch_shape = tuple(s for i, s in enumerate(x.shape) if i not in axes)

        def fold(t: jax.Array) -> jax.Array:  # x → [batch..., in_dim]
            t = jnp.moveaxis(t, axes, range(t.ndim - len(axes), t.ndim))
            return t.reshape(batch_shape + (in_dim,)).astype(self.dtype)

        if self.base_quant == "int8":
            if self.use_bias:
                raise NotImplementedError("int8 base_quant has no bias path")
            # Deterministic shared init scale (≈clip at 4σ of lecun-normal)
            # keeps kernel/scale self-consistent under random init; real
            # use quantizes pretrained weights via
            # llama_io.quantize_base_int8 (per-channel absmax).
            q0 = 4.0 / math.sqrt(in_dim) / 127.0

            def qinit(key, shape, _dtype=jnp.int8):
                w = nn.initializers.lecun_normal()(
                    key, (shape[0], math.prod(shape[1:])), jnp.float32)
                return jnp.clip(jnp.round(w / q0), -127, 127).astype(
                    jnp.int8).reshape(shape)

            kernel_q = self.param("base_q8", qinit, (in_dim,) + feats)
            scale = self.param("base_scale",
                               lambda _k, shape: jnp.full(shape, q0, jnp.float32),
                               feats)
            # dequant rides the dot's operand read (convert+mul fuse into
            # the matmul on TPU): HBM traffic stays ~1 byte/weight
            w = kernel_q.astype(self.dtype) * scale.astype(self.dtype)
            y = fold(x) @ w.reshape(in_dim, math.prod(feats))
            y = y.reshape(batch_shape + feats)
        else:
            y = nn.DenseGeneral(self.features, axis=self.axis,
                                use_bias=self.use_bias, dtype=self.dtype,
                                param_dtype=self.param_dtype, name="base")(x)
        if self.rank:
            a_mat = self.param("lora_a", nn.initializers.he_uniform(), (in_dim, self.rank),
                               jnp.float32)
            b_mat = self.param("lora_b", nn.initializers.zeros,
                               (self.rank, math.prod(feats)), jnp.float32)
            delta = (fold(x) @ a_mat.astype(self.dtype)) @ b_mat.astype(self.dtype)
            delta = delta.reshape(batch_shape + feats) * (self.alpha / self.rank)
            y = y + delta.astype(y.dtype)
        return y


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array | None,
                 segment_ids: jax.Array | None = None) -> jax.Array:
        cfg = self.cfg
        hd, nh, nkv = cfg.head_dim, cfg.num_heads, cfg.num_kv_heads

        def proj(name, heads):
            rank = cfg.lora_rank if name in cfg.lora_targets else 0
            return LoRADenseGeneral((heads, hd), rank=rank, alpha=cfg.lora_alpha,
                                    dtype=cfg.dtype,
                                    param_dtype=cfg.param_dtype,
                                    base_quant=cfg.base_quant, name=name)

        q = proj("wq", nh)(x)                                   # [B,S,nh,hd]
        k = proj("wk", nkv)(x)
        v = proj("wv", nkv)(x)
        if cfg.decode:
            if mask is not None:
                raise ValueError(
                    "decode mode has no padding-mask support: the KV cache "
                    "assumes equal-length prompts (drop attention_mask and "
                    "bucket/pad prompts to one length upstream)")
            if segment_ids is not None:
                raise ValueError(
                    "decode mode does not take segment_ids (generation is "
                    "one document per row)")
            y = self._decode_attend(q, k, v)
        else:
            positions = jnp.arange(x.shape[1])[None, :]
            q = rotary_embedding(q, positions, cfg.rope_theta)
            k = rotary_embedding(k, positions, cfg.rope_theta)
            # GQA K/V stay at nkv heads: flash indexes groups directly, ring
            # runs grouped einsums; only the xla fallback broadcasts.
            y = dot_product_attention(q, k, v, mask=mask, causal=True,
                                      segment_ids=segment_ids,
                                      impl=cfg.attention_impl)
        rank = cfg.lora_rank if "wo" in cfg.lora_targets else 0
        return LoRADenseGeneral(cfg.hidden_size, axis=(-2, -1), rank=rank,
                                alpha=cfg.lora_alpha, dtype=cfg.dtype,
                                param_dtype=cfg.param_dtype,
                                base_quant=cfg.base_quant, name="wo")(y)

    def _decode_attend(self, q, k, v):
        """KV-cached attention: append the T new tokens at the cache index,
        attend q over the cached prefix. One code path serves prefill (T =
        prompt length at index 0) and decode (T = 1). Static shapes: the
        cache is [B, max_cache_len, nkv, hd]; masking, not slicing, bounds
        the attended positions (XLA-friendly — no dynamic shapes).

        The write index is PER ROW (``index`` is [B], not a scalar): plain
        ``generate`` advances every row in lockstep so the values stay
        equal, but the continuous-batching server (serve/generate.py) keys
        each KV slot at its own sequence position — a request admitted
        mid-flight decodes from its prompt length while its neighbors are
        hundreds of tokens in. Rows never see each other's stale cache:
        ``kpos <= qpos`` bounds attention at each row's own position, and
        every decode step writes its token before attending, so any
        garbage beyond a row's index is both masked and overwritten before
        it could ever be read."""
        cfg = self.cfg
        b, t = q.shape[0], q.shape[1]
        max_len = cfg.max_cache_len or cfg.max_position
        ck = self.variable("cache", "k", jnp.zeros,
                           (b, max_len, cfg.num_kv_heads, cfg.head_dim), cfg.dtype)
        cv = self.variable("cache", "v", jnp.zeros,
                           (b, max_len, cfg.num_kv_heads, cfg.head_dim), cfg.dtype)
        cidx = self.variable("cache", "index",
                             lambda: jnp.zeros((b,), jnp.int32))
        idx = cidx.value                                       # [B]
        positions = idx[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
        q = rotary_embedding(q, positions, cfg.rope_theta)
        k = rotary_embedding(k, positions, cfg.rope_theta)

        def write_row(cache_row, new_row, start):
            return jax.lax.dynamic_update_slice(
                cache_row, new_row, (start, 0, 0))

        ck.value = jax.vmap(write_row)(ck.value, k.astype(cfg.dtype), idx)
        cv.value = jax.vmap(write_row)(cv.value, v.astype(cfg.dtype), idx)
        cidx.value = idx + t
        kpos = jnp.arange(max_len, dtype=jnp.int32)[None, None, None, :]
        qpos = positions[:, None, :, None]
        attend = kpos <= qpos                     # causal over cached prefix
        return dot_product_attention(q, ck.value, cv.value, mask=attend,
                                     causal=False, impl="xla")


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg

        def proj(name, feats, axis=-1):
            rank = cfg.lora_rank if name in cfg.lora_targets else 0
            return LoRADenseGeneral(feats, axis=axis, rank=rank, alpha=cfg.lora_alpha,
                                    dtype=cfg.dtype,
                                    param_dtype=cfg.param_dtype,
                                    base_quant=cfg.base_quant, name=name)

        gate = proj("gate", cfg.intermediate_size)(x)
        up = proj("up", cfg.intermediate_size)(x)
        return proj("down", cfg.hidden_size)(nn.silu(gate) * up)


class DecoderLayer(nn.Module):
    """Pre-norm block; returns (x, aux) — the (carry, out) pair nn.scan
    wants; ``aux`` is the layer's ``(moe_lb_loss, moe_dropped_frac)`` pair
    (both 0 when dense)."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x: jax.Array, mask: jax.Array | None,
                 segment_ids: jax.Array | None = None):
        cfg = self.cfg
        h = RMSNorm(cfg.rms_eps, cfg.dtype, name="attention_norm")(x)
        x = x + LlamaAttention(cfg, name="attention")(h, mask,
                                                      segment_ids)
        h = RMSNorm(cfg.rms_eps, cfg.dtype, name="mlp_norm")(x)
        if cfg.moe_experts:
            from distributeddeeplearningspark_tpu.models.moe import MoEMLP

            y, aux = MoEMLP(
                cfg.hidden_size, cfg.intermediate_size, cfg.moe_experts,
                top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                group_size=cfg.moe_group_size,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                name="moe")(h)
        else:
            y = LlamaMLP(cfg, name="mlp")(h)
            aux = (jnp.float32(0.0), jnp.float32(0.0))
        return x + y, aux


class _LMHead(nn.Module):
    """Untied LM head with the exact param path/init/compute of
    ``nn.Dense(vocab, use_bias=False, name="lm_head")`` — replaced only so
    the fused-loss path can read the kernel without applying it (param tree,
    TP rule ``lm_head/kernel`` and HF interchange stay byte-identical)."""

    vocab: int
    dtype: Any
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, *, return_kernel: bool = False):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.vocab), self.param_dtype)
        if return_kernel:
            return kernel
        return jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))


class LlamaForCausalLM(nn.Module):
    """Decoder-only LM; logits [B,S,vocab] f32 (untied head, as in Llama-2)."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, batch: dict[str, jax.Array], *, train: bool = False) -> jax.Array:
        # no dropout in Llama-2; `train` only gates whether the MoE aux
        # loss is returned (predict/eval consumers expect a plain logits
        # array — see the returns below)
        cfg = self.cfg
        ids = batch["input_ids"]
        if ids.shape[1] > cfg.max_position:
            raise ValueError(
                f"sequence length {ids.shape[1]} exceeds max_position {cfg.max_position}"
            )
        if cfg.base_quant is not None:
            if cfg.base_quant != "int8":
                raise ValueError(f"unknown base_quant {cfg.base_quant!r}; "
                                 "supported: 'int8'")
            if not cfg.lora_rank:
                # int8 leaves carry float0 tangents — full-parameter
                # training would feed them to the optimizer; the quantized
                # base only makes sense frozen under adapters
                raise ValueError("base_quant='int8' requires lora_rank > 0 "
                                 "(frozen-base LoRA; train with "
                                 "trainable=lora_trainable)")
            if cfg.moe_experts:
                raise NotImplementedError(
                    "base_quant with moe_experts: the expert bank TRAINS "
                    "from scratch (f32) — quantizing it would silently "
                    "freeze garbage; drop one of the two")
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype, name="token_embed")(ids)
        pad = batch.get("attention_mask")
        # causal handled inside attention; only pass an explicit mask for padding
        mask = padding_mask(pad) if pad is not None else None
        # packed-document batches (data/text.py lm_dataset(segment_ids=True)):
        # per-position doc ids block cross-document attention — streamed
        # natively by the flash kernel and the ring's riding blocks
        segment_ids = batch.get("segment_ids")

        layer_cls = DecoderLayer
        if cfg.scan_layers and cfg.scan_param_barrier \
                and _barrier_differentiable():
            # barrier each SLICED layer's params (see the config field's
            # rationale). MUST wrap inside the remat region (i.e. before
            # nn.remat): outside it, the barrier's outputs become per-layer
            # saved residuals and the forward scan stashes a full stacked
            # copy of every weight (+12.5 GiB at 7B, measured) — inside,
            # the backward replay re-slices the loop-invariant params and
            # re-applies the free barrier instead.
            layer_cls = nn.map_variables(
                layer_cls, "params",
                trans_in_fn=lambda tree: jax.tree.map(
                    jax.lax.optimization_barrier, tree),
                init=self.is_initializing())
        if cfg.remat:
            layer_cls = nn.remat(layer_cls, prevent_cse=False,
                                 policy=_remat_policy(cfg.remat_policy))
        if cfg.scan_layers:
            var_axes = {"params": 0}
            if cfg.decode:
                var_axes["cache"] = 0           # per-layer KV caches, stacked
            stacked = nn.scan(
                layer_cls,
                variable_axes=var_axes,
                split_rngs={"params": True},
                in_axes=nn.broadcast,           # mask is shared, not scanned
                length=cfg.num_layers,
            )(cfg, name="layers")
            x, (aux, dropped) = stacked(x, mask, segment_ids)
            moe_aux = jnp.sum(aux) if cfg.moe_experts else None
            moe_dropped = jnp.mean(dropped) if cfg.moe_experts else None
        else:
            auxes, droppeds = [], []
            for i in range(cfg.num_layers):
                x, (aux, drp) = layer_cls(cfg, name=f"layers_{i}")(
                    x, mask, segment_ids)
                auxes.append(aux)
                droppeds.append(drp)
            moe_aux = (jnp.sum(jnp.stack(auxes))
                       if cfg.moe_experts else None)
            moe_dropped = (jnp.mean(jnp.stack(droppeds))
                           if cfg.moe_experts else None)

        x = RMSNorm(cfg.rms_eps, cfg.dtype, name="final_norm")(x)
        head = _LMHead(cfg.vocab_size, cfg.dtype, cfg.param_dtype,
                       name="lm_head")
        if cfg.fused_head_loss and not cfg.decode:
            # hand the pieces to losses.causal_lm_fused; the [B,S,V] f32
            # logits (and their cotangent) never exist
            out = {"hidden": x, "lm_head": head(x, return_kernel=True)}
            if moe_aux is not None and train:
                out["moe_aux"] = cfg.moe_aux_weight * moe_aux
                out["moe_dropped_frac"] = moe_dropped
            return out
        logits = head(x).astype(jnp.float32)
        if moe_aux is not None and train and not cfg.decode:
            # train only: predict/eval consumers (Trainer.predict row
            # indexing, argmax output_fns) expect a bare logits array
            return {"logits": logits,
                    "moe_aux": cfg.moe_aux_weight * moe_aux,
                    "moe_dropped_frac": moe_dropped}
        return logits


def llama2_7b(**kw) -> LlamaForCausalLM:
    return LlamaForCausalLM(LlamaConfig.llama2_7b(**kw))


def llama2_13b(**kw) -> LlamaForCausalLM:
    return LlamaForCausalLM(LlamaConfig.llama2_13b(**kw))


def llama_tiny(**kw) -> LlamaForCausalLM:
    return LlamaForCausalLM(LlamaConfig.tiny(**kw))


def lora_trainable(path: str) -> bool:
    """Optimizer mask for LoRA fine-tuning: train adapters only.

    Use with :func:`distributeddeeplearningspark_tpu.train.optim.masked` — the
    rebuild of the reference's per-param-group ``requires_grad=False`` on all
    base weights.
    """
    return "lora_a" in path or "lora_b" in path


def llama_rules(cfg: LlamaConfig, *, fsdp: bool = True,
                fsdp_min_size: int = 2**14, pipeline: bool = False) -> ShardingRules:
    """FSDP + Megatron-style tensor-parallel layout for the Llama tree.

    Attention QKV shard heads over ``tensor``; the out-projection and MLP
    down-projection shard their *input* (contracting) dim so GSPMD turns the
    pair into a split-matmul + psum (one all-reduce per block, the Megatron
    pattern). Embedding and LM head shard the vocab dim. LoRA adapters stay
    replicated — rank-r factors are too small to be worth a collective. The
    auto-FSDP pass then shards the largest remaining dim of every large
    param over ``fsdp`` (with scanned layers that is usually the [L, ...]
    leading dim — uniform and always divisible).

    ``pipeline=True`` (requires ``scan_layers``): the stacked [L, ...]
    leading dim of every decoder-layer param shards over ``pipe`` instead —
    each device then STORES only its own stages, making PP a param-memory
    partitioning like the reference's FSDP but along depth; auto-FSDP moves
    to the next-largest dim.
    """
    if pipeline and not cfg.scan_layers:
        raise ValueError("pipeline rules need scan_layers=True stacked params")
    lead = (("pipe",) if pipeline else (None,)) if cfg.scan_layers else ()
    rules = (
        (r"lora_", P(*lead) if pipeline else P()),
        (r"(wq|wk|wv)/base/kernel", P(*lead, None, "tensor", None)),
        (r"wo/base/kernel", P(*lead, "tensor", None, None)),
        (r"(gate|up)/base/kernel", P(*lead, None, "tensor")),
        (r"down/base/kernel", P(*lead, "tensor", None)),
        # int8 base (base_quant): kernels mirror their bf16 siblings'
        # layouts; per-out-channel scales follow the kernel's OUTPUT dims
        # (wo/down outputs are the psum'd hidden dim → replicated)
        # int8 kernels fold input axes: wq/wk/wv stay (in, heads, hd)
        # like their dense siblings, but wo folds (heads, hd) → one 2-D
        # (heads*hd, hidden) contracting-sharded kernel
        *(((r"(wq|wk|wv)/base_q8", P(*lead, None, "tensor", None)),
           (r"(wq|wk|wv)/base_scale", P(*lead, "tensor", None)),
           (r"wo/base_q8", P(*lead, "tensor", None)),
           (r"(gate|up)/base_q8", P(*lead, None, "tensor")),
           (r"(gate|up)/base_scale", P(*lead, "tensor")),
           (r"(wo|down)/base_scale", P(*lead, None)),
           (r"down/base_q8", P(*lead, "tensor", None)),
           ) if cfg.base_quant else ()),
        (r"token_embed/embedding", P("tensor", None)),
        (r"lm_head/kernel", P(None, "tensor")),
        # MoE expert bank: stacked expert kernels shard over `expert`
        # (+ FFN dims over `tensor`); the tiny router replicates
        *(((r"moe/(w_gate|w_up)", P(*lead, "expert", None, "tensor")),
           (r"moe/w_down", P(*lead, "expert", "tensor", None)),
           (r"moe/router", P(*lead) if pipeline else P()),
           ) if cfg.moe_experts else ()),
        # PP catch-all: any remaining stacked layer param (norm scales)
        # stores on its own stage's devices. (`(^|/)` anchor: TrainState
        # paths are prefixed, e.g. "params/layers/...".)
        *(((r"(^|/)layers/", P(*lead)),) if pipeline else ()),
    )
    return ShardingRules(rules=rules, fsdp=fsdp, fsdp_min_size=fsdp_min_size,
                         fsdp_exclude=(r"lora_",))
