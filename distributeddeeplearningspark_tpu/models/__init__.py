"""Model zoo: the five contract architectures (BASELINE.json configs), in flax."""

from distributeddeeplearningspark_tpu.models.lenet import LeNet5

__all__ = ["LeNet5"]
