"""Model zoo: the five contract architectures (BASELINE.json configs), in flax."""

from distributeddeeplearningspark_tpu.models.dlrm import (
    DLRM,
    FusedEmbedding,
    WideAndDeep,
    dlrm_rules,
)
from distributeddeeplearningspark_tpu.models.lenet import LeNet5
from distributeddeeplearningspark_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    llama2_7b,
    llama2_13b,
    llama_rules,
    llama_tiny,
    lora_trainable,
)
from distributeddeeplearningspark_tpu.models.bert import (
    BertConfig,
    BertEncoder,
    BertForMLM,
    bert_base,
    bert_large,
    bert_tiny,
)
from distributeddeeplearningspark_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)

__all__ = [
    "BertConfig",
    "BertEncoder",
    "BertForMLM",
    "bert_base",
    "bert_large",
    "bert_tiny",
    "DLRM",
    "FusedEmbedding",
    "WideAndDeep",
    "dlrm_rules",
    "LeNet5",
    "LlamaConfig",
    "LlamaForCausalLM",
    "llama2_7b",
    "llama2_13b",
    "llama_rules",
    "llama_tiny",
    "lora_trainable",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
]
