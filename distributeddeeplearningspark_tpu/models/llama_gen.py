"""Autoregressive generation for Llama — KV-cached decode, fully jitted.

The training contract (BASELINE.json config 5) ends at the fine-tune, but a
usable flagship needs sampling; the reference world serves its tuned model
via the same predict path it trains with. TPU-first decode design:

- **Static shapes end-to-end**: the KV cache is ``[B, max_cache_len, ...]``
  from the first call; masking (not slicing) bounds attention, and the
  decode loop is one ``lax.scan`` of single-token steps — one compiled
  program regardless of prompt/output lengths (pad prompts per bucket to
  avoid recompiles).
- **Prefill + decode share one cache path** (``LlamaAttention._decode_attend``):
  prefill writes the whole prompt at index 0 in one MXU-sized pass, then
  each scan step appends one token.
- Sampling: greedy (``temperature=0``) or temperature softmax with optional
  top-k truncation; an ``eos_id`` freezes finished rows (they emit ``pad_id``
  thereafter) while the batch keeps stepping — SPMD-friendly, no early exit.

Equal-length prompts per batch (left-pad or bucket upstream — documented
limitation of the shared cache index).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp

from distributeddeeplearningspark_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def _sample(logits: jax.Array, key: jax.Array, *, temperature: float,
            top_k: int, top_p: float = 1.0) -> jax.Array:
    """[B, V] f32 logits → [B] int32 token ids.

    ``top_k`` and ``top_p`` (nucleus) compose: k-truncation first, then the
    smallest prefix of the remaining sorted probabilities whose mass
    reaches ``top_p`` (the first token always survives, so sampling is
    never empty). Everything is sort/cumsum/where — static shapes, scans
    cleanly under jit.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.float32(temperature)
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]          # desc
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep sorted position j while the mass BEFORE it is < top_p
        # (position 0 always kept); threshold = smallest kept logit
        keep = (cum - probs) < top_p
        kept_min = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < kept_min, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def decode_model(cfg: LlamaConfig, max_cache_len: int) -> LlamaForCausalLM:
    """The decode-mode twin of a training model (same params tree)."""
    return LlamaForCausalLM(dataclasses.replace(
        cfg, decode=True, max_cache_len=max_cache_len,
        attention_impl="xla", remat=False))


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "temperature", "top_k",
                     "top_p", "eos_id", "pad_id", "max_cache_len"),
)
def generate(
    params: Any,
    input_ids: jax.Array,
    *,
    cfg: LlamaConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    seed: int = 0,
    eos_id: int | None = None,
    pad_id: int = 0,
    max_cache_len: int | None = None,
) -> jax.Array:
    """Generate ``[B, max_new_tokens]`` continuations of ``input_ids`` [B, T].

    ``params`` is the training model's param tree (LoRA adapters, if any,
    stay active — merge first via ``llama_io.merge_lora`` for merged-weight
    speed). Deterministic for ``temperature=0`` (greedy).
    """
    b, t = input_ids.shape
    total = max_cache_len or (t + max_new_tokens)
    if t + max_new_tokens > total:
        raise ValueError(
            f"prompt {t} + new {max_new_tokens} exceeds max_cache_len {total} "
            f"— cache writes would clamp and corrupt output")
    if total > cfg.max_position:
        raise ValueError(
            f"cache length {total} exceeds max_position {cfg.max_position}")
    model = decode_model(cfg, total)

    # prefill: whole prompt in one pass; cache initialized by flax on first
    # apply (mutable collection), so init+prefill are a single call
    variables = {"params": params}
    logits, mutated = model.apply(
        variables, {"input_ids": input_ids}, train=False, mutable=["cache"])
    key = jax.random.PRNGKey(seed)
    key, sub = jax.random.split(key)
    tok = _sample(logits[:, -1].astype(jnp.float32), sub,
                  temperature=temperature, top_k=top_k, top_p=top_p)
    done = jnp.zeros((b,), bool)
    if eos_id is not None:
        done = tok == eos_id

    def step(carry, _):
        cache, tok, key, done = carry
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            {"input_ids": tok[:, None]}, train=False, mutable=["cache"])
        key, sub = jax.random.split(key)
        nxt = _sample(logits[:, -1].astype(jnp.float32), sub,
                      temperature=temperature, top_k=top_k, top_p=top_p)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.int32(pad_id), nxt)
            done = done | (nxt == eos_id)
        return (mutated["cache"], nxt, key, done), tok

    (_, last, _, _), toks = jax.lax.scan(
        step, (mutated["cache"], tok, key, done), None,
        length=max_new_tokens - 1)
    # toks holds tokens 0..N-2 (each step emits its INPUT token); append last
    return jnp.concatenate(
        [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
