"""Checkpoint-free live redistribution under a peak-memory bound.

:mod:`.reshard` moves a pytree between layouts in one shot — fine for small
models, but a whole-leaf move materializes the full leaf in transit, and a
geometry change that rides disk (checkpoint walk-back) loses every step since
the last save. This module is the live path (ISSUE 16, the `[elastic speed]`
ROADMAP item): the memory-efficient redistribution of arXiv:2112.01075
executed as an explicit block-transfer schedule —

- **schedule** (:func:`chunk_rows`): each leaf is split along its leading
  dimension into chunks sized so one chunk's bytes fit the budget from
  ``DLS_RESHARD_MEM_MB``. Chunks are grouped into bounded *rounds*; the
  in-flight bytes of a round never exceed the budget (a single row wider
  than the budget is moved whole and reported honestly).
- **transfer** (:func:`redistribute`): leaves already laid out right pass
  through untouched; small leaves ride ``jax.device_put`` (XLA's
  all-gather/dynamic-slice pair); large leaves are streamed chunk-by-chunk —
  each chunk pulls only the overlapping slices of the source's addressable
  shards, scatters them into per-target-span buffers, and the assembled
  blocks are placed via ``make_array_from_single_device_arrays``. The
  budget bounds the transfer working set (bytes pulled per round), the
  quantity 2112.01075 bounds on device; destination residency is the leaf
  itself and cannot be smaller.
- **verification**: every moved leaf is blake2b-hashed chunk-wise in logical
  row order on the source during the pull and re-read from the target after
  placement; a mismatch raises :class:`ReshardVerifyError` before anyone
  checkpoints corrupt state. Verification re-reads are not counted as
  transfer rounds.

The second half is the **handoff**: a drained host's live state persisted as
digest-verified raw blocks (:func:`save_handoff` / :func:`load_handoff`) so a
shrunk gang resumes from the *current* step instead of walking back through
the checkpoint. On a real pod the survivors would re-gather the doomed rank's
shards over collectives; on single-controller CPU rigs (and across the
supervisor's process boundary) the handoff directory is the transport — same
schedule, same digests, different wire.

Consumers: ``Trainer.apply_plan`` (live plan_sweep application),
``Trainer`` graceful SIGTERM drain + ``supervisor`` shrink (ISSUE 16
drill), and ``serve.fleet`` replica warm-up from a peer's exported weights.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import shutil
import time
from typing import Any

import jax
import numpy as np
from jax.sharding import NamedSharding

from distributeddeeplearningspark_tpu.parallel.reshard import (
    SpanUnavailableError,
    _assemble_block,
    _slices_cover,
    geometry_of,
)

RESHARD_MEM_ENV = "DLS_RESHARD_MEM_MB"
DEFAULT_MEM_MB = 256.0

HANDOFF_DIRNAME = "live_handoff"
HANDOFF_MANIFEST = "manifest.json"
HANDOFF_FORMAT = 1

_DIGEST_SIZE = 16


class ReshardVerifyError(RuntimeError):
    """A leaf's post-move digest does not match its source digest — the
    live transfer corrupted bytes. Do NOT checkpoint this state; restore
    from the last verified checkpoint instead."""


class HandoffError(RuntimeError):
    """A live handoff could not be ingested (missing/extra leaves, shape or
    digest mismatch). The caller should fall back to the checkpoint."""


def memory_budget_bytes(mem_mb: float | None = None) -> int:
    """The in-flight byte budget: ``mem_mb`` if given, else
    ``DLS_RESHARD_MEM_MB``, else :data:`DEFAULT_MEM_MB`."""
    if mem_mb is None:
        raw = os.environ.get(RESHARD_MEM_ENV, "").strip()
        mem_mb = float(raw) if raw else DEFAULT_MEM_MB
    if mem_mb <= 0:
        raise ValueError(
            f"reshard memory budget must be > 0 MB, got {mem_mb} "
            f"(set {RESHARD_MEM_ENV} or pass mem_mb)")
    return max(1, int(mem_mb * 1024 * 1024))


def chunk_rows(shape: tuple[int, ...], itemsize: int,
               budget: int) -> tuple[tuple[int, int], ...]:
    """Row ranges ``[lo, hi)`` along dim 0 sized so one chunk ≤ ``budget``
    bytes. 0-d leaves get the single pseudo-row ``(0, 1)``; a row wider than
    the budget is one chunk (it cannot be split along dim 0)."""
    if not shape:
        return ((0, 1),)
    rows = int(shape[0])
    if rows == 0:
        return ()
    row_bytes = itemsize * max(1, math.prod(shape[1:]))
    per = max(1, budget // row_bytes)
    return tuple((lo, min(lo + per, rows)) for lo in range(0, rows, per))


@dataclasses.dataclass
class TransferStats:
    """Ledger of one :func:`redistribute` call — the live-path fields the
    ``reshard`` telemetry event carries (bytes moved, rounds, peak
    in-flight, wall)."""

    leaves: int = 0
    leaves_moved: int = 0
    bytes_total: int = 0
    bytes_moved: int = 0
    rounds: int = 0
    peak_inflight_bytes: int = 0
    mem_budget_bytes: int = 0
    wall_s: float = 0.0
    verified: bool = False

    def to_record(self) -> dict:
        return dataclasses.asdict(self)


class _RoundLedger:
    """Group chunk transfers into rounds whose in-flight bytes stay under
    the budget; track the honest peak (a single over-budget chunk makes a
    round of one and the peak shows it)."""

    def __init__(self, budget: int):
        self.budget = budget
        self.rounds = 0
        self.peak = 0
        self._inflight = 0

    def add(self, nbytes: int) -> None:
        if self._inflight and self._inflight + nbytes > self.budget:
            self.close()
        self._inflight += int(nbytes)
        self.peak = max(self.peak, self._inflight)

    def close(self) -> None:
        if self._inflight:
            self.rounds += 1
            self._inflight = 0


def _iter_chunks(x: jax.Array, chunks):
    """Yield ``(lo, hi, block)`` where ``block`` is the host ndarray of rows
    ``[lo, hi)`` (the full leaf for 0-d), assembled by pulling only the
    overlapping slice of each addressable source shard — the bounded read
    primitive both the transfer and the digest passes share."""
    if x.ndim == 0:
        yield 0, 1, np.asarray(jax.device_get(x))
        return
    shape = x.shape
    sources = [(_slices_cover(shape, s.index), s.data)
               for s in x.addressable_shards]
    if not sources:
        raise SpanUnavailableError(
            f"array of shape {shape} has no addressable shards on this "
            f"host — nothing to redistribute from")
    for lo, hi in chunks:
        subs = []
        for span, data in sources:
            slo, shi = span[0]
            olo, ohi = max(lo, slo), min(hi, shi)
            if olo >= ohi:
                continue
            pulled = np.asarray(data[olo - slo:ohi - slo])
            subs.append(([(olo, ohi)] + span[1:], pulled))
        target_span = [(lo, hi)] + [(0, d) for d in shape[1:]]
        yield lo, hi, _assemble_block(shape, target_span, subs)


def _digest_chunks(x: jax.Array, chunks) -> str:
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
    for _, _, block in _iter_chunks(x, chunks):
        h.update(np.ascontiguousarray(block).tobytes())
    return h.hexdigest()


def _place_chunked(x: jax.Array, target: NamedSharding, chunks,
                   ledger: _RoundLedger, hasher) -> jax.Array:
    """Stream ``x`` into ``target`` layout chunk-by-chunk. ``hasher`` sees
    every chunk in logical row order — the source digest for free."""
    shape, dtype = x.shape, x.dtype
    spans: dict[tuple, list] = {}
    for dev, idx in target.addressable_devices_indices_map(shape).items():
        span = tuple(tuple(p) for p in _slices_cover(shape, idx))
        spans.setdefault(span, []).append(dev)
    bufs = {span: np.empty([hi - lo for lo, hi in span], dtype)
            for span in spans}
    for lo, hi, block in _iter_chunks(x, chunks):
        ledger.add(block.nbytes)
        hasher.update(np.ascontiguousarray(block).tobytes())
        for span, buf in bufs.items():
            (tlo, thi), rest = span[0], span[1:]
            olo, ohi = max(lo, tlo), min(hi, thi)
            if olo >= ohi:
                continue
            cols = tuple(slice(slo, shi) for slo, shi in rest)
            buf[olo - tlo:ohi - tlo] = block[(slice(olo - lo, ohi - lo),)
                                             + cols]
    arrays = []
    for span, devs in spans.items():
        for dev in devs:
            arrays.append(jax.device_put(bufs[span], dev))
    return jax.make_array_from_single_device_arrays(shape, target, arrays)


def _move_leaf(x: jax.Array, target: NamedSharding, chunks,
               ledger: _RoundLedger) -> tuple[jax.Array, str]:
    """Move one leaf; returns ``(moved, source_digest)``."""
    if x.ndim == 0 or x.nbytes <= ledger.budget:
        digest = _digest_chunks(x, chunks)
        try:
            out = jax.device_put(x, target)
            ledger.add(x.nbytes)
            ledger.close()
            return out, digest
        except (ValueError, TypeError, RuntimeError):
            pass  # cross-mesh device_put unsupported: stream it
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
    out = _place_chunked(x, target, chunks, ledger, h)
    ledger.close()
    return out, h.hexdigest()


def redistribute(tree: Any, target_shardings: Any, *,
                 mem_mb: float | None = None,
                 verify: bool = True) -> tuple[Any, TransferStats]:
    """Move every leaf of ``tree`` to its sharding in ``target_shardings``
    in bounded-peak-memory rounds; returns ``(tree, stats)``.

    Unlike :func:`.reshard.redistribute` (one-shot, unbounded), this path
    chunks each leaf so in-flight transfer bytes per round stay within
    ``DLS_RESHARD_MEM_MB`` (or ``mem_mb``) and, with ``verify=True``,
    re-reads every moved leaf from its new layout to check the blake2b
    digest taken during the pull — a corrupt move raises
    :class:`ReshardVerifyError` instead of silently training on garbage.
    """
    budget = memory_budget_bytes(mem_mb)
    ledger = _RoundLedger(budget)
    stats = TransferStats(mem_budget_bytes=budget)
    t0 = time.perf_counter()

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    # None means "leave this leaf alone" — keep it as a LEAF of the
    # shardings tree (to jax, a bare None is structure, and dropping it
    # would misalign the zip against the state's leaves)
    targets = jax.tree_util.tree_leaves(target_shardings,
                                        is_leaf=lambda t: t is None)
    out = []
    pending: list[tuple[str, jax.Array, str, tuple]] = []
    for (path, x), sh in zip(flat, targets):
        stats.leaves += 1
        if sh is None or not hasattr(x, "addressable_shards"):
            out.append(x if sh is None else jax.device_put(x, sh))
            continue
        stats.bytes_total += int(x.nbytes)
        if x.sharding.is_equivalent_to(sh, x.ndim):
            out.append(x)
            continue
        chunks = chunk_rows(tuple(x.shape), x.dtype.itemsize, budget)
        moved, digest = _move_leaf(x, sh, chunks, ledger)
        stats.leaves_moved += 1
        stats.bytes_moved += int(x.nbytes)
        out.append(moved)
        if verify:
            from distributeddeeplearningspark_tpu.parallel.sharding import (
                path_str)

            pending.append((path_str(path), moved, digest, chunks))

    for name, moved, digest, chunks in pending:
        got = _digest_chunks(moved, chunks)
        if got != digest:
            raise ReshardVerifyError(
                f"leaf {name!r}: blake2b mismatch after live reshard "
                f"(source {digest}, target {got}) — transfer corrupted "
                f"bytes; do not checkpoint this state, restore from the "
                f"last verified checkpoint")
    stats.verified = bool(verify)
    stats.rounds = ledger.rounds
    stats.peak_inflight_bytes = ledger.peak
    stats.wall_s = time.perf_counter() - t0
    _probe(stats)
    return jax.tree_util.tree_unflatten(treedef, out), stats


def _probe(stats: TransferStats) -> None:
    from distributeddeeplearningspark_tpu.parallel import collectives

    collectives.transfer_probe("live_reshard", stats.bytes_moved,
                               stats.wall_s, rounds=stats.rounds)


def emit_reshard_event(stats: TransferStats, *, step: int | None = None,
                       transport: str = "collectives",
                       walk_back: bool = False, **fields: Any) -> None:
    """Emit the ``reshard`` recovery event with the live-path fields
    (bytes moved, rounds, peak in-flight, wall) through the process-wide
    telemetry writer; no-op when telemetry is unconfigured."""
    from distributeddeeplearningspark_tpu import telemetry

    tele = telemetry.get()
    if tele is None:
        return
    tele.recovery(step, "reshard", transport=transport,
                  walk_back=bool(walk_back),
                  bytes_moved=int(stats.bytes_moved),
                  rounds=int(stats.rounds),
                  peak_inflight_bytes=int(stats.peak_inflight_bytes),
                  mem_budget_mb=round(stats.mem_budget_bytes / 2**20, 3),
                  wall_s=round(stats.wall_s, 4),
                  leaves_moved=int(stats.leaves_moved),
                  verified=bool(stats.verified), **fields)


# -- live handoff -------------------------------------------------------------


def handoff_dir(directory: str | os.PathLike) -> str:
    return os.path.join(str(directory), HANDOFF_DIRNAME)


def has_handoff(directory: str | os.PathLike) -> bool:
    return os.path.exists(os.path.join(handoff_dir(directory),
                                       HANDOFF_MANIFEST))


def tree_digest(tree: Any) -> str:
    """One blake2b over every leaf's bytes in path order — the cheap
    whole-state fingerprint the fleet warm-up and tests compare."""
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
    from distributeddeeplearningspark_tpu.parallel.sharding import path_str

    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(jax.device_get(leaf))
        h.update(path_str(path).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def save_handoff(directory: str | os.PathLike, step: int, state: Any, *,
                 data_state: dict | None = None,
                 stats: TransferStats | None = None) -> str:
    """Persist a drained host's live state atomically as raw ``.npy`` blocks
    plus a digest manifest. Written to a temp dir and renamed into place, so
    a handoff either exists completely or not at all — the supervisor's
    relaunch decision keys off :func:`has_handoff`."""
    from distributeddeeplearningspark_tpu.parallel.sharding import path_str

    final = handoff_dir(directory)
    tmp = f"{final}.tmp.{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    leaves = []
    for i, (path, leaf) in enumerate(
            jax.tree_util.tree_flatten_with_path(state)[0]):
        arr = np.asarray(jax.device_get(leaf))
        fname = f"leaf{i:05d}.npy"
        np.save(os.path.join(tmp, fname), arr)
        leaves.append({
            "path": path_str(path), "file": fname,
            "shape": list(arr.shape), "dtype": str(arr.dtype),
            "digest": hashlib.blake2b(
                np.ascontiguousarray(arr).tobytes(),
                digest_size=_DIGEST_SIZE).hexdigest(),
        })
    manifest = {
        "format": HANDOFF_FORMAT,
        "step": int(step),
        "data_state": data_state,
        "geometry": geometry_of(state),
        "leaves": leaves,
        "stats": stats.to_record() if stats is not None else None,
    }
    with open(os.path.join(tmp, HANDOFF_MANIFEST), "w") as f:
        json.dump(manifest, f)
    shutil.rmtree(final, ignore_errors=True)
    os.replace(tmp, final)
    return final


def peek_handoff(directory: str | os.PathLike) -> dict | None:
    """The handoff manifest without ingesting it (None when absent)."""
    path = os.path.join(handoff_dir(directory), HANDOFF_MANIFEST)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def load_handoff(directory: str | os.PathLike, template: Any,
                 shardings: Any) -> tuple[Any, dict]:
    """Ingest a handoff onto ``shardings``: every leaf digest-verified
    against the manifest, shapes checked against ``template``, placed with
    ``jax.device_put``. Returns ``(state, manifest)``; raises
    :class:`HandoffError` on any mismatch (fall back to the checkpoint)."""
    from distributeddeeplearningspark_tpu.parallel.sharding import path_str

    hd = handoff_dir(directory)
    manifest = peek_handoff(directory)
    if manifest is None:
        raise HandoffError(f"no live handoff at {hd}")
    if manifest.get("format") != HANDOFF_FORMAT:
        raise HandoffError(
            f"handoff at {hd} has format {manifest.get('format')!r}, this "
            f"build reads format {HANDOFF_FORMAT} — fall back to the "
            f"checkpoint")
    by_path = {rec["path"]: rec for rec in manifest["leaves"]}
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    sh_leaves = jax.tree_util.tree_leaves(shardings)
    out = []
    for (path, leaf), sh in zip(flat, sh_leaves):
        key = path_str(path)
        rec = by_path.pop(key, None)
        if rec is None:
            raise HandoffError(
                f"handoff at {hd} has no leaf for {key!r} — state "
                f"structure changed; fall back to the checkpoint")
        arr = np.load(os.path.join(hd, rec["file"]))
        want = tuple(getattr(leaf, "shape", arr.shape))
        if tuple(arr.shape) != want:
            raise HandoffError(
                f"handoff leaf {key!r} has shape {tuple(arr.shape)}, the "
                f"restoring state wants {want} — fall back to the "
                f"checkpoint")
        got = hashlib.blake2b(np.ascontiguousarray(arr).tobytes(),
                              digest_size=_DIGEST_SIZE).hexdigest()
        if got != rec["digest"]:
            raise HandoffError(
                f"handoff leaf {key!r}: blake2b {got} does not match the "
                f"manifest's {rec['digest']} — torn or corrupt handoff; "
                f"fall back to the checkpoint")
        out.append(jax.device_put(arr, sh))
    if by_path:
        raise HandoffError(
            f"handoff at {hd} carries leaves the restoring state lacks: "
            f"{sorted(by_path)} — fall back to the checkpoint")
    return jax.tree_util.tree_unflatten(treedef, out), manifest


def clear_handoff(directory: str | os.PathLike) -> None:
    """Consume the handoff once ingested (idempotent)."""
    shutil.rmtree(handoff_dir(directory), ignore_errors=True)
