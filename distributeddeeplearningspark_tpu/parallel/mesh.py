"""Device-mesh construction — the rebuild's answer to NCCL process groups.

The reference (SURVEY.md §1 L3/L2) bootstraps a Horovod / ``torch.distributed``
NCCL process group per executor and ranks GPUs into a ring. On TPU the
equivalent object is a :class:`jax.sharding.Mesh`: a named, multi-dimensional
arrangement of chips over which GSPMD lays out arrays and schedules XLA
collectives on ICI (intra-slice) / DCN (inter-slice) links.

Every mesh built here always carries the full set of parallelism axes, in a
fixed order, so that :class:`jax.sharding.PartitionSpec` values written against
axis *names* are valid on any topology (unused axes simply have size 1):

- ``data``    — data parallelism (gradient psum; the reference's core mode)
- ``fsdp``    — ZeRO/FSDP-style sharded data parallelism (BASELINE.json config 5)
- ``tensor``  — tensor/model parallelism (Megatron-style, within attention/MLP)
- ``seq``     — sequence/context parallelism (ring attention; reserved per
  SURVEY.md §5 "long-context")
- ``expert``  — expert parallelism (reserved; no MoE model in the contract)

Axis ordering puts ``tensor``/``seq`` innermost so they map to the
fastest ICI links on a real pod slice, with ``data`` outermost (crossing DCN
on multi-slice jobs) — the standard layout from the scaling-book recipe.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_PIPE = "pipe"

#: Fixed axis order, outermost (slowest links, DCN) → innermost (fastest ICI).
#: `pipe` sits outside expert/seq/tensor: stage boundaries are point-to-point
#: transfers, tolerant of slower links; TP/SP collectives need the fastest.
MESH_AXES: tuple[str, ...] = (AXIS_DATA, AXIS_FSDP, AXIS_PIPE, AXIS_EXPERT, AXIS_SEQ, AXIS_TENSOR)

#: PartitionSpec for the leading (batch) axis of inputs: batch is split across
#: both the pure-DP and the FSDP axes (FSDP is data parallelism with sharded
#: parameter storage, so it consumes batch too).
BATCH_AXES = (AXIS_DATA, AXIS_FSDP)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape; ``-1`` means "absorb all remaining devices".

    Mirrors the knob surface the reference exposes through
    ``spark.executor.instances`` (number of data-parallel workers): ``data=-1``
    with everything else 1 reproduces the reference's pure data-parallel
    layout. At most one axis may be ``-1``.
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def axis_sizes(self, num_devices: int) -> tuple[int, ...]:
        sizes = [self.data, self.fsdp, self.pipe, self.expert, self.seq, self.tensor]
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got spec {self}")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if num_devices % fixed:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes {fixed} ({self})"
                )
            sizes[wild[0]] = num_devices // fixed
        if math.prod(sizes) != num_devices:
            raise ValueError(
                f"mesh spec {tuple(sizes)} needs {math.prod(sizes)} devices, "
                f"got {num_devices}"
            )
        return tuple(sizes)

    def build(self, devices: Sequence[jax.Device] | None = None) -> Mesh:
        """Build a :class:`jax.sharding.Mesh` over ``devices`` (default: all)."""
        if devices is None:
            devices = jax.devices()
        devices = np.asarray(devices, dtype=object)
        sizes = self.axis_sizes(devices.size)
        return Mesh(devices.reshape(sizes), MESH_AXES)

    @property
    def dp_degree_is_wild(self) -> bool:
        return self.data == -1


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """A 1-chip mesh (all axes size 1) — used for dev-box smoke tests."""
    dev = device if device is not None else jax.devices()[0]
    return MeshSpec(data=1).build([dev])


def batch_spec(mesh: Mesh, *, extra_rank: int = 0, seq_sharded: bool = False) -> P:
    """PartitionSpec for an input batch: leading axis over (data, fsdp).

    With ``seq_sharded=True`` the second axis (sequence) is split over the
    ``seq`` mesh axis — the context-parallel input layout. Rank-1 leaves
    (per-example labels/weights) have no sequence dim and stay batch-only.
    """
    del mesh  # uniform axis names make this mesh-independent
    tail: list = [None] * extra_rank
    if seq_sharded and extra_rank:
        tail[0] = AXIS_SEQ
    return P(BATCH_AXES, *tail)


def batch_sharding(mesh: Mesh, arr_ndim: int, *, seq_sharded: bool = False) -> NamedSharding:
    """NamedSharding for a rank-``arr_ndim`` input array with batch leading."""
    return NamedSharding(mesh, batch_spec(mesh, extra_rank=arr_ndim - 1, seq_sharded=seq_sharded))


def replicated(mesh: Mesh) -> NamedSharding:
    """GSPMD-replicated sharding — the reference's driver parameter broadcast.

    The reference's driver pickles weights and ``sc.broadcast``-s them to every
    executor each round (SURVEY.md §3.1). Under GSPMD a replicated layout *is*
    that broadcast: XLA materializes one copy per chip and keeps them in sync.
    """
    return NamedSharding(mesh, P())


def num_data_shards(mesh: Mesh) -> int:
    """How many ways the global batch is split (the 'executor count')."""
    return mesh.shape[AXIS_DATA] * mesh.shape[AXIS_FSDP]
