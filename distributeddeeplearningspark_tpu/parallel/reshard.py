"""Portable cross-topology redistribution — load mesh-M state onto mesh M′.

The elasticity story (ISSUE 11 / ROADMAP `[elastic]`) needs arrays laid out
for one device mesh to move onto a *different* one: a checkpoint written by a
2-host FSDP gang restored by the single surviving host, or a live state
handed from a train mesh to a differently shaped serve mesh. arXiv:2112.01075
(PAPERS.md) frames the portable mechanism: each participant all-gathers the
spans it is missing and dynamic-slices out exactly the block its new layout
assigns it — no host ever needs the full array unless its new shard IS the
full array.

Two layers live here:

- **spec re-projection** (:func:`project_spec`, :func:`shardings_from_record`)
  — map a PartitionSpec written against mesh M onto mesh M′, dropping axis
  references M′ lacks (or can no longer divide the dimension by) down to
  replicated. This is how a checkpoint's *recorded* layout is re-expressed on
  whatever topology the restoring process actually has.
- **data movement** (:func:`redistribute`) — move live arrays to target
  shardings. Same-process mesh changes go through ``jax.device_put`` (XLA
  emits the all-gather/dynamic-slice pair); when that is not possible the
  explicit fallback assembles each target device's block from the
  host-available source shards by interval slicing — the dynamic-slice half
  done host-side — and raises :class:`SpanUnavailableError` naming the
  missing span when the local shards cannot cover it (the caller must then
  fetch it from a peer, e.g. by restoring from the shared checkpoint).

:mod:`..checkpoint` builds its metadata-templated reshard-on-restore path on
the spec layer; the data layer serves in-process geometry changes and the
tests that pin the bitwise round-trip acceptance (fsdp-saved →
tensor-restored → replicated, identical bytes).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class SpanUnavailableError(RuntimeError):
    """A target shard needs an index span no host-available source shard
    covers — cross-host redistribution is required (restore from the shared
    checkpoint, or run the gather on a mesh that spans both hosts)."""


# -- spec re-projection -------------------------------------------------------


def spec_to_record(spec: P) -> list:
    """JSON-serializable form of a PartitionSpec: one entry per dimension,
    ``None`` | axis name | list of axis names."""
    out: list = []
    for entry in spec:
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:  # tuple of axis names (e.g. ("data", "fsdp") batch axes)
            out.append(list(entry))
    return out


def spec_from_record(entries: list | tuple) -> P:
    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


def project_spec(spec: P, shape: tuple[int, ...], mesh: Mesh) -> P:
    """Re-express ``spec`` on ``mesh``, dropping what no longer fits.

    An axis reference survives iff the target mesh has that axis AND the
    dimension is still divisible by its (new) size; otherwise it degrades to
    replicated for that dimension. Shrinking fsdp=4 → fsdp=2 keeps the
    sharding at the new degree; shrinking to a mesh with no ``fsdp`` axis (or
    fsdp=1) yields a replicated dimension — exactly the "survivors hold
    everything" layout a 1-host restore wants.
    """
    entries = list(spec) + [None] * (len(shape) - len(spec))
    out: list = []
    for dim, entry in zip(shape, entries):
        if entry is None:
            out.append(None)
            continue
        names = (entry,) if isinstance(entry, str) else tuple(entry)
        kept = []
        degree = 1
        for name in names:
            size = mesh.shape.get(name, 1)
            if size > 1 and dim % (degree * size) == 0:
                kept.append(name)
                degree *= size
        out.append(None if not kept
                   else (kept[0] if len(kept) == 1 else tuple(kept)))
    return P(*out)


def shardings_from_record(record: dict, abstract: Any, mesh: Mesh) -> Any:
    """Per-leaf NamedShardings for ``abstract`` on ``mesh`` from a recorded
    geometry (:func:`..checkpoint.Checkpointer.saved_geometry`).

    ``record["specs"]`` maps '/'-joined leaf paths to recorded spec entries;
    each is re-projected onto ``mesh`` via :func:`project_spec`. Leaves the
    record does not name (new optimizer slots, renamed params) come out
    replicated — the safe layout everywhere.
    """
    from distributeddeeplearningspark_tpu.parallel.sharding import path_str

    specs: dict = record.get("specs") or {}

    def leaf_sharding(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        entries = specs.get(path_str(path))
        if not shape or entries is None:
            return NamedSharding(mesh, P())
        return NamedSharding(
            mesh, project_spec(spec_from_record(entries), shape, mesh))

    return jax.tree_util.tree_map_with_path(leaf_sharding, abstract)


# -- data movement ------------------------------------------------------------


def _slices_cover(shape, index) -> list[tuple[int, int]]:
    """Normalize a shard index (tuple of slices) to [lo, hi) per dimension."""
    out = []
    for dim, sl in zip(shape, tuple(index) + (slice(None),) * (len(shape) - len(index))):
        lo = 0 if sl.start is None else int(sl.start)
        hi = dim if sl.stop is None else int(sl.stop)
        out.append((lo, hi))
    return out


def _assemble_block(shape, target_span, source_shards) -> np.ndarray:
    """Fill one target device's block from overlapping source shards.

    ``target_span``: [lo, hi) per dimension. ``source_shards``: list of
    (span, ndarray). Host-side dynamic-slice: for every source shard compute
    the intersection with the target span and copy it in. Raises
    :class:`SpanUnavailableError` if any cell stays unwritten.
    """
    block_shape = tuple(hi - lo for lo, hi in target_span)
    block = np.empty(block_shape, dtype=source_shards[0][1].dtype)
    covered = np.zeros(block_shape, dtype=bool) if block.size else None
    for span, data in source_shards:
        dst, src = [], []
        empty = False
        for (tlo, thi), (slo, shi) in zip(target_span, span):
            lo, hi = max(tlo, slo), min(thi, shi)
            if lo >= hi:
                empty = True
                break
            dst.append(slice(lo - tlo, hi - tlo))
            src.append(slice(lo - slo, hi - slo))
        if empty:
            continue
        block[tuple(dst)] = np.asarray(data)[tuple(src)]
        if covered is not None:
            covered[tuple(dst)] = True
    if covered is not None and not covered.all():
        missing = int(covered.size - covered.sum())
        raise SpanUnavailableError(
            f"target span {target_span} of a {tuple(shape)} array has "
            f"{missing} element(s) no host-available shard covers — the "
            f"missing span lives on another host; restore it from the "
            f"shared checkpoint instead of redistributing live state")
    return block


def _reshard_leaf(x: jax.Array, target: NamedSharding) -> jax.Array:
    if getattr(x, "sharding", None) is not None and x.sharding.is_equivalent_to(
            target, x.ndim):
        return x
    try:
        return jax.device_put(x, target)
    except (ValueError, TypeError, RuntimeError):
        pass  # cross-mesh device_put unsupported here: explicit assembly
    # materialize each source shard to host ONCE: _assemble_block slices
    # these per target block, and leaving them on-device would re-pay the
    # device→host transfer target-count times over
    sources = [(_slices_cover(x.shape, s.index), np.asarray(s.data))
               for s in x.addressable_shards]
    if not sources:
        raise SpanUnavailableError(
            f"array of shape {x.shape} has no addressable shards on this "
            f"host — nothing to redistribute from")
    index_map = target.addressable_devices_indices_map(x.shape)
    arrays = []
    for dev, idx in index_map.items():
        span = _slices_cover(x.shape, idx)
        block = _assemble_block(x.shape, span, sources)
        arrays.append(jax.device_put(block, dev))
    return jax.make_array_from_single_device_arrays(x.shape, target, arrays)


def redistribute(tree: Any, target_shardings: Any) -> Any:
    """Move every leaf of ``tree`` to its sharding in ``target_shardings``.

    Leaves already laid out equivalently pass through untouched (no copy).
    The general path is ``jax.device_put`` — within one process XLA lowers
    the move to the all-gather/dynamic-slice pair of arXiv:2112.01075 — with
    the explicit host-side shard assembly as the fallback for mesh pairs
    ``device_put`` refuses. Scalars and non-array leaves are placed fresh.
    """
    return jax.tree.map(
        lambda x, s: (_reshard_leaf(x, s) if hasattr(x, "addressable_shards")
                      else jax.device_put(x, s)),
        tree, target_shardings,
    )


def geometry_of(tree: Any) -> dict | None:
    """The recorded-geometry dict for a live sharded pytree: mesh axis sizes,
    device/process counts, and per-leaf spec entries — what
    :meth:`..checkpoint.Checkpointer.save` persists beside the step.

    None when no leaf carries a NamedSharding (host-only trees).
    """
    from distributeddeeplearningspark_tpu.parallel.sharding import path_str

    specs: dict[str, list] = {}
    mesh_shape: dict[str, int] | None = None
    num_devices = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        sh = getattr(leaf, "sharding", None)
        if not isinstance(sh, NamedSharding):
            continue
        specs[path_str(path)] = spec_to_record(sh.spec)
        if mesh_shape is None:
            mesh_shape = {str(k): int(v) for k, v in sh.mesh.shape.items()}
            num_devices = int(math.prod(mesh_shape.values()))
    if mesh_shape is None:
        return None
    return {
        "mesh": mesh_shape,
        "num_devices": num_devices,
        "num_processes": int(jax.process_count()),
        "specs": specs,
    }
