"""Pipeline parallelism — GPipe-style microbatched stages over the ``pipe`` axis.

The reference has no pipeline parallelism (SURVEY.md §2 marks PP "unknown —
no evidence"; no config needs it), so this is a forward-looking primitive, not
a port: homogeneous-stage pipelining in the style GSPMD cannot express on its
own, built the TPU way — ``shard_map`` over the ``pipe`` mesh axis with
``lax.ppermute`` stage-to-stage handoffs (point-to-point on ICI) and a
``lax.scan`` over pipeline ticks.

Model fit: scanned-transformer layers are already stacked [L, ...]
(models/llama.py ``nn.scan``); grouping L layers into P stages of L/P layers
makes ``stage_params`` exactly a reshape of that stack — no model rewrite.

Schedule: classic GPipe. M microbatches flow through P stages in M + P - 1
ticks (bubble fraction (P-1)/(M+P-1)); each tick every stage runs one
microbatch and hands its activation to the next stage. Backward is plain
autodiff through the scan (activations rematerialized per-tick under
``jax.checkpoint`` if the caller wraps ``stage_fn`` — models/llama_pp.py
does, via ``cfg.remat``).

Why GPipe-with-remat and not hand-interleaved 1F1B: 1F1B's advantage over
GPipe is holding P (not M) microbatch activations live. Under XLA, remat
already bounds the scan's saved state to the per-tick boundary activations
(O(M + P) boundary tensors, recompute inside stages), and a hand-written
interleaved forward/backward schedule would require a custom VJP that
fights — instead of rides — XLA's scheduler and rematerialization. The
compiler-friendly scan keeps the bubble identical ((P-1)/(M+P-1)); raise M
to amortize it.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributeddeeplearningspark_tpu.parallel.mesh import AXIS_PIPE, BATCH_AXES
from distributeddeeplearningspark_tpu.parallel.collectives import shard_map

StageFn = Callable[[Any, jax.Array], jax.Array]


def _pipeline_local(stage_params: Any, x_mb: jax.Array, *, stage_fn: StageFn,
                    num_stages: int, num_microbatches: int) -> jax.Array:
    """Per-device body (inside shard_map): run my stage for M + P - 1 ticks.

    ``stage_params``: this stage's params (leading stage axis already sliced
    to size 1 by shard_map). ``x_mb``: [M, mb, ...] microbatched input
    (replicated across stages; only stage 0 reads it).
    """
    idx = lax.axis_index(AXIS_PIPE)
    m, p = num_microbatches, num_stages
    params = jax.tree.map(lambda a: a[0], stage_params)
    mb_shape = x_mb.shape[1:]
    # send activations forward: stage i → i+1 (last wraps to 0, ignored there)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (zeros once input is exhausted —
        # those ticks only flush the tail of the pipeline)
        mb_idx = jnp.minimum(t, m - 1)
        mb = lax.dynamic_index_in_dim(x_mb, mb_idx, axis=0, keepdims=False)
        mb = jnp.where(t < m, mb, jnp.zeros_like(mb))
        inp = jnp.where(idx == 0, mb, state)
        out = stage_fn(params, inp)
        # last stage banks finished microbatch t - (P - 1)
        done_idx = jnp.clip(t - (p - 1), 0, m - 1)
        take = jnp.logical_and(idx == p - 1, t >= p - 1)
        current = lax.dynamic_index_in_dim(outputs, done_idx, axis=0, keepdims=False)
        banked = jnp.where(take, out, current)
        outputs = lax.dynamic_update_index_in_dim(outputs, banked, done_idx, axis=0)
        state = lax.ppermute(out, AXIS_PIPE, perm)
        return (state, outputs), None

    init = (
        jnp.zeros(mb_shape, x_mb.dtype),
        jnp.zeros((m,) + mb_shape, x_mb.dtype),
    )
    (_, outputs), _ = lax.scan(tick, init, jnp.arange(m + p - 1))
    # outputs are valid on the last stage only; broadcast them to every stage
    # so the result is replicated over `pipe` (psum of one-hot contribution)
    outputs = jnp.where(idx == p - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, AXIS_PIPE)


def pipeline(
    stage_fn: StageFn,
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
) -> jax.Array:
    """Run ``x`` through P pipeline stages; returns the final activations.

    ``stage_fn(params_one_stage, activation) -> activation`` must preserve the
    activation shape (transformer-block shaped). ``stage_params`` is a pytree
    whose leaves have a leading stage axis of size P = mesh.shape['pipe'].
    ``x`` is the global batch [B, ...]; B must divide by ``num_microbatches``.

    Composes with data parallelism: on a data×pipe mesh the microbatch rows
    stay sharded over (data, fsdp) inside the shard_map — the ring only spans
    ``pipe``. (The [B] → [M, B/M] reshape regroups rows across data shards,
    so GSPMD inserts one input all-to-all per step; activations inside the
    pipeline never leave their data shard.)

    Differentiable end-to-end (ppermute/scan are); params stay sharded over
    ``pipe`` so each device stores only its stage — PP is also a param-memory
    partitioning, like the reference's FSDP but along depth.
    """
    p = mesh.shape[AXIS_PIPE]
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if leading != {p}:
        raise ValueError(
            f"stage_params leading axes {sorted(leading)} must all equal "
            f"pipe degree {p}")
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} must divide by microbatches {num_microbatches}")
    x_mb = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    act_spec = P(None, BATCH_AXES)  # [M, mb, ...]: rows sharded, rest replicated
    fn = shard_map(
        functools.partial(
            _pipeline_local, stage_fn=stage_fn, num_stages=p,
            num_microbatches=num_microbatches,
        ),
        mesh=mesh,
        in_specs=(P(AXIS_PIPE), act_spec),
        out_specs=act_spec,
        check_vma=False,
    )
    out_mb = fn(stage_params, x_mb)
    return out_mb.reshape((b,) + x.shape[1:])


def stack_stages(layer_params: Any, num_stages: int) -> Any:
    """[L, ...]-stacked layer params → [P, L/P, ...] stage-stacked params.

    The bridge from ``nn.scan``-stacked transformer layers to pipeline
    stages; use a ``stage_fn`` that scans its L/P layers internally.
    """
    def regroup(a):
        l = a.shape[0]
        if l % num_stages:
            raise ValueError(f"{l} layers not divisible into {num_stages} stages")
        return a.reshape((num_stages, l // num_stages) + a.shape[1:])

    return jax.tree.map(regroup, layer_params)
