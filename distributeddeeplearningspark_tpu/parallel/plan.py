"""Unified GSPMD compile layer: one ``Plan`` object drives every layout.

Until now each of the seven parallelism strategies hand-wired its own jit
call: ``train/step.py`` built jit-with-explicit-shardings for DP/FSDP/TP,
``pipeline_trainer`` wired per-stage rules by name, the dryrun fingerprints
called ``jit_train_step`` directly, and every new composition (ulysses×fsdp,
per-stage pipeline layouts) meant new wiring. GSPMD (PAPERS.md 2105.04663)
shows the alternative: ONE declarative object mapping logical axes → mesh
axes is enough to drive all of them through a single compile path.

:class:`Plan` is that object —

- a **logical-axis → mesh-axis mapping** (``batch_axes`` for the input
  batch, ``seq_axis`` for context parallelism) plus **per-leaf sharding
  rules** (:class:`~.sharding.ShardingRules`) for params/optimizer state;
- a **donation spec** (``donate_state``) and a compile **style** —
  ``"jit"`` (jit-with-explicit-shardings, the GSPMD path every strategy
  uses today) or ``"shard_map"`` for map-style bodies that call the
  explicit Horovod verb set;
- **ZeRO weight-update sharding** (PAPERS.md 2004.13336) as plain plan
  data: ``zero_axes`` shards optimizer-state leaves across the replica
  axes while :meth:`Plan.wrap_optimizer` pins the gradient all-reduce to
  the replicated layout — so the update math stays BITWISE identical to
  the replicated optimizer (GSPMD would otherwise switch to a
  reduce-scatter whose different reduction order drifts fp) and no new
  collective code exists anywhere: sharded storage is just out/in
  shardings, the gather-at-apply is GSPMD's.

:func:`compile_step_with_plan` is the single compile path: spec validation
and donation centralized, every executable routed through
``telemetry/anatomy.instrument()`` so each plan gets a ledgered,
cost-analyzed compile for free — which is what makes ``tools/plan_sweep.py``
possible: candidate plans are ranked by *measured* step time / MFU /
bytes-accessed instead of folklore, and the winner serializes
(:meth:`Plan.save` / :meth:`Plan.load`) so a training run can pin it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import warnings
from typing import Any, Callable, Mapping

from distributeddeeplearningspark_tpu.parallel.mesh import BATCH_AXES
from distributeddeeplearningspark_tpu.parallel.sharding import (
    REPLICATED,
    ShardingRules,
    add_axis_spec,
    path_str,
)

#: escape hatch for the tensor-axis refusal below (any value but ""/"0").
TENSOR_ESCAPE_ENV = "DLS_PLAN_ALLOW_TENSOR"

#: current on-disk plan format (Plan.save / Plan.load).
PLAN_FORMAT = 1


class PlanError(ValueError):
    """Base for plan-layer errors."""


class PlanValidationError(PlanError):
    """A plan cannot compile on this mesh (axis mismatch, bad style, or a
    strict-mode refusal such as the tensor-axis skew guard)."""


class PlanTensorAxisWarning(UserWarning):
    """This jax build miscomputes on meshes with a ``tensor`` axis > 1
    (~1.2% wrong losses — ROADMAP 'this round's jax skew', pinned repros
    ``test_pp_composes_with_tp_and_dp`` and the ``dryrun_multichip(8)``
    [data×fsdp×seq×tensor] fingerprint). Non-strict validation warns;
    strict validation (the plan sweep) refuses so the bug cannot silently
    poison a ranking. ``DLS_PLAN_ALLOW_TENSOR=1`` overrides both."""


def tensor_axis_allowed() -> bool:
    return os.environ.get(TENSOR_ESCAPE_ENV, "") not in ("", "0")


_TENSOR_MSG = (
    "mesh has tensor={n} > 1: this jax build's partitioner computes ~1.2% "
    "wrong losses on tensor-sharded param layouts (ROADMAP 'jax skew' — "
    "pinned repros: test_pp_composes_with_tp_and_dp, dryrun_multichip(8) "
    "[data x fsdp x seq x tensor] fingerprint). {action} Set "
    + TENSOR_ESCAPE_ENV + "=1 to override after re-probing on a newer jax."
)


def _spec_entries(spec) -> list:
    """PartitionSpec → plain list (None | str | list[str]) for JSON."""
    out = []
    for e in spec:
        if e is None or isinstance(e, str):
            out.append(e)
        else:
            out.append(list(e))
    return out


def _entries_spec(entries):
    from jax.sharding import PartitionSpec as P

    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


def _rules_record(rules: ShardingRules) -> dict:
    return {
        "rules": [[pat, _spec_entries(spec)] for pat, spec in rules.rules],
        "fsdp": bool(rules.fsdp),
        "fsdp_min_size": int(rules.fsdp_min_size),
        "fsdp_exclude": list(rules.fsdp_exclude),
    }


def _record_rules(rec: Mapping) -> ShardingRules:
    return ShardingRules(
        rules=tuple((pat, _entries_spec(entries))
                    for pat, entries in rec.get("rules", ())),
        fsdp=bool(rec.get("fsdp", False)),
        fsdp_min_size=int(rec.get("fsdp_min_size", 2**14)),
        fsdp_exclude=tuple(rec.get("fsdp_exclude", ())),
    )


def _spec_axes(spec) -> set[str]:
    axes: set[str] = set()
    for e in spec:
        if e is None:
            continue
        if isinstance(e, str):
            axes.add(e)
        else:
            axes.update(e)
    return axes


@dataclasses.dataclass(frozen=True)
class Plan:
    """Declarative layout: logical axes → mesh axes + per-leaf rules +
    donation, the one object :func:`compile_step_with_plan` compiles.

    ``batch_axes`` — mesh axes the logical ``batch`` axis splits over
    (the input feed and map-style bodies both read it).
    ``seq_axis`` — mesh axis for the logical ``sequence`` axis (context
    parallelism); ``None`` = sequence replicated.
    ``rules`` — the per-leaf param/optimizer sharding rule engine.
    ``zero_axes`` — ZeRO weight-update sharding: optimizer-state leaves
    (size ≥ ``zero_min_size``) get their largest divisible dim sharded
    over these replica axes; pair with :meth:`wrap_optimizer` for the
    bitwise-parity gradient pin.
    ``style`` — ``"jit"`` (GSPMD jit with explicit shardings) or
    ``"shard_map"`` (map-style body using explicit collectives).
    ``model_hints`` — serializable model-config overrides a probe/driver
    applies before building the model (e.g. ``attention_impl=ulysses``);
    the plan layer itself never reads them.
    """

    name: str
    rules: ShardingRules = REPLICATED
    batch_axes: tuple[str, ...] = BATCH_AXES
    seq_axis: str | None = None
    style: str = "jit"
    zero_axes: tuple[str, ...] = ()
    zero_min_size: int = 2**11
    donate_state: bool = True
    model_hints: tuple[tuple[str, str], ...] = ()
    description: str = ""

    def __post_init__(self):
        object.__setattr__(self, "batch_axes", tuple(self.batch_axes))
        object.__setattr__(self, "zero_axes", tuple(self.zero_axes))
        object.__setattr__(self, "model_hints",
                           tuple((str(k), str(v))
                                 for k, v in dict(self.model_hints).items()))

    # -- logical view --------------------------------------------------------

    @property
    def seq_sharded(self) -> bool:
        return self.seq_axis is not None

    def logical_axes(self) -> dict[str, tuple[str, ...]]:
        """The logical-axis → mesh-axis mapping this plan declares."""
        out: dict[str, tuple[str, ...]] = {"batch": self.batch_axes}
        if self.seq_axis:
            out["sequence"] = (self.seq_axis,)
        if self.zero_axes:
            out["weight_update"] = self.zero_axes
        param_axes: set[str] = set()
        for _, spec in self.rules.rules:
            param_axes.update(_spec_axes(spec))
        if self.rules.fsdp:
            param_axes.add("fsdp")
        if param_axes:
            out["params"] = tuple(sorted(param_axes))
        return out

    def hints(self) -> dict[str, str]:
        return dict(self.model_hints)

    # -- validation ----------------------------------------------------------

    def validate(self, mesh, *, strict: bool = False) -> None:
        """Centralized spec validation for this plan on ``mesh``.

        Checks every mesh axis the plan mentions exists, the style is
        known, and applies the tensor-axis skew guard: a ``tensor`` axis
        > 1 on this jax build WARNS (:class:`PlanTensorAxisWarning`) on
        the ordinary compile path and REFUSES under ``strict=True`` (the
        plan sweep) — unless ``DLS_PLAN_ALLOW_TENSOR=1``.
        """
        if self.style not in ("jit", "shard_map"):
            raise PlanValidationError(
                f"plan {self.name!r}: style must be 'jit'|'shard_map', got "
                f"{self.style!r}")
        names = set(mesh.axis_names)
        mentioned: set[str] = set(self.batch_axes) | set(self.zero_axes)
        if self.seq_axis:
            mentioned.add(self.seq_axis)
        for _, spec in self.rules.rules:
            mentioned.update(_spec_axes(spec))
        missing = sorted(mentioned - names)
        if missing:
            raise PlanValidationError(
                f"plan {self.name!r} maps logical axes onto mesh axes "
                f"{missing} that do not exist on this mesh (axes: "
                f"{sorted(names)})")
        if not self.batch_axes:
            raise PlanValidationError(
                f"plan {self.name!r}: batch_axes must name at least one "
                f"mesh axis")
        overlap = set(self.zero_axes) - set(self.batch_axes)
        if self.zero_axes and overlap:
            raise PlanValidationError(
                f"plan {self.name!r}: zero_axes {sorted(overlap)} are not "
                f"replica (batch) axes — ZeRO shards optimizer state across "
                f"the axes that replicate it, i.e. a subset of batch_axes "
                f"{self.batch_axes}")
        tensor_n = dict(mesh.shape).get("tensor", 1)
        if tensor_n > 1 and not tensor_axis_allowed():
            if strict:
                raise PlanValidationError(_TENSOR_MSG.format(
                    n=tensor_n,
                    action="Refusing (strict validation: a sweep ranking "
                           "must not be poisoned by wrong-math probes)."))
            warnings.warn(_TENSOR_MSG.format(
                n=tensor_n, action="Proceeding with a warning."),
                PlanTensorAxisWarning, stacklevel=2)

    # -- shardings -----------------------------------------------------------

    def state_shardings(self, state_abstract: Any, mesh) -> Any:
        """Shardings for a full TrainState pytree under this plan.

        Params and mutables follow ``rules`` exactly like
        :func:`~.sharding.state_shardings`; optimizer-state leaves
        additionally get the ZeRO pass (``zero_axes``) — their largest
        still-unsharded divisible dim shards across the replica axes, so
        Adam moments stop being replicated per data-parallel copy."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def leaf_sharding(path, leaf):
            shape = tuple(getattr(leaf, "shape", ()))
            if not shape:
                return NamedSharding(mesh, P())
            p = path_str(path)
            spec = self.rules.spec_for(p, shape, mesh)
            if self.zero_axes and p.startswith("opt_state"):
                spec = add_axis_spec(spec, shape, mesh, self.zero_axes,
                                     self.zero_min_size)
            return NamedSharding(mesh, spec)

        return jax.tree_util.tree_map_with_path(leaf_sharding, state_abstract)

    def wrap_optimizer(self, tx, mesh):
        """The ZeRO bitwise-parity pin: constrain the gradients entering
        ``tx.update`` to the replicated layout.

        With optimizer state sharded over the replica axes, GSPMD would
        otherwise lower the gradient sync as a reduce-scatter — a
        different reduction order, so the trajectory drifts from the
        replicated optimizer at the second step. Pinning grads replicated
        keeps the IDENTICAL all-reduce; the elementwise update then
        computes bit-equal moments per shard, and the gather at apply is
        a pure layout move. No-op when the plan has no ``zero_axes``."""
        if not self.zero_axes:
            return tx
        import jax
        import optax
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(mesh, P())

        def update(grads, state, params=None):
            grads = jax.lax.with_sharding_constraint(grads, rep)
            return tx.update(grads, state, params)

        return optax.GradientTransformation(tx.init, update)

    # -- identity / serialization -------------------------------------------

    def to_record(self) -> dict:
        return {
            "plan_format": PLAN_FORMAT,
            "name": self.name,
            "description": self.description,
            "rules": _rules_record(self.rules),
            "batch_axes": list(self.batch_axes),
            "seq_axis": self.seq_axis,
            "style": self.style,
            "zero_axes": list(self.zero_axes),
            "zero_min_size": int(self.zero_min_size),
            "donate_state": bool(self.donate_state),
            "model_hints": dict(self.model_hints),
        }

    @classmethod
    def from_record(cls, rec: Mapping) -> "Plan":
        fmt = int(rec.get("plan_format", PLAN_FORMAT))
        if fmt > PLAN_FORMAT:
            raise PlanError(
                f"plan record format {fmt} is newer than this build's "
                f"{PLAN_FORMAT}")
        return cls(
            name=str(rec["name"]),
            description=str(rec.get("description", "")),
            rules=_record_rules(rec.get("rules", {})),
            batch_axes=tuple(rec.get("batch_axes", BATCH_AXES)),
            seq_axis=rec.get("seq_axis"),
            style=str(rec.get("style", "jit")),
            zero_axes=tuple(rec.get("zero_axes", ())),
            zero_min_size=int(rec.get("zero_min_size", 2**11)),
            donate_state=bool(rec.get("donate_state", True)),
            model_hints=tuple(dict(rec.get("model_hints", {})).items()),
        )

    def signature(self) -> str:
        """Stable content hash of everything compile-relevant (NOT the
        description) — the id the compile ledger and sweep tables carry."""
        rec = self.to_record()
        rec.pop("description", None)
        return hashlib.blake2b(
            json.dumps(rec, sort_keys=True).encode(),
            digest_size=6).hexdigest()

    def save(self, path: str) -> None:
        """Serialize so a training run can pin a sweep winner."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_record(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "Plan":
        with open(path) as f:
            return cls.from_record(json.load(f))

    def describe(self) -> str:
        la = ", ".join(f"{k}→{'×'.join(v)}"
                       for k, v in self.logical_axes().items())
        return (f"Plan({self.name} [{self.signature()}] {self.style}: {la}"
                + (f", hints={self.hints()}" if self.model_hints else "")
                + ")")


# -- the single compile path --------------------------------------------------


def compile_step_with_plan(
    step_fn: Callable,
    plan: Plan,
    mesh,
    *,
    state_shardings: Any = None,
    state_abstract: Any = None,
    kind: str = "train",
    name: str | None = None,
    instrument: bool = True,
    expected_signatures: int = 1,
    strict: bool = False,
):
    """Compile ``step_fn`` under ``plan`` — the one jit call every
    strategy shares.

    ``kind``: ``"train"`` ((state, batch) → (state, metrics), state
    donated per the plan), ``"eval"`` ((state, batch) → metrics), or
    ``"predict"`` ((state, batch) → replicated outputs).

    ``style="jit"`` compiles via jit-with-explicit-shardings (batch
    shardings inherited from the arrays — ``put_global`` stays the single
    source of truth for the input layout); ``style="shard_map"`` wraps
    the body in :func:`~.collectives.shard_map` over the plan's batch
    axes so map-style code using the explicit Horovod verbs compiles
    through the same path.

    With ``instrument=True`` the executable is routed through
    ``telemetry/anatomy.instrument()``: every compile becomes a ledgered,
    cost-analyzed ``compile`` event TAGGED with the plan's name and
    signature — the measurements ``tools/plan_sweep.py`` ranks on.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if kind not in ("train", "eval", "predict"):
        raise PlanError(f"kind must be 'train'|'eval'|'predict', got {kind!r}")
    plan.validate(mesh, strict=strict)
    if state_shardings is None:
        if state_abstract is None:
            raise PlanError(
                "compile_step_with_plan needs state_shardings or an "
                "abstract state to derive them from the plan's rules")
        state_shardings = plan.state_shardings(state_abstract, mesh)
    rep = NamedSharding(mesh, P())
    donate = (0,) if (kind == "train" and plan.donate_state) else ()

    if plan.style == "shard_map":
        from distributeddeeplearningspark_tpu.parallel.collectives import (
            shard_map,
        )

        row = P(plan.batch_axes)
        out_specs = (P(), P()) if kind == "train" else P()
        body = shard_map(step_fn, mesh=mesh, in_specs=(P(), row),
                         out_specs=out_specs, check_vma=False)
        jitted = jax.jit(body, donate_argnums=donate)
    else:
        out_sh = ((state_shardings, rep) if kind == "train" else rep)
        jitted = jax.jit(step_fn, in_shardings=(state_shardings, None),
                         out_shardings=out_sh, donate_argnums=donate)
    if not instrument:
        return jitted
    from distributeddeeplearningspark_tpu.telemetry import anatomy as anatomy_lib

    return anatomy_lib.instrument(
        jitted, name=name or f"plan:{plan.name}",
        expected_signatures=expected_signatures, plan=plan)


# -- canned plans -------------------------------------------------------------

#: Pure data parallelism — params/opt replicated, batch over (data, fsdp).
DP = Plan(name="dp", rules=REPLICATED,
          description="replicated params, batch over (data, fsdp)")

#: ZeRO-style FSDP: every large param (and its optimizer moments, which
#: follow the same rules) sharded over the ``fsdp`` axis.
FSDP_PLAN = Plan(name="fsdp", rules=ShardingRules(fsdp=True),
                 description="auto-FSDP params + moments over 'fsdp'")


def zero_plan(base: Plan = DP, *, axes: tuple[str, ...] | None = None,
              name: str | None = None) -> Plan:
    """ZeRO weight-update sharding as *just another plan*: ``base``'s
    param layout, optimizer state sharded across the replica axes.

    Defaults to sharding over every batch axis the base declares (the
    axes that replicate the optimizer state today). Pair with
    :meth:`Plan.wrap_optimizer` — :func:`compile_step_with_plan` callers
    (Trainer, the sweep) do this automatically."""
    axes = tuple(axes if axes is not None else base.batch_axes)
    return dataclasses.replace(
        base, name=name or f"{base.name}+zero", zero_axes=axes,
        description=(base.description + " + ZeRO weight-update sharding "
                     f"over {axes}").strip())


def plan_for_rules(rules: ShardingRules, *, context_parallel: bool = False,
                   name: str | None = None) -> Plan:
    """Wrap a legacy (rules, context_parallel) trainer config as a Plan —
    how pre-plan call sites route through the new layer unchanged."""
    if name is None:
        name = "fsdp" if rules.fsdp else ("dp" if not rules.rules else "rules")
        if context_parallel:
            name += "+seq"
    return Plan(name=name, rules=rules,
                seq_axis="seq" if context_parallel else None)


def stage_plan(name: str, cfg=None, *, fsdp_min_size: int = 2**14) -> Plan:
    """Per-stage pipeline layouts by name (``DLS_PIPE_SPEC``'s
    ``stage_plans``/``stage_rules`` values): ``replicated`` | ``fsdp`` |
    ``tensor`` (needs the model cfg) | ``zero``."""
    if name == "replicated":
        return Plan(name="stage-replicated")
    if name == "fsdp":
        return Plan(name="stage-fsdp",
                    rules=ShardingRules(fsdp=True, fsdp_min_size=fsdp_min_size))
    if name == "zero":
        return zero_plan(Plan(name="stage"), name="stage-zero")
    if name == "tensor":
        if cfg is None:
            raise PlanError("stage_plan('tensor') needs the model cfg")
        from distributeddeeplearningspark_tpu.models.llama import llama_rules

        return Plan(name="stage-tensor", rules=llama_rules(cfg, fsdp=False))
    raise PlanError(
        f"unknown stage plan {name!r} (want replicated|fsdp|tensor|zero)")
