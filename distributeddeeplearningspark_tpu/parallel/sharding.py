"""Parameter-sharding rules: replicated DP, auto-FSDP, and tensor parallelism.

The reference has exactly two parameter layouts (SURVEY.md §2):

- replicated everywhere (driver broadcast + NCCL grad all-reduce) for
  LeNet/ResNet/BERT/DLRM MLPs, and
- FSDP-style sharding "across Spark executors" for Llama-2 7B (config 5).

Here both are expressed as :class:`~jax.sharding.PartitionSpec` trees over the
fixed axis names of :mod:`.mesh`, produced by a small rule engine:

1. explicit regex rules (path pattern → PartitionSpec) take precedence —
   used for tensor-parallel layouts and sharded embedding tables;
2. an optional auto-FSDP pass then shards the largest still-unsharded,
   divisible dimension of every large parameter over the ``fsdp`` axis
   (the ZeRO-3 layout; gather-on-use is inserted by GSPMD, cf.
   arXiv:2004.13336 in PAPERS.md);
3. everything else stays replicated.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributeddeeplearningspark_tpu.parallel.mesh import AXIS_FSDP


def path_str(path) -> str:
    """Render a jax key path as 'a/b/kernel' for regex matching."""
    parts = []
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(k.name)
        elif isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """Ordered (regex → PartitionSpec) rules plus an auto-FSDP pass.

    ``rules``: first regex (searched, not fullmatch) that matches the
    '/'.joined param path wins.
    ``fsdp``: if True, params with ``size >= fsdp_min_size`` get their largest
    unsharded divisible dim sharded over the ``fsdp`` mesh axis.
    ``fsdp_exclude``: path regexes whose params the auto-FSDP pass must leave
    alone (e.g. LoRA adapters that should stay fully replicated).
    """

    rules: tuple[tuple[str, P], ...] = ()
    fsdp: bool = False
    fsdp_min_size: int = 2**14
    fsdp_exclude: tuple[str, ...] = ()

    def spec_for(self, path: str, shape: tuple[int, ...], mesh: Mesh) -> P:
        spec = None
        for pattern, s in self.rules:
            if re.search(pattern, path):
                spec = s
                break
        if spec is None:
            spec = P(*([None] * len(shape)))
        if (
            self.fsdp
            and mesh.shape[AXIS_FSDP] > 1
            and not any(re.search(p, path) for p in self.fsdp_exclude)
        ):
            spec = _add_fsdp_axis(spec, shape, mesh, self.fsdp_min_size)
        return spec

    def tree_specs(self, params: Any, mesh: Mesh) -> Any:
        """PartitionSpec tree matching ``params`` (which may be abstract)."""

        def leaf_spec(path, leaf):
            shape = tuple(getattr(leaf, "shape", ()))
            if not shape:
                return P()
            return self.spec_for(path_str(path), shape, mesh)

        return jax.tree_util.tree_map_with_path(leaf_spec, params)

    def tree_shardings(self, params: Any, mesh: Mesh) -> Any:
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            self.tree_specs(params, mesh),
            is_leaf=lambda x: isinstance(x, P),
        )


def _add_fsdp_axis(spec: P, shape: tuple[int, ...], mesh: Mesh, min_size: int) -> P:
    """Shard the largest unsharded divisible dim of ``shape`` over 'fsdp'."""
    return add_axis_spec(spec, shape, mesh, (AXIS_FSDP,), min_size)


def add_axis_spec(spec: P, shape: tuple[int, ...], mesh: Mesh,
                  axes: tuple[str, ...], min_size: int) -> P:
    """Shard the largest unsharded divisible dim of ``shape`` over ``axes``.

    The generalized auto-FSDP pass (also the plan layer's ZeRO
    weight-update pass over the replica axes): leaves smaller than
    ``min_size`` elements, already mentioning one of ``axes``, or with no
    dim divisible by the axes' total extent stay as they were. When more
    than one axis is given the whole tuple lands on ONE dim (divisible by
    the product); if no dim fits, each axis is tried separately,
    largest-dim first."""
    size = 1
    for d in shape:
        size *= d
    if size < min_size:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    if any(_mentions(e, a) for e in entries for a in axes):
        return spec
    extent = 1
    for a in axes:
        extent *= mesh.shape[a]
    if extent <= 1:
        return spec
    candidates = [
        (shape[i], i)
        for i in range(len(shape))
        if entries[i] is None and shape[i] % extent == 0
    ]
    if candidates:
        _, dim = max(candidates)
        entries[dim] = axes[0] if len(axes) == 1 else tuple(axes)
        return P(*entries)
    if len(axes) > 1:
        # no single dim takes the whole tuple: place axes one at a time
        out = spec
        for a in sorted(axes, key=lambda a: -mesh.shape[a]):
            out = add_axis_spec(out, shape, mesh, (a,), min_size)
        return out
    return spec


def _mentions(entry, axis: str) -> bool:
    if entry is None:
        return False
    if isinstance(entry, str):
        return entry == axis
    return axis in entry


# --- canned rule sets -------------------------------------------------------

#: Pure data parallelism: everything replicated (reference configs 1–3).
REPLICATED = ShardingRules()

#: FSDP over the `fsdp` axis for every large param (reference config 5).
FSDP = ShardingRules(fsdp=True)


def state_shardings(state_abstract: Any, mesh: Mesh, rules: ShardingRules) -> Any:
    """Shardings for a full TrainState pytree.

    Parameters *and* optimizer state follow the same rules — optimizer moments
    have the same shapes as their params, so the rule engine applies unchanged
    (this is the cross-replica weight-update sharding of arXiv:2004.13336:
    with FSDP on, Adam moments are sharded exactly like their params). Scalars
    (step counters, schedule counts) come out replicated because empty-shape
    leaves always map to P().
    """

    def leaf_sharding(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if not shape:
            return NamedSharding(mesh, P())
        return NamedSharding(mesh, rules.spec_for(path_str(path), shape, mesh))

    return jax.tree_util.tree_map_with_path(leaf_sharding, state_abstract)
