"""Parallelism layer: mesh construction, sharding rules, collectives.

Replaces the reference's L3 (param broadcast + NCCL all-reduce + FSDP) and the
NCCL native backend (SURVEY.md §1) with GSPMD over a named TPU device mesh.
"""

from distributeddeeplearningspark_tpu.parallel.mesh import (
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_SEQ,
    AXIS_TENSOR,
    BATCH_AXES,
    MESH_AXES,
    MeshSpec,
    batch_sharding,
    batch_spec,
    num_data_shards,
    replicated,
    single_device_mesh,
)
from distributeddeeplearningspark_tpu.parallel.reshard import (
    SpanUnavailableError,
    project_spec,
    redistribute,
    shardings_from_record,
)
from distributeddeeplearningspark_tpu.parallel.plan import (
    DP,
    FSDP_PLAN,
    Plan,
    PlanError,
    PlanTensorAxisWarning,
    PlanValidationError,
    compile_step_with_plan,
    plan_for_rules,
    stage_plan,
    zero_plan,
)
from distributeddeeplearningspark_tpu.parallel.sharding import (
    FSDP,
    REPLICATED,
    ShardingRules,
    add_axis_spec,
    state_shardings,
)

__all__ = [
    "AXIS_DATA",
    "AXIS_EXPERT",
    "AXIS_FSDP",
    "AXIS_SEQ",
    "AXIS_TENSOR",
    "BATCH_AXES",
    "MESH_AXES",
    "MeshSpec",
    "batch_sharding",
    "batch_spec",
    "num_data_shards",
    "replicated",
    "single_device_mesh",
    "ShardingRules",
    "REPLICATED",
    "FSDP",
    "state_shardings",
    "add_axis_spec",
    "Plan",
    "PlanError",
    "PlanValidationError",
    "PlanTensorAxisWarning",
    "compile_step_with_plan",
    "plan_for_rules",
    "stage_plan",
    "zero_plan",
    "DP",
    "FSDP_PLAN",
    "SpanUnavailableError",
    "project_spec",
    "redistribute",
    "shardings_from_record",
]
