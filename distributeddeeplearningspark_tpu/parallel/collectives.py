"""Collective ops: the NCCL/Horovod surface, rebuilt on XLA collectives.

The reference's only native layer is NCCL ring all-reduce reached through
``hvd.allreduce`` / ``dist.all_reduce`` (SURVEY.md §2 "Gradient aggregation",
§5 "Distributed communication backend"). On TPU those calls do not translate
one-to-one: XLA *is* the collective runtime, scheduling ``psum`` /
``all_gather`` / ``reduce_scatter`` / ``all_to_all`` over ICI links at compile
time. Two styles are provided:

- **Implicit (preferred)**: don't call anything — jit a step whose batch is
  sharded over (data, fsdp) and whose params are replicated; GSPMD inserts the
  gradient all-reduce. This is the production path used by
  :mod:`..train.step`.
- **Explicit**: the functions below, valid inside ``shard_map``/``pmap``
  bodies, mirroring the Horovod verb set for code that wants manual control
  (and for tests that pin down collective semantics).

Also here: ``tree_aggregate`` — a driver-side reduction that reproduces the
reference's *round-synchronous* Spark path (``rdd.mapPartitions`` →
``treeAggregate`` → driver update, SURVEY.md §3.1) for CPU parity tests, and
the cross-replica desync sanitizer from SURVEY.md §5.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.parallel.mesh import BATCH_AXES

AxisNames = str | Sequence[str]


# --- opt-in comms probes -----------------------------------------------------
#
# A hung collective is the canonical silent SPMD failure: every host blocks,
# nobody crashes, the step log just stops. These probes put the waiting on
# the record: `collective` telemetry events carrying per-call host-side wait
# time, which telemetry.fleet folds into the per-host comms-wait column. Off
# by default (zero cost); enabled via enable_collective_probes() or
# DLS_COMMS_PROBE=1 (how a supervisor-launched gang opts its workers in).

#: Env toggle for the comms probes (any value but ""/"0" enables).
COMMS_PROBE_ENV = "DLS_COMMS_PROBE"

_probe_override: bool | None = None


def enable_collective_probes(enabled: bool = True) -> None:
    """Force the probes on/off for this process (wins over the env var)."""
    global _probe_override
    _probe_override = enabled


def collective_probes_enabled() -> bool:
    if _probe_override is not None:
        return _probe_override
    return os.environ.get(COMMS_PROBE_ENV, "") not in ("", "0")


def _is_tracing() -> bool:
    """True whenever ANY trace is being built — checked globally, not by
    sniffing the operands: a concrete constant captured inside a jit trace
    would pass a per-leaf Tracer check and emit one bogus trace-time event
    that looks like an execution-time wait."""
    clean = getattr(jax.core, "trace_state_clean", None)
    if clean is not None:
        return not clean()
    return False  # no API to ask — treat as eager (old jax)


def _probed(op: str, fn: Callable) -> Callable:
    """Wrap an explicit-mode collective with the opt-in wait-time probe.

    Only concrete EAGER calls are timed — dispatch through completion
    (``block_until_ready``), emitted as a ``collective`` event. Under any
    active trace the wrapper is a transparent no-op: XLA schedules the op
    at compile time and there is no per-call host wait to measure. Since
    the named-axis verbs are today only legal inside shard_map/pmap bodies
    (always traced), the live comms-wait signal is :func:`barrier_probe`;
    these wrappers exist so any future eager-collective call site is
    covered without another instrumentation pass.
    """

    @functools.wraps(fn)
    def wrapper(tree: Any, axis: AxisNames = BATCH_AXES, **kw: Any) -> Any:
        if not collective_probes_enabled() or _is_tracing():
            return fn(tree, axis, **kw)
        t0 = time.perf_counter()
        out = fn(tree, axis, **kw)
        jax.block_until_ready(out)
        axis_label = axis if isinstance(axis, str) else ",".join(axis)
        telemetry.emit("collective", op=op, axis=axis_label,
                       wait_s=time.perf_counter() - t0)
        return out

    return wrapper


def transfer_probe(op: str, nbytes: int, wall_s: float,
                   **fields: Any) -> None:
    """Report one explicit bulk transfer (the live-reshard engine's
    schedule, a handoff ingest) as a ``collective`` event when probes are
    on. These moves run eagerly host-side, so unlike the named-axis verbs
    there is no trace-time ambiguity — the caller hands us the measured
    wall directly."""
    if not collective_probes_enabled():
        return
    telemetry.emit("collective", op=op, bytes=int(nbytes),
                   wait_s=float(wall_s), **fields)


_barrier_fns: dict = {}


def barrier_probe(mesh, *, tag: str = "barrier") -> float:
    """Time one full-mesh scalar psum from dispatch to completion.

    The cheapest honest measure of "how long does this host wait for the
    gang": a replicated scalar psum cannot return before every device has
    joined, so its host-side latency IS the barrier wait — in a straggling
    gang the fast hosts' samples grow by exactly the straggler's lag. The
    first call per mesh compiles (untimed — warm-up, not wait); each later
    call emits a ``collective`` event (``op=tag``) through the process-wide
    telemetry writer and returns the wait in seconds. Costs one tiny
    dispatch, so calling it once per metrics lap is noise.
    """
    fn = _barrier_fns.get(mesh)
    names = tuple(mesh.axis_names)
    if fn is None:
        from jax.sharding import PartitionSpec as P

        body = shard_map(lambda x: lax.psum(x, names), mesh=mesh,
                         in_specs=P(), out_specs=P())
        fn = jax.jit(body)
        jax.block_until_ready(fn(jnp.zeros((), jnp.float32)))  # compile
        _barrier_fns[mesh] = fn
    t0 = time.perf_counter()
    jax.block_until_ready(fn(jnp.ones((), jnp.float32)))
    wait = time.perf_counter() - t0
    telemetry.emit("collective", op=tag, axis=",".join(names), wait_s=wait)
    return wait


def axis_size(axis_name: AxisNames) -> int:
    """``lax.axis_size`` for jax versions that predate it (the classic
    ``psum(1, axis)`` constant-folds to the static mesh axis size)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def shard_map(f, **kwargs):
    """``jax.shard_map`` across the 0.4→0.5 API move: older jax keeps it in
    ``jax.experimental.shard_map`` and spells ``check_vma`` as ``check_rep``.
    Every shard_map in this package goes through here."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _sm

    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _sm(f, **kwargs)


def all_reduce_sum(tree: Any, axis: AxisNames = BATCH_AXES) -> Any:
    """Horovod ``allreduce(op=Sum)`` ≙ ``lax.psum`` over the mesh axis."""
    return jax.tree.map(lambda x: lax.psum(x, axis), tree)


def all_reduce_mean(tree: Any, axis: AxisNames = BATCH_AXES) -> Any:
    """Horovod's default ``allreduce`` (average) ≙ ``lax.pmean``."""
    return jax.tree.map(lambda x: lax.pmean(x, axis), tree)


def all_gather(tree: Any, axis: AxisNames = BATCH_AXES, *, tiled: bool = True) -> Any:
    """``hvd.allgather`` ≙ ``lax.all_gather`` (tiled: concat along dim 0)."""
    return jax.tree.map(lambda x: lax.all_gather(x, axis, tiled=tiled), tree)


def reduce_scatter(tree: Any, axis: AxisNames = BATCH_AXES, *, scatter_dim: int = 0) -> Any:
    """ZeRO grad sync: ``lax.psum_scatter`` (each shard owns a slice of the sum)."""
    return jax.tree.map(
        lambda x: lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True),
        tree,
    )


# opt-in wait-time probes around the Horovod verb set (no-ops unless
# enabled, transparent under tracing — see _probed)
all_reduce_sum = _probed("all_reduce_sum", all_reduce_sum)
all_reduce_mean = _probed("all_reduce_mean", all_reduce_mean)
all_gather = _probed("all_gather", all_gather)
reduce_scatter = _probed("reduce_scatter", reduce_scatter)


def all_to_all(x: jax.Array, axis: str, *, split_dim: int, concat_dim: int) -> jax.Array:
    """``all_to_all`` — the sharded-embedding-lookup exchange (DLRM, config 4)."""
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=True)


def broadcast_from(tree: Any, axis: AxisNames = BATCH_AXES, *, root: int = 0) -> Any:
    """Driver parameter broadcast ≙ select root's copy on every member.

    Inside SPMD code replication normally makes this a no-op; it exists for
    explicit-mode parity with ``sc.broadcast`` semantics (e.g. re-syncing after
    a deliberately divergent step in the desync tests).
    """
    names = (axis,) if isinstance(axis, str) else tuple(axis)

    def bcast(x):
        y = x
        for name in names:
            y = lax.all_gather(y, name, tiled=False)[root]
        return y

    return jax.tree.map(bcast, tree)


def ppermute_shift(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Ring shift over a mesh axis — the building block of ring attention."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


# --- driver-side (round-synchronous Spark) parity path ----------------------


def tree_aggregate(
    partitions: Sequence[Sequence[Any]],
    zero: Any,
    seq_op: Callable[[Any, Any], Any],
    comb_op: Callable[[Any, Any], Any],
) -> Any:
    """Spark ``RDD.treeAggregate`` semantics on the driver.

    ``partitions`` is a sequence of element sequences. Each partition is
    folded from a fresh copy of ``zero`` with ``seq_op`` (the executor-side
    fold); per-partition results are then combined with ``comb_op`` (the
    driver-side merge). Tree depth only changes scheduling, not the result, so
    the combine is flat. The reference's PR1 pure-CPU path (BASELINE.json
    config 1) aggregates per-partition gradients this way (SURVEY.md §3.1);
    tests use it to assert the SPMD ``psum`` step computes the *same numbers*
    as the round-synchronous Spark loop.
    """
    import copy

    per_part = []
    for part in partitions:
        acc = copy.deepcopy(zero)
        for x in part:
            acc = seq_op(acc, x)
        per_part.append(acc)
    if not per_part:
        return zero
    return functools.reduce(comb_op, per_part)


def grad_average(partition_grads: Sequence[Any]) -> Any:
    """Average per-partition gradient pytrees on the driver (parity mode).

    float32 numpy leaves accumulate through the native (C++) ``sum_into``
    kernel — the host equivalent of the reference's driver-side gradient
    reduction, parallel and GIL-free; other leaves fall back to Python sum.
    """
    import numpy as np

    from distributeddeeplearningspark_tpu.utils import native

    n = len(partition_grads)

    def avg(*xs):
        if all(isinstance(x, np.ndarray) and x.dtype == np.float32 for x in xs):
            acc = np.ascontiguousarray(xs[0]).copy()
            for x in xs[1:]:
                native.sum_into(acc, x)
            return acc / n
        return sum(xs) / n

    return jax.tree.map(avg, *partition_grads)


# The desync sanitizer lives in utils/sanitize.py (one API for both the
# local-device and cross-process checks); re-exported for callers that think
# of it as a collective-layer concern.
from distributeddeeplearningspark_tpu.utils.sanitize import (  # noqa: E402
    assert_replicas_in_sync,  # noqa: F401
)
