"""Collective ops: the NCCL/Horovod surface, rebuilt on XLA collectives.

The reference's only native layer is NCCL ring all-reduce reached through
``hvd.allreduce`` / ``dist.all_reduce`` (SURVEY.md §2 "Gradient aggregation",
§5 "Distributed communication backend"). On TPU those calls do not translate
one-to-one: XLA *is* the collective runtime, scheduling ``psum`` /
``all_gather`` / ``reduce_scatter`` / ``all_to_all`` over ICI links at compile
time. Two styles are provided:

- **Implicit (preferred)**: don't call anything — jit a step whose batch is
  sharded over (data, fsdp) and whose params are replicated; GSPMD inserts the
  gradient all-reduce. This is the production path used by
  :mod:`..train.step`.
- **Explicit**: the functions below, valid inside ``shard_map``/``pmap``
  bodies, mirroring the Horovod verb set for code that wants manual control
  (and for tests that pin down collective semantics).

Also here: ``tree_aggregate`` — a driver-side reduction that reproduces the
reference's *round-synchronous* Spark path (``rdd.mapPartitions`` →
``treeAggregate`` → driver update, SURVEY.md §3.1) for CPU parity tests, and
the cross-replica desync sanitizer from SURVEY.md §5.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from distributeddeeplearningspark_tpu.parallel.mesh import BATCH_AXES

AxisNames = str | Sequence[str]


def axis_size(axis_name: AxisNames) -> int:
    """``lax.axis_size`` for jax versions that predate it (the classic
    ``psum(1, axis)`` constant-folds to the static mesh axis size)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def shard_map(f, **kwargs):
    """``jax.shard_map`` across the 0.4→0.5 API move: older jax keeps it in
    ``jax.experimental.shard_map`` and spells ``check_vma`` as ``check_rep``.
    Every shard_map in this package goes through here."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _sm

    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _sm(f, **kwargs)


def all_reduce_sum(tree: Any, axis: AxisNames = BATCH_AXES) -> Any:
    """Horovod ``allreduce(op=Sum)`` ≙ ``lax.psum`` over the mesh axis."""
    return jax.tree.map(lambda x: lax.psum(x, axis), tree)


def all_reduce_mean(tree: Any, axis: AxisNames = BATCH_AXES) -> Any:
    """Horovod's default ``allreduce`` (average) ≙ ``lax.pmean``."""
    return jax.tree.map(lambda x: lax.pmean(x, axis), tree)


def all_gather(tree: Any, axis: AxisNames = BATCH_AXES, *, tiled: bool = True) -> Any:
    """``hvd.allgather`` ≙ ``lax.all_gather`` (tiled: concat along dim 0)."""
    return jax.tree.map(lambda x: lax.all_gather(x, axis, tiled=tiled), tree)


def reduce_scatter(tree: Any, axis: AxisNames = BATCH_AXES, *, scatter_dim: int = 0) -> Any:
    """ZeRO grad sync: ``lax.psum_scatter`` (each shard owns a slice of the sum)."""
    return jax.tree.map(
        lambda x: lax.psum_scatter(x, axis, scatter_dimension=scatter_dim, tiled=True),
        tree,
    )


def all_to_all(x: jax.Array, axis: str, *, split_dim: int, concat_dim: int) -> jax.Array:
    """``all_to_all`` — the sharded-embedding-lookup exchange (DLRM, config 4)."""
    return lax.all_to_all(x, axis, split_axis=split_dim, concat_axis=concat_dim, tiled=True)


def broadcast_from(tree: Any, axis: AxisNames = BATCH_AXES, *, root: int = 0) -> Any:
    """Driver parameter broadcast ≙ select root's copy on every member.

    Inside SPMD code replication normally makes this a no-op; it exists for
    explicit-mode parity with ``sc.broadcast`` semantics (e.g. re-syncing after
    a deliberately divergent step in the desync tests).
    """
    names = (axis,) if isinstance(axis, str) else tuple(axis)

    def bcast(x):
        y = x
        for name in names:
            y = lax.all_gather(y, name, tiled=False)[root]
        return y

    return jax.tree.map(bcast, tree)


def ppermute_shift(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Ring shift over a mesh axis — the building block of ring attention."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


# --- driver-side (round-synchronous Spark) parity path ----------------------


def tree_aggregate(
    partitions: Sequence[Sequence[Any]],
    zero: Any,
    seq_op: Callable[[Any, Any], Any],
    comb_op: Callable[[Any, Any], Any],
) -> Any:
    """Spark ``RDD.treeAggregate`` semantics on the driver.

    ``partitions`` is a sequence of element sequences. Each partition is
    folded from a fresh copy of ``zero`` with ``seq_op`` (the executor-side
    fold); per-partition results are then combined with ``comb_op`` (the
    driver-side merge). Tree depth only changes scheduling, not the result, so
    the combine is flat. The reference's PR1 pure-CPU path (BASELINE.json
    config 1) aggregates per-partition gradients this way (SURVEY.md §3.1);
    tests use it to assert the SPMD ``psum`` step computes the *same numbers*
    as the round-synchronous Spark loop.
    """
    import copy

    per_part = []
    for part in partitions:
        acc = copy.deepcopy(zero)
        for x in part:
            acc = seq_op(acc, x)
        per_part.append(acc)
    if not per_part:
        return zero
    return functools.reduce(comb_op, per_part)


def grad_average(partition_grads: Sequence[Any]) -> Any:
    """Average per-partition gradient pytrees on the driver (parity mode).

    float32 numpy leaves accumulate through the native (C++) ``sum_into``
    kernel — the host equivalent of the reference's driver-side gradient
    reduction, parallel and GIL-free; other leaves fall back to Python sum.
    """
    import numpy as np

    from distributeddeeplearningspark_tpu.utils import native

    n = len(partition_grads)

    def avg(*xs):
        if all(isinstance(x, np.ndarray) and x.dtype == np.float32 for x in xs):
            acc = np.ascontiguousarray(xs[0]).copy()
            for x in xs[1:]:
                native.sum_into(acc, x)
            return acc / n
        return sum(xs) / n

    return jax.tree.map(avg, *partition_grads)


# The desync sanitizer lives in utils/sanitize.py (one API for both the
# local-device and cross-process checks); re-exported for callers that think
# of it as a collective-layer concern.
from distributeddeeplearningspark_tpu.utils.sanitize import (  # noqa: E402
    assert_replicas_in_sync,  # noqa: F401
)
