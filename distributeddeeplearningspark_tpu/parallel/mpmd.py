"""MPMD inter-stage transport — async authkey'd socket links between gangs.

PAPERS.md 2412.14374 ("Scaling Deep Learning Training with MPMD Pipeline
Parallelism") describes the production pipeline shape: each stage is its own
*program* on its own gang, and stages overlap compute with asynchronous
activation/gradient sends. Inside one program that overlap is XLA's job
(``parallel/pipeline.py``'s ppermute ring); across programs it has to be a
real wire. This module is that wire:

- **Framing**: length-prefixed binary frames — magic, version, kind, stage,
  microbatch index, payload CRC32, payload length — carrying a pickled
  payload (numpy activations + metadata + the PR 7 trace context). The
  CRC and magic make torn/partial frames a *typed* :class:`FrameError`
  instead of a desync that unpickles garbage.
- **Auth**: the serve/fleet authkey'd-connection idiom (hex key via env,
  HMAC challenge both ways) hand-rolled on a raw socket, because the
  framing above — not ``multiprocessing.connection``'s — owns the stream.
- **Async double-buffering**: each :class:`StageLink` runs a sender and a
  receiver thread over bounded deques (default depth 2), so stage *k*
  computes microbatch *i* while microbatch *i+1* is already in flight —
  and a slow consumer propagates bounded backpressure (deque full → TCP
  buffer full → sender blocks) instead of buffering unboundedly.
- **Failure typing**: a peer process dying tears the socket; every blocked
  and future ``send``/``recv`` raises :class:`PeerDiedError` within a
  bounded wait. A peer that is alive but silent past ``timeout`` raises
  :class:`TransportTimeout`. The pipeline supervisor restarts only the
  dead stage; survivors block in :meth:`PipelineTransport.connect` until
  it returns (docs/POD_PLAYBOOK.md "A pipeline stage died").
- **Chain topology + resync**: stage *k* listens on ``ports[k]`` for stage
  *k+1* and dials ``ports[k-1]``; after any (re)connect
  :meth:`PipelineTransport.sync_step` runs a forward-min / backward-
  broadcast wave so every stage agrees on the checkpoint step to resume
  from — the restarted stage restores its own per-stage checkpoint, the
  survivors roll back to the same step, and training continues.

jax-free by design (numpy arrives via pickle): the framing is also the
serve-side prefill/decode disaggregation transport named on the ROADMAP.
"""

from __future__ import annotations

import hmac
import json
import logging
import os
import pickle
import socket
import struct
import threading
import time
import zlib
from collections import deque
from typing import Any

logger = logging.getLogger("distributeddeeplearningspark_tpu.mpmd")

MAGIC = b"DLSP"
VERSION = 1

#: frame kinds. ACT/GRAD are the data plane (bounded queues, double-
#: buffered); the rest are control (small, effectively unbounded).
HELLO = 0
ACT = 1
GRAD = 2
META = 3
SYNC_FWD = 4
SYNC_BWD = 5
METRICS = 6
DONE = 7

_KIND_NAMES = {HELLO: "hello", ACT: "act", GRAD: "grad", META: "meta",
               SYNC_FWD: "sync-fwd", SYNC_BWD: "sync-bwd",
               METRICS: "metrics", DONE: "done"}

#: header: magic, version, kind, sender stage, microbatch index,
#: payload crc32, payload length.
_HEADER = struct.Struct("!4sBBhiII")

#: env contract exported by the PipelineSupervisor to every stage process.
ENV_STAGE = "DLS_STAGE_ID"
ENV_NUM_STAGES = "DLS_NUM_STAGES"
ENV_PORTS = "DLS_PIPE_PORTS"
ENV_AUTHKEY = "DLS_PIPE_AUTHKEY"
ENV_SPEC = "DLS_PIPE_SPEC"


class TransportError(RuntimeError):
    """Base class for inter-stage transport failures."""


class PeerDiedError(TransportError):
    """The peer stage's socket tore (process death / connection reset).
    Raised to every blocked and subsequent caller within a bounded wait."""


class FrameError(TransportError):
    """The byte stream desynced: bad magic, impossible length, CRC
    mismatch, or a frame torn mid-payload. Unlike a clean peer death the
    stream cannot be trusted past this point — the link is marked dead."""


class TransportTimeout(TransportError):
    """The peer is (as far as TCP knows) alive but nothing arrived/ drained
    within the caller's timeout."""


def pack_frame(kind: int, stage: int, mb: int, payload: bytes) -> bytes:
    return _HEADER.pack(MAGIC, VERSION, kind, stage, mb,
                        zlib.crc32(payload) & 0xFFFFFFFF, len(payload)) + payload


def encode_payload(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=4)


def decode_payload(data: bytes) -> Any:
    return pickle.loads(data)


def _read_exact(sock: socket.socket, n: int, *, what: str) -> bytes:
    """Read exactly ``n`` bytes. EOF at offset 0 returns b'' (clean close);
    EOF mid-read raises FrameError (a torn frame)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            k = sock.recv_into(view[got:], n - got)
        except OSError as e:
            raise PeerDiedError(f"socket error reading {what}: {e}") from e
        if k == 0:
            if got == 0:
                return b""
            raise FrameError(
                f"torn frame: stream ended {got}/{n} bytes into {what}")
        got += k
    return bytes(buf)


def read_frame(sock: socket.socket,
               *, max_payload: int = 1 << 31) -> tuple[int, int, int, bytes] | None:
    """One (kind, stage, mb, payload) frame, or None on clean EOF at a
    frame boundary. Validates magic, version, length sanity, and payload
    CRC — any mismatch is a :class:`FrameError`."""
    head = _read_exact(sock, _HEADER.size, what="frame header")
    if not head:
        return None
    magic, version, kind, stage, mb, crc, length = _HEADER.unpack(head)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic!r} (stream desync)")
    if version != VERSION:
        raise FrameError(f"frame version {version} != {VERSION}")
    if length > max_payload:
        raise FrameError(f"frame length {length} exceeds cap {max_payload}")
    payload = _read_exact(sock, length, what=f"{_KIND_NAMES.get(kind, kind)} payload")
    if length and not payload:
        raise FrameError("torn frame: stream ended before payload")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise FrameError(
            f"payload checksum mismatch on {_KIND_NAMES.get(kind, kind)} "
            f"frame (mb={mb}) — torn or corrupted in flight")
    return kind, stage, mb, payload


# -- authkey handshake (the serve/fleet idiom on a raw socket) ----------------


def _challenge(sock: socket.socket, authkey: bytes, *, server: bool) -> None:
    """Mutual HMAC-SHA256 challenge. Both sides prove possession of the
    key; failure closes the socket with TransportError (an unauthenticated
    peer must never reach the frame loop)."""
    def send_nonce() -> bytes:
        nonce = os.urandom(16)
        sock.sendall(b"DLSPCHAL" + nonce)
        return nonce

    def answer() -> None:
        tag = _read_exact(sock, 8, what="challenge tag")
        if tag != b"DLSPCHAL":
            raise TransportError(f"bad challenge tag {tag!r}")
        nonce = _read_exact(sock, 16, what="challenge nonce")
        if len(nonce) != 16:
            raise TransportError("short challenge nonce")
        sock.sendall(hmac.new(authkey, nonce, "sha256").digest())

    def verify(nonce: bytes) -> None:
        digest = _read_exact(sock, 32, what="challenge response")
        want = hmac.new(authkey, nonce, "sha256").digest()
        if not hmac.compare_digest(digest, want):
            raise TransportError("authkey challenge failed")

    if server:
        nonce = send_nonce()
        verify(nonce)
        answer()
    else:
        answer()
        nonce = send_nonce()
        verify(nonce)


class _BoundedChannel:
    """Condition-guarded bounded deque shared by the worker threads and the
    caller; death wakes every waiter with the link's typed error."""

    def __init__(self, depth: int, cond: threading.Condition):
        self.items: deque = deque()
        self.depth = depth
        self.cond = cond


class StageLink:
    """One authenticated, framed, double-buffered link to a peer stage.

    ``send(kind, obj, mb)`` enqueues (bounded; blocks past ``depth`` in
    flight = the backpressure bound) and a sender thread writes frames;
    ``recv(kind)`` pops from that kind's bounded inbox filled by the
    receiver thread. Control kinds (META/SYNC/METRICS/DONE) share an
    unbounded-depth inbox — they are tiny and must never deadlock behind
    a full data queue.
    """

    def __init__(self, sock: socket.socket, *, stage: int, peer_stage: int,
                 depth: int = 2, hello: dict | None = None,
                 hello_timeout: float = 60.0):
        self.stage = stage
        self.peer_stage = peer_stage
        self.sock = sock
        self.depth = max(1, int(depth))
        self._cond = threading.Condition()
        self._send_q: deque = deque()
        self._inbox: dict[int, deque] = {ACT: deque(), GRAD: deque()}
        self._ctrl: deque = deque()
        self._err: TransportError | None = None
        self._done_seen = False
        self._closed = False
        # HELLO crosses synchronously before the threads exist, so both
        # ends learn (stage, committed step, attempt) — the resync wave's
        # inputs — before any data frame can race it.
        sock.settimeout(hello_timeout)
        sock.sendall(pack_frame(HELLO, stage, -1,
                                encode_payload(dict(hello or {}, stage=stage))))
        first = read_frame(sock)
        if first is None:
            raise PeerDiedError(f"peer stage {peer_stage} closed before hello")
        kind, pstage, _, payload = first
        if kind != HELLO:
            raise FrameError(f"expected hello, got {_KIND_NAMES.get(kind, kind)}")
        self.peer_hello: dict = decode_payload(payload)
        if int(self.peer_hello.get("stage", pstage)) != peer_stage:
            raise TransportError(
                f"connected to stage {self.peer_hello.get('stage')}, "
                f"expected {peer_stage} (port map mismatch)")
        sock.settimeout(None)
        self._sender = threading.Thread(
            target=self._send_loop, name=f"mpmd-s{stage}-send", daemon=True)
        self._receiver = threading.Thread(
            target=self._recv_loop, name=f"mpmd-s{stage}-recv", daemon=True)
        self._sender.start()
        self._receiver.start()

    # -- state ---------------------------------------------------------------

    @property
    def dead(self) -> bool:
        return self._err is not None

    def _die(self, err: TransportError) -> None:
        with self._cond:
            if self._err is None:
                self._err = err
            self._cond.notify_all()

    def _raise_dead(self) -> None:
        assert self._err is not None
        raise type(self._err)(*self._err.args)

    # -- worker threads ------------------------------------------------------

    def _send_loop(self) -> None:
        while True:
            with self._cond:
                while not self._send_q and self._err is None and not self._closed:
                    self._cond.wait()
                if self._err is not None or (self._closed and not self._send_q):
                    return
                frame = self._send_q.popleft()
                self._cond.notify_all()
            try:
                self.sock.sendall(frame)
            except OSError as e:
                self._die(PeerDiedError(
                    f"peer stage {self.peer_stage} died mid-send: {e}"))
                return

    def _recv_loop(self) -> None:
        while True:
            try:
                frame = read_frame(self.sock)
            except TransportError as e:
                if self._done_seen and isinstance(e, PeerDiedError):
                    return  # socket torn after DONE: expected teardown
                self._die(e if isinstance(e, (PeerDiedError, FrameError))
                          else PeerDiedError(str(e)))
                return
            if frame is None:
                if self._done_seen or self._closed:
                    return
                self._die(PeerDiedError(
                    f"peer stage {self.peer_stage} closed the link"))
                return
            kind, _, mb, payload = frame
            try:
                obj = decode_payload(payload)
            except Exception as e:  # noqa: BLE001 — checksum passed but the
                # pickle is bad: protocol violation, not recoverable
                self._die(FrameError(f"undecodable {_KIND_NAMES.get(kind, kind)} "
                                     f"payload: {e}"))
                return
            with self._cond:
                if kind == DONE:
                    self._done_seen = True
                    self._ctrl.append((kind, mb, obj))
                elif kind in self._inbox:
                    q = self._inbox[kind]
                    # bounded inbox: stop draining the socket when the
                    # consumer lags `depth` frames — TCP backpressure then
                    # stalls the sender (bounded memory at both ends)
                    while len(q) >= self.depth and self._err is None \
                            and not self._closed:
                        self._cond.wait()
                    if self._err is not None or self._closed:
                        return
                    q.append((kind, mb, obj))
                else:
                    self._ctrl.append((kind, mb, obj))
                self._cond.notify_all()

    # -- caller API ----------------------------------------------------------

    def send(self, kind: int, obj: Any, *, mb: int = -1,
             timeout: float | None = None) -> None:
        """Enqueue one frame (async). Blocks while ``depth`` frames are
        already queued — the bounded-buffering contract; ``timeout``
        bounds that wait with :class:`TransportTimeout`."""
        frame = pack_frame(kind, self.stage, mb, encode_payload(obj))
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while len(self._send_q) >= self.depth:
                if self._err is not None:
                    self._raise_dead()
                if self._closed:
                    raise TransportError("link closed")
                wait = None if deadline is None else deadline - time.monotonic()
                if wait is not None and wait <= 0:
                    raise TransportTimeout(
                        f"send queue to stage {self.peer_stage} full "
                        f"({self.depth} frames) for {timeout:.1f}s — peer "
                        f"not draining")
                self._cond.wait(wait)
            if self._err is not None:
                self._raise_dead()
            if self._closed:
                raise TransportError("link closed")
            self._send_q.append(frame)
            self._cond.notify_all()

    def recv(self, kind: int, *, timeout: float | None = 120.0
             ) -> tuple[int, Any]:
        """Next ``(mb, payload)`` of ``kind``. Buffered frames are delivered
        even after the peer died (they arrived intact); then the typed
        error surfaces."""
        q = self._inbox.get(kind, self._ctrl)
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                item = self._pop(q, kind)
                if item is not None:
                    self._cond.notify_all()  # wake the receiver (space freed)
                    return item[1], item[2]
                if self._err is not None:
                    self._raise_dead()
                if self._closed:
                    raise TransportError("link closed")
                wait = None if deadline is None else deadline - time.monotonic()
                if wait is not None and wait <= 0:
                    raise TransportTimeout(
                        f"no {_KIND_NAMES.get(kind, kind)} frame from stage "
                        f"{self.peer_stage} within {timeout:.1f}s")
                self._cond.wait(wait)

    def try_recv(self, kind: int) -> tuple[int, Any] | None:
        """Non-blocking :meth:`recv`: ``(mb, payload)`` or None. Raises the
        link's typed error only when dead AND nothing is buffered."""
        q = self._inbox.get(kind, self._ctrl)
        with self._cond:
            item = self._pop(q, kind)
            if item is not None:
                self._cond.notify_all()
                return item[1], item[2]
            if self._err is not None:
                self._raise_dead()
            return None

    def _pop(self, q: deque, kind: int):
        if q is self._ctrl:
            for i, item in enumerate(q):
                if item[0] == kind:
                    del q[i]
                    return item
            return None
        return q.popleft() if q else None

    def close(self, *, send_done: bool = True) -> None:
        try:
            if send_done and self._err is None:
                self.send(DONE, {}, timeout=5.0)
        except TransportError:
            pass
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        # let queued frames (incl. DONE) drain before tearing the socket
        self._sender.join(timeout=5.0)
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


# -- chain topology -----------------------------------------------------------


class PipelineTransport:
    """Stage *k*'s two links: ``up`` (to stage k−1) and ``down`` (to k+1).

    Owns the persistent listener on ``ports[stage]`` (SO_REUSEADDR — a
    restarted stage re-binds the same port) so a dead neighbor can
    reconnect without coordination: on :class:`PeerDiedError` the runner
    calls :meth:`connect` again, which re-accepts/re-dials only the broken
    side, then :meth:`sync_step` agrees on the resume step.
    """

    def __init__(self, stage: int, num_stages: int, ports: list[int],
                 authkey: bytes, *, depth: int = 2,
                 connect_timeout: float = 120.0):
        if num_stages < 2:
            raise ValueError(f"a pipeline needs >= 2 stages, got {num_stages}")
        if len(ports) < num_stages - 1:
            raise ValueError(
                f"need {num_stages - 1} ports for {num_stages} stages, "
                f"got {len(ports)}")
        self.stage = stage
        self.num_stages = num_stages
        self.ports = list(ports)
        self.authkey = authkey
        self.depth = depth
        self.connect_timeout = connect_timeout
        self.up: StageLink | None = None
        self.down: StageLink | None = None
        self._listener: socket.socket | None = None
        if stage < num_stages - 1:
            self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind(("127.0.0.1", ports[stage]))
            self._listener.listen(4)

    @classmethod
    def from_env(cls, **kw) -> "PipelineTransport":
        return cls(
            int(os.environ[ENV_STAGE]),
            int(os.environ[ENV_NUM_STAGES]),
            json.loads(os.environ[ENV_PORTS]),
            bytes.fromhex(os.environ[ENV_AUTHKEY]),
            **kw,
        )

    def connect(self, *, hello: dict | None = None,
                timeout: float | None = None) -> None:
        """(Re)establish whichever links are missing or dead.

        Down (accept) before up (dial): the chain then resolves tail-first
        — the last stage dials immediately, each accept unblocks the next
        dial — and the same order is deadlock-free for any single-stage
        restart (the survivors' broken sides are complementary)."""
        deadline = time.monotonic() + (timeout or self.connect_timeout)
        if self._listener is not None and (self.down is None or self.down.dead):
            self.down = self._accept(deadline, hello)
        if self.stage > 0 and (self.up is None or self.up.dead):
            self.up = self._dial(deadline, hello)

    def _accept(self, deadline: float, hello: dict | None) -> StageLink:
        assert self._listener is not None
        while True:
            self._listener.settimeout(max(0.1, deadline - time.monotonic()))
            try:
                sock, _ = self._listener.accept()
            except socket.timeout:
                raise TransportTimeout(
                    f"stage {self.stage}: stage {self.stage + 1} never "
                    f"connected (waited {self.connect_timeout:.0f}s)")
            try:
                _challenge(sock, self.authkey, server=True)
                return StageLink(sock, stage=self.stage,
                                 peer_stage=self.stage + 1, depth=self.depth,
                                 hello=hello)
            except TransportError as e:
                logger.warning("stage %d: rejected downstream connection: %s",
                               self.stage, e)
                try:
                    sock.close()
                except OSError:
                    pass
                if time.monotonic() > deadline:
                    raise

    def _dial(self, deadline: float, hello: dict | None) -> StageLink:
        port = self.ports[self.stage - 1]
        while True:
            try:
                sock = socket.create_connection(("127.0.0.1", port),
                                                timeout=5.0)
                _challenge(sock, self.authkey, server=False)
                return StageLink(sock, stage=self.stage,
                                 peer_stage=self.stage - 1, depth=self.depth,
                                 hello=hello)
            except (OSError, TransportError) as e:
                if time.monotonic() > deadline:
                    raise TransportTimeout(
                        f"stage {self.stage}: could not reach stage "
                        f"{self.stage - 1} on port {port} within "
                        f"{self.connect_timeout:.0f}s: {e}")
                time.sleep(0.2)

    def reset(self) -> None:
        """Drop both links (keeping the listener) ahead of a reconnect —
        a resync must never read a stale pre-failure frame."""
        for link in (self.up, self.down):
            if link is not None:
                link.close(send_done=False)
        self.up = self.down = None

    def sync_step(self, my_step: int, *, timeout: float = 120.0) -> int:
        """Chain consensus on the resume step: forward min-wave, backward
        broadcast. Every stage returns the same global minimum of the
        committed checkpoint steps — the step all stages can restore."""
        cur = int(my_step)
        if self.up is not None:
            _, payload = self.up.recv(SYNC_FWD, timeout=timeout)
            cur = min(cur, int(payload["step"]))
        if self.down is not None:
            self.down.send(SYNC_FWD, {"step": cur})
            _, payload = self.down.recv(SYNC_BWD, timeout=timeout)
            cur = int(payload["step"])
        if self.up is not None:
            self.up.send(SYNC_BWD, {"step": cur})
        return cur

    def close(self) -> None:
        for link in (self.up, self.down):
            if link is not None:
                link.close()
        self.up = self.down = None
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
