"""Elastic supervisor — gang launch, failure detection, restart-from-checkpoint.

Spark's headline fault-tolerance story is task-level: a lost executor's
partitions are recomputed from lineage and the job keeps going (SURVEY.md §5
'Failure detection'). Gang-scheduled SPMD has no per-task retry — one lost
process stalls every collective — so the TPU-native equivalent is job-level
elasticity:

1. train with frequent async checkpoints (:mod:`.checkpoint`),
2. detect a dead/failed worker (process exit, or a missed heartbeat when a
   hang never surfaces as an exit),
3. tear the gang down and relaunch it; workers resume from the latest
   checkpoint (their driver scripts call ``Trainer.restore``).

The supervisor is deliberately dumb — launch, watch, kill, relaunch — because
all actual state lives in checkpoints. Workers rendezvous through
``jax.distributed`` using the ``DLS_*`` env contract that
:class:`~.session.Session` already auto-consumes (session.py
``_create_session``): ``DLS_COORDINATOR``, ``DLS_NUM_PROCESSES``,
``DLS_PROCESS_ID``. The supervisor additionally exports ``DLS_RESTART`` (the
attempt ordinal) so tests can inject faults on attempt 0 only.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import socket
import subprocess
import sys
import time

from distributeddeeplearningspark_tpu import faults
from distributeddeeplearningspark_tpu import telemetry as telemetry_lib

logger = logging.getLogger("distributeddeeplearningspark_tpu.supervisor")

#: Sentinel exit code workers use to say "I died restoring the checkpoint,
#: not training" (tests/workers/worker.py exits with it when
#: ``Trainer.restore`` raises). A relaunch after this code is doomed to the
#: identical crash unless the checkpoint it restores changes — so the
#: supervisor quarantines the latest step and falls back to the previous one
#: instead of burning ``max_restarts`` on a poisoned checkpoint.
RESTORE_FAILED_EXIT = 13

#: Evidence file a gracefully draining gang leaves in the checkpoint root:
#: ``"<doomed_host> <drained_step>"``. Written by the trainer's SIGTERM
#: drain (after the live handoff commits), read by :meth:`Supervisor._classify`
#: to tell "the gang exited zero because it DRAINED" from "the gang finished"
#: — without it a graceful preemption would look like success (or, had the
#: drain path exited non-zero, burn a backoff slot as a training-crash).
DRAIN_EVIDENCE = "DRAIN"


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def drain_evidence_path(directory: str) -> str:
    return os.path.join(directory, DRAIN_EVIDENCE)


def write_drain_evidence(directory: str, *, host: int, step: int) -> str:
    """Atomically record a graceful drain: the doomed host ordinal and the
    step training completed before handing off. The trainer writes this
    LAST (after the live handoff is fully committed) so its existence
    implies an ingestible handoff."""
    path = drain_evidence_path(directory)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{int(host)} {int(step)}\n")
    os.replace(tmp, path)
    return path


def read_drain_evidence(directory: str) -> tuple[int, int] | None:
    """``(doomed_host, drained_step)`` or None (absent/torn evidence)."""
    try:
        with open(drain_evidence_path(directory)) as f:
            host, step = f.read().split()
        return int(host), int(step)
    except (OSError, ValueError):
        return None


def consume_drain_evidence(directory: str, *, ordinal: int) -> None:
    """Retire the evidence once acted on (kept beside the stream as
    ``DRAIN.consumed-<ordinal>`` for post-incident forensics) so a later
    attempt's clean exit is never misread as another drain."""
    path = drain_evidence_path(directory)
    try:
        os.replace(path, f"{path}.consumed-{ordinal}")
    except OSError:
        pass


@dataclasses.dataclass
class Attempt:
    """Outcome of one gang launch."""

    ordinal: int
    returncodes: list[int]
    duration_s: float
    #: Failure class: "clean" | "training-crash" | "restore-failure" | "hang"
    #: | "graceful-shutdown" (see :meth:`Supervisor._classify`). Drives the
    #: restart strategy and gives operators one log line naming WHICH
    #: recovery path fired.
    classification: str = ""
    #: Whether any progress evidence (heartbeat/checkpoint mtime) appeared
    #: during the attempt — the signal separating "crashed at restore" from
    #: "crashed mid-training" when no sentinel exit code arrives.
    made_progress: bool = False
    #: On a "hang": the fleet localization (host/phase/stalled_for_s/...)
    #: from the gang's telemetry streams, when they carry enough evidence
    #: to name a single stalled host (telemetry.fleet.localize_hang).
    culprit: dict | None = None
    #: The ORIGINAL host ordinal this failure points at, when the evidence
    #: names exactly one: the hang culprit's host, or the unique first-
    #: failing process (mapped through the surviving-host list, so the id
    #: stays stable across elastic renumbering). None when ambiguous —
    #: the shrink policy only acts on an unambiguous, repeated verdict.
    dead_host: int | None = None
    #: Gang width of this attempt (shrinks when hosts are dropped).
    num_processes: int = 0

    @property
    def ok(self) -> bool:
        # a graceful drain also exits all-zero — it is a handoff, not a
        # completion, and must not end the run
        return (all(rc == 0 for rc in self.returncodes)
                and self.classification != "graceful-shutdown")


@dataclasses.dataclass
class SupervisorResult:
    attempts: list[Attempt]

    @property
    def ok(self) -> bool:
        return bool(self.attempts) and self.attempts[-1].ok

    @property
    def restarts(self) -> int:
        return max(0, len(self.attempts) - 1)


class Supervisor:
    """Launch ``num_processes`` copies of a worker command as one gang.

    ``argv`` is the worker command (e.g. ``[sys.executable, "train.py",
    "--resume"]``); every process gets the rendezvous env plus its own
    ``DLS_PROCESS_ID``. Any non-zero exit (or death by signal) fails the whole
    attempt: survivors are terminated and, up to ``max_restarts`` times, the
    gang is relaunched on a fresh coordinator port.

    ``poll_interval`` bounds failure-detection latency; ``hang_timeout_s``
    (optional) additionally fails an attempt whose processes are all alive but
    have produced no progress for too long — the hang case NCCL users know as
    the silent stuck all-reduce. Progress is observed as mtime changes under
    ``progress_path`` (typically the checkpoint dir), the same signal a human
    operator would watch.

    **Failure classification & restore fallback.** Each failed attempt is
    classified (``Attempt.classification``): a worker exiting with
    :data:`RESTORE_FAILED_EXIT` — or a gang that dies on a restart attempt
    without ever producing progress evidence while a checkpoint exists — is a
    **restore-failure**: relaunching against the same checkpoint would crash
    identically. On the *explicit sentinel* (and only then — circumstantial
    evidence also fits a crash-right-after-restore and must not destroy a
    healthy step), up to ``max_restore_fallbacks`` times per run, the latest
    step under ``ckpt_dir`` is quarantined to ``<step>.corrupt-N`` before
    the relaunch, forcing the gang onto the previous step. Everything else
    is a **training-crash** (or **hang**), where plain restart-from-latest
    is right.

    **Backoff.** Restart delay grows exponentially from
    ``restart_backoff_s`` (doubling per *consecutive fruitless* attempt,
    capped at ``restart_backoff_max_s``) with ``±backoff_jitter`` relative
    jitter so a fleet of supervisors recovering from a shared-infra blip
    doesn't stampede the storage/coordinator in lockstep. An attempt that
    made observed progress (heartbeat/checkpoint evidence — only when
    progress tracking is configured) resets the ladder: a run that trains
    10k steps and then crashes is a fresh incident, not the next rung of
    its early flaky attempts' 30s max-backoff.

    **Shrink-to-survive (elastic).** With ``shrink_after=K``, once K
    consecutive failed attempts point at the SAME dead host (the hang
    localization's culprit, or the unique first-failing process), the
    supervisor stops relaunching a doomed geometry: it drops that host from
    the gang, recomputes ``DLS_NUM_PROCESSES`` (ranks renumber contiguously;
    each process also gets its stable original ordinal as ``DLS_HOST_ID``),
    and relaunches the survivors from the last checkpoint — workers restore
    through the checkpoint layer's reshard-on-restore path, and the global
    batch is preserved (the feed splits it over fewer hosts, so the
    per-host share grows; recorded as ``batch_policy`` on the
    ``geometry_change`` recovery event). The gang never shrinks below
    ``min_processes``.
    """

    def __init__(
        self,
        argv: list[str],
        *,
        num_processes: int = 1,
        max_restarts: int = 3,
        env: dict[str, str] | None = None,
        poll_interval: float = 0.2,
        restart_backoff_s: float = 0.5,
        restart_backoff_max_s: float = 30.0,
        backoff_jitter: float = 0.25,
        hang_timeout_s: float | None = None,
        progress_path: str | None = None,
        startup_grace_s: float | None = None,
        ckpt_dir: str | None = None,
        fallback_on_restore_failure: bool = True,
        max_restore_fallbacks: int = 1,
        telemetry_dir: str | None = None,
        shrink_after: int | None = None,
        min_processes: int = 1,
    ):
        self.argv = list(argv)
        self.num_processes = num_processes
        # surviving ORIGINAL host ordinals, in launch order: rank i of the
        # next attempt is host self._hosts[i]. Shrinks drop entries; ranks
        # renumber contiguously (jax.distributed wants 0..n-1) while
        # DLS_HOST_ID keeps naming the same machine across attempts.
        self._hosts: list[int] = list(range(num_processes))
        self.shrink_after = shrink_after
        self.min_processes = max(1, min_processes)
        self.max_restarts = max_restarts
        self.env = dict(env or {})
        self.poll_interval = poll_interval
        self.restart_backoff_s = restart_backoff_s
        self.restart_backoff_max_s = restart_backoff_max_s
        self.backoff_jitter = backoff_jitter
        self.hang_timeout_s = hang_timeout_s
        self.progress_path = progress_path
        # checkpoint root for the restore-failure fallback; progress_path is
        # "typically the checkpoint dir", so it doubles as the default
        self.ckpt_dir = ckpt_dir if ckpt_dir is not None else progress_path
        self.fallback_on_restore_failure = fallback_on_restore_failure
        # bound on latest-step quarantines per run: exit-13 can also mean a
        # transient storage error, and unbounded fallback would let a blip
        # lasting max_restarts attempts eat the whole retention window
        self.max_restore_fallbacks = max_restore_fallbacks
        # First-progress latency includes JIT compile + checkpoint_every steps,
        # which can dwarf the steady-state checkpoint cadence — give startup
        # its own (longer) window so a healthy gang isn't killed mid-compile.
        # Default: 5× the hang timeout.
        self.startup_grace_s = (
            startup_grace_s if startup_grace_s is not None
            else (hang_timeout_s * 5.0 if hang_timeout_s is not None else None)
        )
        # Per-process heartbeat files (ADVICE r1: checkpoint-dir mtimes alone
        # can't tell "training between checkpoints" from "spinning"): workers
        # touch DLS_HEARTBEAT_FILE at every metrics lap (Trainer.fit does it
        # automatically), and the stamp below folds those mtimes in.
        self._hb_dir: str | None = None
        if hang_timeout_s is not None:
            import tempfile

            self._hb_dir = tempfile.mkdtemp(prefix="dls_hb_")
        # Telemetry workdir: the supervisor appends attempt lifecycle /
        # classification / backoff records to the SAME per-run stream the
        # workers write (it exports DLS_TELEMETRY_DIR to them), so dlstatus
        # shows one merged timeline. Resolution honors the documented env
        # contract first (an operator-exported DLS_TELEMETRY_DIR — also how
        # `dlsubmit --workdir` hands it down — must not be silently
        # overridden), then falls back to the checkpoint root — the
        # directory an operator already has in hand after an incident.
        self.telemetry_dir = (
            telemetry_dir if telemetry_dir is not None
            else (self.env.get(telemetry_lib.WORKDIR_ENV)
                  or os.environ.get(telemetry_lib.WORKDIR_ENV)
                  or self.ckpt_dir))  # ckpt_dir already fell back to progress_path
        self._tele: telemetry_lib.EventWriter | None = None

    def _telemetry(self) -> telemetry_lib.EventWriter | None:
        if self._tele is None and self.telemetry_dir:
            # host=None: the supervisor describes the gang, it is not a
            # member — its events must stay out of the fleet table (they
            # would otherwise pollute host 0's liveness)
            self._tele = telemetry_lib.EventWriter(
                self.telemetry_dir, process="supervisor", host=None)
        return self._tele

    def _emit_attempt(self, edge: str, ordinal: int, **fields) -> None:
        tele = self._telemetry()
        if tele is not None:
            tele.attempt(edge, ordinal, **fields)

    def _localize_hang(self) -> dict | None:
        """Name the stalled host from the gang's own telemetry streams.

        The watchdog only knows "no progress anywhere"; the per-host
        streams know who went silent FIRST and in what phase — the
        difference between "restart the gang" and "drain host 3". Purely
        best-effort: no telemetry dir, no worker streams, or no clear
        single culprit all degrade to the bare classification.
        """
        if not self.telemetry_dir:
            return None
        try:
            from distributeddeeplearningspark_tpu.telemetry import fleet

            # restrict to the CURRENT gang's ranks: after a shrink the
            # dropped rank's stream is forever silent, and folding it in
            # would make every later hang blame the ghost (its silence
            # always leads) instead of the host actually stuck
            width = len(self._hosts)
            events = [e for e in telemetry_lib.read_events(self.telemetry_dir)
                      if e.get("host") is None or int(e["host"]) < width]
            return fleet.localize_hang(events, now=time.time())
        except Exception:  # noqa: BLE001 — diagnosis must not mask recovery
            logger.debug("hang localization failed", exc_info=True)
            return None

    @staticmethod
    def _culprit_fields(attempt: "Attempt") -> dict:
        """The hang culprit flattened into recovery/attempt event fields."""
        c = attempt.culprit
        if not c:
            return {}
        return {"culprit_host": c.get("host"),
                "culprit_phase": c.get("phase"),
                "stalled_for_s": round(float(c.get("stalled_for_s", 0.0)), 1),
                "others_at_step": c.get("others_at_step"),
                "hang_verdict": c.get("verdict")}

    # -- one gang ------------------------------------------------------------

    def _launch(self, ordinal: int) -> list[subprocess.Popen]:
        port = free_port()
        procs = []
        for pid, host in enumerate(self._hosts):
            env = {
                **os.environ,
                **self.env,
                "DLS_COORDINATOR": f"localhost:{port}",
                "DLS_NUM_PROCESSES": str(self.num_processes),
                "DLS_PROCESS_ID": str(pid),
                # stable machine identity: ranks renumber after a shrink,
                # hosts do not (faults and operators target hosts)
                "DLS_HOST_ID": str(host),
                "DLS_RESTART": str(ordinal),
            }
            if self._hb_dir is not None:
                env["DLS_HEARTBEAT_FILE"] = os.path.join(
                    self._hb_dir, f"hb_{pid}")
            # unconditional when resolved: telemetry_dir already honored an
            # env-supplied value during resolution, and an EXPLICIT
            # constructor argument must win over a conflicting env entry —
            # the whole point is one merged stream, never two half-streams
            if self.telemetry_dir:
                env[telemetry_lib.WORKDIR_ENV] = self.telemetry_dir
            procs.append(subprocess.Popen(self.argv, env=env))
        logger.info(
            "attempt %d: launched %d worker(s) (coordinator :%d)",
            ordinal, self.num_processes, port,
        )
        return procs

    def _progress_stamp(self) -> float:
        """Newest mtime among heartbeat files, progress_path, and its
        immediate children.

        Deliberately shallow: an orbax step dir appears by atomic rename at
        finalize (bumping the parent and step-dir mtimes), so one level is
        enough — recursing into thousands of tensorstore chunk files every
        poll would hammer the filesystem.
        """
        latest = 0.0
        for d in (self._hb_dir, self.progress_path):
            if not d or not os.path.exists(d):
                continue
            try:
                with os.scandir(d) as it:
                    latest = max(latest, os.stat(d).st_mtime)
                    for entry in it:
                        try:
                            latest = max(latest, entry.stat().st_mtime)
                        except OSError:
                            pass
            except OSError:
                pass
        return latest

    def _has_checkpoint(self) -> bool:
        """A committed (numeric) step dir exists under ckpt_dir — i.e. the
        relaunch WILL go down the restore path."""
        if not self.ckpt_dir or not os.path.isdir(self.ckpt_dir):
            return False
        from distributeddeeplearningspark_tpu.checkpoint import latest_step_in

        return latest_step_in(self.ckpt_dir) is not None

    def _classify(self, codes: list[int], *, ordinal: int, hang: bool,
                  made_progress: bool) -> str:
        """Name the failure mode so run() can pick the right recovery.

        ``restore-failure`` needs either the explicit sentinel exit code or
        the circumstantial case: on a RESTART attempt (ordinal > 0 — attempt
        0 may legitimately crash pre-progress for non-restore reasons, e.g.
        compile OOM, and must not get a healthy checkpoint quarantined), a
        checkpoint exists to restore yet the gang died before producing any
        progress evidence — the shape of "every relaunch crashes at the same
        restore". Without progress tracking (no progress_path/heartbeats)
        the circumstantial branch stays quiet: ``made_progress`` is then
        reported True to avoid misclassifying.

        ``graceful-shutdown`` is evidence-driven, not code-driven: a drained
        gang exits all-zero (it would read as "clean" — run over) and a
        drain raced by the kill path could exit non-zero (it would read as
        "training-crash" and burn a backoff slot). The DRAIN file the
        trainer writes after committing the live handoff overrides both.
        """
        if self._drain_evidence() is not None:
            return "graceful-shutdown"
        if all(c == 0 for c in codes):
            return "clean"
        if hang:
            return "hang"
        if any(c == RESTORE_FAILED_EXIT for c in codes):
            return "restore-failure"
        if ordinal > 0 and not made_progress and self._has_checkpoint():
            return "restore-failure"
        return "training-crash"

    def _drain_evidence(self) -> tuple[int, int] | None:
        """``(doomed_host, drained_step)`` when a graceful drain left its
        evidence in the checkpoint root; None otherwise."""
        if not self.ckpt_dir:
            return None
        return read_drain_evidence(self.ckpt_dir)

    def _dead_host_from(self, culprit: dict | None,
                        first_failed: list[int] | None) -> int | None:
        """The original host ordinal this failure unambiguously names.

        Rank → host goes through the surviving-host list; a localization
        that names several ranks (or none) yields None — the shrink policy
        must never amputate on a guess."""
        rank: int | None = None
        if culprit and culprit.get("host") is not None:
            rank = int(culprit["host"])
        elif first_failed and len(set(first_failed)) == 1:
            rank = first_failed[0]
        if rank is None or not (0 <= rank < len(self._hosts)):
            return None
        return self._hosts[rank]

    def _run_attempt(self, ordinal: int) -> Attempt:
        t0 = time.monotonic()
        self._emit_attempt("begin", ordinal,
                           num_processes=self.num_processes,
                           hosts=list(self._hosts))
        procs = self._launch(ordinal)
        last_progress = time.monotonic()
        track_progress = self._hb_dir is not None or self.progress_path is not None
        stamp0 = stamp = self._progress_stamp() if track_progress else 0.0
        seen_progress = False
        hang = False

        def finish(codes: list[int],
                   first_failed: list[int] | None = None) -> Attempt:
            progressed = (not track_progress
                          or seen_progress
                          or self._progress_stamp() > stamp0)
            cls = self._classify(codes, ordinal=ordinal, hang=hang,
                                 made_progress=progressed)
            if first_failed is None and cls != "clean":
                first_failed = [i for i, c in enumerate(codes) if c != 0]
            culprit = self._localize_hang() if hang else None
            att = Attempt(ordinal, codes, time.monotonic() - t0,
                          classification=cls, made_progress=progressed,
                          culprit=culprit,
                          dead_host=(None if cls == "clean" else
                                     self._dead_host_from(culprit,
                                                          first_failed)),
                          num_processes=self.num_processes)
            if att.culprit:
                logger.warning("attempt %d hang localized: %s", ordinal,
                               att.culprit.get("verdict"))
            self._emit_attempt("end", ordinal, returncodes=att.returncodes,
                               duration_s=att.duration_s, classification=cls,
                               made_progress=progressed,
                               num_processes=self.num_processes,
                               **({"dead_host": att.dead_host}
                                  if att.dead_host is not None else {}),
                               **self._culprit_fields(att))
            return att

        try:
            while True:
                codes = [p.poll() for p in procs]
                if all(c is not None for c in codes):
                    return finish([int(c) for c in codes])
                if any(c is not None and c != 0 for c in codes):
                    failed = [i for i, c in enumerate(codes) if c not in (None, 0)]
                    logger.warning(
                        "attempt %d: worker(s) %s failed (codes %s); killing gang",
                        ordinal, failed, [codes[i] for i in failed],
                    )
                    self._kill(procs)
                    return finish([int(p.wait()) for p in procs],
                                  first_failed=failed)
                if self.hang_timeout_s is not None:
                    now_stamp = self._progress_stamp()
                    limit = (self.hang_timeout_s if seen_progress
                             else self.startup_grace_s)
                    if now_stamp > stamp:
                        stamp, last_progress = now_stamp, time.monotonic()
                        seen_progress = True
                    elif time.monotonic() - last_progress > limit:
                        logger.warning(
                            "attempt %d: no progress for %.1fs (%s); killing hung gang",
                            ordinal, limit,
                            "steady state" if seen_progress else "startup grace",
                        )
                        hang = True
                        self._kill(procs)
                        return finish([int(p.wait()) for p in procs])
                elif track_progress and not seen_progress:
                    # no hang watchdog, but classification still wants the
                    # progress bit; sample on the same poll cadence
                    now_stamp = self._progress_stamp()
                    if now_stamp > stamp:
                        stamp = now_stamp
                        seen_progress = True
                time.sleep(self.poll_interval)
        except BaseException:
            self._kill(procs)
            raise

    @staticmethod
    def _kill(procs: list[subprocess.Popen]) -> None:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    # -- the restart loop ----------------------------------------------------

    def _backoff_delay(self, ordinal: int) -> float:
        """Exponential backoff before relaunching after failed attempt
        ``ordinal``: base · 2^ordinal, capped, with relative jitter."""
        delay = min(self.restart_backoff_s * (2.0 ** ordinal),
                    self.restart_backoff_max_s)
        if self.backoff_jitter:
            delay *= 1.0 + random.uniform(-self.backoff_jitter,
                                          self.backoff_jitter)
        return max(0.0, delay)

    def _fallback_to_previous_step(self) -> None:
        """Quarantine the latest checkpoint step so the relaunch restores the
        previous one — the recovery for a verified-but-poisoned checkpoint
        (restore crashes even though the bytes match the manifest)."""
        from distributeddeeplearningspark_tpu.checkpoint import (
            latest_step_in,
            quarantine_step_dir,
        )

        step = latest_step_in(self.ckpt_dir)
        if step is None:
            return
        logger.warning(
            "restore-failure: quarantining checkpoint step %d under %s and "
            "falling back to the previous step", step, self.ckpt_dir)
        quarantine_step_dir(self.ckpt_dir, step)
        tele = self._telemetry()
        if tele is not None:
            tele.recovery(step, "restore-fallback", directory=self.ckpt_dir)

    def _shrink(self, dead_host: int, *, streak: int,
                resume_step: int | None = None,
                resume: str = "checkpoint") -> None:
        """Drop ``dead_host`` from the gang and re-plan onto the survivors.

        The destructive half of elasticity that is NOT destructive to state:
        nothing is quarantined or deleted — the next attempt restores the
        last verified checkpoint through the reshard-on-restore path (or,
        after a graceful drain, ingests the live handoff and resumes from
        the CURRENT step — ``resume="live-handoff"``), on a gang one host
        narrower. One ``geometry_change`` recovery record ties the evidence
        (dead host, streak) to the action (new geometry, resume source,
        batch policy) for ``dlstatus`` and the span model."""
        from distributeddeeplearningspark_tpu.checkpoint import latest_step_in

        old_n = self.num_processes
        self._hosts.remove(dead_host)
        self.num_processes = len(self._hosts)
        if resume_step is None:
            resume_step = (latest_step_in(self.ckpt_dir)
                           if self.ckpt_dir else None)
        # advisory for workers that want to log/scale on it; the feed math
        # already preserves the global batch by splitting it n-1 ways
        self.env["DLS_ELASTIC_GEOMETRY"] = f"{old_n}:{self.num_processes}"
        logger.warning(
            "shrink-to-survive: host %d blamed by %d consecutive failed "
            "attempt(s) — re-planning the gang %d -> %d process(es) "
            "(survivors: %s), resuming from %s step %s",
            dead_host, streak, old_n, self.num_processes, self._hosts,
            resume, resume_step)
        tele = self._telemetry()
        if tele is not None:
            tele.recovery(
                resume_step, "geometry_change", dead_host=dead_host,
                evidence_attempts=streak, from_processes=old_n,
                to_processes=self.num_processes, hosts=list(self._hosts),
                batch_policy="preserve_global", resume=resume)

    def run(self) -> SupervisorResult:
        attempts: list[Attempt] = []
        fallbacks = 0
        backoff_ordinal = 0  # consecutive fruitless attempts (not launches)
        streak_host: int | None = None
        streak = 0
        try:
            for ordinal in range(self.max_restarts + 1):
                attempt = self._run_attempt(ordinal)
                attempts.append(attempt)
                if attempt.ok:
                    logger.info(
                        "attempt %d succeeded after %.1fs (%d restart(s) total)",
                        ordinal, attempt.duration_s, ordinal,
                    )
                    return SupervisorResult(attempts)
                if attempt.classification == "graceful-shutdown":
                    # a drain is a handoff, not a failure: shrink NOW on the
                    # evidence (no K-attempt streak — the gang told us who is
                    # leaving), resume from the DRAINED step via the live
                    # handoff, and burn no backoff slot relaunching
                    evidence = self._drain_evidence()
                    host, drain_step = (evidence if evidence
                                        else (attempt.dead_host, None))
                    if self.ckpt_dir:
                        consume_drain_evidence(self.ckpt_dir, ordinal=ordinal)
                    # a scheduler-delivered runtime notice is retired the
                    # same way: the shrunk relaunch must not re-drain on
                    # the stale file (the .consumed-<ordinal> rename keeps
                    # it beside the stream for forensics)
                    faults.consume_preempt_notice(
                        self.env.get(
                            faults.PREEMPT_NOTICE_ENV,
                            os.environ.get(faults.PREEMPT_NOTICE_ENV)),
                        ordinal=ordinal)
                    tele = self._telemetry()
                    if tele is not None:
                        tele.recovery(
                            drain_step, "graceful_shutdown", ordinal=ordinal,
                            dead_host=host, drained=True,
                            returncodes=attempt.returncodes)
                    if ordinal >= self.max_restarts:
                        break  # notice arrived with no relaunch budget left
                    logger.warning(
                        "attempt %d drained gracefully at step %s (host %s "
                        "preempted); shrinking and relaunching from the "
                        "live handoff without backoff",
                        ordinal, drain_step, host)
                    if (host is not None and host in self._hosts
                            and self.num_processes > self.min_processes):
                        self._shrink(host, streak=0, resume_step=drain_step,
                                     resume="live-handoff")
                    streak_host, streak = None, 0
                    backoff_ordinal = 0
                    continue
                if attempt.dead_host is not None and attempt.dead_host == streak_host:
                    streak += 1
                elif attempt.dead_host is not None:
                    streak_host, streak = attempt.dead_host, 1
                else:
                    streak_host, streak = None, 0
                if ordinal < self.max_restarts:
                    logger.warning(
                        "attempt %d failed (codes %s, classified %s); "
                        "restarting from checkpoint",
                        ordinal, attempt.returncodes, attempt.classification,
                    )
                    tele = self._telemetry()
                    if tele is not None:
                        # one recovery record per restart decision: the audit
                        # line tying the fault (classification) to the action
                        # (no step — the supervisor doesn't know it, and a
                        # fake one would mislead the dlstatus timeline)
                        # a hang restart names the culprit host the fleet
                        # data localized — "restart (hang)" alone sends the
                        # operator grepping four hosts' logs
                        # dead_host rides along even without a hang culprit
                        # (a crash names one from the first failing rank) so
                        # the incident timeline can attribute every restart,
                        # not just the localized hangs
                        tele.recovery(
                            None, "restart", ordinal=ordinal,
                            classification=attempt.classification,
                            returncodes=attempt.returncodes,
                            **({"dead_host": attempt.dead_host}
                               if attempt.dead_host is not None else {}),
                            **self._culprit_fields(attempt))
                    # destructive fallback only on the EXPLICIT sentinel: the
                    # circumstantial classification (no progress + checkpoint
                    # present) can also fit a deterministic training crash
                    # right after a successful restore, and quarantining a
                    # healthy step there would throw away real work — it
                    # stays a log label + backoff input only
                    if (RESTORE_FAILED_EXIT in attempt.returncodes
                            and self.fallback_on_restore_failure
                            and self.ckpt_dir):
                        if fallbacks < self.max_restore_fallbacks:
                            fallbacks += 1
                            self._fallback_to_previous_step()
                        else:
                            logger.warning(
                                "restore-failure again but %d fallback "
                                "quarantine(s) already spent — relaunching "
                                "against the same step (a transient storage "
                                "error must not eat the retention window)",
                                fallbacks)
                    track = (self._hb_dir is not None
                             or self.progress_path is not None)
                    if attempt.made_progress and track:
                        # OBSERVED progress (not the no-tracking default):
                        # this crash is a fresh incident — restart from the
                        # base delay, not the flaky-era ceiling
                        backoff_ordinal = 0
                    if (self.shrink_after is not None
                            and streak >= self.shrink_after
                            and streak_host is not None
                            and self.num_processes > self.min_processes):
                        self._shrink(streak_host, streak=streak)
                        streak_host, streak = None, 0
                        # new geometry = new incident: fresh backoff ladder
                        backoff_ordinal = 0
                    delay = self._backoff_delay(backoff_ordinal)
                    backoff_ordinal += 1
                    self._emit_attempt("backoff", ordinal + 1, delay_s=delay)
                    time.sleep(delay)
            logger.error("giving up after %d attempt(s)", len(attempts))
            return SupervisorResult(attempts)
        finally:
            if self._tele is not None:
                self._tele.close()
                # a closed writer drops emits by design; a second run() on
                # this Supervisor must get a fresh one, not a dead one
                self._tele = None
            if self._hb_dir is not None:
                import shutil

                shutil.rmtree(self._hb_dir, ignore_errors=True)


# -- MPMD stage pipelines -----------------------------------------------------


@dataclasses.dataclass
class StagePlan:
    """One pipeline stage's launch recipe: the worker command plus any
    stage-specific env (its XLA fake-device count, layout strategy knobs).
    ``argv=None`` uses the built-in env-configured stage worker
    (``python -m distributeddeeplearningspark_tpu.train.pipeline_trainer``).
    """

    argv: list[str] | None = None
    env: dict[str, str] = dataclasses.field(default_factory=dict)

    def command(self) -> list[str]:
        if self.argv is not None:
            return list(self.argv)
        return [sys.executable, "-m",
                "distributeddeeplearningspark_tpu.train.pipeline_trainer"]


@dataclasses.dataclass
class PipelineResult:
    """Per-stage attempt histories for one pipeline run."""

    attempts: dict[int, list[Attempt]]

    @property
    def ok(self) -> bool:
        return bool(self.attempts) and all(
            rows and rows[-1].ok for rows in self.attempts.values())

    def restarts_of(self, stage: int) -> int:
        return max(0, len(self.attempts.get(stage, [])) - 1)


class PipelineSupervisor:
    """Launch and monitor an MPMD stage-pipeline: one independent program
    (gang) per stage, each with its OWN env/mesh, failure domain, and
    checkpoint lineage (docs/PERFORMANCE.md "MPMD pipelines").

    The gang Supervisor above restarts the WHOLE gang on any failure —
    correct for SPMD, where one lost rank poisons every collective. A
    pipeline of gangs fails narrower: stages touch each other only through
    the :mod:`..parallel.mpmd` socket transport, so when stage *k* dies its
    peers merely block (re-listening / re-dialing) while THIS supervisor
    relaunches stage *k* alone with a bumped per-stage ``DLS_RESTART``;
    the reconnected pipeline then agrees on the resume step and rolls back
    to it (``PipelineTransport.sync_step``). Failure attribution is
    per-stage by construction — the dead process names its stage — and
    every attempt/recovery record carries ``stage=`` so ``dlstatus`` shows
    which stage burned the restarts.

    Topology env exported to every stage process: ``DLS_STAGE_ID``,
    ``DLS_NUM_STAGES``, ``DLS_PIPE_PORTS`` (JSON — port *k* carries the
    k↔k+1 link), ``DLS_PIPE_AUTHKEY``, plus the familiar contract
    (``DLS_PROCESS_ID``/``DLS_HOST_ID`` = stage ordinal, ``DLS_RESTART`` =
    per-stage attempt, ``DLS_TELEMETRY_DIR``). ``DLS_FAULT=die_host@N``
    with ``DLS_FAULT_HOST=k`` therefore targets exactly one stage's gang
    — the chaos drill ``tools/ci.sh mpmd`` runs.
    """

    def __init__(self, stages: list[StagePlan], *, max_restarts: int = 3,
                 poll_interval: float = 0.1, restart_backoff_s: float = 0.2,
                 backoff_jitter: float = 0.25,
                 env: dict[str, str] | None = None,
                 telemetry_dir: str | None = None,
                 wall_timeout_s: float | None = None,
                 hang_timeout_s: float | None = None):
        if len(stages) < 2:
            raise ValueError(f"a pipeline needs >= 2 stages, got {len(stages)}")
        self.stages = list(stages)
        self.num_stages = len(stages)
        self.max_restarts = max_restarts
        self.poll_interval = poll_interval
        self.restart_backoff_s = restart_backoff_s
        self.backoff_jitter = backoff_jitter
        self.env = dict(env or {})
        self.wall_timeout_s = wall_timeout_s
        # per-stage heartbeat watchdog: the stage runner stamps
        # DLS_HEARTBEAT_FILE every step, so a stage that is alive but
        # wedged (stuck collective, DLS_FAULT=hang) is killed and
        # restarted ALONE — without this, its healthy peers would burn
        # their transport timeouts and restart budgets being blamed for it
        self.hang_timeout_s = hang_timeout_s
        self._hb_dir: str | None = None
        if hang_timeout_s is not None:
            import tempfile

            self._hb_dir = tempfile.mkdtemp(prefix="dls_pipe_hb_")
        from distributeddeeplearningspark_tpu.parallel import mpmd

        for i, plan in enumerate(self.stages):
            if plan.argv is None and not (
                    mpmd.ENV_SPEC in plan.env
                    or mpmd.ENV_SPEC in self.env
                    or mpmd.ENV_SPEC in os.environ):
                # the built-in worker's ONE required input; without this
                # check every stage dies on a raw KeyError and the
                # supervisor silently burns max_restarts per stage
                raise ValueError(
                    f"stage {i} uses the built-in pipeline worker but no "
                    f"{mpmd.ENV_SPEC} is set (pass it via env= or the "
                    f"StagePlan's env) — the worker cannot boot without "
                    f"its run spec")
        self.ports = [free_port() for _ in range(self.num_stages - 1)]
        import secrets

        self.authkey = secrets.token_hex(16)
        self.telemetry_dir = (
            telemetry_dir
            or self.env.get(telemetry_lib.WORKDIR_ENV)
            or os.environ.get(telemetry_lib.WORKDIR_ENV))
        self._tele: telemetry_lib.EventWriter | None = None
        self._ordinals = [0] * self.num_stages   # per-stage DLS_RESTART
        self._attempt_seq = 0                    # global telemetry ordinal
        self._launch_t0: list[float] = [0.0] * self.num_stages
        self._launch_wall: list[float] = [0.0] * self.num_stages
        self._attempt_ordinal: list[int] = [0] * self.num_stages

    def _telemetry(self) -> telemetry_lib.EventWriter | None:
        if self._tele is None and self.telemetry_dir:
            self._tele = telemetry_lib.EventWriter(
                self.telemetry_dir, process="pipeline-supervisor", host=None)
        return self._tele

    def _stage_env(self, idx: int) -> dict[str, str]:
        from distributeddeeplearningspark_tpu.parallel import mpmd

        env = {
            **os.environ,
            **self.env,
            **self.stages[idx].env,
            mpmd.ENV_STAGE: str(idx),
            mpmd.ENV_NUM_STAGES: str(self.num_stages),
            mpmd.ENV_PORTS: json.dumps(self.ports),
            mpmd.ENV_AUTHKEY: self.authkey,
            "DLS_PROCESS_ID": str(idx),
            "DLS_NUM_PROCESSES": str(self.num_stages),
            "DLS_HOST_ID": str(idx),
            "DLS_RESTART": str(self._ordinals[idx]),
        }
        if self.telemetry_dir:
            env[telemetry_lib.WORKDIR_ENV] = self.telemetry_dir
        if self._hb_dir is not None:
            env["DLS_HEARTBEAT_FILE"] = self._hb_path(idx)
        return env

    def _hb_path(self, idx: int) -> str:
        assert self._hb_dir is not None
        return os.path.join(self._hb_dir, f"hb_{idx}")

    def _hb_stale(self, idx: int, since: float) -> bool:
        """True when stage ``idx`` has produced no heartbeat for
        ``hang_timeout_s`` (measured from its launch until the first
        stamp, then from the last stamp)."""
        assert self.hang_timeout_s is not None
        try:
            mtime = os.stat(self._hb_path(idx)).st_mtime
        except OSError:
            mtime = None
        last = since if mtime is None else max(since, mtime)
        return time.time() - last > self.hang_timeout_s

    def _launch_stage(self, idx: int) -> subprocess.Popen:
        if self._hb_dir is not None:
            # reset the liveness clock: a stale file from the previous
            # attempt must not instantly re-condemn the relaunch
            try:
                os.remove(self._hb_path(idx))
            except OSError:
                pass
        proc = subprocess.Popen(self.stages[idx].command(),
                                env=self._stage_env(idx))
        self._launch_t0[idx] = time.monotonic()
        self._launch_wall[idx] = time.time()
        self._attempt_ordinal[idx] = self._attempt_seq
        tele = self._telemetry()
        if tele is not None:
            tele.attempt("begin", self._attempt_seq, stage=idx,
                         stage_restart=self._ordinals[idx],
                         num_processes=1)
        self._attempt_seq += 1
        logger.info("pipeline: launched stage %d (attempt %d, pid %d)",
                    idx, self._ordinals[idx], proc.pid)
        return proc

    def _finish_attempt(self, idx: int, rc: int, attempts: dict, *,
                        hang: bool = False) -> Attempt:
        cls = ("hang" if hang else
               "clean" if rc == 0 else
               "restore-failure" if rc == RESTORE_FAILED_EXIT
               else "stage-crash")
        att = Attempt(self._ordinals[idx], [rc],
                      time.monotonic() - self._launch_t0[idx],
                      classification=cls, num_processes=1,
                      dead_host=None if rc == 0 else idx)
        attempts.setdefault(idx, []).append(att)
        tele = self._telemetry()
        if tele is not None:
            tele.attempt("end", self._attempt_ordinal[idx], stage=idx,
                         returncodes=[rc], classification=cls,
                         duration_s=att.duration_s, num_processes=1,
                         **({"dead_host": idx} if rc != 0 else {}))
        return att

    def run(self) -> PipelineResult:
        attempts: dict[int, list[Attempt]] = {}
        procs: list[subprocess.Popen | None] = [
            self._launch_stage(i) for i in range(self.num_stages)]
        completed = [False] * self.num_stages
        t0 = time.monotonic()
        try:
            while True:
                progressed = False
                for idx, proc in enumerate(procs):
                    if proc is None:
                        continue
                    rc = proc.poll()
                    hang = False
                    if rc is None:
                        if (self.hang_timeout_s is not None
                                and self._hb_stale(
                                    idx, self._launch_wall[idx])):
                            logger.warning(
                                "pipeline: stage %d heartbeat silent for "
                                ">%.0fs — killing the hung stage (peers "
                                "keep running)", idx, self.hang_timeout_s)
                            hang = True
                            Supervisor._kill([proc])
                            rc = proc.poll()
                        else:
                            continue
                    progressed = True
                    self._finish_attempt(idx, int(rc), attempts, hang=hang)
                    if rc == 0 and not hang:
                        procs[idx] = None
                        completed[idx] = True
                        logger.info("pipeline: stage %d completed", idx)
                        continue
                    if self._ordinals[idx] >= self.max_restarts:
                        logger.error(
                            "pipeline: stage %d failed rc=%s with "
                            "max_restarts=%d exhausted — tearing down",
                            idx, rc, self.max_restarts)
                        self._teardown(procs)
                        return PipelineResult(attempts)
                    delay = min(self.restart_backoff_s
                                * (2.0 ** self._ordinals[idx]), 30.0)
                    if self.backoff_jitter:
                        delay *= 1.0 + random.uniform(-self.backoff_jitter,
                                                      self.backoff_jitter)
                    logger.warning(
                        "pipeline: stage %d died rc=%s — restarting ONLY "
                        "this stage in %.2fs (peers block on the transport)",
                        idx, rc, delay)
                    tele = self._telemetry()
                    if tele is not None:
                        tele.recovery(None, "stage-restart", stage=idx,
                                      returncode=int(rc),
                                      ordinal=self._ordinals[idx] + 1,
                                      delay_s=round(delay, 3))
                    time.sleep(max(0.0, delay))
                    self._ordinals[idx] += 1
                    procs[idx] = self._launch_stage(idx)
                if all(completed):
                    return PipelineResult(attempts)
                if (self.wall_timeout_s is not None
                        and time.monotonic() - t0 > self.wall_timeout_s):
                    logger.error("pipeline: wall timeout after %.0fs",
                                 self.wall_timeout_s)
                    self._teardown(procs)
                    for idx, proc in enumerate(procs):
                        if proc is not None:
                            self._finish_attempt(idx, int(proc.returncode
                                                          or -1), attempts)
                    return PipelineResult(attempts)
                if not progressed:
                    time.sleep(self.poll_interval)
        except BaseException:
            self._teardown(procs)
            raise
        finally:
            if self._tele is not None:
                self._tele.close()
                self._tele = None
            if self._hb_dir is not None:
                import shutil

                shutil.rmtree(self._hb_dir, ignore_errors=True)
                self._hb_dir = None

    @staticmethod
    def _teardown(procs: list) -> None:
        Supervisor._kill([p for p in procs if p is not None])


def main(argv: list[str] | None = None) -> int:
    """``dlsupervise -n N [--max-restarts K] -- worker_cmd ...``"""
    import argparse

    p = argparse.ArgumentParser(
        prog="dlsupervise",
        description="Gang-launch a training command with restart-from-checkpoint.",
    )
    p.add_argument("-n", "--num-processes", type=int, default=1)
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--hang-timeout", type=float, default=None)
    p.add_argument("--progress-path", default=None,
                   help="dir watched for mtime progress (checkpoint dir)")
    p.add_argument("--restart-backoff", type=float, default=0.5,
                   help="base restart delay (doubles per attempt, jittered)")
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint root for the restore-failure fallback "
                        "(defaults to --progress-path)")
    p.add_argument("--no-restore-fallback", action="store_true",
                   help="never quarantine the latest step on restore-failure")
    p.add_argument("--shrink-after", type=int, default=None, metavar="K",
                   help="elastic shrink-to-survive: after K consecutive "
                        "failed attempts blaming the SAME dead host, drop "
                        "it from the gang and relaunch the survivors from "
                        "the last checkpoint (default: disabled)")
    p.add_argument("--min-processes", type=int, default=1,
                   help="never shrink the gang below this width")
    p.add_argument("--telemetry-dir", default=None,
                   help="run workdir for the telemetry event stream "
                        "(defaults to --ckpt-dir/--progress-path); inspect "
                        "with `dlstatus <dir>`")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command (prefix with --)")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        p.error("missing worker command")
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    result = Supervisor(
        cmd,
        num_processes=args.num_processes,
        max_restarts=args.max_restarts,
        hang_timeout_s=args.hang_timeout,
        progress_path=args.progress_path,
        restart_backoff_s=args.restart_backoff,
        ckpt_dir=args.ckpt_dir,
        fallback_on_restore_failure=not args.no_restore_fallback,
        telemetry_dir=args.telemetry_dir,
        shrink_after=args.shrink_after,
        min_processes=args.min_processes,
    ).run()
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
