"""Elastic supervisor — gang launch, failure detection, restart-from-checkpoint.

Spark's headline fault-tolerance story is task-level: a lost executor's
partitions are recomputed from lineage and the job keeps going (SURVEY.md §5
'Failure detection'). Gang-scheduled SPMD has no per-task retry — one lost
process stalls every collective — so the TPU-native equivalent is job-level
elasticity:

1. train with frequent async checkpoints (:mod:`.checkpoint`),
2. detect a dead/failed worker (process exit, or a missed heartbeat when a
   hang never surfaces as an exit),
3. tear the gang down and relaunch it; workers resume from the latest
   checkpoint (their driver scripts call ``Trainer.restore``).

The supervisor is deliberately dumb — launch, watch, kill, relaunch — because
all actual state lives in checkpoints. Workers rendezvous through
``jax.distributed`` using the ``DLS_*`` env contract that
:class:`~.session.Session` already auto-consumes (session.py
``_create_session``): ``DLS_COORDINATOR``, ``DLS_NUM_PROCESSES``,
``DLS_PROCESS_ID``. The supervisor additionally exports ``DLS_RESTART`` (the
attempt ordinal) so tests can inject faults on attempt 0 only.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import socket
import subprocess
import sys
import time

logger = logging.getLogger("distributeddeeplearningspark_tpu.supervisor")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class Attempt:
    """Outcome of one gang launch."""

    ordinal: int
    returncodes: list[int]
    duration_s: float

    @property
    def ok(self) -> bool:
        return all(rc == 0 for rc in self.returncodes)


@dataclasses.dataclass
class SupervisorResult:
    attempts: list[Attempt]

    @property
    def ok(self) -> bool:
        return bool(self.attempts) and self.attempts[-1].ok

    @property
    def restarts(self) -> int:
        return max(0, len(self.attempts) - 1)


class Supervisor:
    """Launch ``num_processes`` copies of a worker command as one gang.

    ``argv`` is the worker command (e.g. ``[sys.executable, "train.py",
    "--resume"]``); every process gets the rendezvous env plus its own
    ``DLS_PROCESS_ID``. Any non-zero exit (or death by signal) fails the whole
    attempt: survivors are terminated and, up to ``max_restarts`` times, the
    gang is relaunched on a fresh coordinator port.

    ``poll_interval`` bounds failure-detection latency; ``hang_timeout_s``
    (optional) additionally fails an attempt whose processes are all alive but
    have produced no progress for too long — the hang case NCCL users know as
    the silent stuck all-reduce. Progress is observed as mtime changes under
    ``progress_path`` (typically the checkpoint dir), the same signal a human
    operator would watch.
    """

    def __init__(
        self,
        argv: list[str],
        *,
        num_processes: int = 1,
        max_restarts: int = 3,
        env: dict[str, str] | None = None,
        poll_interval: float = 0.2,
        restart_backoff_s: float = 0.5,
        hang_timeout_s: float | None = None,
        progress_path: str | None = None,
        startup_grace_s: float | None = None,
    ):
        self.argv = list(argv)
        self.num_processes = num_processes
        self.max_restarts = max_restarts
        self.env = dict(env or {})
        self.poll_interval = poll_interval
        self.restart_backoff_s = restart_backoff_s
        self.hang_timeout_s = hang_timeout_s
        self.progress_path = progress_path
        # First-progress latency includes JIT compile + checkpoint_every steps,
        # which can dwarf the steady-state checkpoint cadence — give startup
        # its own (longer) window so a healthy gang isn't killed mid-compile.
        # Default: 5× the hang timeout.
        self.startup_grace_s = (
            startup_grace_s if startup_grace_s is not None
            else (hang_timeout_s * 5.0 if hang_timeout_s is not None else None)
        )
        # Per-process heartbeat files (ADVICE r1: checkpoint-dir mtimes alone
        # can't tell "training between checkpoints" from "spinning"): workers
        # touch DLS_HEARTBEAT_FILE at every metrics lap (Trainer.fit does it
        # automatically), and the stamp below folds those mtimes in.
        self._hb_dir: str | None = None
        if hang_timeout_s is not None:
            import tempfile

            self._hb_dir = tempfile.mkdtemp(prefix="dls_hb_")

    # -- one gang ------------------------------------------------------------

    def _launch(self, ordinal: int) -> list[subprocess.Popen]:
        port = free_port()
        procs = []
        for pid in range(self.num_processes):
            env = {
                **os.environ,
                **self.env,
                "DLS_COORDINATOR": f"localhost:{port}",
                "DLS_NUM_PROCESSES": str(self.num_processes),
                "DLS_PROCESS_ID": str(pid),
                "DLS_RESTART": str(ordinal),
            }
            if self._hb_dir is not None:
                env["DLS_HEARTBEAT_FILE"] = os.path.join(
                    self._hb_dir, f"hb_{pid}")
            procs.append(subprocess.Popen(self.argv, env=env))
        logger.info(
            "attempt %d: launched %d worker(s) (coordinator :%d)",
            ordinal, self.num_processes, port,
        )
        return procs

    def _progress_stamp(self) -> float:
        """Newest mtime among heartbeat files, progress_path, and its
        immediate children.

        Deliberately shallow: an orbax step dir appears by atomic rename at
        finalize (bumping the parent and step-dir mtimes), so one level is
        enough — recursing into thousands of tensorstore chunk files every
        poll would hammer the filesystem.
        """
        latest = 0.0
        for d in (self._hb_dir, self.progress_path):
            if not d or not os.path.exists(d):
                continue
            try:
                with os.scandir(d) as it:
                    latest = max(latest, os.stat(d).st_mtime)
                    for entry in it:
                        try:
                            latest = max(latest, entry.stat().st_mtime)
                        except OSError:
                            pass
            except OSError:
                pass
        return latest

    def _run_attempt(self, ordinal: int) -> Attempt:
        t0 = time.monotonic()
        procs = self._launch(ordinal)
        last_progress = time.monotonic()
        stamp = self._progress_stamp()
        seen_progress = False
        try:
            while True:
                codes = [p.poll() for p in procs]
                if all(c is not None for c in codes):
                    return Attempt(ordinal, [int(c) for c in codes], time.monotonic() - t0)
                if any(c is not None and c != 0 for c in codes):
                    failed = [i for i, c in enumerate(codes) if c not in (None, 0)]
                    logger.warning(
                        "attempt %d: worker(s) %s failed (codes %s); killing gang",
                        ordinal, failed, [codes[i] for i in failed],
                    )
                    self._kill(procs)
                    codes = [p.wait() for p in procs]
                    return Attempt(ordinal, [int(c) for c in codes], time.monotonic() - t0)
                if self.hang_timeout_s is not None:
                    now_stamp = self._progress_stamp()
                    limit = (self.hang_timeout_s if seen_progress
                             else self.startup_grace_s)
                    if now_stamp > stamp:
                        stamp, last_progress = now_stamp, time.monotonic()
                        seen_progress = True
                    elif time.monotonic() - last_progress > limit:
                        logger.warning(
                            "attempt %d: no progress for %.1fs (%s); killing hung gang",
                            ordinal, limit,
                            "steady state" if seen_progress else "startup grace",
                        )
                        self._kill(procs)
                        codes = [p.wait() for p in procs]
                        return Attempt(ordinal, [int(c) for c in codes], time.monotonic() - t0)
                time.sleep(self.poll_interval)
        except BaseException:
            self._kill(procs)
            raise

    @staticmethod
    def _kill(procs: list[subprocess.Popen]) -> None:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    # -- the restart loop ----------------------------------------------------

    def run(self) -> SupervisorResult:
        attempts: list[Attempt] = []
        try:
            for ordinal in range(self.max_restarts + 1):
                attempt = self._run_attempt(ordinal)
                attempts.append(attempt)
                if attempt.ok:
                    logger.info(
                        "attempt %d succeeded after %.1fs (%d restart(s) total)",
                        ordinal, attempt.duration_s, ordinal,
                    )
                    return SupervisorResult(attempts)
                if ordinal < self.max_restarts:
                    logger.warning(
                        "attempt %d failed (codes %s); restarting from latest checkpoint",
                        ordinal, attempt.returncodes,
                    )
                    time.sleep(self.restart_backoff_s)
            logger.error("giving up after %d attempt(s)", len(attempts))
            return SupervisorResult(attempts)
        finally:
            if self._hb_dir is not None:
                import shutil

                shutil.rmtree(self._hb_dir, ignore_errors=True)


def main(argv: list[str] | None = None) -> int:
    """``dlsupervise -n N [--max-restarts K] -- worker_cmd ...``"""
    import argparse

    p = argparse.ArgumentParser(
        prog="dlsupervise",
        description="Gang-launch a training command with restart-from-checkpoint.",
    )
    p.add_argument("-n", "--num-processes", type=int, default=1)
    p.add_argument("--max-restarts", type=int, default=3)
    p.add_argument("--hang-timeout", type=float, default=None)
    p.add_argument("--progress-path", default=None,
                   help="dir watched for mtime progress (checkpoint dir)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command (prefix with --)")
    args = p.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        p.error("missing worker command")
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    result = Supervisor(
        cmd,
        num_processes=args.num_processes,
        max_restarts=args.max_restarts,
        hang_timeout_s=args.hang_timeout,
        progress_path=args.progress_path,
    ).run()
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
