"""Data plane: partition feeds, HBM prefetch, and workload dataset sources."""

from distributeddeeplearningspark_tpu.data.feed import (
    device_batches,
    host_batches,
    put_global,
    stack_examples,
)
from distributeddeeplearningspark_tpu.data.dataframe import (
    Column,
    DataFrame,
    DataFrameReader,
    col,
    from_dataset,
    from_rows,
    hash_bucket,
    lit,
    log1p,
    read_csv,
    read_parquet,
    when,
)
from distributeddeeplearningspark_tpu.data.prefetch import prefetch_to_device

__all__ = [
    "device_batches",
    "host_batches",
    "put_global",
    "stack_examples",
    "prefetch_to_device",
    "Column",
    "DataFrame",
    "DataFrameReader",
    "col",
    "from_dataset",
    "from_rows",
    "hash_bucket",
    "lit",
    "log1p",
    "read_csv",
    "read_parquet",
    "when",
]
