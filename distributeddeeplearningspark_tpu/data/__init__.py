"""Data plane: partition feeds, HBM prefetch, and workload dataset sources."""

from distributeddeeplearningspark_tpu.data.feed import (
    device_batches,
    host_batches,
    put_global,
    stack_examples,
)
from distributeddeeplearningspark_tpu.data.dataframe import (
    Column,
    DataFrame,
    DataFrameReader,
    GroupedData,
    col,
    from_dataset,
    from_rows,
    hash_bucket,
    lit,
    log1p,
    read_csv,
    read_parquet,
    when,
)
from distributeddeeplearningspark_tpu.data.prefetch import prefetch_to_device
from distributeddeeplearningspark_tpu.data.records import (
    array_records,
    write_array_records,
    write_imagenet_records,
)

__all__ = [
    "device_batches",
    "host_batches",
    "put_global",
    "stack_examples",
    "prefetch_to_device",
    "array_records",
    "write_array_records",
    "write_imagenet_records",
    "Column",
    "DataFrame",
    "DataFrameReader",
    "GroupedData",
    "col",
    "from_dataset",
    "from_rows",
    "hash_bucket",
    "lit",
    "log1p",
    "read_csv",
    "read_parquet",
    "when",
]
