"""Data plane: partition feeds, HBM prefetch, and workload dataset sources."""

from distributeddeeplearningspark_tpu.data.feed import (
    device_batches,
    host_batches,
    put_global,
    stack_examples,
)
from distributeddeeplearningspark_tpu.data.prefetch import prefetch_to_device

__all__ = [
    "device_batches",
    "host_batches",
    "put_global",
    "stack_examples",
    "prefetch_to_device",
]
