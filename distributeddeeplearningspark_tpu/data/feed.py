"""Partition → mesh feed: global batches laid out with batch sharding.

The reference streams RDD partition iterators into each executor's GPU
(SURVEY.md §1 L5). Here, partitions are host-side iterators of example dicts
(``{"image": ..., "label": ...}``, numpy); this module assembles them into
*global* batches and places them on the mesh with the leading axis sharded
over (data, fsdp) — the GSPMD equivalent of "each executor trains on its
partition".

Two assembly modes:

- **aligned** (default when ``num_partitions`` divides evenly into the data
  shards): partition *i* feeds data shard ``i % num_shards``, preserving
  Spark's partition↔task pairing — shard-local data stays shard-local.
- **chained**: partitions are concatenated into one stream and dealt out in
  order. Used when partition count and mesh shape don't line up.

Multi-process placement uses ``jax.make_array_from_process_local_data`` so
each host only materializes its addressable shard of the global batch.
"""

from __future__ import annotations

import itertools
from typing import Any, Iterable, Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributeddeeplearningspark_tpu.parallel.mesh import BATCH_AXES, num_data_shards
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset


def process_shard_range(num_shards: int) -> tuple[int, int] | None:
    """This process's data-shard slice [lo, hi), or None when single-process.

    Mesh device order is process-major (jax.devices() sorts by id, ids are
    assigned per process), so each process's addressable batch rows are one
    contiguous run of shards.
    """
    pc = jax.process_count()
    if pc == 1:
        return None
    if num_shards % pc:
        raise ValueError(
            f"data shards ({num_shards}) must divide evenly across {pc} processes"
        )
    spp = num_shards // pc
    return (jax.process_index() * spp, (jax.process_index() + 1) * spp)


def stack_examples(examples: list[dict[str, Any]]) -> dict[str, np.ndarray]:
    keys = examples[0].keys()
    try:
        return {k: np.stack([np.asarray(e[k]) for e in examples])
                for k in keys}
    except KeyError as e:
        # an ETL stream that mis-joins features (e.g. a DLRM pipeline
        # unioning positive/negative example sources with different
        # fields) fails here with a bare KeyError that names neither the
        # batch nor the fix — diagnose the schema drift instead
        schemas = {tuple(sorted(ex.keys())) for ex in examples}
        raise ValueError(
            f"batch examples disagree on their keys (missing {e}); "
            f"schemas in this batch: {sorted(schemas)} — every example "
            f"dict in a stream must carry the same fields") from e


def _round_robin(iters: list[Iterator]) -> Iterator:
    """Deal elements from iterators in turn; drained ones drop out so uneven
    partitions lose no data (matches Spark consuming every partition fully)."""
    active = list(iters)
    while active:
        still = []
        for it in active:
            try:
                yield next(it)
                still.append(it)
            except StopIteration:
                pass
        active = still


def _pad_to_shards(
    rest: list[dict[str, Any]], num_shards: int
) -> dict[str, np.ndarray]:
    """Stack a sub-shard remainder padded to a ``num_shards`` multiple.

    Pad rows are copies of row 0 carrying ``eval_mask == 0.0`` (real rows
    carry 1.0); every contract loss downweights masked rows to exactly
    nothing (train/losses.py), so the padded batch's weighted metrics equal
    the unpadded remainder's — GSPMD gets its equal shard sizes without a
    single dropped row (VERDICT r3 missing-#5).
    """
    n = len(rest)
    target = -(-n // num_shards) * num_shards
    batch = stack_examples(rest + [rest[0]] * (target - n))
    if "eval_mask" in batch:
        raise ValueError(
            "'eval_mask' is reserved for remainder padding — rename the "
            "dataset key or pass pad_remainder=False")
    batch["eval_mask"] = (np.arange(target) < n).astype(np.float32)
    return batch


def host_batches(
    dataset: PartitionedDataset,
    batch_size: int,
    *,
    num_shards: int = 1,
    drop_remainder: bool = True,
    shard_range: tuple[int, int] | None = None,
    pad_remainder: bool = False,
    num_workers: int | None = None,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield stacked host batches from an RDD of example dicts.

    ``num_workers`` overrides the worker-process count of a pool-backed
    dataset (:class:`~.workers.WorkerMappedDataset`, e.g. from
    ``imagenet_train(num_workers=...)``): the per-example map fans out over
    that many processes with shared-memory delivery, and ``stack_examples``
    stacks the ring views straight into the batch. ``None`` keeps the
    dataset's own setting (ultimately ``DLS_DATA_WORKERS``); 0 forces the
    in-process path. The batch stream is byte-identical either way —
    ordered delivery is part of the pool contract — so this knob is pure
    throughput. On a dataset without a pool spec it is ignored (there is
    no map to fan out).

    ``shard_range=(lo, hi)`` restricts output to data shards [lo, hi) — the
    multi-process mode: each host STACKS only the rows its own devices will
    hold (``batch_size`` stays the GLOBAL batch size), as each Spark executor
    trains only its own partitions. Every host still *advances* all shard
    streams in lockstep so that end-of-data is decided identically everywhere
    — uneven shards must never let one host yield a batch its peers don't,
    or the stragglers hang in the next collective. The partition→shard mapping
    is global (partition *i* → shard ``i % num_shards``).

    ``pad_remainder`` (eval exactness): a final batch that cannot fill every
    shard equally is padded with ``eval_mask == 0`` rows instead of dropping
    the sub-shard tail (see :func:`_pad_to_shards`) — including in
    multi-process mode, where the tail was previously dropped whole.
    """
    if num_workers is not None and hasattr(dataset, "with_num_workers"):
        dataset = dataset.with_num_workers(num_workers)
    n_parts = dataset.num_partitions
    lo, hi = shard_range if shard_range is not None else (0, num_shards)

    def checked(batch: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
        """Non-padded yields under pad_remainder: the reserved key must be
        rejected on EVERY batch, not only when a remainder happens to occur
        — otherwise a dataset carrying its own 'eval_mask' column is
        silently reinterpreted as pad weights on exactly-divisible sizes
        and errors data-size-dependently on others."""
        if pad_remainder and "eval_mask" in batch:
            raise ValueError(
                "'eval_mask' is reserved for remainder padding — rename "
                "the dataset key or pass pad_remainder=False")
        return batch
    if shard_range is not None and batch_size % num_shards:
        raise ValueError(
            f"multi-process feed needs batch_size ({batch_size}) divisible by "
            f"num_shards ({num_shards})"
        )
    aligned = n_parts % num_shards == 0 and batch_size % num_shards == 0
    if aligned and n_parts > 1:
        # partition i → shard (i % num_shards); lockstep draw keeps pairing.
        per_shard = batch_size // num_shards
        # Infinite dataset (.repeat(), the training config): end-of-data can
        # never need cross-host agreement, so this host opens and walks ONLY
        # its own shards' partitions — per-host-local input IO at pod scale
        # (VERDICT r1 weak-5: the lockstep walk is for finite datasets only).
        local_only = (getattr(dataset, "is_infinite", False)
                      and shard_range is not None)
        groups: list[list[Iterator] | None] = [None] * num_shards
        for s in range(num_shards):
            if local_only and not (lo <= s < hi):
                continue
            groups[s] = [dataset.iter_partition(i)
                         for i in range(s, n_parts, num_shards)]
        shard_streams = [
            None if g is None else (_round_robin(g) if len(g) > 1 else g[0])
            for g in groups]
        while True:
            shard_chunks = []
            short = False
            for s in shard_streams:
                if s is None:  # non-local shard of an infinite dataset
                    shard_chunks.append([])
                    continue
                chunk = list(itertools.islice(s, per_shard))
                if len(chunk) < per_shard:
                    short = True
                shard_chunks.append(chunk)
            if short:
                rest = [e for chunk in shard_chunks for e in chunk]
                if not drop_remainder and pad_remainder and rest:
                    batch = _pad_to_shards(rest, num_shards)
                    if shard_range is not None:
                        per = batch["eval_mask"].shape[0] // num_shards
                        batch = {k: v[lo * per:hi * per]
                                 for k, v in batch.items()}
                    yield batch
                elif not drop_remainder and shard_range is None:
                    # legacy mode: keep only what divides evenly across
                    # shards (GSPMD needs equal shard sizes)
                    keep = len(rest) - len(rest) % num_shards
                    if keep:
                        yield stack_examples(rest[:keep])
                return
            yield checked(stack_examples(
                [e for chunk in shard_chunks[lo:hi] for e in chunk]
            ))
    else:
        # chained fallback: every host walks the same global stream in order
        # and keeps only its shards' rows — correct but not bandwidth-minimal;
        # align partitions to shards to avoid it.
        per_shard = batch_size // num_shards if batch_size % num_shards == 0 else None
        stream = itertools.chain.from_iterable(
            dataset.iter_partition(i) for i in range(n_parts)
        )
        while True:
            chunk = list(itertools.islice(stream, batch_size))
            if len(chunk) < batch_size:
                if chunk and not drop_remainder:
                    if pad_remainder:
                        batch = _pad_to_shards(chunk, num_shards)
                        if shard_range is not None:
                            per = batch["eval_mask"].shape[0] // num_shards
                            batch = {k: v[lo * per:hi * per]
                                     for k, v in batch.items()}
                        yield batch
                    elif shard_range is None:
                        yield stack_examples(chunk)
                return
            if shard_range is not None:
                assert per_shard is not None
                chunk = chunk[lo * per_shard:hi * per_shard]
            out = checked(stack_examples(chunk))
            # release the example refs BEFORE the next islice refill: a
            # worker-pool dataset's examples are views into the shared-
            # memory ring (data/workers.py), and holding a full batch of
            # them across the refill would make the ring carry 2× the
            # batch bytes and stall on backpressure
            chunk.clear()
            yield out


def put_global(
    batch: dict[str, np.ndarray], mesh: Mesh, *, seq_sharded: bool = False
) -> dict[str, jax.Array]:
    """Place a host batch onto the mesh with batch sharding.

    Single-process: a plain sharded ``device_put`` (XLA slices per device).
    Multi-process: each process passes its *local* rows and JAX assembles the
    global array — the moral replacement for "each executor reads its own
    partition" with zero driver round-trip.

    ``seq_sharded`` (context parallelism): rank≥2 leaves additionally split
    dim 1 over the ``seq`` mesh axis; rank-1 leaves stay batch-only.
    """
    from distributeddeeplearningspark_tpu.parallel.mesh import batch_sharding

    def sharding_for(v) -> NamedSharding:
        return batch_sharding(mesh, np.ndim(v), seq_sharded=seq_sharded)

    if jax.process_count() > 1:
        return {
            k: jax.make_array_from_process_local_data(sharding_for(v), v)
            for k, v in batch.items()
        }
    return {k: jax.device_put(v, sharding_for(v)) for k, v in batch.items()}


def device_batches(
    dataset: PartitionedDataset,
    mesh: Mesh,
    batch_size: int,
    *,
    drop_remainder: bool = True,
    probe=None,
    num_workers: int | None = None,
) -> Iterator[dict[str, jax.Array]]:
    """host_batches → sharded device arrays (no prefetch; see prefetch.py).

    ``probe`` (a :class:`~.prefetch.StarvationProbe`) times each host-batch
    assembly — on this unbuffered path every assembly blocks the consumer,
    so the same wait the prefetch ring would hide is measured directly.
    ``num_workers`` passes through to :func:`host_batches` (worker-pool
    override for pool-backed datasets).
    """
    nshards = num_data_shards(mesh)
    hb: Iterator[dict[str, np.ndarray]] = host_batches(
        dataset, batch_size, num_shards=nshards, drop_remainder=drop_remainder,
        shard_range=process_shard_range(nshards), num_workers=num_workers,
    )
    if probe is not None:
        hb = probe.timed(hb)
    for b in hb:
        yield put_global(b, mesh)
