"""Partition → mesh feed: global batches laid out with batch sharding.

The reference streams RDD partition iterators into each executor's GPU
(SURVEY.md §1 L5). Here, partitions are host-side iterators of example dicts
(``{"image": ..., "label": ...}``, numpy); this module assembles them into
*global* batches and places them on the mesh with the leading axis sharded
over (data, fsdp) — the GSPMD equivalent of "each executor trains on its
partition".

Two assembly modes:

- **aligned** (default when ``num_partitions`` divides evenly into the data
  shards): partition *i* feeds data shard ``i % num_shards``, preserving
  Spark's partition↔task pairing — shard-local data stays shard-local.
- **chained**: partitions are concatenated into one stream and dealt out in
  order. Used when partition count and mesh shape don't line up.

Multi-process placement uses ``jax.make_array_from_process_local_data`` so
each host only materializes its addressable shard of the global batch.
"""

from __future__ import annotations

import itertools
from typing import Any, Iterable, Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributeddeeplearningspark_tpu.parallel.mesh import BATCH_AXES, num_data_shards
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset


def stack_examples(examples: list[dict[str, Any]]) -> dict[str, np.ndarray]:
    keys = examples[0].keys()
    return {k: np.stack([np.asarray(e[k]) for e in examples]) for k in keys}


def _round_robin(iters: list[Iterator]) -> Iterator:
    """Deal elements from iterators in turn; drained ones drop out so uneven
    partitions lose no data (matches Spark consuming every partition fully)."""
    active = list(iters)
    while active:
        still = []
        for it in active:
            try:
                yield next(it)
                still.append(it)
            except StopIteration:
                pass
        active = still


def host_batches(
    dataset: PartitionedDataset,
    batch_size: int,
    *,
    num_shards: int = 1,
    drop_remainder: bool = True,
) -> Iterator[dict[str, np.ndarray]]:
    """Yield stacked global host batches from an RDD of example dicts."""
    n_parts = dataset.num_partitions
    aligned = n_parts % num_shards == 0 and batch_size % num_shards == 0
    if aligned and n_parts > 1:
        # partition i → shard (i % num_shards); lockstep draw keeps pairing.
        per_shard = batch_size // num_shards
        groups: list[list[Iterator]] = [[] for _ in range(num_shards)]
        for i in range(n_parts):
            groups[i % num_shards].append(dataset.iter_partition(i))
        shard_streams = [_round_robin(g) if len(g) > 1 else g[0] for g in groups]
        while True:
            shard_chunks = []
            short = False
            for s in shard_streams:
                chunk = list(itertools.islice(s, per_shard))
                if len(chunk) < per_shard:
                    short = True
                shard_chunks.append(chunk)
            if short:
                # Partial final batch: only meaningful if it still divides
                # evenly across shards (GSPMD needs equal shard sizes).
                if not drop_remainder:
                    rest = [e for chunk in shard_chunks for e in chunk]
                    keep = len(rest) - len(rest) % num_shards
                    if keep:
                        yield stack_examples(rest[:keep])
                return
            yield stack_examples([e for chunk in shard_chunks for e in chunk])
    else:
        stream = itertools.chain.from_iterable(
            dataset.iter_partition(i) for i in range(n_parts)
        )
        while True:
            chunk = list(itertools.islice(stream, batch_size))
            if len(chunk) < batch_size:
                if chunk and not drop_remainder:
                    yield stack_examples(chunk)
                return
            yield stack_examples(chunk)


def put_global(batch: dict[str, np.ndarray], mesh: Mesh) -> dict[str, jax.Array]:
    """Place a host batch onto the mesh with batch sharding.

    Single-process: a plain sharded ``device_put`` (XLA slices per device).
    Multi-process: each process passes its *local* rows and JAX assembles the
    global array — the moral replacement for "each executor reads its own
    partition" with zero driver round-trip.
    """
    sharding = NamedSharding(mesh, P(BATCH_AXES))
    if jax.process_count() > 1:
        return {
            k: jax.make_array_from_process_local_data(sharding, v) for k, v in batch.items()
        }
    return jax.device_put(batch, sharding)


def device_batches(
    dataset: PartitionedDataset,
    mesh: Mesh,
    batch_size: int,
    *,
    drop_remainder: bool = True,
) -> Iterator[dict[str, jax.Array]]:
    """host_batches → sharded device arrays (no prefetch; see prefetch.py)."""
    for hb in host_batches(
        dataset, batch_size, num_shards=num_data_shards(mesh), drop_remainder=drop_remainder
    ):
        yield put_global(hb, mesh)
