"""Multi-process input-pipeline worker pool — the host-side map, scaled out.

BENCH_r05 measured the JPEG input path at 51.8 images/sec/host with
``nproc: 1``: every decode/resize/crop/normalize ran on one Python thread,
and the only concurrency in the whole feed was the lone ``dls-prefetch``
daemon thread. This module is the fix PR 2's :class:`~.prefetch.
StarvationProbe` measures the need for: the per-example decode/augment map
(the Spark partitioned-map, executed host-side) fans out over ``N`` worker
*processes* — real cores, no GIL — with three contracts the rest of the
stack depends on:

- **Deterministic, seed-stable ordered delivery.** Worker ``w`` of ``N``
  processes exactly the elements ``j`` with ``j % N == w`` of the source
  stream, and the consumer reassembles ``j = 0, 1, 2, ...`` by round-robin
  over per-worker FIFO queues — so the mapped stream is byte-identical for
  ANY ``num_workers`` (including 0, the in-process path), and checkpoint
  fast-forward resume (Trainer ``skip_batches``) stays reproducible.
  Augmentation randomness is content-seeded per example (vision.py), so
  scheduling cannot change a single output byte.
- **Shared-memory batch assembly, no pickling of pixel data.** Each worker
  owns an arena of ``multiprocessing.shared_memory`` bytes; decoded
  uint8/float32 planes are written there and only a tiny metadata record
  (key, dtype, shape, offset) crosses the queue. The consumer wraps numpy
  views over the arena and stacks them straight into the batch buffer —
  one copy total, into the batch, never through pickle. Allocations free
  themselves when the views are garbage-collected (CPython refcounting:
  right after ``np.stack``; out-of-order frees reclaim immediately —
  first-fit intervals, not a FIFO ring), and when a batch is too big to
  hold as views (``batch_size/num_workers`` × example bytes vs
  ``DLS_DATA_WORKER_RING_MB``) the consumer adaptively copies-and-releases
  so the worker never stalls. Backpressure is the arena plus a bounded
  metadata queue: a slow consumer parks the workers, memory stays capped.
- **Crash recovery: a dead worker respawns; a raising one propagates.**
  A worker that *dies* (OOM-kill, segfault) is detected by liveness
  polling and — within the ``DLS_DATA_WORKER_MAX_RETRIES`` budget
  (default 2, ISSUE 14) — replaced in place: a fresh process takes over
  the same residue class with a fresh arena and queues (the dead
  consumer pipe may hold a frame its feeder tore mid-write), fast-
  forwarded past the examples already delivered, so ordered
  byte-identical delivery resumes exactly where the stream left off (the
  determinism contract above is what makes the replay safe — lost
  in-flight examples regenerate bit-equal). Each respawn emits a
  ``recovery`` telemetry event. Past the budget — or when a worker
  *raises* (user decode code, deterministic on this input; a retry would
  just raise again) — the consumer raises a typed :class:`WorkerCrashed`
  within a bounded wait, and the PR 1 supervisor classifies the run as a
  training CRASH (nonzero exit with the error on stderr), not a hang,
  because the exception propagates out of ``Trainer.fit`` like any other
  training error. A worker that is alive but *stuck* (``fn`` blocked on
  dead NFS, a lock taken pre-fork) is indistinguishable from a slow map
  and is deliberately NOT timed out — any per-example deadline would
  misfire on legitimately slow work; it surfaces instead through the
  per-worker utilization gauges and the supervisor's own hang detection,
  whose job that is.

Workers are started with the ``fork`` start method: the map ``fn`` and the
source partition are ordinary closures (lambdas over tokenizers, transform
configs, record paths) that fork inherits for free and spawn could never
pickle. Children run numpy/PIL/the native C kernels only — never JAX — and
the native ``parallel_for`` spawns threads per call (csrc/dls_native.cc),
so there is no pre-fork thread pool to lose. Where fork is unavailable the
pool degrades to the serial in-process map with a one-time warning: same
bytes, no speedup.

Each worker re-iterates its partition's *source* and maps only its residue
class — no input pickling, no dispatcher thread. That duplicates the cheap
source walk (file reads / record seeks, page-cached) ``k``× per partition
and is the right trade while the map (JPEG decode ~20 ms) dominates it by
orders of magnitude; materialize records (data/records.py) first if your
source walk is the expensive part.

Sizing: one decoded 500px JPEG is ~750 KB, a 224px float32 plane ~600 KB;
the default 32 MB ring per worker (``DLS_DATA_WORKER_RING_MB``) holds
~40–50 in-flight examples, and tmpfs allocates pages only when touched.
An example that cannot get ring space within a bounded wait (consumer
holding too many views, or bigger than the whole ring) falls back to queue
transport — pickled, slower, counted in the ``overflow`` gauge — so
liveness never depends on ring capacity.
"""

from __future__ import annotations

import bisect
import multiprocessing as mp
import os
import queue as queue_lib
import time
import traceback
import uuid
import warnings
import weakref
from multiprocessing import shared_memory
from typing import Any, Callable, Iterable, Iterator

import numpy as np

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

#: env knob: default worker count when ``num_workers=None`` (0 = in-process).
WORKERS_ENV = "DLS_DATA_WORKERS"
#: env knob: shared-memory ring size per worker, in MB.
RING_MB_ENV = "DLS_DATA_WORKER_RING_MB"
#: env knob: how many SIGKILL'd workers one pool may respawn before a
#: death escalates to the typed WorkerCrashed (0 = today's fail-fast).
INPUT_RETRIES_ENV = "DLS_DATA_WORKER_MAX_RETRIES"
_DEFAULT_INPUT_RETRIES = 2

_DEFAULT_RING_MB = 32
#: metadata-queue bound = max mapped examples in flight per worker beyond
#: the ring — the item-count half of the backpressure contract. 32 × 20 ms
#: decodes ≈ 640 ms of lookahead; more just bloats the ring/queue.
_DEFAULT_MAX_AHEAD = 32
#: arrays below this ride the metadata queue (labels, scalars); at/above it
#: they go through shared memory (pixel/token planes).
_SHM_MIN_BYTES = 256
_ALIGN = 64
#: how long a worker waits for ring space before the pickle fallback. Kept
#: short: frees arrive in bulk at batch boundaries (the feed clears its
#: example refs before refilling), so mid-batch fullness means the ring is
#: genuinely undersized for batch_size/num_workers and queue transport
#: (one extra memcpy-scale pickle, ~2 ms vs ~20 ms decode) beats stalling.
_ALLOC_WAIT_S = 0.25
#: consumer liveness-poll interval while waiting on a worker queue.
_POLL_S = 0.2

# stats array layout (one float64 stride per worker, single-writer cells:
# the worker owns all four, the consumer only reads)
_ST_BUSY, _ST_PRODUCED, _ST_OVERFLOW, _ST_RING_USED, _ST_STRIDE = 0, 1, 2, 3, 4

#: transport wrapper for non-dict map results (token arrays, scalars).
_VALUE_KEY = "__dls_pool_value__"

#: live pools, for telemetry aggregation (prefetch.StarvationProbe.snapshot
#: merges pool_gauges() so dlstatus can tell pool-bound from consumer-bound).
_LIVE_POOLS: "weakref.WeakSet[WorkerPool]" = weakref.WeakSet()


def resolve_num_workers(num_workers: int | None) -> int:
    """Explicit value wins; ``None`` reads ``DLS_DATA_WORKERS`` (default 0 =
    today's in-process path, unchanged)."""
    if num_workers is not None:
        return max(0, int(num_workers))
    try:
        return max(0, int(os.environ.get(WORKERS_ENV, "0") or 0))
    except ValueError:
        warnings.warn(f"ignoring non-integer {WORKERS_ENV}="
                      f"{os.environ.get(WORKERS_ENV)!r}")
        return 0


def _ring_bytes(override: int | None) -> int:
    if override is not None:
        return max(1 << 20, int(override))
    try:
        mb = float(os.environ.get(RING_MB_ENV, "") or _DEFAULT_RING_MB)
    except ValueError:
        mb = _DEFAULT_RING_MB
    return max(1 << 20, int(mb * (1 << 20)))


def fork_available() -> bool:
    return "fork" in mp.get_all_start_methods()


def env_num(name: str, default, lo=None, cast=int):
    """The shared env-knob parse contract (used here and by
    data/exchange.py's retry/blacklist/speculation knobs): empty or
    malformed values fall back to the default silently — tuning knobs
    must never crash a run, unlike fault SPECS (faults.parse), where a
    typo'd drill must fail loudly — and ``lo`` clamps the floor."""
    try:
        v = cast(os.environ.get(name, "") or default)
    except ValueError:
        v = default
    return v if lo is None else max(lo, v)


def input_worker_retries(explicit: int | None = None) -> int:
    """The pool's respawn budget: explicit value, else
    ``DLS_DATA_WORKER_MAX_RETRIES``, else 2."""
    if explicit is not None:
        return max(0, int(explicit))
    return env_num(INPUT_RETRIES_ENV, _DEFAULT_INPUT_RETRIES, lo=0)


class WorkerCrashed(RuntimeError):
    """A pool worker raised or died. Typed so the consumer (and the PR 1
    supervisor behind it) can tell "the input pipeline crashed" from a
    hang: the error surfaces in the training process within a bounded
    wait and exits it nonzero — a training crash, never silence."""

    def __init__(self, message: str, *, worker: int, exitcode: int | None = None):
        super().__init__(message)
        self.worker = worker
        self.exitcode = exitcode


def _safe_put(q, item) -> None:
    """free-queue put from GC/finalizer context: at interpreter shutdown the
    queue's feeder may already be gone — releasing a ring slot then is moot
    (the pool is dying too), so never let it raise."""
    try:
        q.put_nowait(item)
    except Exception:  # noqa: BLE001 — shutdown races only
        pass


def _release(free_q, alloc_id, counter, nbytes) -> None:
    _safe_put(free_q, alloc_id)
    counter[0] -= nbytes


class _ReleaseToken:
    """One per shm-transported example; frees its ring allocation (and the
    parent-side outstanding-bytes counter) when the last view dies — or
    explicitly, in copy mode. Finalizers run in the parent, so the counter
    is an accurate live view of how many ring bytes the consumer holds."""

    __slots__ = ("_fin", "__weakref__")

    def __init__(self, free_q, alloc_id: int, counter: list, nbytes: int):
        self._fin = weakref.finalize(
            self, _release, free_q, alloc_id, counter, nbytes)

    def release(self) -> None:
        self._fin()


class _ShmArray(np.ndarray):
    """ndarray view into a pool ring; carries the release token so the slot
    frees itself when the (last) view is garbage-collected."""

    _dls_token: Any = None


class _Arena:
    """Worker-side byte arena over the shm slab, with OUT-OF-ORDER free.

    The consumer frees allocations by id in whatever order its views die —
    and the hold pattern is adversarial for FIFO reclaim: a batch's
    *first* examples are held as views until the batch stacks, so a ring
    that can only reclaim from the tail wedges full behind them for the
    whole batch (measured: 80% of a 256-batch fell to pickle overflow).
    So: first-fit over a sorted free-interval list with coalescing. Hole
    count stays tiny (≈ the handful of concurrently-held views), keeping
    the scan O(few).
    """

    def __init__(self, size: int):
        self.size = size
        self.used = 0
        self._free: list[list[int]] = [[0, size]]  # sorted disjoint [s, e)
        self._live: dict[int, tuple[int, int]] = {}

    def free(self, alloc_id: int) -> None:
        iv = self._live.pop(alloc_id, None)
        if iv is None:
            return
        s, e = iv
        self.used -= e - s
        i = bisect.bisect_left(self._free, [s, e])
        # coalesce with the right then the left neighbor
        if i < len(self._free) and self._free[i][0] == e:
            self._free[i][0] = s
        else:
            self._free.insert(i, [s, e])
        if i > 0 and self._free[i - 1][1] == self._free[i][0]:
            self._free[i - 1][1] = self._free[i][1]
            del self._free[i]

    def try_alloc(self, alloc_id: int, need: int) -> int | None:
        """Offset for ``need`` bytes, or None (full / fragmented)."""
        if need <= 0 or need > self.size:
            return None
        for i, iv in enumerate(self._free):
            s, e = iv
            if e - s >= need:
                self._live[alloc_id] = (s, s + need)
                self.used += need
                if e - s == need:
                    del self._free[i]
                else:
                    iv[0] = s + need
                return s
        return None

    def largest_hole(self) -> int:
        """Biggest allocation that could succeed RIGHT NOW (0 when full).
        Advisory — frees land asynchronously — but a good sizing signal:
        the columnar exchange splits whole-plane payloads so each slice
        fits a plausible hole instead of collapsing a 16MB plane into the
        pickled-queue overflow path."""
        return max((e - s for s, e in self._free), default=0)


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def _worker_loop(wid: int, num_workers: int, source_factory, fn,
                 shm, out_q, free_q, stats, stop_evt, skip: int = 0) -> None:
    """Child body (fork-inherited state): iterate the source, map this
    worker's residue class, publish through the ring + metadata queue.
    ``skip`` fast-forwards a respawned replacement past the first ``skip``
    class elements the consumer already received — the source walk still
    happens (cheap, page-cached) but the map and the transport don't."""
    # cap the native kernels' per-call thread fan-out to this one process:
    # N workers each spawning hardware_concurrency threads oversubscribe
    # the host N× (measured 52 → 77 img/s at 4 workers on 2 cores when
    # capped); the parallelism now comes from the processes themselves.
    # Unconditional assignment — a parent-set DLS_NATIVE_THREADS tunes the
    # PARENT's serial path and must not leak N× fan-out into the children
    # (the child env is private; fork copied it, the parent keeps its own)
    os.environ["DLS_NATIVE_THREADS"] = "1"
    ring = _Arena(shm.size)
    buf = shm.buf
    base = wid * _ST_STRIDE
    alloc_id = 0

    def put(rec) -> bool:
        while not stop_evt.is_set():
            try:
                out_q.put(rec, timeout=_POLL_S)
                return True
            except queue_lib.Full:
                continue
        return False

    starved = [False]  # last alloc timed out and no free has arrived since

    def alloc(need: int) -> int | None:
        # while starved, don't re-pay the wait per example — the consumer
        # is holding views (or the ring is undersized for this batch
        # size), so degrade to queue transport IMMEDIATELY until a free
        # arrives; per-example waits here once turned an undersized ring
        # into a 10× throughput collapse instead of a few % of pickling
        deadline = time.perf_counter() + _ALLOC_WAIT_S
        while True:
            got_free = False
            try:  # drain frees accumulated since the last allocation
                while True:
                    ring.free(free_q.get_nowait())
                    got_free = True
            except queue_lib.Empty:
                pass
            if got_free:
                starved[0] = False
            off = ring.try_alloc(alloc_id, need)
            if off is not None or need > ring.size:
                return off
            if (stop_evt.is_set() or starved[0]
                    or time.perf_counter() > deadline):
                starved[0] = True
                return None
            try:
                ring.free(free_q.get(timeout=_POLL_S))
                starved[0] = False
            except queue_lib.Empty:
                pass

    try:
        ci = -1  # this worker's class-element ordinal, for skip
        for j, item in enumerate(source_factory()):
            if stop_evt.is_set():
                return
            if j % num_workers != wid:
                continue
            ci += 1
            if ci < skip:
                continue
            t0 = time.perf_counter()
            ex = fn(item) if fn is not None else item
            stats[base + _ST_BUSY] += time.perf_counter() - t0
            if not isinstance(ex, dict):
                # non-dict results (token arrays, scalars) ride the same
                # transport under a wrapper key the consumer unwraps
                ex = {_VALUE_KEY: ex}
            planes = [(k, np.ascontiguousarray(v)) for k, v in ex.items()
                      if isinstance(v, np.ndarray)
                      and not v.dtype.hasobject  # object arrays can't be
                      and v.nbytes >= _SHM_MIN_BYTES]  # raw-byte views
            shm_keys = {k for k, _ in planes}
            inline = {k: v for k, v in ex.items() if k not in shm_keys}
            need = sum(_align(a.nbytes) for _, a in planes)
            off = alloc(need) if planes else None
            if planes and off is None:
                # ring full past the wait (or example > ring): queue
                # transport keeps liveness; the overflow gauge tells you
                # to raise DLS_DATA_WORKER_RING_MB
                stats[base + _ST_OVERFLOW] += 1
                if not put(("pkl", j, ex)):
                    return
            elif planes:
                metas = []
                rel = 0
                for k, a in planes:
                    dst = np.frombuffer(buf, dtype=a.dtype, count=a.size,
                                        offset=off + rel).reshape(a.shape)
                    np.copyto(dst, a)
                    metas.append((k, a.dtype.str, a.shape, off + rel))
                    rel += _align(a.nbytes)
                if not put(("shm", j, alloc_id, metas, inline)):
                    return
                alloc_id += 1
            else:
                if not put(("pkl", j, ex)):
                    return
            stats[base + _ST_PRODUCED] += 1
            stats[base + _ST_RING_USED] = ring.used
        put(("end", wid, None))
    except BaseException:  # noqa: BLE001 — forward ANY failure, typed
        put(("err", wid, traceback.format_exc()))


class WorkerPool:
    """``N`` forked processes mapping one ordered source stream.

    ``source_factory``: zero-arg callable returning the source iterable —
    opened *inside each worker* (post-fork), never iterated in the parent.
    ``fn``: the per-example map (None = identity). :meth:`stream` yields
    ``fn(element)`` in exact source order; see the module docstring for the
    determinism / shared-memory / crash contracts.

    Single-use: one :meth:`stream` pass, then the pool is closed (the
    stream's ``finally`` does it; :func:`weakref.finalize` and the daemon
    flag are the backstops, so interpreter exit leaks neither processes
    nor shared-memory segments).
    """

    def __init__(self, source_factory: Callable[[], Iterable[Any]],
                 fn: Callable[[Any], Any] | None, num_workers: int, *,
                 ring_bytes: int | None = None, max_ahead: int | None = None,
                 copy: bool = False, label: str = "",
                 max_retries: int | None = None):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        if not fork_available():  # pragma: no cover - platform-dependent
            raise RuntimeError(
                "WorkerPool needs the 'fork' start method (the map fn and "
                "source are closures spawn cannot pickle); use num_workers=0")
        self.n = num_workers
        self.label = label
        self._copy = copy or bool(os.environ.get("DLS_DATA_WORKER_COPY"))
        self._t0 = time.perf_counter()
        self._consumed = [0] * num_workers
        #: per-worker ring bytes the consumer currently holds as live
        #: views (one-element lists so release finalizers can decrement)
        self._outstanding = [[0] for _ in range(num_workers)]
        self._closed = False
        self._source_factory = source_factory
        self._fn = fn
        self._respawns_left = input_worker_retries(max_retries)
        ctx = mp.get_context("fork")
        rb = _ring_bytes(ring_bytes)
        self._ring_bytes = rb
        self._ahead = (max_ahead if max_ahead is not None
                       else _DEFAULT_MAX_AHEAD)
        self._stats = ctx.RawArray("d", num_workers * _ST_STRIDE)
        self._stop = ctx.Event()
        self._shms = [shared_memory.SharedMemory(
            create=True, size=rb,
            name=f"dlsw-{os.getpid()}-{uuid.uuid4().hex[:8]}-{w}")
            for w in range(num_workers)]
        self._out_qs = [ctx.Queue(maxsize=max(2, self._ahead))
                        for _ in range(num_workers)]
        self._free_qs = [ctx.Queue() for _ in range(num_workers)]
        self._retired_qs: list = []   # pre-respawn queues, closed at close()
        self._procs = [
            ctx.Process(
                target=_worker_loop, daemon=True, name=f"dls-worker-{w}",
                args=(w, num_workers, source_factory, fn, self._shms[w],
                      self._out_qs[w], self._free_qs[w], self._stats,
                      self._stop))
            for w in range(num_workers)]
        with warnings.catch_warnings():
            # os.fork() under a multithreaded JAX parent warns about
            # deadlock risk; it does not apply here — children run
            # numpy/PIL/our C kernels only (never JAX), and the native
            # parallel_for spawns threads per call, so no pre-fork thread
            # or its lock is ever awaited in the child
            warnings.filterwarnings(
                "ignore", message=r".*os\.fork\(\) was called.*",
                category=RuntimeWarning)
            for p in self._procs:
                p.start()
        # LIVE lists shared with the finalizer: respawned workers and
        # their fresh arenas append here, so interpreter-exit teardown
        # reaps them too — not just the children alive at registration
        self._all_procs = list(self._procs)
        self._all_shms = list(self._shms)
        self._finalizer = weakref.finalize(
            self, WorkerPool._cleanup, self._stop, self._all_procs,
            self._all_shms)
        _LIVE_POOLS.add(self)

    # -- consumer side ------------------------------------------------------

    def stream(self) -> Iterator[Any]:
        """The mapped stream, in exact source order. Closes the pool on
        exhaustion, on error, and on generator close."""
        try:
            j = 0
            while True:
                w = j % self.n
                rec = self._next_record(w)
                kind = rec[0]
                if kind == "end":
                    # worker (j % n) exhausted ⇒ the source has ≤ j elements
                    # ⇒ no worker holds an element ≥ j: the stream is done.
                    return
                if kind == "err":
                    raise WorkerCrashed(
                        f"input worker {rec[1]} raised:\n{rec[2]}",
                        worker=rec[1])
                yield self._materialize(w, rec)
                self._consumed[w] += 1
                j += 1
        finally:
            self.close()

    def _next_record(self, w: int):
        while True:
            q = self._out_qs[w]
            try:
                return q.get(timeout=_POLL_S)
            except queue_lib.Empty:
                if self._procs[w].is_alive():
                    continue
            except Exception:  # noqa: BLE001 — a frame the dying feeder
                # tore mid-write surfacing on the PRIMARY get (unpickle/
                # EOF error). Survivable only when the producer is dead —
                # a live worker handing up garbage is a real bug. (A tear
                # that splits the frame HEADER can still wedge recv
                # inside this get; that residual window closes only by
                # never sharing a pipe with a killable producer, which is
                # the exchange's retained-file design, not the pool's.)
                if self._procs[w].is_alive():
                    raise
            try:  # drain race: a whole record may have landed meanwhile
                return q.get_nowait()
            except queue_lib.Empty:
                pass
            except Exception:  # noqa: BLE001 — the torn frame again
                pass
            rc = self._procs[w].exitcode
            if self._respawns_left > 0:
                self._respawn(w, rc)
                continue
            raise WorkerCrashed(
                f"input worker {w} died (exit code {rc}) without "
                f"reporting an error — killed (OOM/SIGKILL) or "
                f"crashed in native code (respawn budget "
                f"{INPUT_RETRIES_ENV} exhausted)", worker=w,
                exitcode=rc) from None

    def _respawn(self, w: int, exitcode: int | None) -> None:
        """Replace a dead worker in place (ISSUE 14): fresh arena and
        queues — the dead worker's pipe may hold a frame its feeder tore
        mid-write, and in-flight examples regenerate deterministically —
        same residue class, fast-forwarded past the ``consumed[w]``
        examples already delivered, so ordered byte-identical delivery
        resumes exactly where the stream left off."""
        self._respawns_left -= 1
        telemetry.emit("recovery", event="input-worker-respawn", worker=w,
                       exitcode=exitcode, skipped=self._consumed[w],
                       respawns_left=self._respawns_left,
                       label=self.label or None)
        ctx = mp.get_context("fork")
        shm = shared_memory.SharedMemory(
            create=True, size=self._ring_bytes,
            name=f"dlsw-{os.getpid()}-{uuid.uuid4().hex[:8]}-{w}")
        out_q = ctx.Queue(maxsize=max(2, self._ahead))
        free_q = ctx.Queue()
        # rebase the (single-writer, but its writer is dead) produced cell
        # on what the consumer actually took, so `ahead` stays truthful
        self._stats[w * _ST_STRIDE + _ST_PRODUCED] = self._consumed[w]
        self._stats[w * _ST_STRIDE + _ST_RING_USED] = 0
        p = ctx.Process(
            target=_worker_loop, daemon=True, name=f"dls-worker-{w}",
            args=(w, self.n, self._source_factory, self._fn, shm, out_q,
                  free_q, self._stats, self._stop, self._consumed[w]))
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=r".*os\.fork\(\) was called.*",
                category=RuntimeWarning)
            p.start()
        self._retired_qs.extend((self._out_qs[w], self._free_qs[w]))
        # old arena stays in _all_shms for unlink at close; views the
        # consumer still holds keep its pages alive until they die
        self._shms[w] = shm
        self._out_qs[w] = out_q
        self._free_qs[w] = free_q
        self._outstanding[w] = [0]  # old tokens decrement their own list
        self._procs[w] = p
        self._all_procs.append(p)
        self._all_shms.append(shm)

    def _materialize(self, w: int, rec) -> Any:
        if rec[0] == "pkl":
            ex = rec[2]
            return ex[_VALUE_KEY] if (isinstance(ex, dict)
                                      and _VALUE_KEY in ex) else ex
        _, _j, alloc_id, metas, inline = rec
        ex = dict(inline)
        buf = self._shms[w].buf
        ex_bytes = sum(
            int(np.prod(shape, dtype=np.int64) if shape else 1)
            * np.dtype(dstr).itemsize for _k, dstr, shape, _o in metas)
        # adaptive assembly: hand out views while the consumer's held
        # bytes fit the ring; once a batch would out-hold it (large
        # batch_size / num_workers vs DLS_DATA_WORKER_RING_MB), copy-and-
        # release instead — one memcpy, but the worker keeps streaming
        # through the ring rather than stalling into pickle overflow
        # hold at most a quarter of the ring as live views — the rest must
        # stay available as streaming room for the worker's lookahead, or
        # the worker starves into pickle overflow exactly when batches are
        # big (the case the adaptive copy exists for)
        counter = self._outstanding[w]
        copy = self._copy or (counter[0] + ex_bytes
                              > 0.25 * self._ring_bytes)
        token = _ReleaseToken(self._free_qs[w], alloc_id, counter,
                              0 if copy else ex_bytes)
        if not copy:
            counter[0] += ex_bytes
        for key, dstr, shape, off in metas:
            dt = np.dtype(dstr)
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            view = np.frombuffer(buf, dtype=dt, count=count,
                                 offset=off).reshape(shape)
            if copy:
                ex[key] = view.copy()
            else:
                arr = view.view(_ShmArray)
                arr._dls_token = token
                ex[key] = arr
        if copy:
            token.release()
        if len(ex) == 1 and _VALUE_KEY in ex:
            return ex[_VALUE_KEY]
        return ex

    # -- observability ------------------------------------------------------

    def gauges(self) -> dict:
        """Per-worker utilization/queue-depth gauges (pool-lifetime)."""
        wall = max(time.perf_counter() - self._t0, 1e-9)
        per = []
        for w in range(self.n):
            b = w * _ST_STRIDE
            produced = int(self._stats[b + _ST_PRODUCED])
            per.append({
                "util": min(1.0, self._stats[b + _ST_BUSY] / wall),
                "items": produced,
                "overflow": int(self._stats[b + _ST_OVERFLOW]),
                "ring_used_bytes": int(self._stats[b + _ST_RING_USED]),
                "ahead": produced - self._consumed[w],
            })
        return {"workers": self.n, "label": self.label, "wall_s": wall,
                "per_worker": per}

    # -- lifecycle ----------------------------------------------------------

    @staticmethod
    def _cleanup(stop, procs, shms) -> None:
        """Idempotent teardown, callable from finalize/atexit context."""
        stop.set()
        for p in procs:
            p.join(timeout=1.0)
        for p in procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=1.0)
        for s in shms:
            try:
                s.unlink()
            except FileNotFoundError:
                pass
            try:
                s.close()
            except BufferError:
                # consumer still holds views into the mapping: detach so
                # __del__ doesn't retry-and-whine — the name is already
                # unlinked above, the pages die with the last view
                s._buf = None
                s._mmap = None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._finalizer.detach()
        WorkerPool._cleanup(self._stop, self._all_procs, self._all_shms)
        for q in (*self._out_qs, *self._free_qs, *self._retired_qs):
            try:
                q.close()
                q.cancel_join_thread()
            except Exception:  # noqa: BLE001 — best-effort queue teardown
                pass


def pool_gauges() -> dict:
    """Aggregate gauges over every live pool, keyed for the telemetry
    step_metrics record (merged by ``StarvationProbe.snapshot``). Empty dict
    when no pool is running, so the non-worker path emits nothing new."""
    pools = [p for p in list(_LIVE_POOLS) if not p._closed]
    per = [g for p in pools for g in p.gauges()["per_worker"]]
    if not per:
        return {}
    utils = [g["util"] for g in per]
    return {
        "input_workers": len(per),
        "worker_util_mean": round(sum(utils) / len(per), 4),
        "worker_util_min": round(min(utils), 4),
        "worker_items": int(sum(g["items"] for g in per)),
        "worker_overflow": int(sum(g["overflow"] for g in per)),
        "worker_ahead_mean": round(
            sum(g["ahead"] for g in per) / len(per), 2),
        "worker_ring_used_mb": round(
            sum(g["ring_used_bytes"] for g in per) / 1e6, 2),
    }


def _split_budget(total: int, num_partitions: int, index: int) -> int:
    """Workers for partition ``index`` out of a ``total`` budget.

    Rounded UP to at least one per partition once enabled: a partition left
    serial would decode on the consumer thread and gate the whole
    round-robin interleave (measured: ``num_workers=2`` over 4 partitions
    with two serial partitions ran *slower* than no pool at all). So the
    effective floor is one process per partition; budgets beyond that
    spread round-robin. Bytes are identical regardless of the split.
    """
    if total <= 0:
        return 0
    k, rem = divmod(total, num_partitions)
    return max(1, k + (1 if index < rem else 0))


class WorkerMappedDataset(PartitionedDataset):
    """A ``map`` whose execution fans out over a process pool per partition.

    Behaves exactly like ``base.map(fn)`` — same partitions, same element
    order, same bytes — but each partition's iterator, when opened, starts
    its share of the ``num_workers`` budget as a :class:`WorkerPool`
    (closed when the iterator is). ``num_workers=None`` defers to
    ``DLS_DATA_WORKERS`` at iteration time; resolved 0 (or no fork) is the
    plain serial map. The feed layer (`data/feed.py:host_batches`) can
    override the count via its ``num_workers=`` knob →
    :meth:`with_num_workers`.
    """

    def __init__(self, base: PartitionedDataset, fn: Callable[[Any], Any],
                 num_workers: int | None = None, *,
                 ring_bytes: int | None = None, max_ahead: int | None = None,
                 label: str = ""):
        self.base = base
        self.fn = fn
        self.num_workers = num_workers
        self._ring_bytes = ring_bytes
        self._max_ahead = max_ahead
        self._label = label
        P = base.num_partitions
        warned: list[bool] = []

        def make(i: int):
            src = base._parts[i]

            def gen() -> Iterator[Any]:
                k = _split_budget(resolve_num_workers(self.num_workers), P, i)
                if k > 0 and not fork_available():  # pragma: no cover
                    if not warned:
                        warned.append(True)
                        warnings.warn(
                            "DLS_DATA_WORKERS requested but the 'fork' start "
                            "method is unavailable; using the in-process map")
                    k = 0
                if k <= 0:
                    return map(fn, src())
                pool = WorkerPool(src, fn, k, ring_bytes=self._ring_bytes,
                                  max_ahead=self._max_ahead,
                                  label=label or f"part{i}")
                return pool.stream()

            return gen

        super().__init__([make(i) for i in range(P)],
                         infinite=base.is_infinite)

    def with_num_workers(self, num_workers: int | None
                         ) -> "WorkerMappedDataset":
        return WorkerMappedDataset(
            self.base, self.fn, num_workers, ring_bytes=self._ring_bytes,
            max_ahead=self._max_ahead, label=self._label)
