"""Text pipeline — the rebuild of the reference's Wikipedia text RDD plane.

The reference tokenizes Wikipedia into MLM examples inside text RDD
partitions (SURVEY.md §2 'Data: text pipeline'). Same shape here: RDD-style
transforms over :class:`~distributeddeeplearningspark_tpu.rdd.
PartitionedDataset` running on the host, yielding fixed-shape example dicts
(static shapes keep the jitted step compile count at one):

``{"input_ids": [S] i32, "attention_mask": [S] i32,
   "mlm_labels": [S] i32, "mlm_weights": [S] f32}``

Tokenizer: greedy-longest-match WordPiece over a corpus-built vocab — the
BERT scheme, self-contained (no HF download; the env has no egress). For real
runs a pre-built vocab file can be loaded.
"""

from __future__ import annotations

import collections
import os
import re
from typing import Iterable, Iterator, Sequence

import numpy as np

from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

PAD, UNK, CLS, SEP, MASK = "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"
SPECIAL_TOKENS = (PAD, UNK, CLS, SEP, MASK)

_WORD_RE = re.compile(r"[a-z0-9]+|[^\sa-z0-9]")


class WordPieceTokenizer:
    """Greedy longest-match-first subword tokenizer (BERT's scheme)."""

    def __init__(self, vocab: dict[str, int]):
        self.vocab = dict(vocab)
        self.inv = {i: t for t, i in self.vocab.items()}
        for tok in SPECIAL_TOKENS:
            if tok not in self.vocab:
                raise ValueError(f"vocab missing special token {tok}")
        self.pad_id = self.vocab[PAD]
        self.unk_id = self.vocab[UNK]
        self.cls_id = self.vocab[CLS]
        self.sep_id = self.vocab[SEP]
        self.mask_id = self.vocab[MASK]
        #: ids never selected for masking
        self.special_ids = frozenset(self.vocab[t] for t in SPECIAL_TOKENS)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def tokenize_word(self, word: str) -> list[int]:
        ids, start = [], 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.unk_id]
            ids.append(cur)
            start = end
        return ids

    def encode(self, text: str) -> list[int]:
        ids: list[int] = []
        for word in _WORD_RE.findall(text.lower()):
            ids.extend(self.tokenize_word(word))
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        pieces = [self.inv.get(int(i), UNK) for i in ids]
        out: list[str] = []
        for p in pieces:
            if p.startswith("##") and out:
                out[-1] += p[2:]
            else:
                out.append(p)
        return " ".join(out)

    @staticmethod
    def train(corpus: Iterable[str], vocab_size: int = 8192, *, min_freq: int = 2
              ) -> "WordPieceTokenizer":
        """Frequency-based vocab: whole words first, then char fallbacks.

        A full WordPiece-training (likelihood-driven merges) is overkill for
        the contract; frequency top-k with char-level backstop gives the same
        interface and sub-linear UNK rates on natural text.
        """
        counts: collections.Counter = collections.Counter()
        chars: set[str] = set()
        for line in corpus:
            for w in _WORD_RE.findall(line.lower()):
                counts[w] += 1
                chars.update(w)
        vocab: dict[str, int] = {t: i for i, t in enumerate(SPECIAL_TOKENS)}
        for ch in sorted(chars):  # char backstop: no word is ever fully UNK
            for piece in (ch, "##" + ch):
                if piece not in vocab:
                    vocab[piece] = len(vocab)
        for w, c in counts.most_common():
            if len(vocab) >= vocab_size:
                break
            if c >= min_freq and w not in vocab:
                vocab[w] = len(vocab)
        return WordPieceTokenizer(vocab)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            for tok, _ in sorted(self.vocab.items(), key=lambda kv: kv[1]):
                f.write(tok + "\n")

    @staticmethod
    def load(path: str) -> "WordPieceTokenizer":
        with open(path) as f:
            return WordPieceTokenizer({line.rstrip("\n"): i for i, line in enumerate(f)})


def segments_from_docs(
    docs: Iterable[str], tokenizer: WordPieceTokenizer, seq_len: int
) -> Iterator[np.ndarray]:
    """Pack tokenized documents into fixed [CLS] ... [SEP] windows."""
    for ids, _ in packed_segments_from_docs(docs, tokenizer, seq_len):
        yield ids


def _pack_token_windows(
    doc_tokens: Iterable[list[int]], window: int
) -> Iterator[tuple[list[int], list[int], bool]]:
    """Lockstep token/segment-id packer shared by the MLM and causal-LM
    pipelines: concatenate per-document token lists, tag every position
    with a running document counter, and cut ``window``-sized chunks →
    ``(chunk, seg_ids, is_partial)``. The final partial chunk (corpus
    tail) is yielded unpadded with ``is_partial=True`` — framing (CLS/SEP
    vs EOS, pad conventions) belongs to the caller. ONE copy of the
    buffer-slicing invariant lives here.
    """
    buf: list[int] = []
    seg: list[int] = []
    doc_id = 0
    for toks in doc_tokens:
        buf.extend(toks)
        seg.extend([doc_id] * len(toks))
        doc_id += 1
        while len(buf) >= window:
            chunk, buf = buf[:window], buf[window:]
            cseg, seg = seg[:window], seg[window:]
            yield chunk, cseg, False
    if buf:
        yield buf, seg, True


def packed_segments_from_docs(
    docs: Iterable[str], tokenizer: WordPieceTokenizer, seq_len: int
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Pack documents back-to-back into full windows, tracking which document
    owns each position → (ids [S] i32, segment_ids [S] i32).

    Every window is completely full (zero padding) except the corpus tail —
    this is why the measured tokens/sec here IS effective tokens/sec
    (VERDICT r2 #4; contrast ``padded_segments_from_docs``). Segment ids are
    a running document counter; [CLS] joins the window's first document and
    the final [SEP] its last; padding (tail window only) gets id -1 so real
    tokens never attend to pad positions even without a padding mask.
    """
    return packed_segments_from_tokens(
        (tokenizer.encode(doc) for doc in docs), tokenizer, seq_len)


def packed_segments_from_tokens(
    doc_tokens: Iterable, tokenizer: WordPieceTokenizer, seq_len: int
) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """:func:`packed_segments_from_docs` over PRE-tokenized documents — the
    split that lets the tokenize stage (the expensive per-doc map) run in
    the :mod:`.workers` process pool while the stateful cross-document
    packing stays on the consumer. Accepts lists or int arrays per doc."""
    for chunk, cseg, partial in _pack_token_windows(doc_tokens, seq_len - 2):
        ids = [tokenizer.cls_id, *chunk, tokenizer.sep_id]
        sids = [cseg[0], *cseg, cseg[-1]]
        if partial:
            pad = seq_len - len(ids)
            ids += [tokenizer.pad_id] * pad
            sids += [-1] * pad
        yield np.array(ids, np.int32), np.array(sids, np.int32)


def padded_segments_from_docs(
    docs: Iterable[str], tokenizer: WordPieceTokenizer, seq_len: int
) -> Iterator[np.ndarray]:
    """One document per window, padded to ``seq_len`` (long docs split).

    The reference-era per-document pipeline shape — kept as the measured
    baseline for the packing A/B (VERDICT r2 #4): real Wikipedia documents
    average far under 512 tokens, so most of each window is [PAD] and the
    naive tokens/sec number is mostly padding throughput.
    """
    return _padded_from_tokens(
        (tokenizer.encode(doc) for doc in docs), tokenizer, seq_len)


def _padded_from_tokens(
    doc_tokens: Iterable, tokenizer: WordPieceTokenizer, seq_len: int
) -> Iterator[np.ndarray]:
    """Padded-window framing over pre-tokenized docs (lists or int arrays)
    — the tokenize/frame split that lets the worker pool own the encode."""
    budget = seq_len - 2
    for toks in doc_tokens:
        toks = list(toks)
        if not toks:
            continue
        for off in range(0, len(toks), budget):
            chunk = toks[off:off + budget]
            ids = [tokenizer.cls_id, *chunk, tokenizer.sep_id]
            ids += [tokenizer.pad_id] * (seq_len - len(ids))
            yield np.array(ids, np.int32)


def _tokens_dataset(docs: PartitionedDataset, tok_fn, num_workers: int | None,
                    *, label: str) -> PartitionedDataset:
    """Per-doc tokenize as a dataset stage: pooled over worker processes
    when ``num_workers`` (or ``DLS_DATA_WORKERS``) asks for it, the plain
    in-process ``map`` otherwise — same token stream either way."""
    from distributeddeeplearningspark_tpu.data import workers as workers_lib

    if workers_lib.resolve_num_workers(num_workers) > 0:
        return workers_lib.WorkerMappedDataset(docs, tok_fn, num_workers,
                                               label=label)
    return docs.map(tok_fn)


def mask_tokens(
    ids: np.ndarray,
    tokenizer: WordPieceTokenizer,
    rng: np.random.Generator,
    *,
    mask_prob: float = 0.15,
) -> dict[str, np.ndarray]:
    """BERT's 80/10/10 MLM corruption → fixed-shape example dict."""
    ids = np.asarray(ids, np.int32)
    maskable = ~np.isin(ids, list(tokenizer.special_ids))
    sel = (rng.random(ids.shape) < mask_prob) & maskable
    if not sel.any() and maskable.any():  # guarantee ≥1 target per segment
        sel[rng.choice(np.flatnonzero(maskable))] = True

    corrupted = ids.copy()
    r = rng.random(ids.shape)
    corrupted[sel & (r < 0.8)] = tokenizer.mask_id
    rand_sel = sel & (r >= 0.8) & (r < 0.9)
    if rand_sel.any():
        # draw replacements from NON-special ids (loaded vocabs — e.g. the
        # stock BERT vocab.txt — don't keep specials in a contiguous prefix)
        candidates = np.setdiff1d(
            np.arange(tokenizer.vocab_size, dtype=np.int32),
            np.fromiter(tokenizer.special_ids, np.int32),
        )
        corrupted[rand_sel] = rng.choice(candidates, rand_sel.sum())
    # remaining 10%: keep original token

    return {
        "input_ids": corrupted,
        "attention_mask": (ids != tokenizer.pad_id).astype(np.int32),
        "mlm_labels": ids,
        "mlm_weights": sel.astype(np.float32),
    }


def pack_mlm_predictions(
    example: dict[str, np.ndarray], max_predictions: int
) -> dict[str, np.ndarray]:
    """Full-length MLM example → gathered form (original TPU BERT layout).

    Adds ``mlm_positions`` [P] and rewrites ``mlm_labels``/``mlm_weights``
    to [P] (P = ``max_predictions``, zero-padded/weighted-0), so
    :class:`~..models.bert.BertForMLM` runs its vocab projection on masked
    positions only. Targets beyond P are dropped (weight-0), matching the
    reference BERT data pipeline's ``max_predictions_per_seq`` truncation.
    """
    sel = np.flatnonzero(example["mlm_weights"] > 0)[:max_predictions]
    pos = np.zeros((max_predictions,), np.int32)
    labels = np.zeros((max_predictions,), np.int32)
    weights = np.zeros((max_predictions,), np.float32)
    pos[: len(sel)] = sel
    labels[: len(sel)] = example["mlm_labels"][sel]
    weights[: len(sel)] = example["mlm_weights"][sel]  # preserve weighting
    out = {
        "input_ids": example["input_ids"],
        "attention_mask": example["attention_mask"],
        "mlm_positions": pos,
        "mlm_labels": labels,
        "mlm_weights": weights,
    }
    if "segment_ids" in example:  # packed batches keep their doc boundaries
        out["segment_ids"] = example["segment_ids"]
    return out


def mlm_dataset(
    docs: PartitionedDataset,
    tokenizer: WordPieceTokenizer,
    *,
    seq_len: int = 128,
    mask_prob: float = 0.15,
    seed: int = 0,
    max_predictions: int | None = None,
    segment_ids: bool = False,
    pack: bool = True,
    num_workers: int | None = None,
) -> PartitionedDataset:
    """Text RDD → MLM example RDD (tokenize → pack → mask, per partition).

    ``max_predictions``: emit the gathered (``mlm_positions``) form so the
    model's vocab projection runs on masked positions only (recommended:
    ``ceil(seq_len * mask_prob) + a few``, e.g. 80 for 512×0.15).
    ``segment_ids``: also emit per-position document ids so attention is
    blocked across packed-document boundaries (the model/flash kernel
    consume them — VERDICT r2 #4); without them packing follows the
    RoBERTa FULL-SENTENCES convention (documents share the window).
    ``pack=False``: one padded document per window — the reference-era
    shape, kept for the padding-waste A/B (see ``token_stats``).
    ``num_workers`` (default ``DLS_DATA_WORKERS``): tokenize — the per-doc
    hot loop — across worker processes (:mod:`.workers`); the stateful
    window packing and the per-partition-seeded masking stay on the
    consumer, so the example stream is byte-identical for any count.
    """

    if not pack and segment_ids:
        raise ValueError(
            "segment_ids=True requires pack=True (padded mode has one "
            "document per window — there are no boundaries to mark)")

    token_ds = _tokens_dataset(
        docs, lambda doc: np.asarray(tokenizer.encode(doc), np.int32),
        num_workers, label="mlm_tokenize")

    def per_partition(pidx: int, toks: Iterable[np.ndarray]) -> Iterator[dict]:
        rng = np.random.default_rng(seed * 100003 + pidx)
        if not pack:
            gen: Iterator = (
                (ids, None)
                for ids in _padded_from_tokens(toks, tokenizer, seq_len))
        else:
            gen = packed_segments_from_tokens(toks, tokenizer, seq_len)
            if not segment_ids:
                gen = ((ids, None) for ids, _ in gen)
        for seg, sids in gen:
            ex = mask_tokens(seg, tokenizer, rng, mask_prob=mask_prob)
            if sids is not None:
                ex["segment_ids"] = sids
            yield (pack_mlm_predictions(ex, max_predictions)
                   if max_predictions else ex)

    return token_ds.map_partitions_with_index(per_partition)


def token_stats(dataset: PartitionedDataset, *, max_examples: int = 10_000) -> dict:
    """Measured padding waste of an MLM/LM example stream (VERDICT r2 #4).

    Returns ``{examples, tokens, pad_tokens, pad_frac, effective_frac}``
    over up to ``max_examples`` examples — ``effective_frac`` is the factor
    that turns raw tokens/sec into honest non-pad tokens/sec.
    """
    examples = tokens = pad = 0
    stream = (ex for p in range(dataset.num_partitions)
              for ex in dataset.iter_partition(p))
    for i, ex in enumerate(stream):
        if i >= max_examples:
            break
        am = ex.get("attention_mask")
        if am is None:  # LM form: loss_mask plays the same role
            am = ex["loss_mask"]
        examples += 1
        tokens += int(np.size(am))
        pad += int(np.size(am) - np.count_nonzero(am))
    eff = (tokens - pad) / tokens if tokens else 0.0
    return {"examples": examples, "tokens": tokens, "pad_tokens": pad,
            "pad_frac": round(1.0 - eff, 4), "effective_frac": round(eff, 4)}


class HFTokenizerAdapter:
    """Wrap a local Hugging Face tokenizer behind this module's interface.

    Used when fine-tuning imported checkpoints (config 5): token ids must
    index the *pretrained* embedding rows, so the checkpoint's own vocab is
    mandatory — a corpus-trained WordPiece vocab would map text to unrelated
    rows. Loads strictly from local files (the env has no egress).
    """

    def __init__(self, hf_tokenizer):
        self._tok = hf_tokenizer
        self.pad_id = hf_tokenizer.pad_token_id
        self.sep_id = hf_tokenizer.eos_token_id
        if self.sep_id is None:
            raise ValueError("tokenizer must define an EOS token")
        if self.pad_id is None:  # Llama tokenizers ship without a pad token
            self.pad_id = self.sep_id

    @staticmethod
    def load(path: str) -> "HFTokenizerAdapter":
        from transformers import AutoTokenizer

        return HFTokenizerAdapter(AutoTokenizer.from_pretrained(path, local_files_only=True))

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids))


def lm_dataset(
    docs: PartitionedDataset,
    tokenizer: WordPieceTokenizer,
    *,
    seq_len: int = 512,
    eos_between_docs: bool = True,
    segment_ids: bool = False,
    num_workers: int | None = None,
) -> PartitionedDataset:
    """Text RDD → packed causal-LM blocks (config 5's fine-tune feed).

    Documents are tokenized and concatenated (SEP as document separator, the
    standard packing trick that keeps every position a real target), then cut
    into fixed [seq_len] windows: ``{"input_ids": [S] i32, "loss_mask": [S]
    f32}``. ``loss_mask`` zeroes padding in the final short block so
    :func:`~distributeddeeplearningspark_tpu.train.losses.causal_lm` ignores it.

    ``segment_ids=True`` adds per-position document ids (running counter;
    the SEP separator belongs to the document it ends; pads get -1) so
    attention is blocked across packed-document boundaries — the model
    consumes ``batch["segment_ids"]`` through the flash kernel / ring
    (GPT-style packing without it is also standard; measure both).
    ``num_workers``: tokenize across worker processes, packing stays on
    the consumer — byte-identical stream for any count (see
    :func:`mlm_dataset`).
    """
    token_ds = _tokens_dataset(
        docs,
        lambda doc: np.asarray(
            tokenizer.encode(doc)
            + ([tokenizer.sep_id] if eos_between_docs else []), np.int32),
        num_workers, label="lm_tokenize")

    def per_partition(pidx: int, stream: Iterable[np.ndarray]) -> Iterator[dict]:
        del pidx
        for chunk, cseg, partial in _pack_token_windows(stream, seq_len):
            if partial and len(chunk) <= 1:
                continue  # a lone token has no next-token target
            mask = np.zeros(seq_len, np.float32)
            mask[: len(chunk)] = 1.0
            ids = chunk + [tokenizer.pad_id] * (seq_len - len(chunk))
            ex = {"input_ids": np.array(ids, np.int32),
                  "loss_mask": (np.ones(seq_len, np.float32)
                                if not partial else mask)}
            if segment_ids:
                sids = cseg + [-1] * (seq_len - len(cseg))
                ex["segment_ids"] = np.array(sids, np.int32)
            yield ex

    return token_ds.map_partitions_with_index(per_partition)


def synthetic_wikipedia(
    num_docs: int = 512, *, num_partitions: int = 4, seed: int = 0
) -> PartitionedDataset:
    """Markov-chain pseudo-prose: learnable bigram structure, Zipfian vocab.

    Gives MLM training real signal (predictable successors) so tests can
    assert loss decreases and masked accuracy beats chance.
    """
    base = [
        "the", "of", "and", "in", "to", "was", "is", "for", "as", "on", "by",
        "with", "city", "river", "history", "population", "century", "state",
        "university", "world", "war", "government", "species", "music", "film",
        "science", "theory", "system", "language", "island", "mountain",
    ]

    def make_partition(pidx: int):
        def gen() -> Iterator[str]:
            rng = np.random.default_rng(seed * 1000 + pidx)
            n = num_docs // num_partitions
            # fixed bigram table (shared across partitions: same "language")
            trng = np.random.default_rng(20260729)
            nxt = {w: trng.choice(base, 4, replace=True) for w in base}
            for _ in range(n):
                w = base[int(rng.integers(len(base)))]
                words = [w]
                for _ in range(int(rng.integers(60, 120))):
                    w = nxt[w][int(rng.integers(4))]
                    words.append(w)
                yield " ".join(words)

        return gen

    return PartitionedDataset([make_partition(i) for i in range(num_partitions)])


def wikipedia_dump(
    path: str,
    *,
    num_partitions: int = 8,
    min_chars: int = 64,
) -> PartitionedDataset:
    """Real Wikipedia text → document RDD (VERDICT r1 missing-#3, config 3).

    Accepts the three on-disk shapes Wikipedia pretraining corpora come in:

    - a **mediawiki XML dump** (``*.xml`` / ``*.xml.bz2``, the enwiki
      download): streamed with stdlib ``iterparse`` (constant memory), one
      document per ``<page>``'s ``<text>``, redirects skipped, wikitext
      lightly cleaned (markup → plain-ish text — the same level of cleaning
      the reference-era BERT pipelines applied);
    - a **wikiextractor output tree** (``AA/wiki_00`` files of ``<doc>``
      blocks): one document per ``<doc>`` element;
    - **plain text**: one document per line (or per blank-line-separated
      paragraph group when lines are short), matching this module's
      synthetic corpus shape.

    Documents stream lazily per partition (files are dealt round-robin;
    a single big XML file is read by every partition with stride — cheap
    relative to tokenization, and keeps partition boundaries deterministic).
    """
    import glob as _glob

    if os.path.isdir(path):
        files = sorted(
            f for f in _glob.glob(os.path.join(path, "**", "*"), recursive=True)
            if os.path.isfile(f) and not os.path.basename(f).startswith(".")
        )
    else:
        files = [path]
    if not files:
        raise FileNotFoundError(f"no corpus files under {path}")

    def open_maybe_bz2(fname: str):
        if fname.endswith(".bz2"):
            import bz2

            return bz2.open(fname, "rt", encoding="utf-8", errors="replace")
        return open(fname, "rt", encoding="utf-8", errors="replace")

    def iter_xml_docs(fname: str) -> Iterator[str]:
        from xml.etree import ElementTree

        with open_maybe_bz2(fname) as f:
            # namespace-agnostic: match on the tag's local name
            for _, elem in ElementTree.iterparse(f, events=("end",)):
                tag = elem.tag.rsplit("}", 1)[-1]
                if tag == "page":
                    text_el = None
                    redirect = False
                    for child in elem.iter():
                        ctag = child.tag.rsplit("}", 1)[-1]
                        if ctag == "redirect":
                            redirect = True
                        elif ctag == "text":
                            text_el = child
                    if not redirect and text_el is not None and text_el.text:
                        doc = clean_wikitext(text_el.text)
                        if len(doc) >= min_chars:
                            yield doc
                    elem.clear()  # constant memory

    def iter_docfile(fname: str) -> Iterator[str]:
        """wikiextractor '<doc ...> text </doc>' blocks or plain text."""
        with open_maybe_bz2(fname) as f:
            first = f.readline()
            if first.lstrip().startswith("<doc"):
                buf: list[str] = []
                for line in f:
                    if line.startswith("</doc>"):
                        doc = "\n".join(buf[1:] if buf and not buf[0].strip() else buf)
                        if len(doc) >= min_chars:
                            yield doc.strip()
                        buf = []
                    elif line.startswith("<doc"):
                        buf = []
                    else:
                        buf.append(line.rstrip("\n"))
            else:
                # plain text: a line per doc; short lines merge into paragraphs
                para: list[str] = []
                for line in [first] + list(f):
                    s = line.strip()
                    if not s:
                        if para:
                            doc = " ".join(para)
                            if len(doc) >= min_chars:
                                yield doc
                            para = []
                    elif len(s) >= min_chars:
                        yield s
                    else:
                        para.append(s)
                if para and len(" ".join(para)) >= min_chars:
                    yield " ".join(para)

    def iter_file(fname: str) -> Iterator[str]:
        base = fname[:-4] if fname.endswith(".bz2") else fname
        if base.endswith(".xml"):
            yield from iter_xml_docs(fname)
        else:
            yield from iter_docfile(fname)

    def make_partition(pidx: int):
        def gen() -> Iterator[str]:
            if len(files) >= num_partitions:
                for fname in files[pidx::num_partitions]:
                    yield from iter_file(fname)
            else:
                # few big files: stride documents across partitions
                for fname in files:
                    for i, doc in enumerate(iter_file(fname)):
                        if i % num_partitions == pidx:
                            yield doc

        return gen

    return PartitionedDataset([make_partition(i) for i in range(num_partitions)])


_WIKI_PATTERNS: "list[tuple[re.Pattern, str]] | None" = None


def clean_wikitext(text: str) -> str:
    """Light wikitext → plain text (the BERT-era preprocessing level).

    Drops templates/tables/refs/files, unwraps [[links|label]] and quotes,
    strips headings and html tags. Not a full parser — the goal is clean
    *training prose*, not rendering fidelity.
    """
    global _WIKI_PATTERNS
    if _WIKI_PATTERNS is None:
        _WIKI_PATTERNS = [
            (re.compile(r"<ref[^>]*/>|<ref[^>]*>.*?</ref>", re.S), " "),
            (re.compile(r"<!--.*?-->", re.S), " "),
            (re.compile(r"\{\|.*?\|\}", re.S), " "),            # tables
            (re.compile(r"\[\[(?:File|Image|Category):[^\]]*\]\]"), " "),
            (re.compile(r"\[\[[^\]|]*\|([^\]]*)\]\]"), r"\1"),  # [[a|b]] → b
            (re.compile(r"\[\[([^\]]*)\]\]"), r"\1"),           # [[a]] → a
            (re.compile(r"\[https?://\S*\s([^\]]*)\]"), r"\1"),
            (re.compile(r"\[https?://\S*\]"), " "),
            (re.compile(r"'{2,}"), ""),                          # bold/italics
            (re.compile(r"^=+.*?=+\s*$", re.M), " "),            # headings
            (re.compile(r"<[^>]+>"), " "),                       # html tags
            (re.compile(r"^\s*[*#:;]+\s*", re.M), ""),           # list markers
            (re.compile(r"[ \t]+"), " "),
            (re.compile(r"\n{3,}"), "\n\n"),
        ]
    # templates {{...}} nest; peel iteratively (bounded)
    for _ in range(4):
        new = re.sub(r"\{\{[^{}]*\}\}", " ", text)
        if new == text:
            break
        text = new
    for pat, repl in _WIKI_PATTERNS:
        text = pat.sub(repl, text)
    return text.strip()
