"""Device-side segment-reduce aggregation — the combines leave the host.

The columnar exchange (:mod:`~.exchange`, ISSUE 12) made the shuffle's
data plane flat arrays; this module takes the last step the DrJAX framing
(PAPERS.md 2403.07128) points at: once the reduce phase's input is a
hash-sorted plane with segment boundaries, the numeric combines —
``count`` / ``sum`` / ``min`` / ``max`` (``mean`` derives from sum/count
at read time) and the top-V vocab filter — are exactly
``jax.ops.segment_*`` / ``jax.lax.top_k`` kernels. ``groupBy().agg(
transport="device")`` runs them here, and the DLRM feature pipeline's
vocab build streams its top-V selection through :class:`TopV`.

Three disciplines keep this honest:

- **Bit-exactness.** Kernels trace under ``jax.experimental.enable_x64``
  so float64 sums stay float64 (this repo otherwise runs x32); the final
  division for ``mean`` happens host-side with the identical formula the
  tuple path uses. The usual proviso carries over unchanged from the
  exchange: float sums are bit-equal across paths while the values make
  the sum exact (integer-valued f64, magnitudes < 2^53) — min/max/count
  are order-free and always exact.
- **No recompiles on warm repeats.** Every kernel input pads to a pow-2
  ladder (data length AND segment count), so steady workloads reuse one
  executable per (op, size bucket); kernels are wrapped in the PR 9
  compile ledger (:func:`~..telemetry.anatomy.instrument`) with a
  generous ``expected_signatures``, so every compile is a ledgered,
  cost-analyzed ``compile`` event in ``dlstatus --anatomy`` and a repeat
  at the same shapes compiles NOTHING.
- **Graceful absence.** No jax / no x64 context → :func:`available` is
  False and callers (dataframe agg, the DLRM example) keep their host
  paths; :func:`segment_combine` itself falls back to the exchange's
  ``reduceat`` fold — same bytes, no device.
"""

from __future__ import annotations

import logging
from typing import Any, Sequence

import numpy as np

from distributeddeeplearningspark_tpu.data import exchange

logger = logging.getLogger(__name__)

#: pad floor: below this, padding overhead dwarfs the work and the ladder
#: would mint one executable per tiny size.
_MIN_PAD = 1 << 10
#: per-kernel distinct-signature allowance: the pow-2 ladder bounds the
#: genuine signature set well under this, so a flagged recompile is a
#: real bug (same signature compiled twice), never ladder noise.
_EXPECTED_SIGS = 64

_kernels: dict[tuple, Any] = {}
_state: dict[str, Any] = {"available": None}


def _pad_len(n: int) -> int:
    return max(_MIN_PAD, 1 << max(0, int(n - 1)).bit_length())


def available() -> bool:
    """Can the device path run here? (jax importable, an x64 scope
    available, at least one device.) Cached per process."""
    if _state["available"] is None:
        try:
            import jax

            jax.experimental.enable_x64  # noqa: B018 — probe the attr
            _state["available"] = bool(jax.devices())
        except Exception as e:  # noqa: BLE001 — any failure = host path
            logger.warning("device_agg unavailable (%s: %s) — callers "
                           "keep their host combine paths",
                           type(e).__name__, e)
            _state["available"] = False
    return _state["available"]


def _x64():
    import jax

    return jax.experimental.enable_x64()


def _identity(op: str, dtype: np.dtype):
    if op == "sum":
        return 0
    if dtype.kind == "f":
        return np.inf if op == "min" else -np.inf
    info = np.iinfo(dtype)
    return info.max if op == "min" else info.min


def _segment_kernel(op: str, nseg_pad: int):
    key = ("seg", op, nseg_pad)
    if key not in _kernels:
        import jax

        from distributeddeeplearningspark_tpu.telemetry.anatomy import (
            instrument)

        def fn(data, seg_ids, _op=op, _n=nseg_pad):
            if _op == "sum":
                return jax.ops.segment_sum(
                    data, seg_ids, num_segments=_n, indices_are_sorted=True)
            if _op == "min":
                return jax.ops.segment_min(
                    data, seg_ids, num_segments=_n, indices_are_sorted=True)
            return jax.ops.segment_max(
                data, seg_ids, num_segments=_n, indices_are_sorted=True)

        _kernels[key] = instrument(
            jax.jit(fn), name=f"device_agg.segment_{op}",
            expected_signatures=_EXPECTED_SIGS)
    return _kernels[key]


def segment_reduce(op: str, values: np.ndarray, seg_ids: np.ndarray,
                   nseg: int) -> np.ndarray:
    """One plane's device fold: ``values`` (sorted so equal segments are
    adjacent) reduce into ``nseg`` outputs. Pads both axes to the pow-2
    ladder (pad rows target a trash segment past ``nseg``) and slices the
    real segments back out."""
    if op not in exchange.NUMERIC_COMBINES:
        raise ValueError(f"op {op!r} not in {exchange.NUMERIC_COMBINES}")
    n = len(values)
    n_pad = _pad_len(n)
    nseg_pad = _pad_len(nseg + 1)
    data = np.full(n_pad, _identity(op, values.dtype), dtype=values.dtype)
    data[:n] = values
    ids = np.full(n_pad, nseg, dtype=np.int32)
    ids[:n] = seg_ids
    import jax.numpy as jnp

    with _x64():
        out = np.asarray(
            _segment_kernel(op, nseg_pad)(jnp.asarray(data),
                                          jnp.asarray(ids)))
    return out[:nseg]


def segment_combine(pl: "exchange._Planes",
                    plan: "exchange.ColumnarPlan") -> "exchange._Planes":
    """The exchange's sort-and-fold, with the folds on device: stable
    argsort by ``key_hash`` (host — ordering is control flow, combining is
    the FLOP work), then one :func:`segment_reduce` per value plane. Hash
    collisions (distinct keys, equal digest) drop to the exchange's
    full-key-compare path, and an unavailable device degrades to its
    ``reduceat`` fold — all three produce identical bytes."""
    n = len(pl)
    if n == 0:
        return pl
    pl, starts, seg_id, collision = exchange.sorted_segments(pl)
    if collision:
        return exchange._combine_colliding(pl, plan)
    if len(starts) == n:
        return pl
    if not available():
        return exchange.combine_planes(pl, plan, assume_sorted=True)
    ids = seg_id.astype(np.int32)
    nseg = len(starts)
    out_vals = tuple(
        segment_reduce(op, col, ids, nseg)
        for col, op in zip(pl.vals, plan.combines))
    return exchange._Planes(pl.h[starts],
                            tuple(a[starts] for a in pl.keys), out_vals)


class TopV:
    """Streaming device top-V filter — the vocab build's reduce phase.

    Feed ``update(scores, payloads)`` blocks (token counts + the tokens
    themselves); the running top-``v`` set lives in two small host arrays
    and every selection round is ONE ``jax.lax.top_k`` over a
    fixed-shape candidate buffer (kept ∪ block, padded to a constant
    length — so the whole stream compiles exactly one executable and warm
    repeats compile none). Tie-breaking matches the host heap it
    replaces: candidates pre-sort by payload descending, and ``top_k``'s
    lowest-index tie rule then prefers the larger payload — the
    ``(count, token)`` ordering ``examples/dlrm_features.py`` has always
    used. ``ranked()`` returns ``[(score, payload), ...]`` best-first.
    """

    def __init__(self, v: int, block: int = 65536):
        if v < 1:
            raise ValueError(f"v must be >= 1, got {v}")
        self.v = int(v)
        self.block = int(block)
        self._cap = _pad_len(self.v + self.block)
        self._scores = np.empty(0, dtype=np.int64)
        self._payloads: np.ndarray | None = None  # dtype from first block

    def _topk_kernel(self):
        key = ("topk", self.v, self._cap)
        if key not in _kernels:
            import jax

            from distributeddeeplearningspark_tpu.telemetry.anatomy import (
                instrument)

            def fn(x, _k=self.v):
                return jax.lax.top_k(x, _k)

            _kernels[key] = instrument(
                jax.jit(fn), name="device_agg.top_v",
                expected_signatures=_EXPECTED_SIGS)
        return _kernels[key]

    def update(self, scores: Sequence[int], payloads: Sequence) -> None:
        scores = np.asarray(scores, dtype=np.int64)
        payloads = np.asarray(payloads)
        if self._payloads is None:
            self._payloads = payloads[:0]
        for off in range(0, len(scores), self.block):
            s = np.concatenate([self._scores, scores[off:off + self.block]])
            p = np.concatenate([self._payloads,
                                payloads[off:off + self.block]])
            order = np.argsort(p, kind="stable")[::-1]  # tie-break: payload desc
            s, p = s[order], p[order]
            n = len(s)
            pad = np.full(self._cap, np.iinfo(np.int64).min, dtype=np.int64)
            pad[:n] = s
            import jax.numpy as jnp

            with _x64():
                _vals, idx = self._topk_kernel()(jnp.asarray(pad))
            idx = np.asarray(idx)
            idx = idx[idx < n][:self.v]
            self._scores, self._payloads = s[idx], p[idx]

    def ranked(self) -> list[tuple[int, Any]]:
        if self._payloads is None or not len(self._scores):
            return []
        order = np.lexsort((self._payloads, self._scores))[::-1]
        return list(zip(self._scores[order].tolist(),
                        self._payloads[order].tolist()))
