"""DataFrame — the Spark-SQL-shaped feature-engineering plane.

The reference's config 4 ("Wide&Deep / DLRM recommender on Criteo") feeds the
trainer from *Spark DataFrame features*: ``spark.read.csv`` → ``withColumn`` /
``fillna`` / hashing → executor partitions (SURVEY.md §2 "Data: tabular
pipeline"; VERDICT r1 flagged the missing DataFrame surface). This module
rebuilds that surface TPU-first:

- **Columnar partitions.** A DataFrame partition is a stream of *column
  chunks* (``dict[str, np.ndarray]``, a few thousand rows each). All
  expressions evaluate vectorized over whole chunks — numpy is the host-side
  vector engine standing in for Spark SQL's codegen'd JVM loops — so the
  feature plane keeps up with the HBM feed instead of burning the host on
  per-row Python.
- **Lazy + partition-parallel**, riding :class:`~..rdd.PartitionedDataset`:
  transformations compose chunk functions; actions materialize. One
  partition ≙ one data shard, same as the RDD plane.
- **No shuffle engine** (SURVEY.md §7 "What NOT to build"): joins remain
  out of scope, and ``groupBy(...).agg(...)`` exists WITHOUT one — chunk
  partials merge in a driver dict (vocab-sized results; enforced by the
  ``max_groups`` ceiling, which refuses high-cardinality keys with the
  ``hash_bucket`` remediation), the same honest
  narrow-engine stance as ``rdd.reduce_by_key``. The Criteo feature
  pipeline — typed read, fillna, log-scaling, categorical hashing,
  count-features, split — is fully covered.

Expressions are :class:`Column` trees built from :func:`col` / :func:`lit`
and composed with operators and functions (:func:`log1p`,
:func:`hash_bucket`, ...), mirroring ``pyspark.sql.functions``.
"""

from __future__ import annotations

import itertools
import zlib
from typing import Any, Callable, Iterable, Iterator, Mapping, Sequence

import numpy as np

from ..rdd import PartitionedDataset

Chunk = dict[str, np.ndarray]

DEFAULT_CHUNK_ROWS = 4096


# ---------------------------------------------------------------------------
# Column expressions
# ---------------------------------------------------------------------------

class Column:
    """A vectorized expression over column chunks (pyspark ``Column``-shaped).

    Wraps ``fn(chunk) -> np.ndarray`` plus the output name. Operators build
    new Columns; nothing evaluates until a DataFrame action runs.
    """

    def __init__(self, fn: Callable[[Chunk], np.ndarray], name: str):
        self._fn = fn
        self._name = name

    def __call__(self, chunk: Chunk) -> np.ndarray:
        return self._fn(chunk)

    @property
    def name(self) -> str:
        return self._name

    def alias(self, name: str) -> "Column":
        return Column(self._fn, name)

    def cast(self, dtype) -> "Column":
        return Column(lambda c: self._fn(c).astype(dtype), self._name)

    # -- operators ----------------------------------------------------------

    def _bin(self, other, op, sym) -> "Column":
        other = other if isinstance(other, Column) else lit(other)
        return Column(lambda c: op(self._fn(c), other._fn(c)),
                      f"({self._name} {sym} {other._name})")

    def __add__(self, o): return self._bin(o, np.add, "+")
    def __radd__(self, o): return lit(o)._bin(self, np.add, "+")
    def __sub__(self, o): return self._bin(o, np.subtract, "-")
    def __rsub__(self, o): return lit(o)._bin(self, np.subtract, "-")
    def __mul__(self, o): return self._bin(o, np.multiply, "*")
    def __rmul__(self, o): return lit(o)._bin(self, np.multiply, "*")
    def __truediv__(self, o): return self._bin(o, np.divide, "/")
    def __mod__(self, o): return self._bin(o, np.mod, "%")
    def __gt__(self, o): return self._bin(o, np.greater, ">")
    def __ge__(self, o): return self._bin(o, np.greater_equal, ">=")
    def __lt__(self, o): return self._bin(o, np.less, "<")
    def __le__(self, o): return self._bin(o, np.less_equal, "<=")
    def __eq__(self, o):  # noqa: D105 — pyspark semantics: expr, not identity
        return self._bin(o, np.equal, "==")
    def __ne__(self, o): return self._bin(o, np.not_equal, "!=")
    def __and__(self, o): return self._bin(o, np.logical_and, "&")
    def __or__(self, o): return self._bin(o, np.logical_or, "|")
    def __invert__(self): return Column(lambda c: np.logical_not(self._fn(c)),
                                        f"(~{self._name})")
    __hash__ = None  # unhashable, like pyspark Columns

    def fillna(self, value) -> "Column":
        """NaN (float) / '' (string) → ``value``."""
        def fn(c: Chunk) -> np.ndarray:
            x = self._fn(c)
            if x.dtype.kind == "f":
                return np.where(np.isnan(x), np.asarray(value, x.dtype), x)
            if x.dtype.kind in ("U", "S", "O"):
                return np.where(x == "", value, x)
            return x
        return Column(fn, self._name)

    def isNotNull(self) -> "Column":
        def fn(c: Chunk) -> np.ndarray:
            x = self._fn(c)
            if x.dtype.kind == "f":
                return ~np.isnan(x)
            if x.dtype.kind in ("U", "S", "O"):
                return x != ""
            return np.ones(len(x), bool)
        return Column(fn, f"({self._name} IS NOT NULL)")

    def isNull(self) -> "Column":
        inner = self.isNotNull()
        return Column(lambda c: ~inner(c), f"({self._name} IS NULL)")


def col(name: str) -> Column:
    def fn(chunk: Chunk) -> np.ndarray:
        try:
            return chunk[name]
        except KeyError:
            raise KeyError(
                f"no column {name!r}; have {sorted(chunk)}") from None
    return Column(fn, name)


def lit(value) -> Column:
    def fn(chunk: Chunk) -> np.ndarray:
        n = len(next(iter(chunk.values()))) if chunk else 0
        return np.full(n, value)
    return Column(fn, str(value))


def log1p(c: Column) -> Column:
    """``log(1+x)`` with negatives clamped to 0 first — the standard Criteo
    dense-feature transform (negatives appear in the raw dumps)."""
    return Column(lambda ch: np.log1p(np.maximum(c(ch), 0.0)),
                  f"log1p({c.name})")


def clip(c: Column, lo, hi) -> Column:
    return Column(lambda ch: np.clip(c(ch), lo, hi), f"clip({c.name})")


def when(cond: Column, value) -> "_When":
    return _When([(cond, value)])


class _When:
    """``when(cond, v).otherwise(d)`` chain (vectorized nested where)."""

    def __init__(self, branches: list):
        self._branches = branches

    def when(self, cond: Column, value) -> "_When":
        return _When(self._branches + [(cond, value)])

    def otherwise(self, default) -> Column:
        branches = self._branches

        def fn(chunk: Chunk) -> np.ndarray:
            default_c = default if isinstance(default, Column) else lit(default)
            out = default_c(chunk)
            for cond, value in reversed(branches):
                value_c = value if isinstance(value, Column) else lit(value)
                out = np.where(cond(chunk), value_c(chunk), out)
            return out
        return Column(fn, "CASE WHEN")


def _hash_int_array(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer — deterministic across processes."""
    z = x.astype(np.uint64, copy=True)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def hash_bucket(c: Column, num_buckets: int) -> Column:
    """Stable hash → ``[0, num_buckets)`` int32 (Spark's feature hashing).

    Numeric columns hash via a vectorized splitmix64; string columns via
    crc32 (per-element, host-side — fine at feature-engineering rates).
    Deterministic across runs and processes, unlike Python's ``hash``.
    """
    if num_buckets < 1:
        raise ValueError("num_buckets must be >= 1")

    def fn(chunk: Chunk) -> np.ndarray:
        x = c(chunk)
        if x.dtype.kind in ("i", "u"):
            h = _hash_int_array(x)
        elif x.dtype.kind == "f":
            h = _hash_int_array(x.astype(np.float64).view(np.uint64))
        else:
            h = np.fromiter(
                (zlib.crc32(str(s).encode()) for s in x),
                dtype=np.uint64, count=len(x))
            h = _hash_int_array(h)
        return (h % np.uint64(num_buckets)).astype(np.int32)

    return Column(fn, f"hash_bucket({c.name}, {num_buckets})")


# ---------------------------------------------------------------------------
# DataFrame
# ---------------------------------------------------------------------------

def _chunk_rows(chunk: Chunk) -> int:
    return len(next(iter(chunk.values()))) if chunk else 0


class DataFrame:
    """Lazy columnar dataset: partitions stream column chunks.

    Wraps a :class:`PartitionedDataset` whose elements are chunks
    (``dict[str, np.ndarray]``); ``columns`` is the declared schema order.
    """

    def __init__(self, chunks: PartitionedDataset, columns: Sequence[str]):
        self._chunks = chunks
        self._columns = list(columns)

    # -- schema -------------------------------------------------------------

    @property
    def columns(self) -> list[str]:
        return list(self._columns)

    @property
    def num_partitions(self) -> int:
        return self._chunks.num_partitions

    @property
    def rdd(self) -> PartitionedDataset:
        """Row view: a PartitionedDataset of per-row dicts (Spark ``df.rdd``)."""
        return self.to_dataset()

    # -- transformations (lazy) ---------------------------------------------

    def _map_chunks(self, f: Callable[[Chunk], Chunk],
                    columns: Sequence[str]) -> "DataFrame":
        return DataFrame(
            self._chunks.map_partitions(lambda it: (f(ch) for ch in it)),
            columns)

    def select(self, *exprs: str | Column) -> "DataFrame":
        cols = [col(e) if isinstance(e, str) else e for e in exprs]
        names = [c.name for c in cols]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate output columns: {names}")
        return self._map_chunks(
            lambda ch: {c.name: np.asarray(c(ch)) for c in cols}, names)

    def withColumn(self, name: str, expr: Column) -> "DataFrame":
        names = self._columns + ([] if name in self._columns else [name])

        def f(ch: Chunk) -> Chunk:
            out = dict(ch)
            out[name] = np.asarray(expr(ch))
            return out
        return self._map_chunks(f, names)

    def withColumns(self, mapping: Mapping[str, Column]) -> "DataFrame":
        """All expressions evaluate against the INPUT chunk (pyspark's
        simultaneous semantics: ``{'a': col('b'), 'b': col('a')}`` swaps)."""
        mapping = dict(mapping)
        names = list(self._columns)
        names += [n for n in mapping if n not in names]

        def f(ch: Chunk) -> Chunk:
            out = dict(ch)
            out.update({n: np.asarray(e(ch)) for n, e in mapping.items()})
            return out
        return self._map_chunks(f, names)

    def drop(self, *names: str) -> "DataFrame":
        keep = [c for c in self._columns if c not in names]
        return self._map_chunks(
            lambda ch: {k: v for k, v in ch.items() if k not in names}, keep)

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        return self.withColumn(new, col(old)).drop(old) if old != new else self

    def filter(self, cond: Column) -> "DataFrame":
        def f(ch: Chunk) -> Chunk:
            m = cond(ch).astype(bool)
            return {k: v[m] for k, v in ch.items()}
        return self._map_chunks(f, self._columns)

    where = filter

    def fillna(self, value, subset: Sequence[str] | None = None) -> "DataFrame":
        names = subset if subset is not None else self._columns
        return self.withColumns({n: col(n).fillna(value) for n in names})

    def randomSplit(self, weights: Sequence[float], seed: int = 0
                    ) -> list["DataFrame"]:
        """Split rows by a deterministic per-row hash (stable across runs,
        unlike sampling state threaded through an iterator)."""
        w = np.asarray(weights, np.float64)
        if (w <= 0).any():
            raise ValueError("weights must be positive")
        edges = np.cumsum(w / w.sum())

        def part_for(bucket_frac: np.ndarray) -> np.ndarray:
            return np.searchsorted(edges, bucket_frac, side="right")

        outs = []
        for i in range(len(w)):
            def f(ch: Chunk, i=i) -> Chunk:
                n = _chunk_rows(ch)
                # row identity: position within chunk + a per-chunk content
                # fingerprint, so identical positions in different chunks
                # land independently and the split is replay-stable
                base = np.arange(n, dtype=np.uint64)
                first = next(iter(ch.values())) if ch else base
                fp = zlib.crc32(np.asarray(first).tobytes()) if n else 0
                base = base + np.uint64(fp)
                frac = (_hash_int_array(base + np.uint64(seed)) >> np.uint64(11)
                        ).astype(np.float64) / float(1 << 53)
                m = part_for(frac) == i
                return {k: v[m] for k, v in ch.items()}
            outs.append(self._map_chunks(f, self._columns))
        return outs

    def groupBy(self, *keys: str) -> "GroupedData":
        """Spark ``groupBy(...).agg(...)`` — the aggregation half of the
        Criteo feature-engineering surface (per-category counts/means are
        the classic CTR count-features). Chunk-vectorized per partition
        (np.unique over stacked key rows + bincount / ufunc.at — no
        per-row Python), then per-chunk partials merge in a driver dict:
        the same honest narrow-engine stance as ``rdd.reduce_by_key``
        (SURVEY §7: no shuffle service), sized for grouped results that
        fit the driver — which category vocabularies do.

        Multi-column keys are stacked for the unique pass, so mixed key
        dtypes coerce to the numpy common type (int+str keys become
        strings in the output); keep keys same-typed when that matters.
        """
        missing = [k for k in keys if k not in self._columns]
        if missing or not keys:
            raise ValueError(
                f"groupBy keys {missing or '()'} not in columns "
                f"{self._columns}")
        return GroupedData(self, list(keys))

    def repartition(self, n: int) -> "DataFrame":
        """Down: concatenate adjacent partitions. Up: split each partition's
        chunk stream round-robin (each new partition re-walks its source
        partition and keeps every k-th chunk — extra host IO, no shuffle)."""
        cur = self.num_partitions
        if n <= cur:
            return DataFrame(self._chunks.coalesce(n), self._columns)
        chunks = self._chunks
        fan = [[] for _ in range(cur)]
        for j in range(n):
            fan[j % cur].append(j)

        def make(k: int, slot: int, stride: int):
            def gen() -> Iterator[Chunk]:
                for idx, ch in enumerate(chunks.iter_partition(k)):
                    if idx % stride == slot:
                        yield ch
            return gen

        plan: dict[int, Any] = {}
        for k in range(cur):
            for slot, j in enumerate(fan[k]):
                plan[j] = (k, slot, len(fan[k]))
        parts = [make(*plan[j]) for j in range(n)]
        return DataFrame(PartitionedDataset.from_generators(parts),
                         self._columns)

    # -- actions ------------------------------------------------------------

    def _iter_chunks(self) -> Iterator[Chunk]:
        for i in range(self._chunks.num_partitions):
            yield from self._chunks.iter_partition(i)

    def count(self) -> int:
        return sum(_chunk_rows(ch) for ch in self._iter_chunks())

    def take(self, n: int) -> list[dict]:
        rows: list[dict] = []
        for ch in self._iter_chunks():
            for r in range(_chunk_rows(ch)):
                rows.append({k: v[r] for k, v in ch.items()})
                if len(rows) == n:
                    return rows
        return rows

    def collect(self) -> list[dict]:
        return self.take(float("inf"))  # type: ignore[arg-type]

    def toPandas(self):
        """Concatenate all chunks into one dict of arrays (no pandas in this
        env — returns the columnar dict, which is what callers index anyway)."""
        chunks = list(self._iter_chunks())
        if not chunks:
            return {c: np.empty((0,)) for c in self._columns}
        return {c: np.concatenate([ch[c] for ch in chunks]) for c in self._columns}

    def show(self, n: int = 10) -> None:
        rows = self.take(n)
        print(" | ".join(self._columns))
        for r in rows:
            print(" | ".join(str(r[c]) for c in self._columns))

    # -- bridge to the feed/trainer -----------------------------------------

    def to_dataset(self, *, columns: Sequence[str] | None = None,
                   vector_columns: Mapping[str, Sequence[str]] | None = None
                   ) -> PartitionedDataset:
        """Row view for the HBM feed: a PartitionedDataset of example dicts.

        ``vector_columns`` packs scalar columns into one feature vector per
        example — e.g. ``{"dense": [f"I{i}" for i in range(13)]}`` yields a
        ``[13]`` float array per row, the DLRM input contract — packed
        vectorized per chunk, not per row.
        """
        names = list(columns) if columns is not None else list(self._columns)
        vec = {k: list(v) for k, v in (vector_columns or {}).items()}
        flat_used = {c for cols in vec.values() for c in cols}
        scalars = [c for c in names if c not in flat_used]

        def rows(it: Iterable[Chunk]) -> Iterator[dict]:
            for ch in it:
                n = _chunk_rows(ch)
                packed = {k: np.stack([ch[c] for c in cols], axis=1)
                          for k, cols in vec.items()}
                for r in range(n):
                    ex = {c: ch[c][r] for c in scalars}
                    ex.update({k: v[r] for k, v in packed.items()})
                    yield ex

        return self._chunks.map_partitions(rows)

    toDataset = to_dataset

    def __repr__(self) -> str:
        return (f"DataFrame(columns={self._columns}, "
                f"num_partitions={self.num_partitions})")


#: supported GroupedData aggregations; mean derives from (sum, count) so
#: every entry here is mergeable across chunk partials
_AGG_FNS = ("count", "sum", "mean", "min", "max")


def _agg_partial(ch: Chunk, keys: Sequence[str],
                 spec: Mapping[str, str]) -> list:
    """One chunk's vectorized group partials: ``[(key_tuple, (count,
    (col_stats, ...)))]`` with one ``col_stats`` per spec column in spec
    order — ``None`` for count-only columns, ``(sum, min|None, max|None)``
    otherwise. Per value column only the stats its fn needs are computed
    (ufunc.at is a per-element C loop; paying min/max passes for a
    sum-only spec would undercut the vectorized claim); mean derives from
    (sum, count). Keys and stats are PYTHON scalars (``.tolist()``), not
    numpy ones — a 10M-key shuffle pickles every entry, and np.int64
    pickles ~20x slower and 5x bigger than int. Keys are unique within the
    returned list (np.unique dedups the chunk). Shared by the serial
    driver merge and the distributed exchange mappers, so both paths
    produce identical partials."""
    n = _chunk_rows(ch)
    if n == 0:
        return []
    key_arrays = [np.asarray(ch[k]) for k in keys]
    for k, a in zip(keys, key_arrays):
        if a.dtype == object:
            # np.unique(axis=0) can't take object arrays and its
            # TypeError names neither column nor fix — fail clearly
            raise ValueError(
                f"groupBy key '{k}' has object dtype (e.g. None "
                f"among values); fillna()/hash_bucket it to a "
                f"concrete dtype first")
        if np.issubdtype(a.dtype, np.floating) and np.isnan(a).any():
            # tuple(nan) dict keys never compare equal, so NaN
            # groups would silently split per chunk instead of
            # merging — the fillna-first flow is the documented fix
            raise ValueError(
                f"groupBy key '{k}' contains NaN; fillna() it "
                f"first (NaN never equals NaN, so NaN groups "
                f"cannot merge)")
    stacked = np.stack(key_arrays, axis=1)
    uniq, inv = np.unique(stacked, axis=0, return_inverse=True)
    g = uniq.shape[0]
    cnt = np.bincount(inv, minlength=g).tolist()
    cols: list = []
    for c, fn in spec.items():
        if fn == "count":
            # bincount already carries the answer; coercing the
            # column would also crash string-typed count() keys
            cols.append(None)
            continue
        v = np.asarray(ch[c], np.float64)
        s = np.bincount(inv, weights=v, minlength=g)
        mn = mx = None
        if fn == "min":
            mn = np.full(g, np.inf)
            np.minimum.at(mn, inv, v)
        elif fn == "max":
            mx = np.full(g, -np.inf)
            np.maximum.at(mx, inv, v)
        cols.append((s.tolist(),
                     None if mn is None else mn.tolist(),
                     None if mx is None else mx.tolist()))
    # zip-built (C speed): a per-key Python genexpr here was the single
    # hottest line of a 10M-key shuffle's map phase
    per_col: list = []
    for col in cols:
        if col is None:
            per_col.append(itertools.repeat(None, g))
        else:
            s, mn, mx = col
            per_col.append(zip(s,
                               mn if mn is not None
                               else itertools.repeat(None),
                               mx if mx is not None
                               else itertools.repeat(None)))
    entries = zip(cnt, zip(*per_col))
    return list(zip(map(tuple, uniq.tolist()), entries))


def _merge_agg_entry(a: tuple, b: tuple) -> tuple:
    """Merge two group partials (commutative — sum/min/max/count), the
    exchange's combine/merge function for ``groupBy().agg``."""
    cnt = a[0] + b[0]
    out: list = []
    for sa, sb in zip(a[1], b[1]):
        if sa is None:
            out.append(None)
            continue
        out.append((sa[0] + sb[0],
                    sa[1] if sb[1] is None else
                    (sb[1] if sa[1] is None else min(sa[1], sb[1])),
                    sa[2] if sb[2] is None else
                    (sb[2] if sa[2] is None else max(sa[2], sb[2]))))
    return (cnt, tuple(out))


def _agg_row_value(fn: str, cnt: int, stats) -> Any:
    """One output cell from a merged group entry — the ONE formula both
    paths share (mean = sum/count, so bit-equality follows from the
    partials being equal)."""
    if fn == "count":
        return cnt
    s, mn, mx = stats
    return {"sum": s, "mean": s / cnt if cnt else np.nan,
            "min": mn, "max": mx}[fn]


def _agg_plane_layout(spec: Mapping[str, str]) -> tuple[tuple, tuple]:
    """The columnar value-plane layout for one agg spec: plane 0 is the
    int64 group count (combined by sum), then per non-count spec column
    its f64 sum plane (+ a min or max plane when the fn needs one).
    ``slots[i]`` maps spec column i back into the plane tuple —
    ``None`` for count-only columns, else ``(sum_i, min_i, max_i)``."""
    combines: list[str] = ["sum"]
    slots: list = []
    for _c, fn in spec.items():
        if fn == "count":
            slots.append(None)
            continue
        s_i = len(combines)
        combines.append("sum")
        m_i = x_i = None
        if fn == "min":
            m_i = len(combines)
            combines.append("min")
        elif fn == "max":
            x_i = len(combines)
            combines.append("max")
        slots.append((s_i, m_i, x_i))
    return tuple(combines), tuple(slots)


def _agg_partial_planes(ch: Chunk, keys: Sequence[str],
                        spec: Mapping[str, str]):
    """One chunk's group partials as flat planes — the columnar twin of
    :func:`_agg_partial`, staying numpy end to end (no ``.tolist()``, no
    per-key tuples except the one hash pass). Returns ``None`` when the
    chunk's keys are not columnar-eligible (strings, objects, NaN floats,
    uint64) — that chunk then walks :func:`_agg_partial` and ships as
    pickled tuples, byte-identically. The key hashes ARE computed from
    the same python-scalar tuples the tuple path would pickle, so both
    formats land every key in the same bucket at the same sort position."""
    from distributeddeeplearningspark_tpu.data import exchange

    key_arrays = [np.asarray(ch[k]) for k in keys]
    if any(exchange.canon_key_dtype(a.dtype) is None for a in key_arrays):
        return None
    for a in key_arrays:
        if np.issubdtype(a.dtype, np.floating):
            # NaN keys: the tuple path refuses them with the fillna
            # remediation (_agg_partial) — fall back so the error is
            # THAT error, not a silently different grouping. Zeros:
            # -0.0 == 0.0 under np.unique/dict merging but they pickle
            # to different key bytes, and only the tuple path carries
            # the dict-merge semantics — every ±0.0 float key goes
            # there (a columnar +0.0 could never merge with a
            # tuple-path -0.0 from another chunk)
            if np.isnan(a).any():
                return None
            if (a == 0).any():
                return None
    stacked = np.stack(key_arrays, axis=1)
    canon = exchange.canon_key_dtype(stacked.dtype)
    if canon is None:  # mixed dtypes promoted past fixed-width numerics
        return None
    uniq, inv = np.unique(stacked, axis=0, return_inverse=True)
    g = uniq.shape[0]
    h = exchange.hash_rows(list(map(tuple, uniq.tolist())))
    key_cols = tuple(np.ascontiguousarray(uniq[:, i]).astype(canon)
                     for i in range(uniq.shape[1]))
    vals: list[np.ndarray] = [
        np.bincount(inv, minlength=g).astype(np.int64)]
    for c, fn in spec.items():
        if fn == "count":
            continue
        v = np.asarray(ch[c], np.float64)
        vals.append(np.bincount(inv, weights=v, minlength=g))
        if fn == "min":
            mn = np.full(g, np.inf)
            np.minimum.at(mn, inv, v)
            vals.append(mn)
        elif fn == "max":
            mx = np.full(g, -np.inf)
            np.maximum.at(mx, inv, v)
            vals.append(mx)
    return exchange._Planes(h, key_cols, tuple(vals))


def _agg_columnar_plan(keys: Sequence[str], spec: Mapping[str, str]):
    """The agg spec's :class:`~.exchange.ColumnarPlan` plus the slot map
    read-side consumers decode value planes with."""
    from distributeddeeplearningspark_tpu.data import exchange

    combines, slots = _agg_plane_layout(spec)

    def vals_to_acc(vs: tuple):
        per_col = []
        for slot in slots:
            if slot is None:
                per_col.append(None)
            else:
                s_i, m_i, x_i = slot
                per_col.append((vs[s_i],
                                vs[m_i] if m_i is not None else None,
                                vs[x_i] if x_i is not None else None))
        return (vs[0], tuple(per_col))

    plan = exchange.ColumnarPlan(
        combines=combines,
        pre_planes=lambda ch: _agg_partial_planes(ch, keys, spec),
        key_of_row=lambda kr: kr,
        vals_to_acc=vals_to_acc,
        row_emit=lambda k, vs: (k, vals_to_acc(vs)))
    return plan, slots


def _agg_chunk_from_planes(keys: Sequence[str], spec: Mapping[str, str],
                           slots: tuple):
    """Chunk builder off raw combined planes — the read-side finalize
    (mean = sum/count happens HERE, with the same f64 division
    :func:`_agg_row_value` performs per row, so the bytes agree)."""
    def build(pl) -> Chunk:
        ch: Chunk = {k: pl.keys[i] for i, k in enumerate(keys)}
        cnt = pl.vals[0]
        for (c, fn), slot in zip(spec.items(), slots):
            name = f"{fn}({c})"
            if fn == "count":
                ch[name] = cnt
            else:
                s_i, m_i, x_i = slot
                if fn == "sum":
                    ch[name] = pl.vals[s_i]
                elif fn == "mean":
                    ch[name] = pl.vals[s_i] / cnt
                elif fn == "min":
                    ch[name] = pl.vals[m_i]
                else:
                    ch[name] = pl.vals[x_i]
        return ch

    return build


class GroupedData:
    """Result of :meth:`DataFrame.groupBy`; terminal ops produce a
    single-partition DataFrame of one row per group."""

    def __init__(self, df: DataFrame, keys: list[str]):
        self._df = df
        self._keys = keys

    def count(self) -> DataFrame:
        """Group sizes as a ``count`` column (pyspark's ``.count()``)."""
        if "count" in self._keys:
            # withColumnRenamed would REPLACE the key column with the
            # counts, silently losing the group identities
            raise ValueError(
                "a groupBy key is literally named 'count'; use "
                "agg({key: 'count'}) (keeps the 'count(key)' name) or "
                "rename the key column first")
        out = self.agg({self._keys[0]: "count"})
        return out.withColumnRenamed(f"count({self._keys[0]})", "count")

    def agg(self, spec: Mapping[str, str], *,
            max_groups: int | None = None,
            num_workers: int | None = None,
            transport: str | None = None) -> DataFrame:
        """``{"col": "sum"|"mean"|"min"|"max"|"count"}`` → one row per
        distinct key tuple, pyspark-style ``fn(col)`` output names.

        Lazy like every other verb (the module's contract): the source
        scan runs on the output's first iteration, memoized cache()-style
        after that.

        With workers (``num_workers=`` / ``DLS_DATA_WORKERS``): the scan
        routes through the distributed exchange (:mod:`~.exchange`) —
        chunk partials bucket by canonical key hash, per-bucket reducers
        merge with spill-to-disk under ``DLS_SHUFFLE_MEM_MB`` — so there
        is NO cardinality ceiling; a 10M-key aggregation completes under a
        bounded memory budget. Output rows stream bucket-major in
        canonical key order, one partition per bucket.

        ``transport`` (default ``DLS_SHUFFLE_TRANSPORT`` or ``auto``)
        picks the exchange's data-plane format: ``auto``/``columnar``
        ships numeric-key chunks as flat planes (key-hash + key columns +
        value arrays; an order of magnitude faster at 10M keys) with
        byte-identical per-chunk fallback to ``tuple`` for non-conforming
        keys; ``tuple`` forces the per-key pickled path (the measurement
        baseline); ``device`` skips the worker exchange entirely and
        lowers the combines onto the accelerator as jitted
        ``jax.ops.segment_*`` kernels (:mod:`~.device_agg`, compiles
        ledgered by ``dlstatus --anatomy``) — numeric keys required,
        result arrays driver-resident (~32B/key, no ``max_groups``
        ceiling), output bit-equal to the exchange under the float-sum
        exactness proviso both paths share.

        Serial (no workers): chunk partials merge in a DRIVER-SIDE dict —
        fine for the vocab-sized results this plane is documented for
        (Criteo's 26 categorical vocabularies) — bounded by ``max_groups``
        (default ``DLS_AGG_MAX_GROUPS`` or 1_000_000); past the ceiling
        the scan refuses loudly, naming ``DLS_DATA_WORKERS`` (the exchange)
        as the first remediation. Rows come in the SAME canonical bucket-
        major order as the exchange path, so results are byte-identical at
        any worker count.
        """
        keys, df = self._keys, self._df
        from distributeddeeplearningspark_tpu.data import exchange

        if max_groups is None:
            max_groups = exchange.max_groups_limit()
        if max_groups < 1:
            raise ValueError(f"max_groups must be >= 1, got {max_groups}")
        bad = {c: f for c, f in spec.items()
               if f not in _AGG_FNS or c not in df.columns}
        if bad or not spec:
            raise ValueError(
                f"unsupported agg spec {bad or spec!r}; columns="
                f"{df.columns}, fns={_AGG_FNS}")
        names = keys + [f"{f}({c})" for c, f in spec.items()]
        spec = dict(spec)
        n_out = df._chunks.num_partitions
        transport = exchange.resolve_transport(transport, allow_device=True)

        if transport == "device":
            return _device_agg_frame(df, keys, spec, names, n_out)

        nw = exchange.resolve_shuffle_workers(num_workers)
        if nw:
            ex_spec = exchange._Spec(
                pre=lambda ch: _agg_partial(ch, keys, spec),
                combine=_merge_agg_entry)

            def to_chunks(it: Iterable) -> Iterator[Chunk]:
                buf: list[tuple] = []

                def emit(buf: list[tuple]) -> Chunk:
                    ch: Chunk = {
                        k: np.asarray([key[i] for key, _ in buf])
                        for i, k in enumerate(keys)}
                    for ci, (c, f) in enumerate(spec.items()):
                        ch[f"{f}({c})"] = np.asarray(
                            [_agg_row_value(f, cnt, per_col[ci])
                             for _, (cnt, per_col) in buf])
                    return ch

                for rec in it:
                    buf.append(rec)
                    if len(buf) >= DEFAULT_CHUNK_ROWS:
                        yield emit(buf)
                        buf = []
                if buf:
                    yield emit(buf)

            if transport == "tuple":
                recs = exchange._lazy_exchange_dataset(
                    df._chunks._parts, num_workers=nw, n_out=n_out,
                    spec=ex_spec, label="groupBy.agg")
                return DataFrame(recs.map_partitions(to_chunks), names)

            # columnar: share one memoized ShuffleResult so columnar
            # buckets build chunks STRAIGHT from the output planes (no
            # per-row Python on the read side either); tuple-format
            # buckets (mixed-eligibility datasets) fall back to the row
            # reader — same bytes, chunked at the block size instead
            plan, slots = _agg_columnar_plan(keys, spec)
            result = exchange.lazy_exchange(
                df._chunks._parts, num_workers=nw, n_out=n_out,
                spec=ex_spec, label="groupBy.agg", plan=plan)
            from_planes = _agg_chunk_from_planes(keys, spec, slots)

            def make_part(bucket: int):
                def gen() -> Iterator[Chunk]:
                    res = result()
                    pit = res.iter_bucket_planes(bucket)
                    if pit is not None:
                        for pl in pit:
                            if len(pl):
                                yield from_planes(pl)
                    else:
                        yield from to_chunks(res.iter_bucket(bucket))
                return gen

            return DataFrame(
                PartitionedDataset.from_generators(
                    [make_part(b) for b in range(n_out)]), names)

        memo: dict = {}

        def result_chunk() -> Chunk:
            if "chunk" in memo:
                return memo["chunk"]
            acc: dict = {}
            for ch in df._iter_chunks():
                for key, (cnt, per_col) in _agg_partial(ch, keys, spec):
                    if key not in acc:
                        if len(acc) >= max_groups:
                            raise ValueError(
                                f"groupBy({keys}).agg() exceeded max_groups="
                                f"{max_groups} distinct keys — the partials "
                                f"merge in a driver-side dict sized for "
                                f"vocab-scale results, and this key looks "
                                f"high-cardinality (user-id-like). Set "
                                f"DLS_DATA_WORKERS=N (or pass num_workers=) "
                                f"to route through the distributed shuffle "
                                f"exchange, which spills to disk under "
                                f"DLS_SHUFFLE_MEM_MB and has no ceiling; or "
                                f"hash_bucket(col({keys[0]!r}), num_buckets) "
                                f"the key first to bound the result; or "
                                f"raise max_groups= / DLS_AGG_MAX_GROUPS if "
                                f"the grouped result genuinely fits the "
                                f"driver")
                        acc[key] = (cnt, per_col)
                    else:
                        acc[key] = _merge_agg_entry(acc[key],
                                                    (cnt, per_col))
            # canonical bucket-major, key_bytes-ordered rows — the exact
            # layout the exchange path streams, so 0 workers == N workers
            keyed = []
            for k in acc:
                kb = exchange.key_bytes(k)
                keyed.append(((exchange.bucket_of(kb, n_out), kb), k))
            keyed.sort(key=lambda t: t[0])
            rows_keys = [k for _, k in keyed]
            chunk: Chunk = {
                k: np.asarray([rk[i] for rk in rows_keys])
                for i, k in enumerate(keys)
            }
            for ci, (c, f) in enumerate(spec.items()):
                chunk[f"{f}({c})"] = np.asarray(
                    [_agg_row_value(f, acc[rk][0], acc[rk][1][ci])
                     for rk in rows_keys])
            memo["chunk"] = chunk
            return chunk

        return DataFrame(
            PartitionedDataset.from_generators(
                [lambda: iter([result_chunk()])]),
            names)


def _device_agg_frame(df: DataFrame, keys: list[str],
                      spec: Mapping[str, str], names: list[str],
                      n_out: int) -> DataFrame:
    """``groupBy().agg(transport="device")``: serial chunk scan into
    columnar partials, combines lowered onto the accelerator
    (:func:`~.device_agg.segment_combine` — jitted ``jax.ops.segment_*``
    under the PR 9 compile ledger), output in the SAME canonical
    bucket-major key-hash order as the exchange, so the bytes agree.

    Partials compact against ``DLS_SHUFFLE_MEM_MB`` as they accumulate
    (each compaction is itself a device combine), so the scan's resident
    set is bounded; the RESULT is driver-resident flat arrays (~32 bytes
    per key — no ``max_groups`` ceiling, that guard exists for python
    dict blowup, not for arrays). Emits the standard ``shuffle`` done
    event with ``transport="device"`` plus the map/merge phase pair, so
    ``dlstatus`` renders it like any exchange."""
    import time as _time

    from distributeddeeplearningspark_tpu import telemetry
    from distributeddeeplearningspark_tpu.data import device_agg, exchange

    plan, slots = _agg_columnar_plan(keys, spec)
    from_planes = _agg_chunk_from_planes(keys, spec, slots)
    memo: dict = {}

    def buckets() -> dict:
        if "b" in memo:
            return memo["b"]
        if not device_agg.available():
            raise RuntimeError(
                "transport='device' needs a usable jax backend "
                "(data/device_agg.py probe failed — see its warning); "
                "use transport='columnar' with DLS_DATA_WORKERS instead")
        budget = exchange.mem_budget_bytes()
        t0 = _time.perf_counter()
        telemetry.emit("phase", name="shuffle-map", edge="begin",
                       op="groupBy.agg")
        batches: list = []
        held = elems = pairs = moved = 0
        # compaction threshold doubles when a combine fails to shrink
        # below it (distinct keys legitimately outgrowing the budget) —
        # otherwise EVERY later chunk would re-sort the whole accumulated
        # set and the scan would go quadratic in chunk count
        compact_at = budget
        aborted = True
        try:
            for ch in df._iter_chunks():
                elems += 1
                pl = plan.pre_planes(ch)
                if pl is None:
                    raise ValueError(
                        f"transport='device' needs numeric (int/float/"
                        f"bool, non-NaN, no ±0.0 floats) groupBy keys; "
                        f"{keys} do not conform — fillna()/hash_bucket "
                        f"them first, or use transport='columnar', whose "
                        f"per-chunk tuple fallback handles them")
                pairs += len(pl)
                moved += pl.nbytes
                batches.append(pl)
                held += pl.nbytes
                if held >= compact_at and len(batches) > 1:
                    batches = [device_agg.segment_combine(
                        exchange._Planes.concat(batches), plan)]
                    held = batches[0].nbytes
                    while held >= compact_at:
                        compact_at *= 2
            aborted = False
        finally:
            map_s = _time.perf_counter() - t0
            telemetry.emit("phase", name="shuffle-map", edge="end",
                           dur_s=map_s, op="groupBy.agg",
                           **({"aborted": True} if aborted else {}))
        t1 = _time.perf_counter()
        telemetry.emit("phase", name="shuffle-merge", edge="begin",
                       op="groupBy.agg")
        aborted = True
        try:
            out: dict[int, Any] = {}
            if batches:
                combined = device_agg.segment_combine(
                    exchange._Planes.concat(batches), plan)
                out = {b: sub for b, sub
                       in exchange._bucket_split(combined, n_out)}
            aborted = False
        finally:
            merge_s = _time.perf_counter() - t1
            telemetry.emit("phase", name="shuffle-merge", edge="end",
                           dur_s=merge_s, op="groupBy.agg",
                           **({"aborted": True} if aborted else {}))
        rows_list = [len(out.get(b, ())) for b in range(n_out)]
        telemetry.emit(
            "shuffle", edge="done", op="groupBy.agg", workers=0,
            reducers=0, buckets=n_out, elems_in=elems, pairs_in=pairs,
            rows_out=sum(rows_list), bytes_moved=moved, overflow=0,
            spills=0, spill_bytes=0, map_s=round(map_s, 3),
            merge_s=round(merge_s, 3), bucket_rows=rows_list,
            mem_budget_mb=round(budget / (1 << 20), 1),
            transport="device", columnar_pairs=pairs,
            columnar_bytes=moved, tuple_pairs=0, tuple_bytes=0,
            columnar_buckets=sum(1 for r in rows_list if r),
            tuple_buckets=0)
        memo["b"] = out
        return out

    def make_part(bucket: int):
        def gen() -> Iterator[Chunk]:
            pl = buckets().get(bucket)
            if pl is not None and len(pl):
                for lo in range(0, len(pl), DEFAULT_CHUNK_ROWS):
                    yield from_planes(
                        pl.cut(lo, min(lo + DEFAULT_CHUNK_ROWS, len(pl))))
        return gen

    return DataFrame(
        PartitionedDataset.from_generators(
            [make_part(b) for b in range(n_out)]), names)


# ---------------------------------------------------------------------------
# Readers
# ---------------------------------------------------------------------------

def from_rows(rows: Sequence[Mapping[str, Any]], *, num_partitions: int = 2,
              chunk_rows: int = DEFAULT_CHUNK_ROWS) -> DataFrame:
    """``createDataFrame``: columnarize a row sequence (driver-side)."""
    if not rows:
        raise ValueError("cannot infer schema from zero rows")
    names = list(rows[0].keys())
    ds = PartitionedDataset.parallelize(list(rows), num_partitions)
    return from_dataset(ds, names, chunk_rows=chunk_rows)


def from_dataset(ds: PartitionedDataset, columns: Sequence[str], *,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS) -> DataFrame:
    """Columnarize a PartitionedDataset of row dicts (the RDD→DF bridge)."""
    names = list(columns)

    def chunker(it: Iterable[Mapping]) -> Iterator[Chunk]:
        buf: list[Mapping] = []
        for r in it:
            buf.append(r)
            if len(buf) == chunk_rows:
                yield {n: np.asarray([b[n] for b in buf]) for n in names}
                buf = []
        if buf:
            yield {n: np.asarray([b[n] for b in buf]) for n in names}

    return DataFrame(ds.map_partitions(chunker), names)


def _expand_paths(paths: str | Sequence[str]) -> list[str]:
    """Glob-or-literal path expansion shared by the readers.

    A string containing glob metacharacters expands (sorted); a literal
    string that exists is used as-is even if it contains ``[``/``?``
    (e.g. ``data[1].parquet``); lists pass through with existence checks.
    """
    import glob as _glob
    import os

    if isinstance(paths, str):
        if os.path.exists(paths):
            expanded = [paths]
        elif any(ch in paths for ch in "*?["):
            expanded = sorted(_glob.glob(paths))
        else:
            expanded = [paths]
    else:
        expanded = list(paths)
    if not expanded:
        raise FileNotFoundError(f"no files match {paths!r}")
    for p in expanded:
        if not os.path.exists(p):
            raise FileNotFoundError(p)
    return expanded


def read_csv(
    paths: str | Sequence[str],
    *,
    names: Sequence[str],
    sep: str = ",",
    dtypes: Mapping[str, Any] | None = None,
    num_partitions: int = 2,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
) -> DataFrame:
    """Typed delimited-text reader (``spark.read.csv``-shaped).

    Files (or one file split by contiguous line ranges) spread over
    ``num_partitions``. Missing fields parse as NaN (float columns) / ''
    (string columns). ``dtypes`` maps column → numpy dtype; default f4.
    """
    expanded = _expand_paths(paths)
    names = list(names)
    dtypes = dict(dtypes or {})
    np_dtypes = {n: np.dtype(dtypes.get(n, np.float32)) for n in names}

    def parse_lines(lines: Iterable[str]) -> Iterator[Chunk]:
        buf: list[list[str]] = []

        def flush(buf: list[list[str]]) -> Chunk:
            cols: Chunk = {}
            for j, n in enumerate(names):
                raw = [row[j] if j < len(row) else "" for row in buf]
                dt = np_dtypes[n]
                if dt.kind == "f":
                    cols[n] = np.array(
                        [float(x) if x else np.nan for x in raw], dt)
                elif dt.kind in ("i", "u"):
                    cols[n] = np.array(
                        [int(x) if x else 0 for x in raw], dt)
                else:
                    cols[n] = np.array(raw, dtype=np.str_)
            return cols

        for line in lines:
            line = line.rstrip("\n")
            if not line:
                continue
            buf.append(line.split(sep))
            if len(buf) == chunk_rows:
                yield flush(buf)
                buf = []
        if buf:
            yield flush(buf)

    # several files but fewer than requested partitions: clamp to one
    # partition per file (repartition(n) can split streams afterwards)
    if 1 < len(expanded) < num_partitions:
        num_partitions = len(expanded)
    if len(expanded) >= num_partitions:
        file_groups = np.array_split(np.array(expanded, object), num_partitions)

        def make_part(group) -> Callable[[], Iterator[Chunk]]:
            def gen() -> Iterator[Chunk]:
                def lines() -> Iterator[str]:
                    for fname in group:
                        with open(fname, "r") as f:
                            yield from f
                return parse_lines(lines())
            return gen

        parts = [make_part(g) for g in file_groups if len(g)]
    else:
        # split each file by contiguous line ranges (counted once, driver-side)
        fname = expanded[0]
        with open(fname, "r") as f:
            total = sum(1 for _ in f)
        bounds = [(i * total // num_partitions, (i + 1) * total // num_partitions)
                  for i in range(num_partitions)]

        def make_range(lo: int, hi: int) -> Callable[[], Iterator[Chunk]]:
            def gen() -> Iterator[Chunk]:
                def lines() -> Iterator[str]:
                    with open(fname, "r") as f:
                        for i, line in enumerate(f):
                            if i >= hi:
                                break
                            if i >= lo:
                                yield line
                return parse_lines(lines())
            return gen

        parts = [make_range(lo, hi) for lo, hi in bounds]

    return DataFrame(PartitionedDataset.from_generators(parts), names)


def read_parquet(
    paths: str | Sequence[str],
    *,
    columns: Sequence[str] | None = None,
    num_partitions: int = 2,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
) -> DataFrame:
    """Parquet reader (``spark.read.parquet``-shaped), via pyarrow.

    Files spread over partitions (one partition reads a contiguous file
    group; a single file splits by row-group ranges). Column chunks stream
    batch-at-a-time — a partition never materializes its whole file set.
    """
    import os

    import pyarrow.parquet as pq

    # Spark's canonical input is a directory of part files (escape the
    # directory name so its own glob metacharacters stay literal)
    if isinstance(paths, str) and os.path.isdir(paths):
        import glob as _glob

        paths = os.path.join(_glob.escape(paths), "*.parquet")
    expanded = _expand_paths(paths)
    if columns is not None:
        names = list(columns)
    else:
        names = list(pq.read_schema(expanded[0]).names)

    def batches_to_chunks(batches) -> Iterator[Chunk]:
        for rb in batches:
            yield {n: rb.column(n).to_numpy(zero_copy_only=False)
                   for n in names}

    if 1 < len(expanded) < num_partitions:
        num_partitions = len(expanded)
    if len(expanded) >= num_partitions:
        groups = np.array_split(np.array(expanded, object), num_partitions)

        def make_files(group) -> Callable[[], Iterator[Chunk]]:
            def gen() -> Iterator[Chunk]:
                for fname in group:
                    f = pq.ParquetFile(fname)
                    yield from batches_to_chunks(
                        f.iter_batches(batch_size=chunk_rows, columns=names))
            return gen

        parts = [make_files(g) for g in groups if len(g)]
    else:
        f0 = pq.ParquetFile(expanded[0])
        n_rg = f0.num_row_groups
        rg_bounds = [(i * n_rg // num_partitions, (i + 1) * n_rg // num_partitions)
                     for i in range(num_partitions)]

        def make_rgs(lo: int, hi: int) -> Callable[[], Iterator[Chunk]]:
            def gen() -> Iterator[Chunk]:
                if lo >= hi:
                    return
                f = pq.ParquetFile(expanded[0])
                yield from batches_to_chunks(
                    f.iter_batches(batch_size=chunk_rows, columns=names,
                                   row_groups=list(range(lo, hi))))
            return gen

        parts = [make_rgs(lo, hi) for lo, hi in rg_bounds]

    return DataFrame(PartitionedDataset.from_generators(parts), names)


class DataFrameReader:
    """``session.read`` surface: ``.option(...).schema(...).csv(path)`` /
    ``.parquet(path)``."""

    def __init__(self, *, default_parallelism: int = 2):
        self._opts: dict[str, Any] = {"sep": ","}
        self._names: Sequence[str] | None = None
        self._dtypes: Mapping[str, Any] | None = None
        self._parallelism = default_parallelism

    def option(self, key: str, value) -> "DataFrameReader":
        self._opts[key] = value
        return self

    def schema(self, names: Sequence[str],
               dtypes: Mapping[str, Any] | None = None) -> "DataFrameReader":
        self._names = names
        self._dtypes = dtypes
        return self

    def csv(self, path: str | Sequence[str]) -> DataFrame:
        if self._names is None:
            raise ValueError("call .schema([...column names...]) before .csv()")
        return read_csv(
            path, names=self._names, sep=str(self._opts.get("sep", ",")),
            dtypes=self._dtypes,
            num_partitions=int(self._opts.get(
                "num_partitions", self._parallelism)))

    def parquet(self, path: str | Sequence[str]) -> DataFrame:
        # schema travels in the file; .schema() narrows columns, and its
        # dtypes (meaningful for text parsing in .csv) apply here as casts
        # so the same .schema(...) pipeline behaves identically on parquet
        df = read_parquet(
            path, columns=self._names,
            num_partitions=int(self._opts.get(
                "num_partitions", self._parallelism)))
        if self._dtypes:
            df = df.withColumns(
                {n: col(n).cast(dt) for n, dt in self._dtypes.items()})
        return df
