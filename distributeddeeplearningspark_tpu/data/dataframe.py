"""DataFrame — the Spark-SQL-shaped feature-engineering plane.

The reference's config 4 ("Wide&Deep / DLRM recommender on Criteo") feeds the
trainer from *Spark DataFrame features*: ``spark.read.csv`` → ``withColumn`` /
``fillna`` / hashing → executor partitions (SURVEY.md §2 "Data: tabular
pipeline"; VERDICT r1 flagged the missing DataFrame surface). This module
rebuilds that surface TPU-first:

- **Columnar partitions.** A DataFrame partition is a stream of *column
  chunks* (``dict[str, np.ndarray]``, a few thousand rows each). All
  expressions evaluate vectorized over whole chunks — numpy is the host-side
  vector engine standing in for Spark SQL's codegen'd JVM loops — so the
  feature plane keeps up with the HBM feed instead of burning the host on
  per-row Python.
- **Lazy + partition-parallel**, riding :class:`~..rdd.PartitionedDataset`:
  transformations compose chunk functions; actions materialize. One
  partition ≙ one data shard, same as the RDD plane.
- **No shuffle engine** (SURVEY.md §7 "What NOT to build"): joins remain
  out of scope, and ``groupBy(...).agg(...)`` exists WITHOUT one — chunk
  partials merge in a driver dict (vocab-sized results; enforced by the
  ``max_groups`` ceiling, which refuses high-cardinality keys with the
  ``hash_bucket`` remediation), the same honest
  narrow-engine stance as ``rdd.reduce_by_key``. The Criteo feature
  pipeline — typed read, fillna, log-scaling, categorical hashing,
  count-features, split — is fully covered.

Expressions are :class:`Column` trees built from :func:`col` / :func:`lit`
and composed with operators and functions (:func:`log1p`,
:func:`hash_bucket`, ...), mirroring ``pyspark.sql.functions``.
"""

from __future__ import annotations

import itertools
import zlib
from typing import Any, Callable, Iterable, Iterator, Mapping, Sequence

import numpy as np

from ..rdd import PartitionedDataset

Chunk = dict[str, np.ndarray]

DEFAULT_CHUNK_ROWS = 4096


# ---------------------------------------------------------------------------
# Column expressions
# ---------------------------------------------------------------------------

class Column:
    """A vectorized expression over column chunks (pyspark ``Column``-shaped).

    Wraps ``fn(chunk) -> np.ndarray`` plus the output name. Operators build
    new Columns; nothing evaluates until a DataFrame action runs.
    """

    def __init__(self, fn: Callable[[Chunk], np.ndarray], name: str):
        self._fn = fn
        self._name = name

    def __call__(self, chunk: Chunk) -> np.ndarray:
        return self._fn(chunk)

    @property
    def name(self) -> str:
        return self._name

    def alias(self, name: str) -> "Column":
        return Column(self._fn, name)

    def cast(self, dtype) -> "Column":
        return Column(lambda c: self._fn(c).astype(dtype), self._name)

    # -- operators ----------------------------------------------------------

    def _bin(self, other, op, sym) -> "Column":
        other = other if isinstance(other, Column) else lit(other)
        return Column(lambda c: op(self._fn(c), other._fn(c)),
                      f"({self._name} {sym} {other._name})")

    def __add__(self, o): return self._bin(o, np.add, "+")
    def __radd__(self, o): return lit(o)._bin(self, np.add, "+")
    def __sub__(self, o): return self._bin(o, np.subtract, "-")
    def __rsub__(self, o): return lit(o)._bin(self, np.subtract, "-")
    def __mul__(self, o): return self._bin(o, np.multiply, "*")
    def __rmul__(self, o): return lit(o)._bin(self, np.multiply, "*")
    def __truediv__(self, o): return self._bin(o, np.divide, "/")
    def __mod__(self, o): return self._bin(o, np.mod, "%")
    def __gt__(self, o): return self._bin(o, np.greater, ">")
    def __ge__(self, o): return self._bin(o, np.greater_equal, ">=")
    def __lt__(self, o): return self._bin(o, np.less, "<")
    def __le__(self, o): return self._bin(o, np.less_equal, "<=")
    def __eq__(self, o):  # noqa: D105 — pyspark semantics: expr, not identity
        return self._bin(o, np.equal, "==")
    def __ne__(self, o): return self._bin(o, np.not_equal, "!=")
    def __and__(self, o): return self._bin(o, np.logical_and, "&")
    def __or__(self, o): return self._bin(o, np.logical_or, "|")
    def __invert__(self): return Column(lambda c: np.logical_not(self._fn(c)),
                                        f"(~{self._name})")
    __hash__ = None  # unhashable, like pyspark Columns

    def fillna(self, value) -> "Column":
        """NaN (float) / '' (string) → ``value``."""
        def fn(c: Chunk) -> np.ndarray:
            x = self._fn(c)
            if x.dtype.kind == "f":
                return np.where(np.isnan(x), np.asarray(value, x.dtype), x)
            if x.dtype.kind in ("U", "S", "O"):
                return np.where(x == "", value, x)
            return x
        return Column(fn, self._name)

    def isNotNull(self) -> "Column":
        def fn(c: Chunk) -> np.ndarray:
            x = self._fn(c)
            if x.dtype.kind == "f":
                return ~np.isnan(x)
            if x.dtype.kind in ("U", "S", "O"):
                return x != ""
            return np.ones(len(x), bool)
        return Column(fn, f"({self._name} IS NOT NULL)")

    def isNull(self) -> "Column":
        inner = self.isNotNull()
        return Column(lambda c: ~inner(c), f"({self._name} IS NULL)")


def col(name: str) -> Column:
    def fn(chunk: Chunk) -> np.ndarray:
        try:
            return chunk[name]
        except KeyError:
            raise KeyError(
                f"no column {name!r}; have {sorted(chunk)}") from None
    return Column(fn, name)


def lit(value) -> Column:
    def fn(chunk: Chunk) -> np.ndarray:
        n = len(next(iter(chunk.values()))) if chunk else 0
        return np.full(n, value)
    return Column(fn, str(value))


def log1p(c: Column) -> Column:
    """``log(1+x)`` with negatives clamped to 0 first — the standard Criteo
    dense-feature transform (negatives appear in the raw dumps)."""
    return Column(lambda ch: np.log1p(np.maximum(c(ch), 0.0)),
                  f"log1p({c.name})")


def clip(c: Column, lo, hi) -> Column:
    return Column(lambda ch: np.clip(c(ch), lo, hi), f"clip({c.name})")


def when(cond: Column, value) -> "_When":
    return _When([(cond, value)])


class _When:
    """``when(cond, v).otherwise(d)`` chain (vectorized nested where)."""

    def __init__(self, branches: list):
        self._branches = branches

    def when(self, cond: Column, value) -> "_When":
        return _When(self._branches + [(cond, value)])

    def otherwise(self, default) -> Column:
        branches = self._branches

        def fn(chunk: Chunk) -> np.ndarray:
            default_c = default if isinstance(default, Column) else lit(default)
            out = default_c(chunk)
            for cond, value in reversed(branches):
                value_c = value if isinstance(value, Column) else lit(value)
                out = np.where(cond(chunk), value_c(chunk), out)
            return out
        return Column(fn, "CASE WHEN")


def _hash_int_array(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer — deterministic across processes."""
    z = x.astype(np.uint64, copy=True)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def hash_bucket(c: Column, num_buckets: int) -> Column:
    """Stable hash → ``[0, num_buckets)`` int32 (Spark's feature hashing).

    Numeric columns hash via a vectorized splitmix64; string columns via
    crc32 (per-element, host-side — fine at feature-engineering rates).
    Deterministic across runs and processes, unlike Python's ``hash``.
    """
    if num_buckets < 1:
        raise ValueError("num_buckets must be >= 1")

    def fn(chunk: Chunk) -> np.ndarray:
        x = c(chunk)
        if x.dtype.kind in ("i", "u"):
            h = _hash_int_array(x)
        elif x.dtype.kind == "f":
            h = _hash_int_array(x.astype(np.float64).view(np.uint64))
        else:
            h = np.fromiter(
                (zlib.crc32(str(s).encode()) for s in x),
                dtype=np.uint64, count=len(x))
            h = _hash_int_array(h)
        return (h % np.uint64(num_buckets)).astype(np.int32)

    return Column(fn, f"hash_bucket({c.name}, {num_buckets})")


# ---------------------------------------------------------------------------
# DataFrame
# ---------------------------------------------------------------------------

def _chunk_rows(chunk: Chunk) -> int:
    return len(next(iter(chunk.values()))) if chunk else 0


class DataFrame:
    """Lazy columnar dataset: partitions stream column chunks.

    Wraps a :class:`PartitionedDataset` whose elements are chunks
    (``dict[str, np.ndarray]``); ``columns`` is the declared schema order.
    """

    def __init__(self, chunks: PartitionedDataset, columns: Sequence[str]):
        self._chunks = chunks
        self._columns = list(columns)

    # -- schema -------------------------------------------------------------

    @property
    def columns(self) -> list[str]:
        return list(self._columns)

    @property
    def num_partitions(self) -> int:
        return self._chunks.num_partitions

    @property
    def rdd(self) -> PartitionedDataset:
        """Row view: a PartitionedDataset of per-row dicts (Spark ``df.rdd``)."""
        return self.to_dataset()

    # -- transformations (lazy) ---------------------------------------------

    def _map_chunks(self, f: Callable[[Chunk], Chunk],
                    columns: Sequence[str]) -> "DataFrame":
        return DataFrame(
            self._chunks.map_partitions(lambda it: (f(ch) for ch in it)),
            columns)

    def select(self, *exprs: str | Column) -> "DataFrame":
        cols = [col(e) if isinstance(e, str) else e for e in exprs]
        names = [c.name for c in cols]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate output columns: {names}")
        return self._map_chunks(
            lambda ch: {c.name: np.asarray(c(ch)) for c in cols}, names)

    def withColumn(self, name: str, expr: Column) -> "DataFrame":
        names = self._columns + ([] if name in self._columns else [name])

        def f(ch: Chunk) -> Chunk:
            out = dict(ch)
            out[name] = np.asarray(expr(ch))
            return out
        return self._map_chunks(f, names)

    def withColumns(self, mapping: Mapping[str, Column]) -> "DataFrame":
        """All expressions evaluate against the INPUT chunk (pyspark's
        simultaneous semantics: ``{'a': col('b'), 'b': col('a')}`` swaps)."""
        mapping = dict(mapping)
        names = list(self._columns)
        names += [n for n in mapping if n not in names]

        def f(ch: Chunk) -> Chunk:
            out = dict(ch)
            out.update({n: np.asarray(e(ch)) for n, e in mapping.items()})
            return out
        return self._map_chunks(f, names)

    def drop(self, *names: str) -> "DataFrame":
        keep = [c for c in self._columns if c not in names]
        return self._map_chunks(
            lambda ch: {k: v for k, v in ch.items() if k not in names}, keep)

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        return self.withColumn(new, col(old)).drop(old) if old != new else self

    def filter(self, cond: Column) -> "DataFrame":
        def f(ch: Chunk) -> Chunk:
            m = cond(ch).astype(bool)
            return {k: v[m] for k, v in ch.items()}
        return self._map_chunks(f, self._columns)

    where = filter

    def fillna(self, value, subset: Sequence[str] | None = None) -> "DataFrame":
        names = subset if subset is not None else self._columns
        return self.withColumns({n: col(n).fillna(value) for n in names})

    def randomSplit(self, weights: Sequence[float], seed: int = 0
                    ) -> list["DataFrame"]:
        """Split rows by a deterministic per-row hash (stable across runs,
        unlike sampling state threaded through an iterator)."""
        w = np.asarray(weights, np.float64)
        if (w <= 0).any():
            raise ValueError("weights must be positive")
        edges = np.cumsum(w / w.sum())

        def part_for(bucket_frac: np.ndarray) -> np.ndarray:
            return np.searchsorted(edges, bucket_frac, side="right")

        outs = []
        for i in range(len(w)):
            def f(ch: Chunk, i=i) -> Chunk:
                n = _chunk_rows(ch)
                # row identity: position within chunk + a per-chunk content
                # fingerprint, so identical positions in different chunks
                # land independently and the split is replay-stable
                base = np.arange(n, dtype=np.uint64)
                first = next(iter(ch.values())) if ch else base
                fp = zlib.crc32(np.asarray(first).tobytes()) if n else 0
                base = base + np.uint64(fp)
                frac = (_hash_int_array(base + np.uint64(seed)) >> np.uint64(11)
                        ).astype(np.float64) / float(1 << 53)
                m = part_for(frac) == i
                return {k: v[m] for k, v in ch.items()}
            outs.append(self._map_chunks(f, self._columns))
        return outs

    def groupBy(self, *keys: str) -> "GroupedData":
        """Spark ``groupBy(...).agg(...)`` — the aggregation half of the
        Criteo feature-engineering surface (per-category counts/means are
        the classic CTR count-features). Chunk-vectorized per partition
        (np.unique over stacked key rows + bincount / ufunc.at — no
        per-row Python), then per-chunk partials merge in a driver dict:
        the same honest narrow-engine stance as ``rdd.reduce_by_key``
        (SURVEY §7: no shuffle service), sized for grouped results that
        fit the driver — which category vocabularies do.

        Multi-column keys are stacked for the unique pass, so mixed key
        dtypes coerce to the numpy common type (int+str keys become
        strings in the output); keep keys same-typed when that matters.
        """
        missing = [k for k in keys if k not in self._columns]
        if missing or not keys:
            raise ValueError(
                f"groupBy keys {missing or '()'} not in columns "
                f"{self._columns}")
        return GroupedData(self, list(keys))

    def repartition(self, n: int) -> "DataFrame":
        """Down: concatenate adjacent partitions. Up: split each partition's
        chunk stream round-robin (each new partition re-walks its source
        partition and keeps every k-th chunk — extra host IO, no shuffle)."""
        cur = self.num_partitions
        if n <= cur:
            return DataFrame(self._chunks.coalesce(n), self._columns)
        chunks = self._chunks
        fan = [[] for _ in range(cur)]
        for j in range(n):
            fan[j % cur].append(j)

        def make(k: int, slot: int, stride: int):
            def gen() -> Iterator[Chunk]:
                for idx, ch in enumerate(chunks.iter_partition(k)):
                    if idx % stride == slot:
                        yield ch
            return gen

        plan: dict[int, Any] = {}
        for k in range(cur):
            for slot, j in enumerate(fan[k]):
                plan[j] = (k, slot, len(fan[k]))
        parts = [make(*plan[j]) for j in range(n)]
        return DataFrame(PartitionedDataset.from_generators(parts),
                         self._columns)

    # -- actions ------------------------------------------------------------

    def _iter_chunks(self) -> Iterator[Chunk]:
        for i in range(self._chunks.num_partitions):
            yield from self._chunks.iter_partition(i)

    def count(self) -> int:
        return sum(_chunk_rows(ch) for ch in self._iter_chunks())

    def take(self, n: int) -> list[dict]:
        rows: list[dict] = []
        for ch in self._iter_chunks():
            for r in range(_chunk_rows(ch)):
                rows.append({k: v[r] for k, v in ch.items()})
                if len(rows) == n:
                    return rows
        return rows

    def collect(self) -> list[dict]:
        return self.take(float("inf"))  # type: ignore[arg-type]

    def toPandas(self):
        """Concatenate all chunks into one dict of arrays (no pandas in this
        env — returns the columnar dict, which is what callers index anyway)."""
        chunks = list(self._iter_chunks())
        if not chunks:
            return {c: np.empty((0,)) for c in self._columns}
        return {c: np.concatenate([ch[c] for ch in chunks]) for c in self._columns}

    def show(self, n: int = 10) -> None:
        rows = self.take(n)
        print(" | ".join(self._columns))
        for r in rows:
            print(" | ".join(str(r[c]) for c in self._columns))

    # -- bridge to the feed/trainer -----------------------------------------

    def to_dataset(self, *, columns: Sequence[str] | None = None,
                   vector_columns: Mapping[str, Sequence[str]] | None = None
                   ) -> PartitionedDataset:
        """Row view for the HBM feed: a PartitionedDataset of example dicts.

        ``vector_columns`` packs scalar columns into one feature vector per
        example — e.g. ``{"dense": [f"I{i}" for i in range(13)]}`` yields a
        ``[13]`` float array per row, the DLRM input contract — packed
        vectorized per chunk, not per row.
        """
        names = list(columns) if columns is not None else list(self._columns)
        vec = {k: list(v) for k, v in (vector_columns or {}).items()}
        flat_used = {c for cols in vec.values() for c in cols}
        scalars = [c for c in names if c not in flat_used]

        def rows(it: Iterable[Chunk]) -> Iterator[dict]:
            for ch in it:
                n = _chunk_rows(ch)
                packed = {k: np.stack([ch[c] for c in cols], axis=1)
                          for k, cols in vec.items()}
                for r in range(n):
                    ex = {c: ch[c][r] for c in scalars}
                    ex.update({k: v[r] for k, v in packed.items()})
                    yield ex

        return self._chunks.map_partitions(rows)

    toDataset = to_dataset

    def __repr__(self) -> str:
        return (f"DataFrame(columns={self._columns}, "
                f"num_partitions={self.num_partitions})")


#: supported GroupedData aggregations; mean derives from (sum, count) so
#: every entry here is mergeable across chunk partials
_AGG_FNS = ("count", "sum", "mean", "min", "max")


def _agg_partial(ch: Chunk, keys: Sequence[str],
                 spec: Mapping[str, str]) -> list:
    """One chunk's vectorized group partials: ``[(key_tuple, (count,
    (col_stats, ...)))]`` with one ``col_stats`` per spec column in spec
    order — ``None`` for count-only columns, ``(sum, min|None, max|None)``
    otherwise. Per value column only the stats its fn needs are computed
    (ufunc.at is a per-element C loop; paying min/max passes for a
    sum-only spec would undercut the vectorized claim); mean derives from
    (sum, count). Keys and stats are PYTHON scalars (``.tolist()``), not
    numpy ones — a 10M-key shuffle pickles every entry, and np.int64
    pickles ~20x slower and 5x bigger than int. Keys are unique within the
    returned list (np.unique dedups the chunk). Shared by the serial
    driver merge and the distributed exchange mappers, so both paths
    produce identical partials."""
    n = _chunk_rows(ch)
    if n == 0:
        return []
    key_arrays = [np.asarray(ch[k]) for k in keys]
    for k, a in zip(keys, key_arrays):
        if a.dtype == object:
            # np.unique(axis=0) can't take object arrays and its
            # TypeError names neither column nor fix — fail clearly
            raise ValueError(
                f"groupBy key '{k}' has object dtype (e.g. None "
                f"among values); fillna()/hash_bucket it to a "
                f"concrete dtype first")
        if np.issubdtype(a.dtype, np.floating) and np.isnan(a).any():
            # tuple(nan) dict keys never compare equal, so NaN
            # groups would silently split per chunk instead of
            # merging — the fillna-first flow is the documented fix
            raise ValueError(
                f"groupBy key '{k}' contains NaN; fillna() it "
                f"first (NaN never equals NaN, so NaN groups "
                f"cannot merge)")
    stacked = np.stack(key_arrays, axis=1)
    uniq, inv = np.unique(stacked, axis=0, return_inverse=True)
    g = uniq.shape[0]
    cnt = np.bincount(inv, minlength=g).tolist()
    cols: list = []
    for c, fn in spec.items():
        if fn == "count":
            # bincount already carries the answer; coercing the
            # column would also crash string-typed count() keys
            cols.append(None)
            continue
        v = np.asarray(ch[c], np.float64)
        s = np.bincount(inv, weights=v, minlength=g)
        mn = mx = None
        if fn == "min":
            mn = np.full(g, np.inf)
            np.minimum.at(mn, inv, v)
        elif fn == "max":
            mx = np.full(g, -np.inf)
            np.maximum.at(mx, inv, v)
        cols.append((s.tolist(),
                     None if mn is None else mn.tolist(),
                     None if mx is None else mx.tolist()))
    # zip-built (C speed): a per-key Python genexpr here was the single
    # hottest line of a 10M-key shuffle's map phase
    per_col: list = []
    for col in cols:
        if col is None:
            per_col.append(itertools.repeat(None, g))
        else:
            s, mn, mx = col
            per_col.append(zip(s,
                               mn if mn is not None
                               else itertools.repeat(None),
                               mx if mx is not None
                               else itertools.repeat(None)))
    entries = zip(cnt, zip(*per_col))
    return list(zip(map(tuple, uniq.tolist()), entries))


def _merge_agg_entry(a: tuple, b: tuple) -> tuple:
    """Merge two group partials (commutative — sum/min/max/count), the
    exchange's combine/merge function for ``groupBy().agg``."""
    cnt = a[0] + b[0]
    out: list = []
    for sa, sb in zip(a[1], b[1]):
        if sa is None:
            out.append(None)
            continue
        out.append((sa[0] + sb[0],
                    sa[1] if sb[1] is None else
                    (sb[1] if sa[1] is None else min(sa[1], sb[1])),
                    sa[2] if sb[2] is None else
                    (sb[2] if sa[2] is None else max(sa[2], sb[2]))))
    return (cnt, tuple(out))


def _agg_row_value(fn: str, cnt: int, stats) -> Any:
    """One output cell from a merged group entry — the ONE formula both
    paths share (mean = sum/count, so bit-equality follows from the
    partials being equal)."""
    if fn == "count":
        return cnt
    s, mn, mx = stats
    return {"sum": s, "mean": s / cnt if cnt else np.nan,
            "min": mn, "max": mx}[fn]


class GroupedData:
    """Result of :meth:`DataFrame.groupBy`; terminal ops produce a
    single-partition DataFrame of one row per group."""

    def __init__(self, df: DataFrame, keys: list[str]):
        self._df = df
        self._keys = keys

    def count(self) -> DataFrame:
        """Group sizes as a ``count`` column (pyspark's ``.count()``)."""
        if "count" in self._keys:
            # withColumnRenamed would REPLACE the key column with the
            # counts, silently losing the group identities
            raise ValueError(
                "a groupBy key is literally named 'count'; use "
                "agg({key: 'count'}) (keeps the 'count(key)' name) or "
                "rename the key column first")
        out = self.agg({self._keys[0]: "count"})
        return out.withColumnRenamed(f"count({self._keys[0]})", "count")

    def agg(self, spec: Mapping[str, str], *,
            max_groups: int | None = None,
            num_workers: int | None = None) -> DataFrame:
        """``{"col": "sum"|"mean"|"min"|"max"|"count"}`` → one row per
        distinct key tuple, pyspark-style ``fn(col)`` output names.

        Lazy like every other verb (the module's contract): the source
        scan runs on the output's first iteration, memoized cache()-style
        after that.

        With workers (``num_workers=`` / ``DLS_DATA_WORKERS``): the scan
        routes through the distributed exchange (:mod:`~.exchange`) —
        chunk partials bucket by canonical key hash, per-bucket reducers
        merge with spill-to-disk under ``DLS_SHUFFLE_MEM_MB`` — so there
        is NO cardinality ceiling; a 10M-key aggregation completes under a
        bounded memory budget. Output rows stream bucket-major in
        canonical key order, one partition per bucket.

        Serial (no workers): chunk partials merge in a DRIVER-SIDE dict —
        fine for the vocab-sized results this plane is documented for
        (Criteo's 26 categorical vocabularies) — bounded by ``max_groups``
        (default ``DLS_AGG_MAX_GROUPS`` or 1_000_000); past the ceiling
        the scan refuses loudly, naming ``DLS_DATA_WORKERS`` (the exchange)
        as the first remediation. Rows come in the SAME canonical bucket-
        major order as the exchange path, so results are byte-identical at
        any worker count.
        """
        keys, df = self._keys, self._df
        from distributeddeeplearningspark_tpu.data import exchange

        if max_groups is None:
            max_groups = exchange.max_groups_limit()
        if max_groups < 1:
            raise ValueError(f"max_groups must be >= 1, got {max_groups}")
        bad = {c: f for c, f in spec.items()
               if f not in _AGG_FNS or c not in df.columns}
        if bad or not spec:
            raise ValueError(
                f"unsupported agg spec {bad or spec!r}; columns="
                f"{df.columns}, fns={_AGG_FNS}")
        names = keys + [f"{f}({c})" for c, f in spec.items()]
        spec = dict(spec)
        n_out = df._chunks.num_partitions

        nw = exchange.resolve_shuffle_workers(num_workers)
        if nw:
            ex_spec = exchange._Spec(
                pre=lambda ch: _agg_partial(ch, keys, spec),
                combine=_merge_agg_entry)
            recs = exchange._lazy_exchange_dataset(
                df._chunks._parts, num_workers=nw, n_out=n_out,
                spec=ex_spec, label="groupBy.agg")

            def to_chunks(it: Iterable) -> Iterator[Chunk]:
                buf: list[tuple] = []

                def emit(buf: list[tuple]) -> Chunk:
                    ch: Chunk = {
                        k: np.asarray([key[i] for key, _ in buf])
                        for i, k in enumerate(keys)}
                    for ci, (c, f) in enumerate(spec.items()):
                        ch[f"{f}({c})"] = np.asarray(
                            [_agg_row_value(f, cnt, per_col[ci])
                             for _, (cnt, per_col) in buf])
                    return ch

                for rec in it:
                    buf.append(rec)
                    if len(buf) >= DEFAULT_CHUNK_ROWS:
                        yield emit(buf)
                        buf = []
                if buf:
                    yield emit(buf)

            return DataFrame(recs.map_partitions(to_chunks), names)

        memo: dict = {}

        def result_chunk() -> Chunk:
            if "chunk" in memo:
                return memo["chunk"]
            acc: dict = {}
            for ch in df._iter_chunks():
                for key, (cnt, per_col) in _agg_partial(ch, keys, spec):
                    if key not in acc:
                        if len(acc) >= max_groups:
                            raise ValueError(
                                f"groupBy({keys}).agg() exceeded max_groups="
                                f"{max_groups} distinct keys — the partials "
                                f"merge in a driver-side dict sized for "
                                f"vocab-scale results, and this key looks "
                                f"high-cardinality (user-id-like). Set "
                                f"DLS_DATA_WORKERS=N (or pass num_workers=) "
                                f"to route through the distributed shuffle "
                                f"exchange, which spills to disk under "
                                f"DLS_SHUFFLE_MEM_MB and has no ceiling; or "
                                f"hash_bucket(col({keys[0]!r}), num_buckets) "
                                f"the key first to bound the result; or "
                                f"raise max_groups= / DLS_AGG_MAX_GROUPS if "
                                f"the grouped result genuinely fits the "
                                f"driver")
                        acc[key] = (cnt, per_col)
                    else:
                        acc[key] = _merge_agg_entry(acc[key],
                                                    (cnt, per_col))
            # canonical bucket-major, key_bytes-ordered rows — the exact
            # layout the exchange path streams, so 0 workers == N workers
            keyed = []
            for k in acc:
                kb = exchange.key_bytes(k)
                keyed.append(((exchange.bucket_of(kb, n_out), kb), k))
            keyed.sort(key=lambda t: t[0])
            rows_keys = [k for _, k in keyed]
            chunk: Chunk = {
                k: np.asarray([rk[i] for rk in rows_keys])
                for i, k in enumerate(keys)
            }
            for ci, (c, f) in enumerate(spec.items()):
                chunk[f"{f}({c})"] = np.asarray(
                    [_agg_row_value(f, acc[rk][0], acc[rk][1][ci])
                     for rk in rows_keys])
            memo["chunk"] = chunk
            return chunk

        return DataFrame(
            PartitionedDataset.from_generators(
                [lambda: iter([result_chunk()])]),
            names)


# ---------------------------------------------------------------------------
# Readers
# ---------------------------------------------------------------------------

def from_rows(rows: Sequence[Mapping[str, Any]], *, num_partitions: int = 2,
              chunk_rows: int = DEFAULT_CHUNK_ROWS) -> DataFrame:
    """``createDataFrame``: columnarize a row sequence (driver-side)."""
    if not rows:
        raise ValueError("cannot infer schema from zero rows")
    names = list(rows[0].keys())
    ds = PartitionedDataset.parallelize(list(rows), num_partitions)
    return from_dataset(ds, names, chunk_rows=chunk_rows)


def from_dataset(ds: PartitionedDataset, columns: Sequence[str], *,
                 chunk_rows: int = DEFAULT_CHUNK_ROWS) -> DataFrame:
    """Columnarize a PartitionedDataset of row dicts (the RDD→DF bridge)."""
    names = list(columns)

    def chunker(it: Iterable[Mapping]) -> Iterator[Chunk]:
        buf: list[Mapping] = []
        for r in it:
            buf.append(r)
            if len(buf) == chunk_rows:
                yield {n: np.asarray([b[n] for b in buf]) for n in names}
                buf = []
        if buf:
            yield {n: np.asarray([b[n] for b in buf]) for n in names}

    return DataFrame(ds.map_partitions(chunker), names)


def _expand_paths(paths: str | Sequence[str]) -> list[str]:
    """Glob-or-literal path expansion shared by the readers.

    A string containing glob metacharacters expands (sorted); a literal
    string that exists is used as-is even if it contains ``[``/``?``
    (e.g. ``data[1].parquet``); lists pass through with existence checks.
    """
    import glob as _glob
    import os

    if isinstance(paths, str):
        if os.path.exists(paths):
            expanded = [paths]
        elif any(ch in paths for ch in "*?["):
            expanded = sorted(_glob.glob(paths))
        else:
            expanded = [paths]
    else:
        expanded = list(paths)
    if not expanded:
        raise FileNotFoundError(f"no files match {paths!r}")
    for p in expanded:
        if not os.path.exists(p):
            raise FileNotFoundError(p)
    return expanded


def read_csv(
    paths: str | Sequence[str],
    *,
    names: Sequence[str],
    sep: str = ",",
    dtypes: Mapping[str, Any] | None = None,
    num_partitions: int = 2,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
) -> DataFrame:
    """Typed delimited-text reader (``spark.read.csv``-shaped).

    Files (or one file split by contiguous line ranges) spread over
    ``num_partitions``. Missing fields parse as NaN (float columns) / ''
    (string columns). ``dtypes`` maps column → numpy dtype; default f4.
    """
    expanded = _expand_paths(paths)
    names = list(names)
    dtypes = dict(dtypes or {})
    np_dtypes = {n: np.dtype(dtypes.get(n, np.float32)) for n in names}

    def parse_lines(lines: Iterable[str]) -> Iterator[Chunk]:
        buf: list[list[str]] = []

        def flush(buf: list[list[str]]) -> Chunk:
            cols: Chunk = {}
            for j, n in enumerate(names):
                raw = [row[j] if j < len(row) else "" for row in buf]
                dt = np_dtypes[n]
                if dt.kind == "f":
                    cols[n] = np.array(
                        [float(x) if x else np.nan for x in raw], dt)
                elif dt.kind in ("i", "u"):
                    cols[n] = np.array(
                        [int(x) if x else 0 for x in raw], dt)
                else:
                    cols[n] = np.array(raw, dtype=np.str_)
            return cols

        for line in lines:
            line = line.rstrip("\n")
            if not line:
                continue
            buf.append(line.split(sep))
            if len(buf) == chunk_rows:
                yield flush(buf)
                buf = []
        if buf:
            yield flush(buf)

    # several files but fewer than requested partitions: clamp to one
    # partition per file (repartition(n) can split streams afterwards)
    if 1 < len(expanded) < num_partitions:
        num_partitions = len(expanded)
    if len(expanded) >= num_partitions:
        file_groups = np.array_split(np.array(expanded, object), num_partitions)

        def make_part(group) -> Callable[[], Iterator[Chunk]]:
            def gen() -> Iterator[Chunk]:
                def lines() -> Iterator[str]:
                    for fname in group:
                        with open(fname, "r") as f:
                            yield from f
                return parse_lines(lines())
            return gen

        parts = [make_part(g) for g in file_groups if len(g)]
    else:
        # split each file by contiguous line ranges (counted once, driver-side)
        fname = expanded[0]
        with open(fname, "r") as f:
            total = sum(1 for _ in f)
        bounds = [(i * total // num_partitions, (i + 1) * total // num_partitions)
                  for i in range(num_partitions)]

        def make_range(lo: int, hi: int) -> Callable[[], Iterator[Chunk]]:
            def gen() -> Iterator[Chunk]:
                def lines() -> Iterator[str]:
                    with open(fname, "r") as f:
                        for i, line in enumerate(f):
                            if i >= hi:
                                break
                            if i >= lo:
                                yield line
                return parse_lines(lines())
            return gen

        parts = [make_range(lo, hi) for lo, hi in bounds]

    return DataFrame(PartitionedDataset.from_generators(parts), names)


def read_parquet(
    paths: str | Sequence[str],
    *,
    columns: Sequence[str] | None = None,
    num_partitions: int = 2,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
) -> DataFrame:
    """Parquet reader (``spark.read.parquet``-shaped), via pyarrow.

    Files spread over partitions (one partition reads a contiguous file
    group; a single file splits by row-group ranges). Column chunks stream
    batch-at-a-time — a partition never materializes its whole file set.
    """
    import os

    import pyarrow.parquet as pq

    # Spark's canonical input is a directory of part files (escape the
    # directory name so its own glob metacharacters stay literal)
    if isinstance(paths, str) and os.path.isdir(paths):
        import glob as _glob

        paths = os.path.join(_glob.escape(paths), "*.parquet")
    expanded = _expand_paths(paths)
    if columns is not None:
        names = list(columns)
    else:
        names = list(pq.read_schema(expanded[0]).names)

    def batches_to_chunks(batches) -> Iterator[Chunk]:
        for rb in batches:
            yield {n: rb.column(n).to_numpy(zero_copy_only=False)
                   for n in names}

    if 1 < len(expanded) < num_partitions:
        num_partitions = len(expanded)
    if len(expanded) >= num_partitions:
        groups = np.array_split(np.array(expanded, object), num_partitions)

        def make_files(group) -> Callable[[], Iterator[Chunk]]:
            def gen() -> Iterator[Chunk]:
                for fname in group:
                    f = pq.ParquetFile(fname)
                    yield from batches_to_chunks(
                        f.iter_batches(batch_size=chunk_rows, columns=names))
            return gen

        parts = [make_files(g) for g in groups if len(g)]
    else:
        f0 = pq.ParquetFile(expanded[0])
        n_rg = f0.num_row_groups
        rg_bounds = [(i * n_rg // num_partitions, (i + 1) * n_rg // num_partitions)
                     for i in range(num_partitions)]

        def make_rgs(lo: int, hi: int) -> Callable[[], Iterator[Chunk]]:
            def gen() -> Iterator[Chunk]:
                if lo >= hi:
                    return
                f = pq.ParquetFile(expanded[0])
                yield from batches_to_chunks(
                    f.iter_batches(batch_size=chunk_rows, columns=names,
                                   row_groups=list(range(lo, hi))))
            return gen

        parts = [make_rgs(lo, hi) for lo, hi in rg_bounds]

    return DataFrame(PartitionedDataset.from_generators(parts), names)


class DataFrameReader:
    """``session.read`` surface: ``.option(...).schema(...).csv(path)`` /
    ``.parquet(path)``."""

    def __init__(self, *, default_parallelism: int = 2):
        self._opts: dict[str, Any] = {"sep": ","}
        self._names: Sequence[str] | None = None
        self._dtypes: Mapping[str, Any] | None = None
        self._parallelism = default_parallelism

    def option(self, key: str, value) -> "DataFrameReader":
        self._opts[key] = value
        return self

    def schema(self, names: Sequence[str],
               dtypes: Mapping[str, Any] | None = None) -> "DataFrameReader":
        self._names = names
        self._dtypes = dtypes
        return self

    def csv(self, path: str | Sequence[str]) -> DataFrame:
        if self._names is None:
            raise ValueError("call .schema([...column names...]) before .csv()")
        return read_csv(
            path, names=self._names, sep=str(self._opts.get("sep", ",")),
            dtypes=self._dtypes,
            num_partitions=int(self._opts.get(
                "num_partitions", self._parallelism)))

    def parquet(self, path: str | Sequence[str]) -> DataFrame:
        # schema travels in the file; .schema() narrows columns, and its
        # dtypes (meaningful for text parsing in .csv) apply here as casts
        # so the same .schema(...) pipeline behaves identically on parquet
        df = read_parquet(
            path, columns=self._names,
            num_partitions=int(self._opts.get(
                "num_partitions", self._parallelism)))
        if self._dtypes:
            df = df.withColumns(
                {n: col(n).cast(dt) for n, dt in self._dtypes.items()})
        return df
