"""Image pipeline — the rebuild of the reference's Spark RDD image plane.

The reference decodes/augments/batches ImageNet inside executor partitions
(SURVEY.md §2 'Data: image pipeline'). Here the same steps are RDD-style
``map`` transforms over a :class:`~distributeddeeplearningspark_tpu.rdd.
PartitionedDataset`, executed on the *host* by the prefetch thread (device
time is reserved for the MXU; host decode overlaps device compute via
:mod:`.prefetch`).

All transforms are numpy, per-example, composable with ``dataset.map``. JPEG
decoding is our own native baseline decoder (csrc/dls_jpeg.cc) with a PIL
fallback for non-baseline streams — see :func:`decode_jpeg`.
"""

from __future__ import annotations

from typing import Callable, Iterable

import numpy as np

from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

#: ImageNet channel statistics (the universal constants every framework bakes in).
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def normalize(image: np.ndarray, mean: np.ndarray = IMAGENET_MEAN,
              std: np.ndarray = IMAGENET_STD) -> np.ndarray:
    """[0,1] float or uint8 HWC → standardized float32."""
    if image.dtype == np.uint8:
        image = image.astype(np.float32) / 255.0
    return (image.astype(np.float32) - mean) / std


def resize_bilinear(image: np.ndarray, size: tuple[int, int]) -> np.ndarray:
    """Minimal bilinear resize (numpy; avoids a PIL/TF dependency)."""
    h, w = image.shape[:2]
    out_h, out_w = size
    if (h, w) == (out_h, out_w):
        return image
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0).astype(np.float32)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0).astype(np.float32)[None, :, None]
    img = image.astype(np.float32)
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


def _resize(image: np.ndarray, size: tuple[int, int]) -> np.ndarray:
    """Bilinear resize via the native (C++) kernel when built, numpy otherwise.

    Identical math either way (csrc/dls_native.cc mirrors resize_bilinear);
    the native path parallelizes across rows and releases the GIL.
    """
    from distributeddeeplearningspark_tpu.utils import native

    return native.resize_bilinear(np.asarray(image, np.float32), size)


def sample_crop_region(h: int, w: int, rng: np.random.Generator,
                       scale: tuple[float, float] = (0.08, 1.0),
                       ratio: tuple[float, float] = (3 / 4, 4 / 3),
                       ) -> tuple[int, int, int, int] | None:
    """Inception-style crop sampling: (y, x, ch, cw), or None when 10 draws
    of random area/aspect never fit (extreme aspect ratios) — callers fall
    back to a center crop. Split from :func:`random_resized_crop` so the
    fused native path consumes the SAME rng stream and picks the same crop."""
    area = h * w
    for _ in range(10):
        target = area * rng.uniform(*scale)
        aspect = np.exp(rng.uniform(np.log(ratio[0]), np.log(ratio[1])))
        cw = int(round(np.sqrt(target * aspect)))
        ch = int(round(np.sqrt(target / aspect)))
        if cw <= w and ch <= h:
            y = int(rng.integers(0, h - ch + 1))
            x = int(rng.integers(0, w - cw + 1))
            return y, x, ch, cw
    return None


def random_resized_crop(image: np.ndarray, rng: np.random.Generator, size: int = 224,
                        scale: tuple[float, float] = (0.08, 1.0),
                        ratio: tuple[float, float] = (3 / 4, 4 / 3)) -> np.ndarray:
    """Inception-style crop: random area/aspect, resized to ``size``."""
    h, w = image.shape[:2]
    region = sample_crop_region(h, w, rng, scale, ratio)
    if region is None:
        return center_crop(image, size)  # fallback
    y, x, ch, cw = region
    return _resize(image[y:y + ch, x:x + cw], (size, size))


def center_crop(image: np.ndarray, size: int = 224, resize_shorter: int = 256) -> np.ndarray:
    """Eval transform: resize shorter side then center crop."""
    h, w = image.shape[:2]
    scale = resize_shorter / min(h, w)
    image = _resize(image, (int(round(h * scale)), int(round(w * scale))))
    h, w = image.shape[:2]
    y, x = (h - size) // 2, (w - size) // 2
    return image[y:y + size, x:x + size]


def random_flip(image: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    return image[:, ::-1] if rng.random() < 0.5 else image


def _content_seed(img: np.ndarray) -> int:
    """Process-stable 32-bit content hash (built-in hash() is siphash-salted
    per process, which would break cross-host augmentation determinism)."""
    import hashlib

    return int.from_bytes(
        hashlib.blake2b(img.tobytes()[:64], digest_size=4).digest(), "little"
    )


def decode_jpeg(path_or_bytes) -> np.ndarray:
    """JPEG → uint8 HWC.

    Decode order (VERDICT r1 missing-#3: the old path hard-depended on the
    absent torchvision):

    1. the native baseline decoder (csrc/dls_jpeg.cc — GIL-free, our own
       host data plane, covers the sequential-DCT files ImageNet consists of);
    2. PIL, for non-baseline streams (progressive) or when the native
       library didn't build.
    """
    if isinstance(path_or_bytes, (bytes, bytearray)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    from distributeddeeplearningspark_tpu.utils import native

    try:
        out = native.jpeg_decode(data)
        if out is not None:
            return out
    except native.JpegUnsupported:
        pass  # progressive etc. → PIL
    try:
        import io

        from PIL import Image

        img = Image.open(io.BytesIO(data))
        if img.mode not in ("RGB", "L"):
            img = img.convert("RGB")
        arr = np.asarray(img)
        return arr[..., None] if arr.ndim == 2 else arr
    except ImportError as e:  # pragma: no cover - environment-dependent
        raise RuntimeError(
            "no JPEG decoder available (native build failed and PIL absent)") from e


def _augment_decision(img: np.ndarray, seed: int, size: int
                      ) -> tuple[tuple[int, int, int, int] | None, bool]:
    """THE content-seeded crop/flip decision → ``(region, flip)``.

    One copy of the rng-stream contract shared by :func:`train_transform`'s
    uint8 paths, :func:`_fused_example_transform` (worker pool) and
    ``imagenet_train_batched``'s fused batch — the byte-parity between the
    in-process and worker-pool feeds (and checkpoint fast-forward resume
    with it) depends on every path drawing the same stream: an already-
    ``size``-sized frame consumes NO region draw (region is the full
    frame), then ONE flip draw; otherwise the 10-draw crop sampler runs
    first. ``region`` is None when the sampler gave up — callers fall back
    to a center crop.
    """
    rng = np.random.default_rng(
        (seed * 2654435761 + _content_seed(img)) & 0xFFFFFFFF)
    h, w = img.shape[:2]
    if h == w == size:
        region: tuple[int, int, int, int] | None = (0, 0, h, w)
    else:
        region = sample_crop_region(h, w, rng)
    return region, bool(rng.random() < 0.5)


def train_transform(size: int = 224, seed: int = 0) -> Callable[[dict], dict]:
    """Per-example ImageNet train augmentation: crop + flip + normalize.

    Contract: uint8 input is raw pixels → unit-scaled then standardized with
    the ImageNet stats; float input is assumed already normalized → geometric
    ops only. Deterministic per example content hash + seed so multi-host
    pipelines don't need rng plumbing through partitions.
    """

    def apply(example: dict) -> dict:
        example = _decode_if_bytes(example)
        img = example["image"]
        if img.dtype == np.uint8:
            region, flip = _augment_decision(img, seed, size)
            if img.shape[0] == img.shape[1] == size:
                # fused flip+normalize in one native pass (numpy fallback)
                from distributeddeeplearningspark_tpu.utils import native

                img = native.crop_flip_normalize_batch(
                    img[None], np.zeros(1, np.int32), np.zeros(1, np.int32),
                    np.array([flip], np.uint8), (size, size),
                    IMAGENET_MEAN, IMAGENET_STD,
                )[0]
                return {**example, "image": img}
            # uint8 + crop (the record input path): one fused native pass —
            # crop→resize→flip→normalize with no float intermediate frame.
            # Same rng stream as the numpy chain, so native/numpy pick the
            # same crop and agree to fp tolerance.
            from distributeddeeplearningspark_tpu.utils import native

            fused = (
                native.rrc_flip_normalize(
                    img, region, flip, (size, size), IMAGENET_MEAN, IMAGENET_STD)
                if region is not None else None)
            if fused is not None:
                return {**example, "image": fused}
            if region is not None:
                y, x, ch, cw = region
                img = _resize(img[y:y + ch, x:x + cw].astype(np.float32) / 255.0,
                              (size, size))
            else:
                img = center_crop(img.astype(np.float32) / 255.0, size)
            img = normalize(img[:, ::-1] if flip else img)
        else:
            rng = np.random.default_rng(
                (seed * 2654435761 + _content_seed(img)) & 0xFFFFFFFF)
            if img.shape[0] != size or img.shape[1] != size:
                img = random_resized_crop(img, rng, size)
            img = random_flip(img, rng)
        return {**example, "image": np.ascontiguousarray(img, np.float32)}

    return apply


def _decode_if_bytes(example: dict) -> dict:
    """``{"jpeg": bytes}`` (imagenet_folder(decode=False)) → decoded
    ``{"image": ...}``. Decoding INSIDE the transform is what lets
    ``map_parallel`` spread it over cores — decode in the source iterator
    runs on the single consumer thread no matter the pool size."""
    if "jpeg" not in example:
        return example
    out = {k: v for k, v in example.items() if k != "jpeg"}
    out["image"] = decode_jpeg(example["jpeg"])
    return out


def eval_transform(size: int = 224) -> Callable[[dict], dict]:
    """uint8 → scale+standardize (see train_transform contract); float → crop only.

    The shorter-side resize scales with the crop (ratio 0.875 — the standard
    256→224 ImageNet recipe generalized): a fixed 256 would be a zoom for any
    other crop size (e.g. size=64 would evaluate on the central 24×24 of the
    original image — measured as a 1.0-train / 0.28-eval accuracy split on a
    memorized toy set before this scaled)."""
    resize_shorter = int(round(size / 0.875))

    def apply(example: dict) -> dict:
        example = _decode_if_bytes(example)
        img = example["image"]
        needs_crop = img.shape[0] != size or img.shape[1] != size
        if img.dtype == np.uint8:
            if not needs_crop:
                from distributeddeeplearningspark_tpu.utils import native

                return {**example, "image": native.normalize_u8_batch(
                    img[None], IMAGENET_MEAN, IMAGENET_STD)[0]}
            h, w = img.shape[:2]
            if min(h, w) == resize_shorter:
                # record path (already shorter-side == resize_shorter): crop
                # in uint8 and normalize in one native pass — no resize, no
                # float intermediate frame
                from distributeddeeplearningspark_tpu.utils import native

                y, x = (h - size) // 2, (w - size) // 2
                return {**example, "image": native.crop_flip_normalize_batch(
                    img[None], np.array([y], np.int32), np.array([x], np.int32),
                    np.zeros(1, np.uint8), (size, size),
                    IMAGENET_MEAN, IMAGENET_STD)[0]}
            img = normalize(center_crop(img.astype(np.float32) / 255.0, size,
                                        resize_shorter))
        elif needs_crop:
            img = center_crop(img, size, resize_shorter)
        return {**example, "image": np.ascontiguousarray(img, np.float32)}

    return apply


def imagenet_train(dataset: PartitionedDataset, *, size: int = 224, seed: int = 0,
                   num_threads: int | None = None,
                   repeat: bool = False,
                   num_workers: int | None = None) -> PartitionedDataset:
    """RDD-shaped pipeline: shuffle → (repeat) → decode+augment.

    Feed it ``imagenet_folder(root, decode=False)`` so JPEG decode happens
    INSIDE the (optionally parallel) transform — decode in the source
    iterator would stay on the single consumer thread and cap a host at one
    core's ~50–100 img/s while a chip consumes thousands (``bench.py
    --model input``). ``num_threads``: thread-pool decode/augment (the
    Spark task-slots-per-executor analog; 0/1 = serial; augmentation is
    content-seeded per example, so thread scheduling cannot change WHICH
    augmentation an example gets — but concurrent native-kernel calls have
    been observed to race at the byte level on oversubscribed shared hosts
    (tests/test_input_workers.py quarantine note), so pipelines that need
    bit-determinism should use ``num_threads=0`` or worker processes,
    which reproduce exactly at any width).
    ``repeat=True`` makes the stream infinite HERE — shuffle must precede
    repeat, and repeating before the parallel map keeps one thread pool
    alive across epochs instead of respawning per pass.

    ``num_workers`` (default ``DLS_DATA_WORKERS``, 0 = off): run the
    decode/augment map across worker *processes* instead of threads —
    :class:`~.workers.WorkerMappedDataset`, real cores with no GIL and
    shared-memory delivery. The batch stream is byte-identical for any
    worker count (content-seeded augmentation + ordered delivery), so
    checkpoint fast-forward resume is unaffected. When enabled it replaces
    the thread pool (``num_threads`` is ignored) — process×thread pools
    would oversubscribe the host.
    """
    from distributeddeeplearningspark_tpu.data import workers as workers_lib

    ds = dataset.shuffle(seed)
    if repeat:
        ds = ds.repeat()
    tf = train_transform(size, seed)
    if workers_lib.resolve_num_workers(num_workers) > 0:
        return workers_lib.WorkerMappedDataset(ds, tf, num_workers,
                                               label="imagenet_train")
    return ds.map_parallel(tf, num_threads=num_threads)


def imagenet_eval(dataset: PartitionedDataset, *, size: int = 224,
                  num_threads: int | None = None,
                  num_workers: int | None = None) -> PartitionedDataset:
    from distributeddeeplearningspark_tpu.data import workers as workers_lib

    if workers_lib.resolve_num_workers(num_workers) > 0:
        return workers_lib.WorkerMappedDataset(
            dataset, eval_transform(size), num_workers, label="imagenet_eval")
    return dataset.map_parallel(eval_transform(size), num_threads=num_threads)


def _fused_example_transform(size: int, seed: int) -> Callable[[dict], dict]:
    """Per-example twin of :func:`imagenet_train_batched`'s fused batch call.

    Exactly the varbatch kernel's per-image math (csrc/dls_native.cc shares
    the float expressions between ``dls_rrc_flip_normalize`` and its
    varbatch loop) with exactly ``_fused_batch``'s decision logic — crop
    region/flip drawn from the same content-seeded rng, same fallbacks —
    so the worker-pool path of the batched feed is byte-identical to the
    in-process path for any ``num_workers``.
    """
    tf_fallback = train_transform(size, seed)

    def one(ex: dict) -> dict:
        from distributeddeeplearningspark_tpu.utils import native

        img = ex.get("image")
        if (native.available() and isinstance(img, np.ndarray)
                and img.dtype == np.uint8 and img.ndim == 3):
            region, flip = _augment_decision(img, seed, size)
            if region is not None:
                fused = native.rrc_flip_normalize(
                    img, region, flip, (size, size),
                    IMAGENET_MEAN, IMAGENET_STD)
                if fused is not None:
                    return {**ex, "image": fused}
        return {**ex, "image": tf_fallback(dict(ex))["image"]}

    return one


def imagenet_train_batched(
    dataset: PartitionedDataset,
    batch_size: int,
    *,
    size: int = 224,
    seed: int = 0,
    drop_remainder: bool = True,
    num_workers: int | None = None,
):
    """Record-path fast feed: yield READY train batches with whole-batch
    fused native augmentation.

    Profiling the record path (BASELINE.md r3) put 38% of host time in
    per-example augment calls, 24% in the np.stack batch copy, and most of
    the rest in thread-pool bookkeeping. This feed removes all three at
    once: records stream serially (cheap), crop/flip decisions stay
    per-example content-seeded (identical stream to ``train_transform``),
    and ONE ``dls_rrc_flip_normalize_varbatch`` call per batch crops,
    resizes, flips and normalizes every image directly into the
    preallocated [B, size, size, 3] batch buffer — parallel over images
    in C, no GIL, no stack pass.

    Yields ``{"image": [B, size, size, 3] f32, "label": [B] i32}``; falls
    back to the per-example chain when the native library is unavailable
    or an image is pre-float. Shuffle/repeat the dataset BEFORE this feed.

    ``num_workers`` (default ``DLS_DATA_WORKERS``, 0 = off): the
    per-example fused augment runs across worker processes
    (:mod:`.workers`) — the same kernel math as the in-process varbatch
    call, so the batch stream stays byte-identical for any worker count —
    and the consumer stacks shared-memory views straight into the batch
    buffer.
    """
    from distributeddeeplearningspark_tpu.data import workers as workers_lib
    from distributeddeeplearningspark_tpu.data.feed import _round_robin
    from distributeddeeplearningspark_tpu.utils import native

    if workers_lib.resolve_num_workers(num_workers) > 0:
        mapped = workers_lib.WorkerMappedDataset(
            dataset, _fused_example_transform(size, seed), num_workers,
            label="imagenet_train_batched")

        def stack_mapped(buf: list[dict]) -> dict:
            out = np.empty((len(buf), size, size, 3), np.float32)
            for j, e in enumerate(buf):
                out[j] = e["image"]  # shm view → batch buffer, one copy
            rest = {k: np.stack([np.asarray(e[k]) for e in buf])
                    for k in buf[0] if k != "image"}
            return {"image": out, **rest}

        def pooled_batches():
            streams = [mapped.iter_partition(i)
                       for i in range(mapped.num_partitions)]
            buf: list[dict] = []
            for ex in _round_robin([iter(s) for s in streams]):
                buf.append(ex)
                if len(buf) < batch_size:
                    continue
                yield stack_mapped(buf)
                buf = []
            if buf and not drop_remainder:
                yield stack_mapped(buf)

        return pooled_batches()

    # the SAME partition interleave as host_batches — the output-parity
    # contract with the per-example path depends on sharing one dealer
    streams = [dataset.iter_partition(i) for i in range(dataset.num_partitions)]
    tf_fallback = train_transform(size, seed)

    def _fused_batch(buf: list[dict]) -> dict:
        # split: images the fused kernel can take vs the rare odd ones
        # (pre-float, or the 10-draw crop sampler gave up) — only the odd
        # ones pay the per-example chain, not the whole batch
        fused_idx, images, regions, flips = [], [], [], []
        fallback_idx: list[int] = []
        if native.available():
            for j, ex in enumerate(buf):
                img = ex["image"]
                if img.dtype != np.uint8 or img.ndim != 3:
                    fallback_idx.append(j)
                    continue
                region, flip = _augment_decision(img, seed, size)
                if region is None:  # center-crop fallback shape — rare
                    fallback_idx.append(j)
                    continue
                fused_idx.append(j)
                images.append(img)
                regions.append(region)
                flips.append(flip)
        else:
            fallback_idx = list(range(len(buf)))

        out = np.empty((len(buf), size, size, 3), np.float32)
        if fused_idx:
            fused = native.rrc_flip_normalize_varbatch(
                images, np.asarray(regions, np.int32),
                np.asarray(flips, np.uint8), (size, size),
                IMAGENET_MEAN, IMAGENET_STD)
            out[np.asarray(fused_idx)] = fused
        for j in fallback_idx:
            out[j] = tf_fallback(dict(buf[j]))["image"]
        rest = {k: np.stack([np.asarray(e[k]) for e in buf])
                for k in buf[0] if k != "image"}
        return {"image": out, **rest}

    def batches():
        buf: list[dict] = []
        for ex in _round_robin([iter(s) for s in streams]):
            buf.append(ex)
            if len(buf) < batch_size:
                continue
            yield _fused_batch(buf)
            buf = []
        if buf and not drop_remainder:
            yield _fused_batch(buf)

    return batches()
