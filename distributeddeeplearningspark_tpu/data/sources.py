"""Dataset sources for the five contract workloads.

The reference reads real MNIST/ImageNet/Wikipedia/Criteo through Spark input
formats (SURVEY.md §2 data pipelines). This sandbox has no datasets and no
egress, so each workload gets:

- a **deterministic synthetic generator** with the real schema/shapes/dtypes
  (label-correlated so models demonstrably *learn* — tests assert loss ↓ and
  accuracy ↑, not just "it runs"), and
- a loader for the real on-disk format where feasible (MNIST IDX files via
  ``load_mnist_idx``) so real data drops in by pointing at a directory.

All sources yield example dicts of numpy arrays, partitioned as a
:class:`~distributeddeeplearningspark_tpu.rdd.PartitionedDataset`.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator

import numpy as np

from distributeddeeplearningspark_tpu.rdd import PartitionedDataset


def synthetic_mnist(
    num_examples: int = 2048, *, num_partitions: int = 2, seed: int = 0
) -> PartitionedDataset:
    """Label-correlated fake MNIST: class k lights up a distinct 7×7 block
    pattern plus noise, so LeNet reaches >90% accuracy within ~100 steps."""

    def make_partition(pidx: int):
        def gen() -> Iterator[dict]:
            rng = np.random.default_rng(seed * 1000 + pidx)
            n = num_examples // num_partitions
            protos = np.zeros((10, 28, 28, 1), np.float32)
            # class prototypes are fixed (seed-independent) so distinct seeds
            # give disjoint train/test draws from the SAME distribution
            prng = np.random.default_rng(20260729)
            for k in range(10):
                mask = prng.random((4, 4)) > 0.5
                protos[k, :, :, 0] = np.kron(mask, np.ones((7, 7))).astype(np.float32)
            for _ in range(n):
                label = int(rng.integers(0, 10))
                img = protos[label] + rng.normal(0, 0.3, (28, 28, 1)).astype(np.float32)
                yield {"image": img.astype(np.float32), "label": np.int32(label)}

        return gen

    return PartitionedDataset([make_partition(i) for i in range(num_partitions)])


def load_mnist_idx(data_dir: str, split: str = "train", *, num_partitions: int = 2) -> PartitionedDataset:
    """Real MNIST from IDX (optionally .gz) files, normalized to [0,1] NHWC."""
    prefix = "train" if split == "train" else "t10k"
    imgs = _read_idx(os.path.join(data_dir, f"{prefix}-images-idx3-ubyte"))
    labels = _read_idx(os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte"))
    imgs = (imgs.astype(np.float32) / 255.0)[..., None]
    labels = labels.astype(np.int32)

    examples = [{"image": imgs[i], "label": labels[i]} for i in range(len(labels))]
    return PartitionedDataset.parallelize(examples, num_partitions)


def _read_idx(path: str) -> np.ndarray:
    opener = open
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        path, opener = path + ".gz", gzip.open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError(f"{path}: bad IDX magic")
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtype = {8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32, 13: np.float32}[dtype_code]
        return np.frombuffer(f.read(), dtype=dtype).reshape(dims)


def synthetic_images(
    num_examples: int,
    *,
    image_size: int = 224,
    num_classes: int = 1000,
    num_partitions: int = 8,
    seed: int = 0,
) -> PartitionedDataset:
    """ImageNet-shaped synthetic images (config 2 dev stand-in)."""

    def make_partition(pidx: int):
        def gen() -> Iterator[dict]:
            rng = np.random.default_rng(seed * 1000 + pidx)
            n = num_examples // num_partitions
            for _ in range(n):
                label = int(rng.integers(0, num_classes))
                img = rng.normal(0, 1, (image_size, image_size, 3)).astype(np.float32)
                img[:4, :4, :] += (label % 64) / 8.0  # weak label signal
                yield {"image": img, "label": np.int32(label)}

        return gen

    return PartitionedDataset([make_partition(i) for i in range(num_partitions)])


def synthetic_criteo(
    num_examples: int = 4096,
    *,
    num_dense: int = 13,
    vocab_sizes: tuple[int, ...] = (100,) * 26,
    num_partitions: int = 4,
    seed: int = 0,
):
    """Criteo-shaped synthetic CTR data (config 4 dev stand-in).

    Click probability depends on a fixed random weighting of the categorical
    ids and two dense features, so CTR models demonstrably learn (AUC/acc
    rises above chance).
    """

    def make_partition(pidx: int):
        def gen() -> Iterator[dict]:
            rng = np.random.default_rng(seed * 1000 + pidx)
            wrng = np.random.default_rng(20260729)  # shared "ground truth"
            cat_w = [wrng.normal(0, 1.5, v) for v in vocab_sizes]
            dense_w = wrng.normal(0, 1.0, num_dense) * (np.arange(num_dense) < 2)
            n = num_examples // num_partitions
            highs = np.asarray(vocab_sizes)
            for _ in range(n):
                sparse = rng.integers(0, highs, dtype=np.int32)
                dense = rng.exponential(2.0, num_dense).astype(np.float32)
                score = sum(w[s] for w, s in zip(cat_w, sparse)) / len(vocab_sizes)
                score += float(np.log1p(dense) @ dense_w) / num_dense
                label = np.int32(rng.random() < 1 / (1 + np.exp(-3 * score)))
                yield {"dense": dense, "sparse": sparse, "label": label}

        return gen

    return PartitionedDataset([make_partition(i) for i in range(num_partitions)])
