"""Dataset sources for the five contract workloads.

The reference reads real MNIST/ImageNet/Wikipedia/Criteo through Spark input
formats (SURVEY.md §2 data pipelines). This sandbox has no datasets and no
egress, so each workload gets:

- a **deterministic synthetic generator** with the real schema/shapes/dtypes
  (label-correlated so models demonstrably *learn* — tests assert loss ↓ and
  accuracy ↑, not just "it runs"), and
- a loader for the real on-disk format where feasible (MNIST IDX files via
  ``load_mnist_idx``) so real data drops in by pointing at a directory.

All sources yield example dicts of numpy arrays, partitioned as a
:class:`~distributeddeeplearningspark_tpu.rdd.PartitionedDataset`.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator

import numpy as np

from distributeddeeplearningspark_tpu.rdd import PartitionedDataset


def synthetic_mnist(
    num_examples: int = 2048, *, num_partitions: int = 2, seed: int = 0
) -> PartitionedDataset:
    """Label-correlated fake MNIST: class k lights up a distinct 7×7 block
    pattern plus noise, so LeNet reaches >90% accuracy within ~100 steps."""

    def make_partition(pidx: int):
        def gen() -> Iterator[dict]:
            rng = np.random.default_rng(seed * 1000 + pidx)
            n = num_examples // num_partitions
            protos = np.zeros((10, 28, 28, 1), np.float32)
            # class prototypes are fixed (seed-independent) so distinct seeds
            # give disjoint train/test draws from the SAME distribution
            prng = np.random.default_rng(20260729)
            for k in range(10):
                mask = prng.random((4, 4)) > 0.5
                protos[k, :, :, 0] = np.kron(mask, np.ones((7, 7))).astype(np.float32)
            for _ in range(n):
                label = int(rng.integers(0, 10))
                img = protos[label] + rng.normal(0, 0.3, (28, 28, 1)).astype(np.float32)
                yield {"image": img.astype(np.float32), "label": np.int32(label)}

        return gen

    return PartitionedDataset([make_partition(i) for i in range(num_partitions)])


def load_mnist_idx(data_dir: str, split: str = "train", *, num_partitions: int = 2) -> PartitionedDataset:
    """Real MNIST from IDX (optionally .gz) files, normalized to [0,1] NHWC."""
    prefix = "train" if split == "train" else "t10k"
    imgs = _read_idx(os.path.join(data_dir, f"{prefix}-images-idx3-ubyte"))
    labels = _read_idx(os.path.join(data_dir, f"{prefix}-labels-idx1-ubyte"))
    imgs = (imgs.astype(np.float32) / 255.0)[..., None]
    labels = labels.astype(np.int32)

    examples = [{"image": imgs[i], "label": labels[i]} for i in range(len(labels))]
    return PartitionedDataset.parallelize(examples, num_partitions)


def _read_idx(path: str) -> np.ndarray:
    opener = open
    if not os.path.exists(path) and os.path.exists(path + ".gz"):
        path, opener = path + ".gz", gzip.open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0:
            raise ValueError(f"{path}: bad IDX magic")
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        dtype = {8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32, 13: np.float32}[dtype_code]
        return np.frombuffer(f.read(), dtype=dtype).reshape(dims)


def synthetic_images(
    num_examples: int,
    *,
    image_size: int = 224,
    num_classes: int = 1000,
    num_partitions: int = 8,
    seed: int = 0,
) -> PartitionedDataset:
    """ImageNet-shaped synthetic images (config 2 dev stand-in)."""

    def make_partition(pidx: int):
        def gen() -> Iterator[dict]:
            rng = np.random.default_rng(seed * 1000 + pidx)
            n = num_examples // num_partitions
            for _ in range(n):
                label = int(rng.integers(0, num_classes))
                img = rng.normal(0, 1, (image_size, image_size, 3)).astype(np.float32)
                img[:4, :4, :] += (label % 64) / 8.0  # weak label signal
                yield {"image": img, "label": np.int32(label)}

        return gen

    return PartitionedDataset([make_partition(i) for i in range(num_partitions)])


def synthetic_criteo(
    num_examples: int = 4096,
    *,
    num_dense: int = 13,
    vocab_sizes: tuple[int, ...] = (100,) * 26,
    num_partitions: int = 4,
    seed: int = 0,
):
    """Criteo-shaped synthetic CTR data (config 4 dev stand-in).

    Click probability depends on a fixed random weighting of the categorical
    ids and two dense features, so CTR models demonstrably learn (AUC/acc
    rises above chance).
    """

    def make_partition(pidx: int):
        def gen() -> Iterator[dict]:
            rng = np.random.default_rng(seed * 1000 + pidx)
            wrng = np.random.default_rng(20260729)  # shared "ground truth"
            cat_w = [wrng.normal(0, 1.5, v) for v in vocab_sizes]
            dense_w = wrng.normal(0, 1.0, num_dense) * (np.arange(num_dense) < 2)
            n = num_examples // num_partitions
            highs = np.asarray(vocab_sizes)
            for _ in range(n):
                sparse = rng.integers(0, highs, dtype=np.int32)
                dense = rng.exponential(2.0, num_dense).astype(np.float32)
                score = sum(w[s] for w, s in zip(cat_w, sparse)) / len(vocab_sizes)
                score += float(np.log1p(dense) @ dense_w) / num_dense
                label = np.int32(rng.random() < 1 / (1 + np.exp(-3 * score)))
                yield {"dense": dense, "sparse": sparse, "label": label}

        return gen

    return PartitionedDataset([make_partition(i) for i in range(num_partitions)])


def folder_classes(root: str) -> dict[str, int]:
    """Class→index mapping of a class-per-subdir tree (sorted-name order,
    the torchvision convention). Use to PIN one mapping across splits —
    letting train and eval dirs each derive their own silently misaligns
    labels whenever the directory sets differ."""
    root = os.path.abspath(root)
    names = sorted(
        d for d in os.listdir(root)
        if os.path.isdir(os.path.join(root, d)) and not d.startswith(".")
    )
    if not names:
        raise FileNotFoundError(f"no class directories under {root}")
    return {n: i for i, n in enumerate(names)}


def imagenet_folder(
    root: str,
    *,
    num_partitions: int = 8,
    class_to_index: dict[str, int] | None = None,
    decode: bool = True,
) -> PartitionedDataset:
    """Real ImageNet from the standard class-per-subdirectory layout
    (``root/n01440764/xxx.JPEG``) — VERDICT r1 missing-#3: "point at a
    directory and train" for config 2.

    Decoding (our native baseline-JPEG decoder, PIL fallback — see
    :func:`..data.vision.decode_jpeg`) happens lazily inside the partition
    iterator, i.e. on the prefetch thread, overlapping device compute the way
    the reference's executors decode inside Spark tasks. Labels follow sorted
    class-directory order (torchvision's convention) unless an explicit
    ``class_to_index`` is given; ``decode=False`` yields raw bytes under
    ``"jpeg"`` for pipelines that want decode inside a later ``.map``.
    """
    root = os.path.abspath(root)
    classes = class_to_index if class_to_index is not None else folder_classes(root)
    files: list[tuple[str, int]] = []
    exts = (".jpeg", ".jpg", ".JPEG", ".JPG")
    for name, idx in sorted(classes.items()):
        cdir = os.path.join(root, name)
        if not os.path.isdir(cdir):
            continue
        for fn in sorted(os.listdir(cdir)):
            if fn.endswith(exts):
                files.append((os.path.join(cdir, fn), idx))
    if not files:
        raise FileNotFoundError(f"no JPEG files under {root}")

    def make_partition(pidx: int):
        shard = files[pidx::num_partitions]

        def gen() -> Iterator[dict]:
            from distributeddeeplearningspark_tpu.data.vision import decode_jpeg

            for path, label in shard:
                if decode:
                    img = decode_jpeg(path)
                    if img.shape[-1] == 1:  # grayscale ImageNet strays → RGB
                        img = np.repeat(img, 3, axis=-1)
                    yield {"image": img, "label": np.int32(label)}
                else:
                    with open(path, "rb") as f:
                        yield {"jpeg": f.read(), "label": np.int32(label)}

        return gen

    return PartitionedDataset([make_partition(i) for i in range(num_partitions)])


#: Criteo display-advertising schema (same constants as models/dlrm.py).
CRITEO_DENSE = 13
CRITEO_SPARSE = 26

#: Criteo display-advertising schema: hashed categorical buckets per feature.
#: The real dataset's per-feature cardinalities vary 10..10M; a fixed
#: hash-bucket size per feature (the standard production trick) bounds table
#: memory and needs no vocabulary pass over the 1TB file.
CRITEO_DEFAULT_BUCKETS = (1 << 18,) * 26


def criteo_tsv(
    path: str,
    *,
    num_partitions: int = 8,
    vocab_sizes: tuple[int, ...] = CRITEO_DEFAULT_BUCKETS,
    has_label: bool = True,
) -> PartitionedDataset:
    """Real Criteo TSV (``label \\t 13 ints \\t 26 hex cats``) → batch dicts
    (VERDICT r1 missing-#3, config 4).

    - missing dense values ('' or absent) → 0.0 (the log1p transform in the
      models treats 0 as the neutral count);
    - categorical hex ids are hashed into per-feature buckets:
      ``int(feat, 16) % vocab_sizes[i]`` (missing → bucket 0);
    - ``path`` may be a file or a directory of ``day_*``/``*.txt`` shards;
      partitions byte-split big files so every partition streams lazily.
    """
    if len(vocab_sizes) != CRITEO_SPARSE:
        raise ValueError(f"need {CRITEO_SPARSE} vocab sizes, got {len(vocab_sizes)}")
    if os.path.isdir(path):
        shards = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if not f.startswith(".") and os.path.isfile(os.path.join(path, f))
        )
    else:
        shards = [path]
    if not shards:
        raise FileNotFoundError(f"no Criteo shards under {path}")

    # byte-range splits: partition i of file f starts at the first full line
    # after offset i·size/P — the same contract as Spark's TextInputFormat
    splits: list[tuple[str, int, int]] = []
    per_file = max(1, num_partitions // len(shards))
    for f in shards:
        size = os.path.getsize(f)
        k = per_file if size > (1 << 20) else 1
        for j in range(k):
            splits.append((f, size * j // k, size * (j + 1) // k))

    highs = np.asarray(vocab_sizes, np.int64)

    def parse_line(line: str):
        cols = line.rstrip("\n").split("\t")
        off = 1 if has_label else 0
        want = off + CRITEO_DENSE + CRITEO_SPARSE
        if len(cols) < want:
            cols = cols + [""] * (want - len(cols))
        label = np.int32(int(cols[0])) if has_label else np.int32(0)
        dense = np.array(
            [float(c) if c else 0.0 for c in cols[off:off + CRITEO_DENSE]],
            np.float32,
        )
        sparse = np.array(
            [
                (int(c, 16) % int(highs[i])) if c else 0
                for i, c in enumerate(
                    cols[off + CRITEO_DENSE:off + CRITEO_DENSE + CRITEO_SPARSE])
            ],
            np.int32,
        )
        return {"dense": dense, "sparse": sparse, "label": label}

    def make_partition(split: tuple[str, int, int]):
        fname, lo, hi = split

        def gen() -> Iterator[dict]:
            # Spark TextInputFormat contract: a split owns every line that
            # STARTS at offset in (lo, hi]; a reader seeked into the middle
            # of a line discards it (the previous split read through it).
            with open(fname, "rb") as f:
                if lo:
                    f.seek(lo)
                    f.readline()
                while True:
                    if f.tell() > hi:
                        break
                    raw = f.readline()
                    if not raw:
                        break
                    line = raw.decode("utf-8", errors="replace")
                    if line.strip():
                        yield parse_line(line)

        return gen

    return PartitionedDataset([make_partition(s) for s in splits])
