"""Preprocessed array records — the materialized-RDD input path.

The reference's answer to "JPEG decode can't feed the cluster" is Spark's
``rdd.cache()``/``persist()``: decode once, keep the decoded partitions, and
every later epoch streams pre-materialized rows (SURVEY.md §2 'Data: image
pipeline'; VERDICT r2 missing-#4 asks for the TPU-native equivalent). This
module is that equivalent as an on-disk format: fixed-preprocessing results
(e.g. decoded + shorter-side-resized uint8 images) written once into sharded
binary record files, then streamed back at memory-bandwidth rates instead of
~50 img/s/core JPEG decode. Randomized augmentation (crop/flip/normalize)
stays online at read time, so records don't bake one epoch's randomness in.

Format (one ``part-NNNNN.dlsrec`` file per shard):

- 8-byte magic ``DLSREC01``;
- records back-to-back, each: ``uint32 nbytes`` then an ``npz``-free body —
  ``uint16 nkeys``; per key ``uint16 klen, key utf8, 2-byte dtype pad...``
  (see ``_pack_record``) — numpy arrays serialized as raw C-order bytes with
  an explicit dtype/shape header (no pickle anywhere: records are shareable
  artifacts and must never execute code on read);
- a footer: ``uint64[count]`` record offsets, ``uint64 count``,
  ``uint64 footer_offset``, 8-byte magic ``DLSIDX01``.

The offset index makes a shard byte-splittable (the same contract as the
Criteo byte-range splits in ``sources.py``): ``array_records`` can fan one
big shard out to many partitions without a scan, so the partition count can
match the mesh's data axis regardless of how many files the writer produced.
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Iterator, Sequence

import numpy as np

from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

_MAGIC = b"DLSREC01"
_IDX_MAGIC = b"DLSIDX01"


def _pack_record(example: dict) -> bytes:
    """Dict[str, np.ndarray | scalar] → bytes. Keys are sorted so byte
    output is deterministic for identical content."""
    parts: list[bytes] = [struct.pack("<H", len(example))]
    for key in sorted(example):
        # NOT ascontiguousarray: it promotes 0-d scalars to shape (1,)
        # (ndmin=1 quirk), which would make labels round-trip as [1] arrays
        # and batch to [B, 1] instead of [B]. tobytes() below already
        # serializes any layout as C-order bytes, so no contiguity copy is
        # needed either.
        arr = np.asarray(example[key])
        kb = key.encode("utf-8")
        ds = arr.dtype.str.encode("ascii")  # e.g. b'|u1', b'<f4', b'<i4'
        parts.append(struct.pack("<H", len(kb)))
        parts.append(kb)
        parts.append(struct.pack("<B", len(ds)))
        parts.append(ds)
        parts.append(struct.pack("<B", arr.ndim))
        parts.append(struct.pack("<" + "Q" * arr.ndim, *arr.shape))
        parts.append(struct.pack("<Q", arr.nbytes))
        parts.append(arr.tobytes())
    return b"".join(parts)


def _unpack_record(buf: bytes) -> dict:
    (nkeys,) = struct.unpack_from("<H", buf, 0)
    out: dict = {}
    pos = 2
    for _ in range(nkeys):
        (klen,) = struct.unpack_from("<H", buf, pos); pos += 2
        key = buf[pos:pos + klen].decode("utf-8"); pos += klen
        (dlen,) = struct.unpack_from("<B", buf, pos); pos += 1
        dtype = np.dtype(buf[pos:pos + dlen].decode("ascii")); pos += dlen
        (ndim,) = struct.unpack_from("<B", buf, pos); pos += 1
        shape = struct.unpack_from("<" + "Q" * ndim, buf, pos); pos += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", buf, pos); pos += 8
        arr = np.frombuffer(buf, dtype, count=nbytes // dtype.itemsize,
                            offset=pos).reshape(shape)
        pos += nbytes
        # 0-d arrays come back as numpy scalars, matching the writers' input
        out[key] = arr[()] if ndim == 0 else arr
    return out


class RecordShardWriter:
    """Streams records into one ``part-NNNNN.dlsrec`` file."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "wb")
        self._f.write(_MAGIC)
        self._offsets: list[int] = []

    def write(self, example: dict) -> None:
        body = _pack_record(example)
        self._offsets.append(self._f.tell())
        self._f.write(struct.pack("<I", len(body)))
        self._f.write(body)

    def close(self) -> None:
        footer_off = self._f.tell()
        if self._offsets:
            self._f.write(np.asarray(self._offsets, "<u8").tobytes())
        self._f.write(struct.pack("<QQ", len(self._offsets), footer_off))
        self._f.write(_IDX_MAGIC)
        self._f.close()

    def abort(self) -> None:
        """Close WITHOUT a footer and delete the file — a shard that failed
        mid-write must not be left looking complete (the footer is the
        integrity check; writing it for a partial body would make truncation
        undetectable)."""
        self._f.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is not None:
            self.abort()
        else:
            self.close()


def _read_index(path: str) -> np.ndarray:
    """Record offsets of one shard (from the footer, no scan)."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        if size < len(_MAGIC) + 24 or (f.read(8) != _MAGIC):
            raise ValueError(f"{path}: not a DLSREC01 file")
        f.seek(size - 24)
        count, footer_off = struct.unpack("<QQ", f.read(16))
        if f.read(8) != _IDX_MAGIC:
            raise ValueError(f"{path}: missing footer index (truncated write?)")
        f.seek(footer_off)
        return np.frombuffer(f.read(8 * count), "<u8")


def _iter_shard(path: str, lo: int, hi: int) -> Iterator[dict]:
    """Yield records [lo, hi) of one shard by footer-indexed seek."""
    offsets = _read_index(path)
    with open(path, "rb") as f:
        for off in offsets[lo:hi]:
            f.seek(int(off))
            (nbytes,) = struct.unpack("<I", f.read(4))
            yield _unpack_record(f.read(nbytes))


def shard_paths(path: str) -> list[str]:
    """All ``*.dlsrec`` shards of a record dir (or the single file)."""
    if os.path.isfile(path):
        return [path]
    shards = sorted(
        os.path.join(path, f) for f in os.listdir(path)
        if f.endswith(".dlsrec")
    )
    if not shards:
        raise FileNotFoundError(f"no .dlsrec shards under {path}")
    return shards


def array_records(path: str, *, num_partitions: int | None = None) -> PartitionedDataset:
    """Read a record dir/file back as a :class:`PartitionedDataset`.

    ``num_partitions=None`` → one partition per shard file. A larger count
    splits shards by record-index ranges via the footer index (so partition
    granularity can match the mesh data axis without rewriting files); a
    smaller count groups whole shards round-robin.
    """
    shards = shard_paths(path)
    counts = [len(_read_index(s)) for s in shards]

    splits: list[list[tuple[str, int, int]]]
    if num_partitions is None or num_partitions == len(shards):
        splits = [[(s, 0, c)] for s, c in zip(shards, counts)]
    elif num_partitions < len(shards):
        splits = [[] for _ in range(num_partitions)]
        for i, (s, c) in enumerate(zip(shards, counts)):
            splits[i % num_partitions].append((s, 0, c))
    else:
        # split each shard into ~equal record ranges; distribute the
        # partition budget proportionally to shard record counts
        total = sum(counts)
        budget = [max(1, round(num_partitions * c / max(1, total))) for c in counts]
        # fix rounding so the total matches exactly
        while sum(budget) > num_partitions:
            budget[int(np.argmax(budget))] -= 1
        while sum(budget) < num_partitions:
            budget[int(np.argmin(budget))] += 1
        splits = []
        for s, c, k in zip(shards, counts, budget):
            bounds = [c * j // k for j in range(k + 1)]
            splits.extend([[(s, bounds[j], bounds[j + 1])] for j in range(k)])

    def make_partition(ranges: Sequence[tuple[str, int, int]]):
        def gen() -> Iterator[dict]:
            for path_, lo, hi in ranges:
                yield from _iter_shard(path_, lo, hi)

        return gen

    return PartitionedDataset([make_partition(r) for r in splits])


def write_array_records(
    dataset: PartitionedDataset | Iterable[dict],
    out_dir: str,
    *,
    num_shards: int | None = None,
) -> list[str]:
    """Materialize a dataset into ``out_dir/part-NNNNN.dlsrec`` shards.

    One shard per source partition by default (preserves the partition
    structure, and each partition streams lazily — never holds a shard in
    memory). Returns the shard paths.
    """
    os.makedirs(out_dir, exist_ok=True)
    if isinstance(dataset, PartitionedDataset):
        parts: list[Iterable[dict]] = [
            dataset.iter_partition(i) for i in range(dataset.num_partitions)
        ]
    else:
        parts = [iter(dataset)]
    if num_shards is not None and num_shards != len(parts):
        # round-robin into N writers WHILE streaming — buffering the whole
        # dataset to reshard would hold ~the full decoded corpus in memory
        writers = [
            RecordShardWriter(os.path.join(out_dir, f"part-{i:05d}.dlsrec"))
            for i in range(num_shards)
        ]
        try:
            i = 0
            for part in parts:
                for ex in part:
                    writers[i % num_shards].write(ex)
                    i += 1
        except BaseException:
            for w in writers:
                w.abort()
            raise
        for w in writers:
            w.close()
        return [w.path for w in writers]
    paths = []
    try:
        for i, part in enumerate(parts):
            p = os.path.join(out_dir, f"part-{i:05d}.dlsrec")
            with RecordShardWriter(p) as w:
                for ex in part:
                    w.write(ex)
            paths.append(p)
    except BaseException:
        # abort-ALL (ADVICE r3): completed earlier shards would otherwise
        # look valid, and a retry into the same out_dir could silently mix
        # shards from two runs (mirrors the resharding branch's abort)
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass
        raise
    return paths


def write_imagenet_records(
    root: str,
    out_dir: str,
    *,
    size: int = 256,
    num_shards: int = 8,
    num_threads: int | None = None,
    class_to_index: dict[str, int] | None = None,
) -> list[str]:
    """One-time ImageNet materialization: JPEG → shorter-side-``size`` uint8.

    The expensive fixed work (decode + big-image resize) happens exactly once
    here — parallel across ``num_threads`` (decode/resize release the GIL in
    the native kernels); training then reads records and pays only the cheap
    randomized tail (crop to 224 + flip + normalize) per epoch. ``size=256``
    keeps the standard 256→224 crop margin.
    """
    from distributeddeeplearningspark_tpu.data.sources import imagenet_folder
    from distributeddeeplearningspark_tpu.data.vision import (
        _decode_if_bytes, _resize)

    def preprocess(example: dict) -> dict:
        example = _decode_if_bytes(example)
        img = example["image"]
        if img.shape[-1] == 1:
            img = np.repeat(img, 3, axis=-1)
        h, w = img.shape[:2]
        scale = size / min(h, w)
        if scale < 1.0:  # never upscale at materialization time
            img = _resize(img.astype(np.float32),
                          (int(round(h * scale)), int(round(w * scale))))
        img = np.clip(img, 0, 255).astype(np.uint8)
        return {"image": np.ascontiguousarray(img), "label": example["label"]}

    ds = imagenet_folder(root, num_partitions=num_shards, decode=False,
                         class_to_index=class_to_index)
    return write_array_records(
        ds.map_parallel(preprocess, num_threads=num_threads), out_dir,
        num_shards=num_shards)
