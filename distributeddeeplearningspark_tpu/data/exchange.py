"""Cross-worker hash-partitioned shuffle — the wide-transformation engine.

PR 5 drew the honest line of the narrow engine: every wide transformation
(``reduce_by_key``, ``group_by_key``, ``groupBy().agg``) merged its partials
in ONE driver-side dict, and the ``max_groups`` ceiling *refused*
high-cardinality workloads (user-id-like keys) rather than run them out of
memory. This module breaks that ceiling with the classic map/reduce-over-
partitions shape (DrJAX, PAPERS.md 2403.07128) built on the process-pool
machinery ``data/workers.py`` already proved:

- **Map side.** ``M`` forked mapper processes walk the source partitions
  (partition ``p`` → mapper ``p % M``; when ``M > P`` mappers split a
  partition by element-residue classes, the WorkerPool discipline) and
  combine locally into a bounded dict. When the dict outgrows its share of
  ``DLS_SHUFFLE_MEM_MB`` it *flushes*: entries bucket by canonical key hash
  (:func:`key_bytes` — blake2b over the pickled key, NOT Python's
  per-process-seeded ``hash``) and each bucket's payload ships to its
  owning reducer through the mapper's shared-memory arena
  (:class:`~.workers._Arena`, the same first-fit out-of-order reclaim),
  falling back to pickled queue transport when the arena is full — counted,
  never stalled, exactly the batch-plane discipline.
- **Exchange.** Barrier-free: one queue per reducer, payloads stream as
  flushes happen, reducers merge incrementally while mappers still run.
  A mapper that raises forwards its traceback; one that *dies* (SIGKILL,
  OOM) is caught by the driver's liveness poll — either way the caller gets
  a typed :class:`~.workers.WorkerCrashed` within a bounded wait and every
  child, shm segment, and spill file is torn down. A dead mapper is a
  supervisor-visible CRASH, not a hang.
- **Reduce side.** ``R`` reducer processes own buckets ``b % R == r`` and
  merge arriving partials into per-bucket dicts under their share of the
  memory budget; past it they **spill**: items sorted by :func:`key_bytes`
  stream to a run file, and finalization k-way-merges the sorted runs
  (``heapq.merge``) combining adjacent equal keys — a 10M-key aggregation
  completes under a budget the old ceiling refused at. Final output streams
  to one file per bucket; the returned dataset's partitions re-read those
  files, so nothing is ever fully materialized driver-side.

**Determinism.** Output order is canonical: bucket-major, :func:`key_bytes`
order within each bucket — data-derived, so results are byte-identical at
ANY worker count (the serial fallbacks in ``rdd.py``/``data/dataframe.py``
emit the same canonical order, which also makes them reproducible across
runs — the old ``hash(k) % n`` bucketing moved with ``PYTHONHASHSEED``).
Value-combine order is NOT fixed across worker counts (partials merge as
they arrive), so reduce functions must be commutative + associative —
Spark's own ``reduceByKey`` contract; results are bit-identical when the
combine is exact (int sums, min/max, counts; float sums are exact while
magnitudes stay within 2^53). ``group_by_key`` value lists ARE exactly
ordered: values travel tagged with their (partition, index) position and
sort back to encounter order at emit.

**Telemetry.** The driver wraps the run in ``shuffle-map`` / ``shuffle-
merge`` phase spans (lowered into the PR 7 span model like any phase) and
mirrors reducer spills plus a final summary as ``shuffle`` gauge events —
``dlstatus`` renders them as the shuffle block (bytes moved, spill count,
per-bucket skew, slowest-bucket verdict, per-format byte/key split).

**Columnar transport (ISSUE 12).** Per-key pickled tuples cap the agg
path at ~35–70k keys/s/worker — at 10M keys the data plane, not the
combine math, is the bottleneck. When an operation declares a
:class:`ColumnarPlan` (``groupBy().agg`` with numeric keys;
``reduce_by_key``/``distinct`` over plain int/float scalars with a
declared numeric combine), conforming batches travel as **flat planes**
instead: a ``key_hash`` uint64 array (the first 8 bytes of
:func:`key_bytes`, so bucketing and ordering stay IDENTICAL to the tuple
path), the key columns, and one value array per combine — whole arrays
pickled once and shipped through the same shm arenas, metered by their
exact ``nbytes`` (:meth:`_ByteMeter.add_exact` — the every-64th-item
sampling that keeps tuple accounting cheap would under-throttle a 16MB
plane). Map side, flushes sort by hash and segment-combine with
``np.argsort``/``ufunc.reduceat`` (no per-key Python); reduce side,
bucket planes merge by sorted hash, spill runs are columnar block files
k-way merged on the hash column, and hash collisions (2⁻⁶⁴, but tested)
resolve by full-key compare against the pickled key bytes. Batches that
do NOT conform (object keys, mixed value types) fall back to the tuple
path per batch, and a bucket that receives both formats degrades to
tuple merging — output is byte-identical to an all-tuple run either
way, which is the whole contract: ``DLS_SHUFFLE_TRANSPORT=tuple``
exists only to measure the difference. The numeric combines themselves
can additionally be lowered onto the accelerator via
:mod:`~.device_agg` (``groupBy().agg(transport="device")``), whose
jitted ``jax.ops.segment_*`` kernels ride the PR 9 compile ledger.

**Task-level fault tolerance (ISSUE 14).** The exchange survives task
failure instead of reporting it — the Spark lineage-retry model, safe
here because mapper tasks are pure callables over partition slices:

- **Tasks, not assignments.** The ``(part, slot, k)`` slices go through a
  shared task queue that mappers *pull* from, so a dead worker's
  unfinished slices flow to a respawned or surviving mapper with no
  rebalancing code. Every shipped payload frame carries a deterministic
  identity ``(part, slot, seq)`` — per-slice state (byte meters, dtype
  pins, batch buffers, the frame counter) resets at slice entry, so a
  replayed slice ships *byte-identical* frames at the same ids no matter
  which worker runs it. Reducers deduplicate on that identity, which is
  what makes retry AND speculative execution safe: first finish wins,
  duplicates drop, output stays byte-identical to a fault-free run (the
  blake2b checksum discipline is the oracle).
- **Retention IS the transport (retain mode).** With retries enabled
  (``DLS_SHUFFLE_MAX_RETRIES`` > 0, default 3) every frame is written
  to the spill dir as an atomically-renamed ``ret-*`` file named by its
  identity, and reducers SWEEP the directory for unseen frames — one
  producer copy either way (page cache instead of shm pages), and,
  decisively, **no shared data queue exists**: a SIGKILLed producer
  cannot tear a frame mid-pipe-write or die holding a queue lock the
  survivors then block on (both observed with shared ``mp.Queue``
  transport under the chaos drill). Control traffic rides per-attempt
  driver-owned queues whose messages stay under the pipe's 4KB
  atomic-write bound. A dead reducer's replacement rebuilds its buckets
  purely from retained files (never touching the dead consumer's
  possibly-torn pipe); retained files are deleted when the exchange
  completes. ``DLS_SHUFFLE_MAX_RETRIES=0`` keeps the legacy
  shm-arena/queue transport byte-for-byte with zero retention and zero
  recovery — the measurement baseline and today's fail-fast behavior
  (docs/PERFORMANCE.md "Retention cost").
- **Legacy mode never recovers, so arenas are attempt-0-only.** With
  the budget at 0 the first failure escalates before any respawn could
  happen; every child that exists forked at exchange start, so the shm
  arenas, free queues, and reducer data queues all belong to those
  original attempts and need no cross-attempt versioning.
- **Reducer termination by count.** Map completion is driven by
  driver-side slice accounting, not per-mapper queue sentinels: each
  ``slice-done`` reports per-reducer unique-frame counts (deterministic
  across replays), the driver sends each reducer an ``eof`` total once
  all slices are done, and a reducer finalizes when its unique-frame set
  reaches that total — late, lost, or duplicated frames all converge.
- **Policy.** A retry budget (``DLS_SHUFFLE_MAX_RETRIES``) bounds total
  recovery actions, escalating to the same typed
  :class:`~.workers.WorkerCrashed` as before when exhausted; per-worker
  failure scoring blacklists a worker slot after
  ``DLS_SHUFFLE_BLACKLIST_AFTER`` strikes (a blacklisted mapper's work
  redistributes; a blacklisted reducer escalates — its buckets are
  pinned); and speculative execution re-enqueues a slice whose runtime
  lags ``DLS_SHUFFLE_SPECULATE_FACTOR`` × the median completed-slice
  duration (first finish wins via dedup). Every retry / speculation /
  blacklist decision is a ``shuffle`` telemetry event rendered by
  ``dlstatus``, and ``DLS_FAULT=die_shuffle_worker@N`` (faults.py) kills
  a mapper at its Nth element / a reducer at its Nth merged frame for
  deterministic drills (``tools/ci.sh shuffle-chaos``).
"""

from __future__ import annotations

import hashlib
import heapq
import math
import multiprocessing as mp
import os
import pickle
import queue as queue_lib
import shutil
import tempfile
import time
import traceback
import uuid
import warnings
import weakref
from multiprocessing import shared_memory
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from distributeddeeplearningspark_tpu import faults, telemetry
from distributeddeeplearningspark_tpu.data.workers import (
    _POLL_S, _Arena, _align, WorkerCrashed, env_num, fork_available,
    resolve_num_workers)

#: env knob: total shuffle memory budget (MB) split over mapper arenas,
#: mapper combine dicts, and reducer merge dicts. Past their share, mappers
#: flush early and reducers spill to disk — the budget bounds resident
#: bytes, it never refuses a workload.
MEM_MB_ENV = "DLS_SHUFFLE_MEM_MB"
_DEFAULT_MEM_MB = 256
#: env knob: where spill runs and bucket output files live (default: a
#: fresh tempdir per shuffle, removed when the result is garbage-collected
#: or the exchange fails).
SPILL_DIR_ENV = "DLS_SHUFFLE_SPILL_DIR"
#: env knob shared with data/dataframe.py: the serial-path distinct-key /
#: materialization ceiling (the exchange path has no ceiling — that is the
#: point of it).
MAX_GROUPS_ENV = "DLS_AGG_MAX_GROUPS"
_DEFAULT_MAX_GROUPS = 1_000_000
#: env knob: transport override for eligible wide ops — ``auto`` (default:
#: columnar where batches conform, tuple elsewhere), ``columnar`` (alias of
#: auto — non-conforming batches still fall back, byte-identically),
#: ``tuple`` (force the per-key pickled path; the measurement baseline), or
#: ``device`` (groupBy.agg only: serial scan + jitted segment-reduce
#: combines, data/device_agg.py).
TRANSPORT_ENV = "DLS_SHUFFLE_TRANSPORT"
TRANSPORTS = ("auto", "tuple", "columnar", "device")
#: env knob: total recovery actions (mapper/reducer respawns, slice
#: re-executions after a raise) one exchange may spend before escalating
#: to the typed :class:`~.workers.WorkerCrashed`. 0 = today's fail-fast
#: behavior exactly (and disables frame retention — the perf baseline).
MAX_RETRIES_ENV = "DLS_SHUFFLE_MAX_RETRIES"
_DEFAULT_MAX_RETRIES = 3
#: env knob: failure strikes before a worker slot is blacklisted.
BLACKLIST_ENV = "DLS_SHUFFLE_BLACKLIST_AFTER"
_DEFAULT_BLACKLIST_AFTER = 2
#: env knob: speculative-execution lag factor — a running slice whose
#: elapsed time exceeds factor × median completed-slice duration (and
#: the 1s floor) is cloned to the task queue; first finish wins, frame
#: dedup makes the clone safe. <= 0 disables speculation.
SPECULATE_ENV = "DLS_SHUFFLE_SPECULATE_FACTOR"
_DEFAULT_SPECULATE_FACTOR = 4.0
#: speculation never triggers below this elapsed time — tiny test slices
#: must not clone themselves just because the median is microseconds.
_SPECULATE_FLOOR_S = 1.0
#: last-resort driver stall window: with no control-queue progress for
#: this long, undone slices with no registered runner are re-enqueued.
#: Custody loss (a task popped by a worker that died before its
#: slice-start landed) is normally repaired by the failure handler
#: itself; this net only exists so an unforeseen loss degrades into one
#: duplicate round per minute instead of a hang. Duplicates are harmless
#: by dedup.
_RESEED_S = 60.0
#: declared numeric combines a ColumnarPlan can vectorize. "count" is a
#: sum of int64 count planes, "mean" derives from (sum, count) at read
#: time — both reduce to these three.
NUMERIC_COMBINES = ("sum", "min", "max")

_PICKLE_PROTO = 4
#: per-reducer metadata queue bound: flush payloads in flight beyond the
#: arenas (backpressure's item-count half, as in workers.py).
_QUEUE_AHEAD = 16
#: how long a mapper waits for arena space before the pickle fallback.
_ALLOC_WAIT_S = 0.25
_MIN_ARENA = 1 << 20
_MIN_CAP = 1 << 18
#: rdd-pair columnar mode: pairs buffered before a vectorization attempt
#: (conformance is judged per batch — one odd batch degrades itself, not
#: the whole shuffle).
_PAIR_BATCH = 8192
#: row cap per pickled plane block in columnar spill runs / output files —
#: the unit the k-way merge streams, so merge residency is O(streams ×
#: block), never O(run).
_COLS_BLOCK_ROWS = 131_072


def max_groups_limit(explicit: int | None = None) -> int:
    """The serial-path cardinality ceiling: explicit value, else
    ``DLS_AGG_MAX_GROUPS``, else 1M (PR 5's default)."""
    if explicit is not None:
        return int(explicit)
    try:
        return int(os.environ.get(MAX_GROUPS_ENV, "") or _DEFAULT_MAX_GROUPS)
    except ValueError:
        return _DEFAULT_MAX_GROUPS


def resolve_shuffle_workers(num_workers: int | None) -> int:
    """Worker count for the exchange: explicit value wins, ``None`` reads
    ``DLS_DATA_WORKERS`` (the pool the shuffle rides on). 0 — or a platform
    without ``fork`` — means the serial driver-side path."""
    nw = resolve_num_workers(num_workers)
    if nw > 0 and not fork_available():  # pragma: no cover - platform
        warnings.warn("shuffle workers requested but the 'fork' start "
                      "method is unavailable; using the serial path")
        return 0
    return nw


def resolve_transport(explicit: str | None = None, *,
                      allow_device: bool = False) -> str:
    """Shuffle transport: explicit value wins, else ``DLS_SHUFFLE_TRANSPORT``,
    else ``auto``. ``device`` is only meaningful where the caller supports
    it (groupBy.agg); elsewhere it resolves to ``auto`` — the env knob must
    never break an ineligible op."""
    t = explicit or os.environ.get(TRANSPORT_ENV, "") or "auto"
    t = t.strip().lower()
    if t not in TRANSPORTS:
        raise ValueError(
            f"unknown shuffle transport {t!r}; choose one of {TRANSPORTS}")
    if t == "device" and not allow_device:
        if explicit:
            raise ValueError(
                "transport='device' is only supported by groupBy().agg "
                "numeric combines (data/device_agg.py); use 'auto', "
                "'columnar', or 'tuple' here")
        return "auto"
    return t


def mem_budget_bytes(explicit_mb: float | None = None) -> int:
    if explicit_mb is None:
        try:
            explicit_mb = float(
                os.environ.get(MEM_MB_ENV, "") or _DEFAULT_MEM_MB)
        except ValueError:
            explicit_mb = _DEFAULT_MEM_MB
    return max(4 << 20, int(explicit_mb * (1 << 20)))


def max_shuffle_retries(explicit: int | None = None) -> int:
    """The exchange's recovery budget: explicit value, else
    ``DLS_SHUFFLE_MAX_RETRIES``, else 3. 0 restores fail-fast."""
    if explicit is not None:
        return max(0, int(explicit))
    return env_num(MAX_RETRIES_ENV, _DEFAULT_MAX_RETRIES, lo=0)


def blacklist_after() -> int:
    """Failure strikes before a worker slot is blacklisted (min 1)."""
    return env_num(BLACKLIST_ENV, _DEFAULT_BLACKLIST_AFTER, lo=1)


def speculate_factor() -> float:
    """Speculation lag factor (``DLS_SHUFFLE_SPECULATE_FACTOR``, default
    4.0); <= 0 disables speculative execution."""
    return env_num(SPECULATE_ENV, _DEFAULT_SPECULATE_FACTOR, cast=float)


def key_bytes(key: Any) -> bytes:
    """Canonical sortable identity of a shuffle key: an 8-byte blake2b
    digest of the pickled key, followed by the pickle itself (the digest
    buckets and sorts; the tail breaks the astronomically-rare collision
    deterministically). Stable across processes and runs — unlike
    ``hash()``, which moves with ``PYTHONHASHSEED``. Keys that compare
    equal but pickle differently (``1`` vs ``np.int64(1)``) are DIFFERENT
    shuffle keys; keep key types canonical (the DataFrame plane already
    does)."""
    kb = pickle.dumps(key, protocol=_PICKLE_PROTO)
    return hashlib.blake2b(kb, digest_size=8).digest() + kb


def bucket_of(kb: bytes, n_out: int) -> int:
    """Owning bucket of a key's :func:`key_bytes` — shared with the serial
    fallbacks so both paths land every key in the same output partition."""
    return int.from_bytes(kb[:8], "big") % n_out


def _approx_nbytes(v: Any) -> int:
    """Cheap upper-ish estimate of an object's resident bytes for the
    flush/spill accounting. Precision is not the point — a stable,
    monotone estimate is (over-estimating just flushes earlier). The hot
    loops call this SAMPLED (every 64th item, :class:`_ByteMeter`): at 10M
    pairs, two recursive walks per pair were the map phase's single
    largest cost."""
    if isinstance(v, np.ndarray):
        return v.nbytes + 128
    if isinstance(v, (bytes, str)):
        return len(v) + 64
    if isinstance(v, (list, tuple)):
        return 64 + 16 * len(v) + sum(
            _approx_nbytes(x) for x in v[:8]) * max(1, len(v) // 8 if len(v) > 8 else 1)
    if isinstance(v, dict):
        return 64 + sum(_approx_nbytes(x) + 32 for x in v.values())
    return 64


class _ByteMeter:
    """Sampled byte accounting for the mapper/reducer stores: every 64th
    ``add`` re-measures the item with :func:`_approx_nbytes` and the
    in-between items are charged the rolling estimate. ``value`` tracks
    the store's resident bytes well enough to bound memory (the budget's
    contract), at 1/64th the walk cost.

    Columnar planes do NOT go through the sampler: a shipped plane is one
    array whose size is already known exactly, and charging it the rolling
    per-tuple estimate would book a 16MB plane as ~200 bytes — the sampled
    heuristic exists to dodge recursive size walks, not to excuse
    under-throttling against ``DLS_SHUFFLE_MEM_MB``. The mapper charges
    planes through :meth:`add_exact` on a dedicated meter; the reducer
    keeps the same exact-``nbytes`` accounting as per-bucket tallies
    (spilling a bucket must subtract exactly its planes, which one
    aggregate counter cannot express)."""

    __slots__ = ("value", "_est", "_n")

    def __init__(self):
        self.value = 0.0
        self._est = 192.0
        self._n = 0

    def add(self, item: Any, overhead: int = 0) -> None:
        self._n += 1
        if self._n & 0x3F == 1:
            self._est = float(_approx_nbytes(item))
        self.value += self._est + overhead

    def add_exact(self, nbytes: int) -> None:
        """Charge a known size verbatim — no sampling, no estimate drift
        (whole shipped planes: one array, exact ``nbytes``)."""
        self.value += float(nbytes)

    def reset(self) -> None:
        self.value = 0.0


# ---------------------------------------------------------------------------
# operation specs
# ---------------------------------------------------------------------------


class _Spec:
    """How one wide operation maps onto the exchange.

    ``pre(elem)``: iterable of (key, value) pairs for one source element
    (None = the element already is the pair). ``seed(v)``: first value →
    accumulator. ``combine(acc, v)``: fold one more value in (map side).
    ``merge(a, b)``: fold two accumulators (reduce side). ``final(key,
    acc)``: the emitted record. ``tag_values``: wrap each value as
    ``(part, idx, v)`` before seeding so ``final`` can restore encounter
    order (group_by_key).
    """

    __slots__ = ("pre", "seed", "combine", "merge", "final", "tag_values")

    def __init__(self, *, pre=None, seed=None, combine=None, merge=None,
                 final=None, tag_values=False):
        self.pre = pre
        self.seed = seed if seed is not None else (lambda v: v)
        self.combine = combine
        self.merge = merge if merge is not None else combine
        self.final = final if final is not None else (lambda k, a: (k, a))
        self.tag_values = tag_values


def _reduce_spec(f: Callable[[Any, Any], Any]) -> _Spec:
    return _Spec(combine=f)


def _group_spec() -> _Spec:
    def final(k, acc):
        acc.sort(key=lambda t: (t[0], t[1]))
        return (k, [v for _, _, v in acc])

    return _Spec(seed=lambda tv: [tv],
                 combine=lambda acc, tv: (acc.append(tv) or acc),
                 merge=lambda a, b: (a.extend(b) or a),
                 final=final, tag_values=True)


def _distinct_spec() -> _Spec:
    return _Spec(pre=lambda x: ((x, None),),
                 combine=lambda acc, v: acc,
                 final=lambda k, a: k)


# ---------------------------------------------------------------------------
# columnar transport (ISSUE 12)
# ---------------------------------------------------------------------------


def canon_key_dtype(dt: np.dtype) -> np.dtype | None:
    """The dtype a key column lands in after the tuple path's
    ``.tolist()`` → ``np.asarray(python scalars)`` round trip — int kinds
    widen to int64, floats to float64, bool stays bool. ``None`` = not a
    fixed-width columnar-eligible key dtype (objects, strings, uint64
    whose values could exceed int64). BOTH paths must emit THESE dtypes
    or bit-identity dies on a dtype byte."""
    dt = np.dtype(dt)
    if dt.kind == "i" or (dt.kind == "u" and dt.itemsize < 8):
        return np.dtype(np.int64)
    if dt.kind == "f":
        return np.dtype(np.float64)
    if dt.kind == "b":
        return np.dtype(np.bool_)
    return None


def hash_rows(keys: Sequence[Any]) -> np.ndarray:
    """``key_hash`` plane for a batch of PYTHON keys: the uint64 big-endian
    read of each key's :func:`key_bytes` 8-byte digest prefix — so
    ``hash % n_out`` IS :func:`bucket_of` and ascending-hash order IS
    ascending ``key_bytes`` order (collisions excepted; those resolve by
    the full pickled bytes, rare path below). Routed through
    :func:`key_bytes` on purpose: one source of truth, and tests can
    force collisions by patching it."""
    return np.fromiter(
        (int.from_bytes(key_bytes(k)[:8], "big") for k in keys),
        dtype=np.uint64, count=len(keys))


class _Planes:
    """One columnar batch: aligned flat arrays — ``h`` (uint64 key hash),
    ``keys`` (one array per key column, canonical dtypes), ``vals`` (one
    array per combine plane). The unit that ships whole through the shm
    arenas and is metered by its exact ``nbytes``."""

    __slots__ = ("h", "keys", "vals")

    def __init__(self, h: np.ndarray, keys: tuple, vals: tuple):
        self.h = h
        self.keys = tuple(keys)
        self.vals = tuple(vals)

    def __len__(self) -> int:
        return len(self.h)

    @property
    def nbytes(self) -> int:
        return (self.h.nbytes + sum(a.nbytes for a in self.keys)
                + sum(a.nbytes for a in self.vals))

    def take(self, idx) -> "_Planes":
        return _Planes(self.h[idx], tuple(a[idx] for a in self.keys),
                       tuple(a[idx] for a in self.vals))

    def cut(self, lo: int, hi: int) -> "_Planes":
        return self.take(slice(lo, hi))

    def dtype_sig(self) -> tuple:
        return (tuple(a.dtype.str for a in self.keys),
                tuple(a.dtype.str for a in self.vals))

    def payload(self) -> tuple:
        """The picklable wire/disk form (a plain tuple, no class on the
        wire — a reducer from a future version must still read it)."""
        return ("cols", self.h, self.keys, self.vals)

    @staticmethod
    def from_payload(rec: tuple) -> "_Planes":
        return _Planes(rec[1], rec[2], rec[3])

    @staticmethod
    def concat(planes: "Sequence[_Planes]") -> "_Planes":
        if len(planes) == 1:
            return planes[0]
        return _Planes(
            np.concatenate([p.h for p in planes]),
            tuple(np.concatenate([p.keys[i] for p in planes])
                  for i in range(len(planes[0].keys))),
            tuple(np.concatenate([p.vals[i] for p in planes])
                  for i in range(len(planes[0].vals))))


class ColumnarPlan:
    """How one wide op's batches become planes (and back).

    ``combines`` names the vectorized fold per value plane (``sum`` /
    ``min`` / ``max``). ``pre_planes(elem)`` turns a source element into a
    :class:`_Planes` batch (unique keys within the batch, hashes filled) or
    ``None`` when the element does not conform — that element then walks
    the tuple path via ``spec.pre``, byte-identically. When ``pre_planes``
    is absent the mapper batches raw ``(key, value)`` pairs and calls
    ``pair_planes`` per batch (the rdd ops). The tuple-interop trio —
    ``key_of_row`` / ``vals_to_acc`` / ``row_emit`` — lets a mixed-format
    bucket degrade to tuple merging and lets generic consumers iterate
    rows off a columnar output file."""

    __slots__ = ("combines", "pre_planes", "pair_planes", "key_of_row",
                 "vals_to_acc", "row_emit")

    def __init__(self, *, combines: Sequence[str], pre_planes=None,
                 pair_planes=None, key_of_row=None, vals_to_acc=None,
                 row_emit=None):
        for c in combines:
            if c not in NUMERIC_COMBINES:
                raise ValueError(
                    f"combine {c!r} not in {NUMERIC_COMBINES}")
        self.combines = tuple(combines)
        self.pre_planes = pre_planes
        self.pair_planes = pair_planes
        self.key_of_row = (key_of_row if key_of_row is not None
                           else (lambda kv: kv[0]))
        self.vals_to_acc = (vals_to_acc if vals_to_acc is not None
                            else (lambda vs: vs[0] if vs else None))
        self.row_emit = (row_emit if row_emit is not None
                         else (lambda k, vs: (k, vs[0])))

    # -- tuple interop ------------------------------------------------------

    def entries_from_planes(self, pl: _Planes) -> list:
        """Planes → the tuple path's ``(kb, key, acc)`` entries (the
        degrade direction for a mixed-format bucket; also feeds columnar
        spill runs into a tuple-mode heapq merge). Entries come out in the
        planes' (hash, kb) order, which IS kb order."""
        key_lists = [a.tolist() for a in self.keys_as_python(pl)]
        val_lists = [a.tolist() for a in pl.vals]
        out = []
        for i in range(len(pl)):
            key = self.key_of_row(tuple(col[i] for col in key_lists))
            out.append((key_bytes(key), key,
                        self.vals_to_acc(tuple(v[i] for v in val_lists))))
        return out

    def keys_as_python(self, pl: _Planes) -> tuple:
        return pl.keys

    def rows_from_planes(self, pl: _Planes) -> Iterator:
        """Output rows off a columnar bucket file, matching what
        ``spec.final`` emits on the tuple path (python scalars — a
        consumer comparing ``5 == np.int64(5)`` is fine, one pickling the
        row is not)."""
        key_lists = [a.tolist() for a in pl.keys]
        val_lists = [a.tolist() for a in pl.vals]
        for i in range(len(pl)):
            yield self.row_emit(
                self.key_of_row(tuple(col[i] for col in key_lists)),
                tuple(v[i] for v in val_lists))


def _cmp_view(a: np.ndarray) -> np.ndarray:
    """Bitwise-comparable view of a key column (float NaN != NaN would
    false-positive the collision check; the tuple path compares pickled
    bytes, i.e. bit patterns)."""
    if a.dtype.kind == "f":
        return a.view(np.uint64 if a.dtype.itemsize == 8 else np.uint32)
    return a


def sorted_segments(pl: _Planes, *, assume_sorted: bool = False
                    ) -> tuple[_Planes, np.ndarray, np.ndarray, bool]:
    """The shared segment prologue for EVERY plane fold (host
    ``combine_planes`` and the device :mod:`~.device_agg` path — one
    source of truth, because the collision check is the bit-identity-
    critical step): stable-sort by ``key_hash`` and return
    ``(sorted planes, segment starts, per-row segment id, collision)``.
    ``collision=True`` means an equal-hash run holds DIFFERENT keys (a
    digest collision) — the caller must fold through
    :func:`_combine_colliding`, which orders by the full pickled key
    bytes, the tuple path's exact tie-break."""
    n = len(pl)
    if not assume_sorted:
        order = np.argsort(pl.h, kind="stable")
        pl = pl.take(order)
    changed = np.empty(n, dtype=bool)
    changed[0] = True
    np.not_equal(pl.h[1:], pl.h[:-1], out=changed[1:])
    starts = np.flatnonzero(changed)
    seg_id = np.cumsum(changed) - 1
    collision = False
    if len(starts) < n:
        # same hash, same key? (the overwhelmingly common duplicate case)
        for col in pl.keys:
            cv = _cmp_view(col)
            if not np.array_equal(cv, cv[starts][seg_id]):
                collision = True
                break
    return pl, starts, seg_id, collision


def combine_planes(pl: _Planes, plan: ColumnarPlan,
                   *, assume_sorted: bool = False) -> _Planes:
    """Sort a batch by ``key_hash`` (stable) and fold equal-key runs with
    the plan's vectorized combines (``np.add.reduceat`` /
    ``np.minimum.reduceat`` / ``np.maximum.reduceat`` — C loops, no
    per-key Python). Equal-hash runs holding DIFFERENT keys (a digest
    collision) drop to :func:`_combine_colliding`, which orders and folds
    by the full pickled key bytes — the tuple path's exact tie-break."""
    n = len(pl)
    if n == 0:
        return pl
    pl, starts, _seg_id, collision = sorted_segments(
        pl, assume_sorted=assume_sorted)
    if collision:
        return _combine_colliding(pl, plan)
    out_vals = []
    for col, op in zip(pl.vals, plan.combines):
        if len(starts) == n:
            out_vals.append(col)
        elif op == "sum":
            out_vals.append(np.add.reduceat(col, starts))
        elif op == "min":
            out_vals.append(np.minimum.reduceat(col, starts))
        else:
            out_vals.append(np.maximum.reduceat(col, starts))
    if len(starts) == n:
        return pl
    return _Planes(pl.h[starts], tuple(a[starts] for a in pl.keys),
                   tuple(out_vals))


def _combine_colliding(pl: _Planes, plan: ColumnarPlan) -> _Planes:
    """The 2⁻⁶⁴ path: at least one hash run holds distinct keys. Fold the
    whole batch per full key in Python, ordered by complete
    :func:`key_bytes` (digest + pickled key — the tuple path's total
    order), and rebuild planes. Correctness over speed: production never
    lands here; the collision tests force it."""
    key_lists = [a.tolist() for a in pl.keys]
    val_lists = [a.tolist() for a in pl.vals]
    acc: dict[bytes, list] = {}
    for i in range(len(pl)):
        key_row = tuple(col[i] for col in key_lists)
        kb = key_bytes(plan.key_of_row(key_row))
        vals = [v[i] for v in val_lists]
        ent = acc.get(kb)
        if ent is None:
            acc[kb] = [key_row, vals]
        else:
            held = ent[1]
            for j, op in enumerate(plan.combines):
                if op == "sum":
                    held[j] = held[j] + vals[j]
                elif op == "min":
                    held[j] = min(held[j], vals[j])
                else:
                    held[j] = max(held[j], vals[j])
    ordered = sorted(acc.items(), key=lambda t: t[0])
    h = np.fromiter((int.from_bytes(kb[:8], "big") for kb, _ in ordered),
                    dtype=np.uint64, count=len(ordered))
    keys = tuple(
        np.asarray([ent[0][c] for _, ent in ordered], dtype=pl.keys[c].dtype)
        for c in range(len(pl.keys)))
    vals = tuple(
        np.asarray([ent[1][j] for _, ent in ordered], dtype=pl.vals[j].dtype)
        for j in range(len(pl.vals)))
    return _Planes(h, keys, vals)


def _bucket_split(pl: _Planes, n_out: int) -> Iterator[tuple[int, _Planes]]:
    """Hash-sorted planes → (bucket, sub-planes) runs, bucket-major with
    hash order preserved inside each bucket (stable argsort over
    ``h % n_out`` of already-hash-sorted rows = the canonical layout)."""
    if len(pl) == 0:
        return
    bucket = (pl.h % np.uint64(n_out)).astype(np.int64)
    order = np.argsort(bucket, kind="stable")
    pl = pl.take(order)
    bucket = bucket[order]
    edges = np.flatnonzero(np.r_[True, bucket[1:] != bucket[:-1]])
    bounds = list(edges) + [len(pl)]
    for i, lo in enumerate(bounds[:-1]):
        yield int(bucket[lo]), pl.cut(lo, bounds[i + 1])


def _merge_cols_streams(streams: list, plan: ColumnarPlan
                        ) -> Iterator[_Planes]:
    """K-way merge of hash-sorted plane streams (spill-run block iterators
    plus the in-memory remainder), yielding combined blocks in hash order.
    Classic min-of-buffered-maxes frontier: rows strictly below the
    smallest buffered maximum of any live stream are complete and emit;
    rows at the frontier wait for the stream that set it to buffer
    another block. Residency is O(streams × block), never O(run)."""
    bufs: list[_Planes | None] = [None] * len(streams)
    alive = [True] * len(streams)

    def fill(i: int) -> None:
        while alive[i] and (bufs[i] is None or len(bufs[i]) == 0):
            try:
                blk = next(streams[i])
            except StopIteration:
                alive[i] = False
                return
            bufs[i] = (blk if bufs[i] is None or len(bufs[i]) == 0
                       else _Planes.concat([bufs[i], blk]))

    for i in range(len(streams)):
        fill(i)
    while True:
        live = [i for i in range(len(streams)) if alive[i]]
        if not live:
            rest = [b for b in bufs if b is not None and len(b)]
            if rest:
                yield combine_planes(_Planes.concat(rest), plan)
            return
        thresh = min(bufs[i].h[-1] for i in live)
        parts = []
        for i, b in enumerate(bufs):
            if b is None or len(b) == 0:
                continue
            cut = int(np.searchsorted(b.h, thresh, side="left"))
            if cut:
                parts.append(b.cut(0, cut))
                bufs[i] = b.cut(cut, len(b))
        if parts:
            yield combine_planes(_Planes.concat(parts), plan)
        # advance every live stream sitting AT the frontier — next loop's
        # threshold must strictly grow or a stream must die
        for i in live:
            if len(bufs[i]) == 0 or bufs[i].h[-1] == thresh:
                blk = None
                try:
                    blk = next(streams[i])
                except StopIteration:
                    alive[i] = False
                if blk is not None:
                    bufs[i] = (_Planes.concat([bufs[i], blk])
                               if len(bufs[i]) else blk)


# -- rdd-side plan factories (the dataframe builds its own in agg) ----------


def _scalar_batch(keys: list, vals: list | None, combines) -> _Planes | None:
    """Vectorize one rdd pair batch, or ``None`` when it does not conform:
    every key must be a plain python ``int``, ``float``, or ``bool`` and
    every value a plain ``int`` or ``float``, type-uniform per batch —
    exact types, because the tuple path pickles the ORIGINAL objects and
    ``np.int64(5)`` pickles differently from ``5`` (and ``True`` min/max
    results must come back as ``True``, so bool VALUES stay tuple-path).
    Two more exactness guards: under ``combine="sum"`` int values must fit
    int32, so even 2³² occurrences of one key cannot wrap the int64
    accumulator the planes sum in (the tuple path's python ints are
    arbitrary-precision — a wrapped plane would be a silently wrong
    answer, not a slow one); and float keys containing ANY zero fall
    back, because ``-0.0 == 0.0`` merges in a tuple-path dict but pickles
    to different key bytes (the documented equal-but-pickles-differently
    caveat — keeping every ±0.0 on one path keeps both transports on the
    same side of it)."""
    def uniform(xs, allowed) -> type | None:
        t = type(xs[0])
        if t not in allowed:
            return None
        for x in xs:
            if type(x) is not t:
                return None
        return t

    kt = uniform(keys, (int, float, bool))
    if kt is None:
        return None
    if kt is int and any(abs(k) > 0x7FFF_FFFF_FFFF_FFFF for k in keys):
        return None  # arbitrary-precision python ints stay tuple-path
    if kt is float and any(k == 0.0 for k in keys):
        # ±0.0 are dict-equal but pickle-different; only the tuple path
        # carries the dict-merge semantics, so EVERY zero goes there —
        # a columnar +0.0 could never merge with a tuple-path -0.0
        return None
    val_planes: tuple = ()
    if combines:
        vt = uniform(vals, (int, float))
        if vt is None:
            return None
        v_bound = (0x7FFF_FFFF if combines[0] == "sum"
                   else 0x7FFF_FFFF_FFFF_FFFF)
        if vt is int and any(abs(v) > v_bound for v in vals):
            return None
        val_planes = (np.asarray(vals, dtype=np.dtype(
            np.int64 if vt is int else np.float64)),)
    key_col = np.asarray(keys, dtype=np.dtype(
        {int: np.int64, float: np.float64, bool: np.bool_}[kt]))
    return _Planes(hash_rows(keys), (key_col,), val_planes)


def reduce_pair_plan(combine: str) -> ColumnarPlan:
    """Plan for ``reduce_by_key(f, combine=...)``: scalar numeric key, one
    value plane folded with the DECLARED combine — the declaration is a
    contract exactly like commutativity is (an ``f`` that disagrees with
    it diverges between paths, and that is the caller's bug)."""
    return ColumnarPlan(
        combines=(combine,),
        pair_planes=lambda ks, vs: _scalar_batch(ks, vs, (combine,)),
        key_of_row=lambda kr: kr[0],
        vals_to_acc=lambda vs: vs[0],
        row_emit=lambda k, vs: (k, vs[0]))


def distinct_pair_plan() -> ColumnarPlan:
    """Plan for ``distinct()`` over numeric scalars: key planes only, the
    segment fold is pure dedup (first row of each hash run)."""
    return ColumnarPlan(
        combines=(),
        pair_planes=lambda ks, vs: _scalar_batch(ks, None, ()),
        key_of_row=lambda kr: kr[0],
        vals_to_acc=lambda vs: None,
        row_emit=lambda k, vs: k)


# ---------------------------------------------------------------------------
# mapper / reducer process bodies (fork-inherited closures, no jax)
# ---------------------------------------------------------------------------


def _drain_frees(ring: _Arena, free_q) -> None:
    try:
        while True:
            ring.free(free_q.get_nowait())
    except queue_lib.Empty:
        pass


def _clip_tb(tb: str, limit: int = 2000) -> str:
    """Bound a forwarded traceback so the whole control message stays
    under the pipe's 4KB atomic-write size: a producer SIGKILLed mid-write
    must never leave a torn frame in a stream someone still reads. The
    TAIL survives — that is where the raising line lives."""
    return tb if len(tb) <= limit else "…" + tb[-limit:]


def _mapper_loop(wid: int, epoch: int, parts, spec: _Spec, n_out: int,
                 n_red: int, shm, arena_size: int, out_qs, free_q, ctl_q,
                 task_q, stop_evt, cancel_evt, all_done_evt, done_flags,
                 cap_bytes: int, retain: bool, spill_dir: str,
                 sort_route=None, plan: ColumnarPlan | None = None) -> None:
    """Child body: PULL (partition, slot, k) slices off the shared task
    queue, combine each into a bounded dict, flush bucketed payload
    frames. In retain mode (retries enabled, the default) frames go to
    disk as atomically-renamed ``ret-*`` files that reducers sweep — no
    shared data queue a SIGKILLed producer could tear or lock-poison; in
    legacy mode (``DLS_SHUFFLE_MAX_RETRIES=0``) they ship through the shm
    arena / reducer queues exactly as before. With a
    :class:`ColumnarPlan`, conforming batches accumulate as planes
    instead (exact-byte metered) and flush via vectorized sort +
    segment-combine + hash-bucket split; non-conforming batches walk the
    tuple dict path.

    ``ctl_q`` is this attempt's PRIVATE control queue (single producer):
    a SIGKILL mid-write can only poison this attempt's own stream, and
    every control message stays under the pipe's 4KB atomic-write bound
    so the driver can keep draining it after the death.

    EVERY piece of per-slice state — combine store, byte meters, batch
    buffers, dtype pin, frame sequence counter — lives inside
    ``run_slice``: a replayed or speculatively cloned slice ships
    byte-identical frames at the same ``(part, slot, seq)`` ids no matter
    which worker runs it or what that worker ran before, which is the
    whole basis of reducer-side dedup (module docstring, ISSUE 14)."""
    os.environ["DLS_NATIVE_THREADS"] = "1"  # same capping rationale as workers
    # retain mode ships through the filesystem — no arena exists (shm is
    # None); the legacy transport gets its ring over the shm slab
    ring = _Arena(shm.size) if shm is not None else None
    buf = shm.buf if shm is not None else None
    alloc_id = [0]
    R = n_red
    stats = {"elems": 0, "pairs": 0, "bytes_moved": 0, "overflow": 0,
             "flushes": 0, "busy_s": 0.0, "cols_pairs": 0, "cols_bytes": 0}
    #: one shipped payload must fit the (would-be) arena with room to
    #: breathe; planes above this split by rows (each slice is
    #: independently decodable). Static, derived from the configured
    #: ``arena_size`` in BOTH modes — splitting by the arena's live hole
    #: size (or by which transport happens to run) would make frame
    #: boundaries depend on runtime state and break replay identity.
    ship_cap = max(_MIN_CAP, arena_size // 4)
    fault_at = None

    def halted() -> bool:
        return stop_evt.is_set() or cancel_evt.is_set()

    def put(q, rec) -> bool:
        while not halted():
            try:
                q.put(rec, timeout=_POLL_S)
                return True
            except queue_lib.Full:
                continue
        return False

    def alloc(need: int) -> int | None:
        deadline = time.perf_counter() + _ALLOC_WAIT_S
        while True:
            _drain_frees(ring, free_q)
            off = ring.try_alloc(alloc_id[0], need)
            if off is not None or need > ring.size:
                return off
            if halted() or time.perf_counter() > deadline:
                return None
            try:
                ring.free(free_q.get(timeout=_POLL_S))
            except queue_lib.Empty:
                pass

    def run_slice(part_idx: int, slot: int, k: int):
        """One slice, deterministically. Returns ``(per-reducer unique
        frame counts, slice stats)`` or ``None`` when halted mid-slice."""
        store: dict = {}
        meter = _ByteMeter()
        cols: list[_Planes] = []      # columnar batches awaiting a flush
        cols_meter = _ByteMeter()     # their EXACT bytes (add_exact — a
        #                               plane's size is known, never sampled)
        pend_k: list = []             # rdd pair-mode vectorization buffer
        pend_v: list = []
        pin_sig: list = [None]        # first columnar batch pins the dtypes
        seq = [0]
        counts = [0] * R
        sl = {"elems": 0, "pairs": 0, "cols_pairs": 0, "bytes": 0,
              "cols_bytes": 0}

        def ship(bucket: int, payload: bytes, columnar: bool = False) -> bool:
            stats["bytes_moved"] += len(payload)
            sl["bytes"] += len(payload)
            if columnar:
                stats["cols_bytes"] += len(payload)
                sl["cols_bytes"] += len(payload)
            hdr = (part_idx, slot, seq[0])
            seq[0] += 1
            r = bucket % R
            counts[r] += 1
            if retain:
                # the retained file IS the transport: reducers sweep
                # their retention subdir, so no shared data queue exists
                # for a killed producer to tear mid-write or lock-poison
                # for the survivors — and the lineage-replay copy costs
                # nothing extra (one write either way)
                _retain_frame(spill_dir, r, bucket, hdr, payload)
                return not halted()
            off = alloc(_align(len(payload)))
            if off is None:
                stats["overflow"] += 1
                return put(out_qs[r], ("pkl", wid, bucket, payload, hdr))
            buf[off:off + len(payload)] = payload
            ok = put(out_qs[r], ("shm", wid, bucket, alloc_id[0], off,
                                 len(payload), hdr))
            alloc_id[0] += 1
            return ok

        def add_tuple_pair(key, v) -> None:
            if key in store:
                store[key] = spec.combine(store[key], v)
                meter.add(v)
            else:
                store[key] = spec.seed(v)
                meter.add(v, 120)

        def drain_pend() -> None:
            """Vectorize the buffered rdd pairs, or route the batch through
            the tuple dict when it does not conform / breaks the pinned
            dtype signature (np.concatenate across mismatched planes would
            silently promote — int keys becoming floats is a wrong answer,
            not a slow one)."""
            if not pend_k:
                return
            pl = plan.pair_planes(pend_k, pend_v)
            if pl is not None and (pin_sig[0] is None
                                   or pl.dtype_sig() == pin_sig[0]):
                pin_sig[0] = pin_sig[0] or pl.dtype_sig()
                cols.append(pl)
                cols_meter.add_exact(pl.nbytes)
                stats["cols_pairs"] += len(pl)
                sl["cols_pairs"] += len(pl)
            else:
                for key, v in zip(pend_k, pend_v):
                    add_tuple_pair(key, v)
            pend_k.clear()
            pend_v.clear()

        def flush() -> bool:
            if plan is not None and plan.pre_planes is None:
                drain_pend()
            if not store and not cols:
                return True
            stats["flushes"] += 1
            if cols:
                combined = combine_planes(_Planes.concat(cols), plan)
                cols.clear()
                cols_meter.reset()
                for b, sub in _bucket_split(combined, n_out):
                    row_bytes = max(1, sub.nbytes // max(1, len(sub)))
                    step = max(1, ship_cap // row_bytes)
                    for lo in range(0, len(sub), step):
                        payload = pickle.dumps(
                            sub.cut(lo, min(lo + step, len(sub))).payload(),
                            protocol=_PICKLE_PROTO)
                        if not ship(b, payload, columnar=True):
                            return False
            if store:
                buckets: dict[int, list] = {}
                for key, acc in store.items():
                    kb = key_bytes(key)
                    buckets.setdefault(bucket_of(kb, n_out), []).append(
                        (kb, key, acc))
                store.clear()
                meter.reset()
                for b in sorted(buckets):
                    if not ship(b, pickle.dumps(buckets[b],
                                                protocol=_PICKLE_PROTO)):
                        return False
            return True

        t0 = time.perf_counter()
        for j, elem in enumerate(parts[part_idx]()):
            if k > 1 and j % k != slot:
                continue
            if halted():
                return None
            stats["elems"] += 1
            sl["elems"] += 1
            if fault_at is not None and stats["elems"] >= fault_at:
                faults.crash()
            if sort_route is not None:
                # sort mode: no combine — route each element straight
                # to its range bucket, tagged with (key, part, idx)
                kv = sort_route[0](elem)
                b = sort_route[1](kv)
                store.setdefault(b, []).append((kv, part_idx, j, elem))
                meter.add(elem, 64)
                stats["pairs"] += 1
                sl["pairs"] += 1
                if meter.value >= cap_bytes:
                    stats["flushes"] += 1
                    for bb in sorted(store):
                        if not ship(bb, pickle.dumps(
                                store[bb], protocol=_PICKLE_PROTO)):
                            return None
                    store.clear()
                    meter.reset()
                continue
            if plan is not None and plan.pre_planes is not None:
                pl = plan.pre_planes(elem)
                if pl is not None and (pin_sig[0] is None
                                       or pl.dtype_sig() == pin_sig[0]):
                    pin_sig[0] = pin_sig[0] or pl.dtype_sig()
                    cols.append(pl)
                    cols_meter.add_exact(pl.nbytes)
                    stats["pairs"] += len(pl)
                    sl["pairs"] += len(pl)
                    stats["cols_pairs"] += len(pl)
                    sl["cols_pairs"] += len(pl)
                    if meter.value + cols_meter.value >= cap_bytes:
                        if not flush():
                            return None
                    continue
            pairs = spec.pre(elem) if spec.pre is not None else (elem,)
            if plan is not None and plan.pre_planes is None:
                for key, v in pairs:
                    stats["pairs"] += 1
                    sl["pairs"] += 1
                    pend_k.append(key)
                    pend_v.append(v)
                    if len(pend_k) >= _PAIR_BATCH:
                        drain_pend()
                if meter.value + cols_meter.value >= cap_bytes:
                    if not flush():
                        return None
                continue
            for key, v in pairs:
                stats["pairs"] += 1
                sl["pairs"] += 1
                if spec.tag_values:
                    v = (part_idx, j, v)
                add_tuple_pair(key, v)
                if meter.value + cols_meter.value >= cap_bytes:
                    if not flush():
                        return None
        # flush at the slice boundary: mapper state never spans slices,
        # so flush points depend only on the slice's own content and the
        # cap — the determinism replay identity rests on
        if sort_route is not None:
            for bb in sorted(store):
                if not ship(bb, pickle.dumps(store[bb],
                                             protocol=_PICKLE_PROTO)):
                    return None
            store.clear()
            meter.reset()
        elif not flush():
            return None
        stats["busy_s"] += time.perf_counter() - t0
        return counts, sl

    try:
        # inside the forwarding try: a malformed spec must surface as this
        # child's typed traceback, not an inscrutable nonzero-exit "death"
        fault_at = faults.shuffle_fault("mapper", wid, epoch)
        while not halted():
            try:
                task = task_q.get(timeout=_POLL_S)
            except queue_lib.Empty:
                if all_done_evt.is_set():
                    break
                continue
            slice_idx, part_idx, slot, k = task
            if done_flags[slice_idx]:
                continue  # finished elsewhere (speculation / reseed dup)
            if not put(ctl_q, ("slice-start", wid, epoch, slice_idx)):
                break
            try:
                out = run_slice(part_idx, slot, k)
            except BaseException:  # noqa: BLE001 — the SLICE failed (user
                # combine raised, bad record); the worker itself is fine —
                # report and keep pulling, the driver budgets the retries
                if not put(ctl_q, ("slice-err", wid, epoch, slice_idx,
                                   _clip_tb(traceback.format_exc()))):
                    break
                continue
            if out is None:
                break
            if not put(ctl_q, ("slice-done", wid, epoch, slice_idx,
                               out[0], out[1])):
                break
        put(ctl_q, ("mapper-done", wid, epoch, stats))
    except BaseException:  # noqa: BLE001 — forward ANY failure, typed
        put(ctl_q, ("err", ("mapper", wid, epoch),
                    _clip_tb(traceback.format_exc())))


def _spill_path(spill_dir: str, rid: int, bucket: int, n: int,
                fmt: str = "pkl") -> str:
    return os.path.join(spill_dir, f"r{rid}-b{bucket}-run{n}.{fmt}")


def _retain_dir(spill_dir: str, r: int) -> str:
    """Per-reducer retention subdirectory (``bucket % R`` owner): each
    reducer sweeps ONLY its own frames, so the poll cost scales with its
    share of the shuffle, not with the whole spill directory."""
    return os.path.join(spill_dir, f"ret{r}")


def _retain_path(spill_dir: str, r: int, bucket: int, hdr: tuple) -> str:
    """Retained-frame file: named by the frame's deterministic identity
    alone — a replayed slice re-writes the SAME name with the SAME bytes,
    so the atomic rename makes retention idempotent across attempts."""
    part, slot, seq = hdr
    return os.path.join(_retain_dir(spill_dir, r),
                        f"ret-b{bucket}-p{part}-s{slot}-q{seq}.pkl")


def _retain_frame(spill_dir: str, r: int, bucket: int, hdr: tuple,
                  payload: bytes) -> str:
    """Persist one frame — this IS the retain-mode transport (write to
    temp + atomic rename, so a sweeping reader never sees a torn file),
    and what a respawned reducer rebuilds from."""
    path = _retain_path(spill_dir, r, bucket, hdr)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)
    return path


def _parse_retained(fname: str) -> tuple[int, tuple] | None:
    """``ret-b{b}-p{p}-s{s}-q{q}.pkl`` → ``(bucket, (part, slot, seq))``,
    or None for any other file in the spill dir."""
    if not fname.startswith("ret-") or not fname.endswith(".pkl"):
        return None
    try:
        b, p, s, q = fname[4:-4].split("-")
        return int(b[1:]), (int(p[1:]), int(s[1:]), int(q[1:]))
    except (ValueError, IndexError):
        return None


def out_path(spill_dir: str, bucket: int) -> str:
    return os.path.join(spill_dir, f"out-b{bucket}.pkl")


def cols_out_path(spill_dir: str, bucket: int) -> str:
    return os.path.join(spill_dir, f"out-b{bucket}.cols")


def _write_cols_run(path: str, pl: _Planes) -> int:
    """One columnar spill run / output: hash-sorted combined planes as a
    stream of independently-pickled row blocks (the k-way merge and the
    readers stream blocks — run size never has to fit memory again)."""
    with open(path, "wb") as f:
        p = pickle.Pickler(f, protocol=_PICKLE_PROTO)
        for lo in range(0, len(pl), _COLS_BLOCK_ROWS):
            p.dump(pl.cut(lo, min(lo + _COLS_BLOCK_ROWS, len(pl))).payload())
        return f.tell()


def _iter_cols_blocks(path: str) -> Iterator[_Planes]:
    for rec in _iter_run(path):
        yield _Planes.from_payload(rec)


def _iter_cols_as_entries(path: str, plan: ColumnarPlan) -> Iterator:
    """A columnar run read as tuple-path ``(kb, key, acc)`` entries, in kb
    order (hash order + in-collision kb order IS kb order) — so a
    degraded bucket's earlier columnar spills merge straight into the
    tuple heapq without re-sorting."""
    for pl in _iter_cols_blocks(path):
        yield from plan.entries_from_planes(pl)


def _write_run(path: str, items: list) -> int:
    """One sorted spill run: a raw pickle stream, re-read with repeated
    loads. Returns bytes written."""
    with open(path, "wb") as f:
        p = pickle.Pickler(f, protocol=_PICKLE_PROTO)
        for it in items:
            p.dump(it)
        return f.tell()


def _iter_run(path: str) -> Iterator:
    with open(path, "rb") as f:
        up = pickle.Unpickler(f)
        while True:
            try:
                yield up.load()
            except EOFError:
                return


def _reducer_loop(rid: int, R: int, n_out: int, spec: _Spec | None,
                  in_q, free_qs, shm_prefix, ctl_q, stop_evt,
                  cap_bytes: int, spill_dir: str, sort_spec=None,
                  plan: ColumnarPlan | None = None, attempt: int = 0,
                  retain: bool = False) -> None:
    """Child body: merge arriving bucket payload frames under a byte
    budget, spill sorted runs past it, k-way-merge runs into one output
    file per owned bucket. A bucket receiving only plane payloads stays
    columnar end to end (exact-byte metered, columnar spill runs,
    vectorized merge, ``.cols`` output); the first tuple payload for a
    bucket degrades THAT bucket to the tuple dict path — output bytes are
    identical either way, the formats differ only in speed.

    Fault tolerance (ISSUE 14): frames dedupe by their ``(part, slot,
    seq)`` identity (mapper replays and speculative clones ship
    byte-identical duplicates); the loop ends when the unique-frame count
    reaches the ``eof`` total the driver computed from winning
    ``slice-done`` reports. In retain mode frames arrive by SWEEPING the
    retained ``ret-*`` files (``in_q`` then carries only the driver's
    ``eof``); in legacy mode (retries=0) they stream through
    shm-arena/queue transport as before. A respawned attempt first
    discards the dead attempt's spill runs and partial out files — their
    merge provenance is unknown — then rebuilds purely from retained
    files; it never touches the dead consumer's queue (whose pipe a
    SIGKILL mid-``recv`` can leave torn). ``ctl_q`` is this attempt's
    private notify channel to the driver."""
    os.environ["DLS_NATIVE_THREADS"] = "1"
    shms: dict[int, shared_memory.SharedMemory] = {}
    # keyed mode: bucket -> {key: [kb, acc]} (tuple) | [_Planes] (cols);
    # sort mode: bucket -> [entry]
    stores: dict[int, Any] = {}
    modes: dict[int, str] = {}          # bucket -> "cols" | "tuple"
    sigs: dict[int, tuple] = {}         # bucket -> pinned plane dtype sig
    cols_bytes: dict[int, int] = {}     # bucket -> exact resident plane B
    runs: dict[int, list] = {}          # bucket -> [(fmt, path)]
    meter = _ByteMeter()
    seen: set = set()                   # merged frame ids (part, slot, seq)
    expected = [None]                   # unique-frame total, from "eof"
    fault_at = None
    stats = {"spills": 0, "spill_bytes": 0, "bucket_rows": {}, "merge_s": 0.0,
             "cols_buckets": 0, "tuple_buckets": 0}

    def notify(msg) -> None:
        try:
            ctl_q.put(msg, timeout=_POLL_S)
        except queue_lib.Full:
            pass

    def arena_bytes(wid: int, alloc_id: int, off: int, size: int) -> bytes:
        if wid not in shms:
            shms[wid] = shared_memory.SharedMemory(
                name=f"{shm_prefix}-m{wid}")
        data = bytes(shms[wid].buf[off:off + size])
        try:  # copy taken — release the mapper's arena slot immediately
            free_qs[wid].put_nowait(alloc_id)
        except Exception:  # noqa: BLE001 — mapper may be gone at teardown
            pass
        return data

    def merge_entries(bucket: int, items) -> None:
        st = stores.setdefault(bucket, {})
        for kb, key, acc in items:
            ent = st.get(key)
            if ent is None:
                st[key] = [kb, acc]
                meter.add(acc, len(kb) + 100)
            else:
                ent[1] = spec.merge(ent[1], acc)
                meter.add(acc)

    def degrade(bucket: int) -> None:
        """Mixed formats arrived for this bucket: convert its resident
        planes to tuple entries and continue dict-side. Earlier columnar
        spill runs stay columnar on disk; the finalize merge reads them
        back as kb-ordered entries."""
        planes = stores.pop(bucket, [])
        cols_bytes.pop(bucket, None)
        modes[bucket] = "tuple"
        sigs.pop(bucket, None)
        stores[bucket] = {}
        if planes:
            merge_entries(bucket, plan.entries_from_planes(
                combine_planes(_Planes.concat(planes), plan)))

    def resident() -> float:
        return meter.value + sum(cols_bytes.values())

    def bucket_size(b: int) -> int:
        s = stores[b]
        return (sum(len(p) for p in s) if modes.get(b) == "cols"
                else len(s))

    def spill_largest() -> None:
        if not stores:
            return
        bucket = max(stores, key=bucket_size)
        n_run = len(runs.setdefault(bucket, []))
        if modes.get(bucket) == "cols":
            combined = combine_planes(_Planes.concat(stores.pop(bucket)),
                                      plan)
            cols_bytes.pop(bucket, None)
            path = _spill_path(spill_dir, rid, bucket, n_run, "cols")
            nbytes = _write_cols_run(path, combined)
            runs[bucket].append(("cols", path))
            n_items = len(combined)
        else:
            if sort_spec is not None:
                items = sorted(stores.pop(bucket), key=sort_spec[0],
                               reverse=sort_spec[1])
            else:
                items = sorted(
                    ((e[0], key, e[1])
                     for key, e in stores.pop(bucket).items()),
                    key=lambda t: t[0])
            path = _spill_path(spill_dir, rid, bucket, n_run)
            nbytes = _write_run(path, items)
            runs[bucket].append(("pkl", path))
            n_items = len(items)
            # rebase surviving tuple buckets at the meter's OWN rolling
            # per-item estimate — a flat constant here would under-charge
            # fat values (group lists) and let residency creep past the
            # budget share. Columnar residency is exact and untouched.
            meter.value = (sum(len(s) for b, s in stores.items()
                               if modes.get(b) != "cols")
                           * (meter._est + 100))
        stats["spills"] += 1
        stats["spill_bytes"] += nbytes
        notify(("spill", rid, bucket, n_items, nbytes))

    def merge_bucket(bucket: int) -> None:
        """Stream the bucket's runs + memory into its final output file."""
        t0 = time.perf_counter()
        rows = 0
        bucket_runs = runs.get(bucket, [])
        if sort_spec is not None:
            streams = [_iter_run(p) for _fmt, p in bucket_runs]
            mem = sorted(stores.pop(bucket, []), key=sort_spec[0],
                         reverse=sort_spec[1])
            merged = heapq.merge(*streams, mem, key=sort_spec[0],
                                 reverse=sort_spec[1])
            with open(out_path(spill_dir, bucket), "wb") as f:
                p = pickle.Pickler(f, protocol=_PICKLE_PROTO)
                for _kv, _part, _j, elem in merged:
                    p.dump(elem)
                    rows += 1
        elif modes.get(bucket) == "cols":
            # pure columnar bucket: k-way merge the sorted runs + memory
            # on the hash column, blockwise — no per-key Python anywhere
            planes = stores.pop(bucket, [])
            cols_bytes.pop(bucket, None)
            streams = [_iter_cols_blocks(p) for _fmt, p in bucket_runs]
            if planes:
                streams.append(iter(
                    [combine_planes(_Planes.concat(planes), plan)]))
            with open(cols_out_path(spill_dir, bucket), "wb") as f:
                pk = pickle.Pickler(f, protocol=_PICKLE_PROTO)
                for blk in _merge_cols_streams(streams, plan):
                    if len(blk):
                        pk.dump(blk.payload())
                        rows += len(blk)
            if rows:
                stats["cols_buckets"] += 1
        else:
            streams = [(_iter_run(p) if fmt == "pkl"
                        else _iter_cols_as_entries(p, plan))
                       for fmt, p in bucket_runs]
            mem = sorted(
                ((e[0], key, e[1])
                 for key, e in stores.pop(bucket, {}).items()),
                key=lambda t: t[0])
            merged = heapq.merge(*streams, mem, key=lambda t: t[0])
            with open(out_path(spill_dir, bucket), "wb") as f:
                p = pickle.Pickler(f, protocol=_PICKLE_PROTO)
                cur_kb = cur_key = cur_acc = None
                for kb, key, acc in merged:
                    if cur_kb is not None and kb == cur_kb:
                        cur_acc = spec.merge(cur_acc, acc)
                        continue
                    if cur_kb is not None:
                        p.dump(spec.final(cur_key, cur_acc))
                        rows += 1
                    cur_kb, cur_key, cur_acc = kb, key, acc
                if cur_kb is not None:
                    p.dump(spec.final(cur_key, cur_acc))
                    rows += 1
            if rows:
                stats["tuple_buckets"] += 1
        for _fmt, p_ in runs.pop(bucket, []):
            try:
                os.remove(p_)
            except OSError:
                pass
        stats["bucket_rows"][bucket] = rows
        stats["merge_s"] += time.perf_counter() - t0

    def ingest(bucket: int, payload: bytes) -> None:
        """Merge one deduplicated frame payload into its bucket store."""
        items = pickle.loads(payload)
        if sort_spec is not None:
            lst = stores.setdefault(bucket, [])
            lst.extend(items)
            for e in items:
                meter.add(e[3], 64)
        elif (isinstance(items, tuple) and items
              and items[0] == "cols"):
            pl = _Planes.from_payload(items)
            mode = modes.get(bucket)
            if mode is None:
                modes[bucket] = "cols"
                sigs[bucket] = pl.dtype_sig()
                stores[bucket] = [pl]
                cols_bytes[bucket] = pl.nbytes
            elif mode == "cols":
                if pl.dtype_sig() != sigs[bucket]:
                    # two mappers pinned different scalar types for
                    # keys landing here — concatenation would promote
                    # (wrong bytes); the tuple path merges them right
                    degrade(bucket)
                    merge_entries(bucket,
                                  plan.entries_from_planes(pl))
                else:
                    stores.setdefault(bucket, []).append(pl)
                    cols_bytes[bucket] = (cols_bytes.get(bucket, 0)
                                          + pl.nbytes)
            else:
                merge_entries(bucket, plan.entries_from_planes(pl))
        else:
            if modes.get(bucket) == "cols":
                degrade(bucket)
            modes.setdefault(bucket, "tuple")
            merge_entries(bucket, items)
        while resident() >= cap_bytes and stores:
            spill_largest()
        if fault_at is not None and len(seen) >= fault_at:
            faults.crash()

    def handle_rec(rec) -> None:
        kind = rec[0]
        if kind == "eof":
            expected[0] = rec[1]
            return
        if kind == "shm":
            _, wid, bucket, aid, off, size, hdr = rec
            # read + free BEFORE the dedup check: a duplicate's arena slot
            # must still be released or speculation would leak the ring
            data = arena_bytes(wid, aid, off, size)
            if hdr in seen:
                return
            seen.add(hdr)
            ingest(bucket, data)
        elif kind == "pkl":
            _, wid, bucket, payload, hdr = rec
            if hdr in seen:
                return
            seen.add(hdr)
            ingest(bucket, payload)

    swept: set = set()  # filenames already handled (or never relevant)
    my_ret_dir = _retain_dir(spill_dir, rid)

    def sweep_retained() -> bool:
        """Merge retained frames not yet seen — the retain-mode data path
        (every attempt, not just respawns). Only THIS reducer's retention
        subdir is listed, and handled (or never-relevant: in-flight
        ``.tmp``s — a tmp becomes visible under its FINAL name) filenames
        memoize into ``swept``, so each sweep parses only new arrivals.
        Retention writes are atomic renames, so any listed file is
        whole; merge order needs no sort — dedup is identity-keyed and
        the final output order is canonicalized by the bucket merge."""
        progressed = False
        for fname in os.listdir(my_ret_dir):
            if fname in swept:
                continue
            parsed = _parse_retained(fname)
            if parsed is None:
                swept.add(fname)
                continue
            bucket, hdr = parsed
            if bucket % R != rid or hdr in seen:
                swept.add(fname)
                continue
            try:
                with open(os.path.join(my_ret_dir, fname), "rb") as f:
                    data = f.read()
            except OSError:  # pragma: no cover - teardown race
                continue
            swept.add(fname)
            seen.add(hdr)
            ingest(bucket, data)
            progressed = True
        return progressed

    try:
        # inside the forwarding try: a malformed spec must surface as this
        # child's typed traceback, not an inscrutable nonzero-exit "death"
        fault_at = faults.shuffle_fault("reducer", rid, attempt)
        if attempt > 0:
            # rebuild from scratch: the dead attempt's spill runs and any
            # partial out files merged an unknown subset of frames
            for fname in os.listdir(spill_dir):
                if fname.startswith(f"r{rid}-b"):
                    try:
                        os.remove(os.path.join(spill_dir, fname))
                    except OSError:
                        pass
            for b in range(rid, n_out, R):
                for p in (out_path(spill_dir, b), cols_out_path(spill_dir, b)):
                    try:
                        os.remove(p)
                    except OSError:
                        pass
        while expected[0] is None or len(seen) < expected[0]:
            if stop_evt.is_set():
                return
            if retain:
                progressed = sweep_retained()
                try:
                    handle_rec(in_q.get_nowait())  # driver "eof" only
                    progressed = True
                except queue_lib.Empty:
                    pass
                if not progressed:
                    time.sleep(0.05)
            else:
                try:
                    handle_rec(in_q.get(timeout=_POLL_S))
                except queue_lib.Empty:
                    pass
        for bucket in range(rid, n_out, R):
            if stop_evt.is_set():
                return
            merge_bucket(bucket)
        notify(("reducer-done", rid, stats))
    except BaseException:  # noqa: BLE001
        notify(("err", ("reducer", rid, attempt),
                _clip_tb(traceback.format_exc())))
    finally:
        for s in shms.values():
            try:
                s.close()
            except Exception:  # noqa: BLE001
                pass


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


class ShuffleResult:
    """Per-bucket output files + the stats the telemetry summary carried.
    Holds the spill directory alive; it is removed when this object (and
    every dataset partition referencing it) is garbage-collected."""

    def __init__(self, spill_dir: str, n_out: int, stats: dict,
                 keep_dir: bool, plan: ColumnarPlan | None = None):
        self.spill_dir = spill_dir
        self.n_out = n_out
        self.stats = stats
        self.plan = plan
        self._fin = (weakref.finalize(self, _rm_dir, spill_dir)
                     if not keep_dir else None)

    def iter_bucket(self, bucket: int) -> Iterator:
        # generator METHOD on purpose: the running frame holds ``self``, so
        # the spill directory cannot be finalized out from under a consumer
        # whose dataset reference was dropped mid-iteration
        path = out_path(self.spill_dir, bucket)
        if os.path.exists(path):
            yield from _iter_run(path)
            return
        cpath = cols_out_path(self.spill_dir, bucket)
        if os.path.exists(cpath) and self.plan is not None:
            for pl in _iter_cols_blocks(cpath):
                yield from self.plan.rows_from_planes(pl)

    def iter_bucket_planes(self, bucket: int) -> Iterator[_Planes] | None:
        """Blockwise plane access for a columnar bucket, or ``None`` when
        this bucket finalized in tuple format (mixed-eligibility buckets
        do) — the caller then falls back to :meth:`iter_bucket` rows. The
        zero-copy read the dataframe agg path builds chunks straight
        from."""
        cpath = cols_out_path(self.spill_dir, bucket)
        if self.plan is None or not os.path.exists(cpath):
            return None
        return self._planes_gen(cpath)

    def _planes_gen(self, cpath: str) -> Iterator[_Planes]:
        # separate generator so iter_bucket_planes can return None eagerly
        self_ref = self  # noqa: F841 — pins the finalizer, like iter_bucket
        yield from _iter_cols_blocks(cpath)


def _rm_dir(path: str) -> None:
    shutil.rmtree(path, ignore_errors=True)


def _assignments(P: int, M: int) -> list[list[tuple[int, int, int]]]:
    """Mapper → [(partition, slot, k)]: whole partitions round-robin onto
    mappers while ``M <= P``; past that, the mappers co-assigned to one
    partition split it by element residue (slot of k) — the WorkerPool
    discipline, so a single-partition source still scales."""
    if M <= P:
        whole: list[list[tuple[int, int, int]]] = [[] for _ in range(M)]
        for p in range(P):
            whole[p % M].append((p, 0, 1))
        return whole
    per_part: list[list[int]] = [[] for _ in range(P)]
    for m in range(M):
        per_part[m % P].append(m)
    out: list[list[tuple[int, int, int]]] = [[] for _ in range(M)]
    for p, ms in enumerate(per_part):
        for slot, m in enumerate(ms):
            out[m].append((p, slot, len(ms)))
    return out


def run_exchange(parts: Sequence[Callable[[], Any]], *, num_workers: int,
                 n_out: int, spec: _Spec | None, label: str,
                 sort_route=None, sort_spec=None,
                 mem_mb: float | None = None,
                 plan: ColumnarPlan | None = None) -> ShuffleResult:
    """Execute one shuffle: spawn mappers + reducers, stream the exchange,
    return the per-bucket output. Task failures self-heal (lineage retry,
    speculation, blacklisting — module docstring, ISSUE 14) under the
    ``DLS_SHUFFLE_MAX_RETRIES`` budget; past it — or with the budget set
    to 0 — raises the typed :class:`WorkerCrashed` (cleaning up every
    child, shm segment, and spill file) exactly as before. With a
    :class:`ColumnarPlan`, conforming batches ship as flat planes (see
    the module docstring) — output is byte-identical either way."""
    P = len(parts)
    M = max(1, int(num_workers))
    R = max(1, min(M, n_out))
    budget = mem_budget_bytes(mem_mb)
    arena_bytes = max(_MIN_ARENA, budget // (4 * M))
    map_cap = max(_MIN_CAP, budget // (4 * M))
    red_cap = max(_MIN_CAP, budget // (2 * R))
    retries_left = max_shuffle_retries()
    retries_budget = retries_left
    retain = retries_left > 0
    strikes_k = blacklist_after()
    spec_factor = speculate_factor()
    # validate any declared shuffle fault HERE, driver-side: a typo'd
    # drill must fail loudly before a single child spawns, not burn the
    # retry budget on children that die at startup and get misread as
    # OOM kills (the children re-check inside their forwarding try)
    faults.shuffle_fault("mapper", 0, 0)
    base = os.environ.get(SPILL_DIR_ENV) or None
    if base:
        os.makedirs(base, exist_ok=True)
    spill_dir = tempfile.mkdtemp(prefix="dlsx-", dir=base)
    if retain:  # per-reducer retention subdirs, created before any child
        for r in range(R):
            os.makedirs(_retain_dir(spill_dir, r))
    ctx = mp.get_context("fork")
    stop = ctx.Event()
    task_q = ctx.Queue()
    all_done_evt = ctx.Event()
    # the shm-arena data plane exists only in legacy mode; retain mode
    # ships frames through the filesystem, so its exchanges carry no data
    # queues, free queues, or arenas at all
    out_qs = ([] if retain
              else [ctx.Queue(maxsize=_QUEUE_AHEAD) for _ in range(R)])
    free_qs = [] if retain else [ctx.Queue() for _ in range(M)]
    #: the canonical slice list — task ids index into it
    slices = sorted((t for a in _assignments(P, M) for t in a),
                    key=lambda t: (t[0], t[1]))
    n_slices = len(slices)
    done_flags = ctx.RawArray("b", max(1, n_slices))
    shm_prefix = f"dlsx-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    # LIVE lists, shared with the finalizer: respawned children and their
    # fresh epoch arenas append here, so interpreter-exit teardown reaps
    # them too (not just the processes alive at registration time)
    live_procs: list = []
    live_shms: list = []
    attempts: list[dict] = []
    red_q: list = [None] * R         # newest attempt's control/data queue
    red_attempt = [0] * R

    def _new_arena(wid: int):
        s = shared_memory.SharedMemory(
            create=True, size=arena_bytes, name=f"{shm_prefix}-m{wid}")
        live_shms.append(s)
        return s

    def _start(proc) -> None:
        with warnings.catch_warnings():
            # children run pure numpy/pickle, never JAX — same rationale
            # as WorkerPool's fork-under-JAX warning filter
            warnings.filterwarnings(
                "ignore", message=r".*os\.fork\(\) was called.*",
                category=RuntimeWarning)
            proc.start()
        live_procs.append(proc)

    def spawn_mapper(wid: int, epoch: int) -> dict:
        # retain mode moves frames through the filesystem — no arena to
        # allocate (ship_cap still derives from arena_bytes so frame
        # boundaries match across modes and attempts); epoch > 0 only
        # happens in retain mode, so arenas never need versioning
        shm = None if retain else _new_arena(wid)
        cancel = ctx.Event()
        # per-attempt control queue, single producer: a SIGKILL mid-write
        # can only poison THIS attempt's stream — the shared-queue version
        # of this deadlocked every surviving producer on the write lock
        ctl = ctx.Queue()
        p = ctx.Process(
            target=_mapper_loop, daemon=True, name=f"dlsx-map-{wid}e{epoch}",
            args=(wid, epoch, list(parts), spec, n_out, R, shm, arena_bytes,
                  out_qs, free_qs[wid] if free_qs else None, ctl, task_q,
                  stop, cancel, all_done_evt, done_flags, map_cap, retain,
                  spill_dir, sort_route, plan))
        _start(p)
        att = {"role": "mapper", "wid": wid, "epoch": epoch, "proc": p,
               "ctl": ctl, "cancel": cancel, "finished": False}
        attempts.append(att)
        return att

    def spawn_reducer(rid: int, attempt: int) -> dict:
        if retain:
            # retain mode: data arrives by sweeping retained files; this
            # queue carries ONLY the driver's "eof" (driver is the sole
            # producer). A replacement never touches the dead consumer's
            # queue — a SIGKILL mid-recv can leave its pipe torn.
            in_q = ctx.Queue()
        else:
            in_q = out_qs[rid]
        ctl = ctx.Queue()
        red_q[rid] = in_q
        p = ctx.Process(
            target=_reducer_loop, daemon=True,
            name=f"dlsx-red-{rid}a{attempt}",
            args=(rid, R, n_out, spec, in_q, free_qs, shm_prefix, ctl,
                  stop, red_cap, spill_dir, sort_spec, plan, attempt,
                  retain))
        _start(p)
        att = {"role": "reducer", "wid": rid, "epoch": attempt, "proc": p,
               "ctl": ctl, "finished": False}
        attempts.append(att)
        return att

    for m in range(M):
        spawn_mapper(m, 0)
    for r in range(R):
        spawn_reducer(r, 0)
    for i, t in enumerate(slices):
        task_q.put((i,) + t)
    finalizer = weakref.finalize(
        run_exchange, _exchange_cleanup, stop, live_procs, live_shms)

    t_start = time.perf_counter()
    sl_done = [False] * n_slices
    sl_counts: list = [None] * n_slices   # winning per-reducer frame counts
    sl_stats: list = [None] * n_slices    # winning per-slice input stats
    sl_running: dict[int, dict] = {}      # slice -> {(wid, epoch): t0}
    sl_speculated = [False] * n_slices
    sl_durations: list[float] = []
    n_done = 0
    strikes: dict[tuple, int] = {}
    blacklisted: set[tuple] = set()
    recovery = {"retries": 0, "mapper_retries": 0, "reducer_retries": 0,
                "speculations": 0, "blacklists": 0}
    map_stats: list[dict] = []
    red_done: dict[int, dict] = {}
    spills = 0
    spill_bytes = 0
    map_end: float | None = None
    eof_totals: list | None = None
    pending_eof: dict[int, tuple] = {}    # rid -> (queue, total)
    last_progress = t_start

    def _active_mappers() -> list:
        return [a for a in attempts if a["role"] == "mapper"
                and not a["finished"] and a["proc"].is_alive()]

    def _find_attempt(role: str, wid: int, epoch: int) -> dict | None:
        for a in attempts:
            if (a["role"], a["wid"], a["epoch"]) == (role, wid, epoch):
                return a
        return None

    def charge_retry(role: str, wid: int, reason: str, *,
                     slice_idx: int | None = None,
                     exitcode: int | None = None) -> None:
        """Burn one unit of the retry budget, or escalate — the typed
        WorkerCrashed of the fail-fast days — when it is spent."""
        nonlocal retries_left
        if retries_left <= 0:
            suffix = ("" if retries_budget == 0 else
                      f" [retry budget {MAX_RETRIES_ENV}="
                      f"{retries_budget} exhausted]")
            raise WorkerCrashed(f"shuffle {role} {wid} {reason}{suffix}",
                                worker=wid, exitcode=exitcode)
        retries_left -= 1
        recovery["retries"] += 1
        recovery[f"{role}_retries"] += 1
        telemetry.emit(
            "shuffle", edge="retry", op=label, role=role, worker=wid,
            reason=("died" if exitcode is not None else "raised"),
            exitcode=exitcode, slice=slice_idx, retries_left=retries_left)

    def strike(role: str, wid: int) -> bool:
        """Score one failure; True when the slot just got blacklisted."""
        k = strikes[(role, wid)] = strikes.get((role, wid), 0) + 1
        if k >= strikes_k and (role, wid) not in blacklisted:
            blacklisted.add((role, wid))
            recovery["blacklists"] += 1
            telemetry.emit("shuffle", edge="blacklist", op=label,
                           role=role, worker=wid, strikes=k)
            return True
        return False

    def clear_runners(wid: int, epoch: int) -> None:
        """Deregister a failed attempt's running slices — enqueueing is
        reseed_unclaimed's job, the SINGLE re-enqueue point, so one
        failure adds at most one copy of any slice to the queue."""
        for runners in sl_running.values():
            runners.pop((wid, epoch), None)

    def reseed_unclaimed() -> None:
        """Repair task custody after a worker failure: the slices the
        failed attempt was running, plus any task it POPPED before its
        slice-start landed (gone from the queue with no runner
        registered). Re-enqueue every undone slice with no runner — a
        duplicate of a task still sitting unclaimed in the queue is
        harmless (done_flags skip + frame dedup) and bounded at one copy
        per failure; this never runs on the hot path."""
        for si in range(n_slices):
            if not sl_done[si] and not sl_running.get(si):
                task_q.put((si,) + slices[si])

    def assert_mappers_remain(wid: int, reason: str,
                              exitcode=None) -> None:
        if not _active_mappers() and n_done < n_slices:
            raise WorkerCrashed(
                f"shuffle mapper {wid} blacklisted after "
                f"{strikes[('mapper', wid)]} failures and no usable "
                f"mappers remain ({n_slices - n_done} slices unfinished); "
                f"last failure: {reason}", worker=wid, exitcode=exitcode)

    def on_mapper_failure(wid: int, epoch: int, reason: str, *,
                          exitcode=None, slice_idx=None) -> None:
        charge_retry("mapper", wid, reason, exitcode=exitcode,
                     slice_idx=slice_idx)
        crossed = strike("mapper", wid)
        clear_runners(wid, epoch)
        if crossed:
            for a in attempts:   # a blacklisted slot stops taking work
                if (a["role"] == "mapper" and a["wid"] == wid
                        and not a["finished"]):
                    a["cancel"].set()
                    a["finished"] = True
            assert_mappers_remain(wid, reason, exitcode)
        else:
            att = _find_attempt("mapper", wid, epoch)
            if exitcode is not None or (att is not None and att["finished"]):
                # the process is gone (death, or infra-err exit): respawn
                # a replacement attempt — it runs the retained-file
                # transport, so no transport state needs recreating
                spawn_mapper(wid, epoch + 1)
        reseed_unclaimed()

    def on_reducer_failure(rid: int, attempt: int, reason: str, *,
                           exitcode=None) -> None:
        if not retain:  # pragma: no cover - retain is False only when
            # retries are 0, and charge_retry escalates first
            raise WorkerCrashed(f"shuffle reducer {rid} {reason}",
                                worker=rid, exitcode=exitcode)
        charge_retry("reducer", rid, reason, exitcode=exitcode)
        if strike("reducer", rid):
            raise WorkerCrashed(
                f"shuffle reducer {rid} blacklisted after "
                f"{strikes[('reducer', rid)]} failures — its buckets "
                f"cannot move to another slot; last failure: {reason}",
                worker=rid, exitcode=exitcode)
        red_attempt[rid] += 1
        spawn_reducer(rid, red_attempt[rid])
        if eof_totals is not None:
            pending_eof[rid] = (red_q[rid], eof_totals[rid])

    def finish_map(now: float) -> None:
        nonlocal map_end, eof_totals
        map_end = now
        telemetry.emit("phase", name="shuffle-map", edge="end",
                       dur_s=map_end - t_start, op=label)
        telemetry.emit("phase", name="shuffle-merge", edge="begin",
                       op=label)
        all_done_evt.set()
        eof_totals = [sum(c[r] for c in sl_counts if c is not None)
                      for r in range(R)]
        for r in range(R):
            pending_eof[r] = (red_q[r], eof_totals[r])

    def maybe_speculate(now: float) -> None:
        if (spec_factor <= 0 or not sl_durations or n_done >= n_slices
                or len(_active_mappers()) < 2):
            return
        med = sorted(sl_durations)[len(sl_durations) // 2]
        lag = max(_SPECULATE_FLOOR_S, spec_factor * med)
        for si, runners in sl_running.items():
            if sl_done[si] or sl_speculated[si] or not runners:
                continue
            started = min(runners.values())
            if now - started > lag:
                sl_speculated[si] = True
                recovery["speculations"] += 1
                telemetry.emit(
                    "shuffle", edge="speculate", op=label, slice=si,
                    part=slices[si][0], slot=slices[si][1],
                    elapsed_s=round(now - started, 3),
                    median_s=round(med, 3))
                task_q.put((si,) + slices[si])

    def maybe_reseed(now: float) -> None:
        """Last-resort custody net: failure handlers already call
        reseed_unclaimed() for every known loss path; this long-window
        sweep only exists so an UNFORESEEN loss degrades into one
        duplicate round per _RESEED_S instead of a silent hang."""
        nonlocal last_progress
        if n_done >= n_slices or now - last_progress < _RESEED_S:
            return
        last_progress = now
        reseed_unclaimed()

    telemetry.emit("phase", name="shuffle-map", edge="begin", op=label)
    try:
        if n_slices == 0 and map_end is None:
            finish_map(time.perf_counter())
        while n_done < n_slices or len(red_done) < R:
            now = time.perf_counter()
            for rid in list(pending_eof):
                q, total = pending_eof[rid]
                try:
                    q.put_nowait(("eof", total))
                    del pending_eof[rid]
                except queue_lib.Full:
                    pass
            msg = None
            for att in attempts:
                if att["finished"]:
                    continue
                try:
                    msg = att["ctl"].get_nowait()
                    break
                except queue_lib.Empty:
                    continue
            if msg is None:
                dead = None
                for att in attempts:
                    if att["finished"] or att["proc"].is_alive():
                        continue
                    # drain race: its last message may still be in flight
                    try:
                        msg = att["ctl"].get(timeout=_POLL_S)
                    except queue_lib.Empty:
                        dead = att
                    break
                if dead is not None:
                    dead["finished"] = True
                    rc = dead["proc"].exitcode
                    rc = -1 if rc is None else rc
                    reason = (f"died (exit code {rc}) mid-exchange — "
                              f"killed (OOM/SIGKILL) or crashed in "
                              f"native code")
                    if dead["role"] == "mapper":
                        if n_done < n_slices:
                            on_mapper_failure(dead["wid"], dead["epoch"],
                                              reason, exitcode=rc)
                        # else: a straggler (speculation loser) dying
                        # after every slice completed costs nothing
                    else:
                        on_reducer_failure(dead["wid"], dead["epoch"],
                                           reason, exitcode=rc)
                    continue
                if msg is None:
                    maybe_speculate(now)
                    maybe_reseed(now)
                    time.sleep(0.02)
                    continue
            last_progress = now
            kind = msg[0]
            if kind == "slice-start":
                _, wid, ep, si = msg
                sl_running.setdefault(si, {})[(wid, ep)] = now
            elif kind == "slice-done":
                _, wid, ep, si, counts, sl = msg
                started = sl_running.get(si, {}).pop((wid, ep), None)
                if not sl_done[si]:
                    sl_done[si] = True
                    done_flags[si] = 1
                    n_done += 1
                    sl_counts[si] = counts
                    sl_stats[si] = sl
                    if started is not None:
                        sl_durations.append(now - started)
                    if n_done == n_slices:
                        finish_map(now)
            elif kind == "slice-err":
                _, wid, ep, si, tb = msg
                sl_running.get(si, {}).pop((wid, ep), None)
                if not sl_done[si]:
                    # the trailing reseed_unclaimed re-enqueues the slice
                    on_mapper_failure(wid, ep, f"raised:\n{tb}",
                                      slice_idx=si)
            elif kind == "err":
                role, wid, ep = msg[1]
                att = _find_attempt(role, wid, ep)
                if att is not None:
                    att["finished"] = True
                if role == "mapper":
                    on_mapper_failure(wid, ep, f"raised:\n{msg[2]}")
                else:
                    on_reducer_failure(wid, ep, f"raised:\n{msg[2]}")
            elif kind == "mapper-done":
                _, wid, ep, st = msg
                att = _find_attempt("mapper", wid, ep)
                if att is not None:
                    att["finished"] = True
                map_stats.append(st)
            elif kind == "reducer-done":
                red_done[msg[1]] = msg[2]
                for a in attempts:
                    if a["role"] == "reducer" and a["wid"] == msg[1]:
                        a["finished"] = True
            elif kind == "spill":
                spills += 1
                spill_bytes += msg[4]
                telemetry.emit("shuffle", edge="spill", op=label,
                               reducer=msg[1], bucket=msg[2], rows=msg[3],
                               bytes=msg[4])
        merge_s = time.perf_counter() - (map_end or t_start)
        telemetry.emit("phase", name="shuffle-merge", edge="end",
                       dur_s=merge_s, op=label)
    except BaseException:
        # failed exchange: nothing must leak — children, shm, spill files.
        # End whichever phase is OPEN (map, or merge once map ended) so a
        # crashed shuffle never pins a stale open phase onto every later
        # heartbeat's hang localization
        stop.set()
        telemetry.emit(
            "phase", edge="end", op=label, aborted=True,
            name="shuffle-map" if map_end is None else "shuffle-merge")
        _exchange_cleanup(stop, live_procs, live_shms)
        finalizer.detach()
        _rm_dir(spill_dir)
        raise

    # reducers are done; give clean-exiting mappers a beat to land their
    # stats, then cancel stragglers (speculation losers still grinding a
    # slice someone else already won)
    grace = time.time() + 2.0
    while (any(a["role"] == "mapper" and not a["finished"]
               for a in attempts) and time.time() < grace):
        progressed = False
        for a in attempts:
            if a["role"] != "mapper" or a["finished"]:
                continue
            try:
                msg = a["ctl"].get_nowait()
            except queue_lib.Empty:
                if not a["proc"].is_alive():
                    a["finished"] = True
                continue
            if msg[0] == "mapper-done":
                a["finished"] = True
                map_stats.append(msg[3])
                progressed = True
        if not progressed:
            time.sleep(0.05)
    for a in attempts:
        if a["role"] == "mapper" and not a["finished"]:
            a["cancel"].set()
            a["finished"] = True
    finalizer.detach()
    _exchange_cleanup(stop, live_procs, live_shms)
    if retain:  # retained frames served their purpose; the result dir
        for r in range(R):   # keeps only bucket output
            shutil.rmtree(_retain_dir(spill_dir, r), ignore_errors=True)

    bucket_rows: dict[int, int] = {}
    for st in red_done.values():
        bucket_rows.update(st["bucket_rows"])
    rows_list = [bucket_rows.get(b, 0) for b in range(n_out)]
    # input-side totals come from the WINNING slice reports, so they are
    # deterministic across retries and speculation (a replayed slice's
    # numbers count once, no matter how many attempts ran it); transport-
    # dependent counters (overflow) still sum over every attempt
    win = [s for s in sl_stats if s is not None]
    pairs_in = sum(s["pairs"] for s in win)
    bytes_moved = sum(s["bytes"] for s in win)
    cols_pairs = sum(s["cols_pairs"] for s in win)
    cols_bytes = sum(s["cols_bytes"] for s in win)
    transport = ("tuple" if plan is None or cols_pairs == 0
                 else ("columnar" if cols_pairs == pairs_in else "mixed"))
    stats = {
        "op": label,
        "workers": M,
        "reducers": R,
        "buckets": n_out,
        "elems_in": sum(s["elems"] for s in win),
        "pairs_in": pairs_in,
        "rows_out": sum(rows_list),
        "bytes_moved": bytes_moved,
        "overflow": sum(st["overflow"] for st in map_stats),
        "spills": spills,
        "spill_bytes": spill_bytes,
        "map_s": round((map_end or t_start) - t_start, 3),
        "merge_s": round(time.perf_counter() - (map_end or t_start), 3),
        "bucket_rows": rows_list,
        "mem_budget_mb": round(budget / (1 << 20), 1),
        # per-format split (ISSUE 12): which bytes/keys rode which
        # transport, and how each bucket finalized — the dlstatus
        # shuffle block's per-format rows
        "transport": transport,
        "columnar_pairs": cols_pairs,
        "columnar_bytes": cols_bytes,
        "tuple_pairs": pairs_in - cols_pairs,
        "tuple_bytes": bytes_moved - cols_bytes,
        "columnar_buckets": sum(
            st.get("cols_buckets", 0) for st in red_done.values()),
        "tuple_buckets": sum(
            st.get("tuple_buckets", 0) for st in red_done.values()),
        # recovery rollup (ISSUE 14): what self-healing cost this run
        "retries": recovery["retries"],
        "mapper_retries": recovery["mapper_retries"],
        "reducer_retries": recovery["reducer_retries"],
        "speculations": recovery["speculations"],
        "blacklists": recovery["blacklists"],
    }
    telemetry.emit("shuffle", edge="done", **stats)
    return ShuffleResult(spill_dir, n_out, stats, keep_dir=False, plan=plan)


def _exchange_cleanup(stop, procs, shms) -> None:
    """Idempotent teardown (finalize/atexit-safe): stop, reap, unlink."""
    stop.set()
    for p in procs:
        p.join(timeout=1.0)
    for p in procs:
        if p.is_alive():
            p.terminate()
            p.join(timeout=1.0)
    for s in shms:
        try:
            s.unlink()
        except FileNotFoundError:
            pass
        try:
            s.close()
        except BufferError:  # pragma: no cover - defensive
            s._buf = None
            s._mmap = None


# ---------------------------------------------------------------------------
# dataset-level entry points (used by rdd.py / data/dataframe.py)
# ---------------------------------------------------------------------------


def lazy_exchange(parts, *, num_workers: int, n_out: int,
                  spec: _Spec | None, label: str,
                  prepare=None, sort_spec=None, plan=None
                  ) -> Callable[[], ShuffleResult]:
    """A memoized exchange runner: the returned callable executes the
    shuffle ONCE, on first call (the lazy + memoized contract every wide
    op keeps), and hands back the same :class:`ShuffleResult` after that.
    ``prepare`` (also deferred to first call) returns the ``sort_route``
    pair for sort mode — it may walk the source (boundary sampling)."""
    memo: dict = {}

    def result() -> ShuffleResult:
        if "r" not in memo:
            memo["r"] = run_exchange(
                parts, num_workers=num_workers, n_out=n_out, spec=spec,
                label=label,
                sort_route=prepare() if prepare is not None else None,
                sort_spec=sort_spec, plan=plan)
        return memo["r"]

    return result


def _lazy_exchange_dataset(parts, *, num_workers: int, n_out: int,
                           spec: _Spec | None, label: str,
                           prepare=None, sort_spec=None, plan=None):
    """A PartitionedDataset whose partitions stream the exchange's bucket
    files (rows); the exchange runs once via :func:`lazy_exchange`. The
    dataframe agg path bypasses this for columnar buckets and reads
    planes directly."""
    from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

    result = lazy_exchange(
        parts, num_workers=num_workers, n_out=n_out, spec=spec,
        label=label, prepare=prepare, sort_spec=sort_spec, plan=plan)

    def make(bucket: int):
        return lambda: result().iter_bucket(bucket)

    return PartitionedDataset([make(b) for b in range(n_out)])


def reduce_by_key(dataset, f, n_out: int, num_workers: int, *,
                  combine: str | None = None, transport: str | None = None):
    plan = None
    if (combine in NUMERIC_COMBINES
            and resolve_transport(transport) != "tuple"):
        plan = reduce_pair_plan(combine)
    return _lazy_exchange_dataset(
        dataset._parts, num_workers=num_workers, n_out=n_out,
        spec=_reduce_spec(f), label="reduce_by_key", plan=plan)


def group_by_key(dataset, n_out: int, num_workers: int):
    return _lazy_exchange_dataset(
        dataset._parts, num_workers=num_workers, n_out=n_out,
        spec=_group_spec(), label="group_by_key")


def distinct(dataset, num_workers: int, *, transport: str | None = None):
    plan = (distinct_pair_plan()
            if resolve_transport(transport) != "tuple" else None)
    return _lazy_exchange_dataset(
        dataset._parts, num_workers=num_workers,
        n_out=dataset.num_partitions, spec=_distinct_spec(),
        label="distinct", plan=plan)


def _sample_boundaries(parts, key_fn, n_out: int) -> list:
    """Range-partition boundaries for sort_by: a deterministic stride-
    thinned sample of the key stream (every s-th key, s doubling once the
    sample would exceed 8192 entries), quantiled into ``n_out - 1`` cut
    points. One serial pre-pass over the source — cheap next to the sort
    itself, and data-derived, so boundaries are identical at any worker
    count."""
    sample: list = []
    stride, phase = 1, 0
    for p in parts:
        for x in p():
            if phase % stride == 0:
                sample.append(key_fn(x))
                if len(sample) >= 8192:
                    sample = sample[::2]
                    stride *= 2
            phase += 1
    if not sample:
        return []
    sample.sort()
    return [sample[(i + 1) * len(sample) // n_out]
            for i in range(n_out - 1)]


def sort_by(dataset, key_fn, *, ascending: bool, n_out: int,
            num_workers: int):
    """Range-partitioned external sort: sample boundaries, route elements
    to range buckets, external-sort each bucket by ``(key, position)`` so
    equal keys keep encounter order — the same total order the serial
    stable sort emits (partition boundaries fall on sample quantiles
    rather than exact equal splits)."""
    import bisect

    parts = dataset._parts

    def prepare():
        boundaries = ([] if n_out == 1
                      else _sample_boundaries(parts, key_fn, n_out))

        def route(kv) -> int:
            b = bisect.bisect_right(boundaries, kv)
            return (n_out - 1 - b) if not ascending else b

        return (key_fn, route)

    if ascending:
        sort_key = lambda e: (e[0], (e[1], e[2]))  # noqa: E731
    else:
        # reverse=True flips both: key DESC, (-part, -idx) DESC = pos ASC,
        # matching the serial stable sort's equal-key encounter order
        sort_key = lambda e: (e[0], (-e[1], -e[2]))  # noqa: E731
    return _lazy_exchange_dataset(
        dataset._parts, num_workers=num_workers, n_out=n_out, spec=None,
        label="sort_by", prepare=prepare,
        sort_spec=(sort_key, not ascending))


def serial_refusal(op: str, limit: int, what: str = "distinct keys") -> str:
    """The serial-path loud failure, with remediations in priority order:
    the exchange first (the fix that scales), then key bounding, then the
    ceiling knob."""
    return (
        f"{op} exceeded max_groups={limit} {what} on the serial driver-side "
        f"path. Set DLS_DATA_WORKERS=N (or pass num_workers=) to route "
        f"through the distributed shuffle exchange (data/exchange.py), "
        f"which spills to disk under DLS_SHUFFLE_MEM_MB instead of growing "
        f"a driver dict; or hash_bucket/pre-bucket the key to bound the "
        f"result; or raise {MAX_GROUPS_ENV} if the result genuinely fits "
        f"the driver.")
