"""Cross-worker hash-partitioned shuffle — the wide-transformation engine.

PR 5 drew the honest line of the narrow engine: every wide transformation
(``reduce_by_key``, ``group_by_key``, ``groupBy().agg``) merged its partials
in ONE driver-side dict, and the ``max_groups`` ceiling *refused*
high-cardinality workloads (user-id-like keys) rather than run them out of
memory. This module breaks that ceiling with the classic map/reduce-over-
partitions shape (DrJAX, PAPERS.md 2403.07128) built on the process-pool
machinery ``data/workers.py`` already proved:

- **Map side.** ``M`` forked mapper processes walk the source partitions
  (partition ``p`` → mapper ``p % M``; when ``M > P`` mappers split a
  partition by element-residue classes, the WorkerPool discipline) and
  combine locally into a bounded dict. When the dict outgrows its share of
  ``DLS_SHUFFLE_MEM_MB`` it *flushes*: entries bucket by canonical key hash
  (:func:`key_bytes` — blake2b over the pickled key, NOT Python's
  per-process-seeded ``hash``) and each bucket's payload ships to its
  owning reducer through the mapper's shared-memory arena
  (:class:`~.workers._Arena`, the same first-fit out-of-order reclaim),
  falling back to pickled queue transport when the arena is full — counted,
  never stalled, exactly the batch-plane discipline.
- **Exchange.** Barrier-free: one queue per reducer, payloads stream as
  flushes happen, reducers merge incrementally while mappers still run.
  A mapper that raises forwards its traceback; one that *dies* (SIGKILL,
  OOM) is caught by the driver's liveness poll — either way the caller gets
  a typed :class:`~.workers.WorkerCrashed` within a bounded wait and every
  child, shm segment, and spill file is torn down. A dead mapper is a
  supervisor-visible CRASH, not a hang.
- **Reduce side.** ``R`` reducer processes own buckets ``b % R == r`` and
  merge arriving partials into per-bucket dicts under their share of the
  memory budget; past it they **spill**: items sorted by :func:`key_bytes`
  stream to a run file, and finalization k-way-merges the sorted runs
  (``heapq.merge``) combining adjacent equal keys — a 10M-key aggregation
  completes under a budget the old ceiling refused at. Final output streams
  to one file per bucket; the returned dataset's partitions re-read those
  files, so nothing is ever fully materialized driver-side.

**Determinism.** Output order is canonical: bucket-major, :func:`key_bytes`
order within each bucket — data-derived, so results are byte-identical at
ANY worker count (the serial fallbacks in ``rdd.py``/``data/dataframe.py``
emit the same canonical order, which also makes them reproducible across
runs — the old ``hash(k) % n`` bucketing moved with ``PYTHONHASHSEED``).
Value-combine order is NOT fixed across worker counts (partials merge as
they arrive), so reduce functions must be commutative + associative —
Spark's own ``reduceByKey`` contract; results are bit-identical when the
combine is exact (int sums, min/max, counts; float sums are exact while
magnitudes stay within 2^53). ``group_by_key`` value lists ARE exactly
ordered: values travel tagged with their (partition, index) position and
sort back to encounter order at emit.

**Telemetry.** The driver wraps the run in ``shuffle-map`` / ``shuffle-
merge`` phase spans (lowered into the PR 7 span model like any phase) and
mirrors reducer spills plus a final summary as ``shuffle`` gauge events —
``dlstatus`` renders them as the shuffle block (bytes moved, spill count,
per-bucket skew, slowest-bucket verdict).
"""

from __future__ import annotations

import hashlib
import heapq
import multiprocessing as mp
import os
import pickle
import queue as queue_lib
import shutil
import tempfile
import time
import traceback
import uuid
import warnings
import weakref
from multiprocessing import shared_memory
from typing import Any, Callable, Iterator, Sequence

import numpy as np

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.data.workers import (
    _POLL_S, _Arena, _align, WorkerCrashed, fork_available,
    resolve_num_workers)

#: env knob: total shuffle memory budget (MB) split over mapper arenas,
#: mapper combine dicts, and reducer merge dicts. Past their share, mappers
#: flush early and reducers spill to disk — the budget bounds resident
#: bytes, it never refuses a workload.
MEM_MB_ENV = "DLS_SHUFFLE_MEM_MB"
_DEFAULT_MEM_MB = 256
#: env knob: where spill runs and bucket output files live (default: a
#: fresh tempdir per shuffle, removed when the result is garbage-collected
#: or the exchange fails).
SPILL_DIR_ENV = "DLS_SHUFFLE_SPILL_DIR"
#: env knob shared with data/dataframe.py: the serial-path distinct-key /
#: materialization ceiling (the exchange path has no ceiling — that is the
#: point of it).
MAX_GROUPS_ENV = "DLS_AGG_MAX_GROUPS"
_DEFAULT_MAX_GROUPS = 1_000_000

_PICKLE_PROTO = 4
#: per-reducer metadata queue bound: flush payloads in flight beyond the
#: arenas (backpressure's item-count half, as in workers.py).
_QUEUE_AHEAD = 16
#: how long a mapper waits for arena space before the pickle fallback.
_ALLOC_WAIT_S = 0.25
_MIN_ARENA = 1 << 20
_MIN_CAP = 1 << 18


def max_groups_limit(explicit: int | None = None) -> int:
    """The serial-path cardinality ceiling: explicit value, else
    ``DLS_AGG_MAX_GROUPS``, else 1M (PR 5's default)."""
    if explicit is not None:
        return int(explicit)
    try:
        return int(os.environ.get(MAX_GROUPS_ENV, "") or _DEFAULT_MAX_GROUPS)
    except ValueError:
        return _DEFAULT_MAX_GROUPS


def resolve_shuffle_workers(num_workers: int | None) -> int:
    """Worker count for the exchange: explicit value wins, ``None`` reads
    ``DLS_DATA_WORKERS`` (the pool the shuffle rides on). 0 — or a platform
    without ``fork`` — means the serial driver-side path."""
    nw = resolve_num_workers(num_workers)
    if nw > 0 and not fork_available():  # pragma: no cover - platform
        warnings.warn("shuffle workers requested but the 'fork' start "
                      "method is unavailable; using the serial path")
        return 0
    return nw


def mem_budget_bytes(explicit_mb: float | None = None) -> int:
    if explicit_mb is None:
        try:
            explicit_mb = float(
                os.environ.get(MEM_MB_ENV, "") or _DEFAULT_MEM_MB)
        except ValueError:
            explicit_mb = _DEFAULT_MEM_MB
    return max(4 << 20, int(explicit_mb * (1 << 20)))


def key_bytes(key: Any) -> bytes:
    """Canonical sortable identity of a shuffle key: an 8-byte blake2b
    digest of the pickled key, followed by the pickle itself (the digest
    buckets and sorts; the tail breaks the astronomically-rare collision
    deterministically). Stable across processes and runs — unlike
    ``hash()``, which moves with ``PYTHONHASHSEED``. Keys that compare
    equal but pickle differently (``1`` vs ``np.int64(1)``) are DIFFERENT
    shuffle keys; keep key types canonical (the DataFrame plane already
    does)."""
    kb = pickle.dumps(key, protocol=_PICKLE_PROTO)
    return hashlib.blake2b(kb, digest_size=8).digest() + kb


def bucket_of(kb: bytes, n_out: int) -> int:
    """Owning bucket of a key's :func:`key_bytes` — shared with the serial
    fallbacks so both paths land every key in the same output partition."""
    return int.from_bytes(kb[:8], "big") % n_out


def _approx_nbytes(v: Any) -> int:
    """Cheap upper-ish estimate of an object's resident bytes for the
    flush/spill accounting. Precision is not the point — a stable,
    monotone estimate is (over-estimating just flushes earlier). The hot
    loops call this SAMPLED (every 64th item, :class:`_ByteMeter`): at 10M
    pairs, two recursive walks per pair were the map phase's single
    largest cost."""
    if isinstance(v, np.ndarray):
        return v.nbytes + 128
    if isinstance(v, (bytes, str)):
        return len(v) + 64
    if isinstance(v, (list, tuple)):
        return 64 + 16 * len(v) + sum(
            _approx_nbytes(x) for x in v[:8]) * max(1, len(v) // 8 if len(v) > 8 else 1)
    if isinstance(v, dict):
        return 64 + sum(_approx_nbytes(x) + 32 for x in v.values())
    return 64


class _ByteMeter:
    """Sampled byte accounting for the mapper/reducer stores: every 64th
    ``add`` re-measures the item with :func:`_approx_nbytes` and the
    in-between items are charged the rolling estimate. ``value`` tracks
    the store's resident bytes well enough to bound memory (the budget's
    contract), at 1/64th the walk cost."""

    __slots__ = ("value", "_est", "_n")

    def __init__(self):
        self.value = 0.0
        self._est = 192.0
        self._n = 0

    def add(self, item: Any, overhead: int = 0) -> None:
        self._n += 1
        if self._n & 0x3F == 1:
            self._est = float(_approx_nbytes(item))
        self.value += self._est + overhead

    def reset(self) -> None:
        self.value = 0.0


# ---------------------------------------------------------------------------
# operation specs
# ---------------------------------------------------------------------------


class _Spec:
    """How one wide operation maps onto the exchange.

    ``pre(elem)``: iterable of (key, value) pairs for one source element
    (None = the element already is the pair). ``seed(v)``: first value →
    accumulator. ``combine(acc, v)``: fold one more value in (map side).
    ``merge(a, b)``: fold two accumulators (reduce side). ``final(key,
    acc)``: the emitted record. ``tag_values``: wrap each value as
    ``(part, idx, v)`` before seeding so ``final`` can restore encounter
    order (group_by_key).
    """

    __slots__ = ("pre", "seed", "combine", "merge", "final", "tag_values")

    def __init__(self, *, pre=None, seed=None, combine=None, merge=None,
                 final=None, tag_values=False):
        self.pre = pre
        self.seed = seed if seed is not None else (lambda v: v)
        self.combine = combine
        self.merge = merge if merge is not None else combine
        self.final = final if final is not None else (lambda k, a: (k, a))
        self.tag_values = tag_values


def _reduce_spec(f: Callable[[Any, Any], Any]) -> _Spec:
    return _Spec(combine=f)


def _group_spec() -> _Spec:
    def final(k, acc):
        acc.sort(key=lambda t: (t[0], t[1]))
        return (k, [v for _, _, v in acc])

    return _Spec(seed=lambda tv: [tv],
                 combine=lambda acc, tv: (acc.append(tv) or acc),
                 merge=lambda a, b: (a.extend(b) or a),
                 final=final, tag_values=True)


def _distinct_spec() -> _Spec:
    return _Spec(pre=lambda x: ((x, None),),
                 combine=lambda acc, v: acc,
                 final=lambda k, a: k)


# ---------------------------------------------------------------------------
# mapper / reducer process bodies (fork-inherited closures, no jax)
# ---------------------------------------------------------------------------


def _drain_frees(ring: _Arena, free_q) -> None:
    try:
        while True:
            ring.free(free_q.get_nowait())
    except queue_lib.Empty:
        pass


def _mapper_loop(mid: int, parts, assignment, spec: _Spec, n_out: int,
                 shm, out_qs, free_q, ctrl_q, stop_evt, cap_bytes: int,
                 sort_route=None) -> None:
    """Child body: walk assigned (partition, slot, k) slices, combine into a
    bounded dict, flush bucketed payloads through the arena/queues."""
    os.environ["DLS_NATIVE_THREADS"] = "1"  # same capping rationale as workers
    ring = _Arena(shm.size)
    buf = shm.buf
    alloc_id = 0
    R = len(out_qs)
    stats = {"elems": 0, "pairs": 0, "bytes_moved": 0, "overflow": 0,
             "flushes": 0, "busy_s": 0.0}
    store: dict = {}
    meter = _ByteMeter()

    def put(q, rec) -> bool:
        while not stop_evt.is_set():
            try:
                q.put(rec, timeout=_POLL_S)
                return True
            except queue_lib.Full:
                continue
        return False

    def alloc(need: int) -> int | None:
        deadline = time.perf_counter() + _ALLOC_WAIT_S
        while True:
            _drain_frees(ring, free_q)
            off = ring.try_alloc(alloc_id, need)
            if off is not None or need > ring.size:
                return off
            if stop_evt.is_set() or time.perf_counter() > deadline:
                return None
            try:
                ring.free(free_q.get(timeout=_POLL_S))
            except queue_lib.Empty:
                pass

    def ship(bucket: int, payload: bytes) -> bool:
        nonlocal alloc_id
        stats["bytes_moved"] += len(payload)
        off = alloc(_align(len(payload)))
        if off is None:
            stats["overflow"] += 1
            return put(out_qs[bucket % R], ("pkl", mid, bucket, payload))
        buf[off:off + len(payload)] = payload
        ok = put(out_qs[bucket % R],
                 ("shm", mid, bucket, alloc_id, off, len(payload)))
        alloc_id += 1
        return ok

    def flush() -> bool:
        if not store:
            return True
        stats["flushes"] += 1
        buckets: dict[int, list] = {}
        for key, acc in store.items():
            kb = key_bytes(key)
            buckets.setdefault(bucket_of(kb, n_out), []).append(
                (kb, key, acc))
        store.clear()
        meter.reset()
        for b in sorted(buckets):
            if not ship(b, pickle.dumps(buckets[b], protocol=_PICKLE_PROTO)):
                return False
        return True

    try:
        for part_idx, slot, k in assignment:
            t0 = time.perf_counter()
            for j, elem in enumerate(parts[part_idx]()):
                if k > 1 and j % k != slot:
                    continue
                if stop_evt.is_set():
                    return
                stats["elems"] += 1
                if sort_route is not None:
                    # sort mode: no combine — route each element straight
                    # to its range bucket, tagged with (key, part, idx)
                    kv = sort_route[0](elem)
                    b = sort_route[1](kv)
                    store.setdefault(b, []).append((kv, part_idx, j, elem))
                    meter.add(elem, 64)
                    stats["pairs"] += 1
                    if meter.value >= cap_bytes:
                        stats["flushes"] += 1
                        for bb in sorted(store):
                            if not ship(bb, pickle.dumps(
                                    store[bb], protocol=_PICKLE_PROTO)):
                                return
                        store.clear()
                        meter.reset()
                    continue
                pairs = spec.pre(elem) if spec.pre is not None else (elem,)
                for key, v in pairs:
                    stats["pairs"] += 1
                    if spec.tag_values:
                        v = (part_idx, j, v)
                    if key in store:
                        store[key] = spec.combine(store[key], v)
                        meter.add(v)
                    else:
                        store[key] = spec.seed(v)
                        meter.add(v, 120)
                    if meter.value >= cap_bytes:
                        if not flush():
                            return
            # flush at every partition boundary: mapper state never spans
            # partitions, so flush points depend only on the partition's
            # own content and the cap
            if sort_route is not None:
                for bb in sorted(store):
                    if not ship(bb, pickle.dumps(store[bb],
                                                 protocol=_PICKLE_PROTO)):
                        return
                store.clear()
                meter.reset()
            elif not flush():
                return
            stats["busy_s"] += time.perf_counter() - t0
        for q in out_qs:
            if not put(q, ("done", mid, None)):
                return
        put(ctrl_q, ("mapper-done", mid, stats))
    except BaseException:  # noqa: BLE001 — forward ANY failure, typed
        put(ctrl_q, ("err", ("mapper", mid), traceback.format_exc()))


def _spill_path(spill_dir: str, rid: int, bucket: int, n: int) -> str:
    return os.path.join(spill_dir, f"r{rid}-b{bucket}-run{n}.pkl")


def out_path(spill_dir: str, bucket: int) -> str:
    return os.path.join(spill_dir, f"out-b{bucket}.pkl")


def _write_run(path: str, items: list) -> int:
    """One sorted spill run: a raw pickle stream, re-read with repeated
    loads. Returns bytes written."""
    with open(path, "wb") as f:
        p = pickle.Pickler(f, protocol=_PICKLE_PROTO)
        for it in items:
            p.dump(it)
        return f.tell()


def _iter_run(path: str) -> Iterator:
    with open(path, "rb") as f:
        up = pickle.Unpickler(f)
        while True:
            try:
                yield up.load()
            except EOFError:
                return


def _reducer_loop(rid: int, M: int, R: int, n_out: int, spec: _Spec | None,
                  in_q, free_qs, shm_names, ctrl_q, stop_evt,
                  cap_bytes: int, spill_dir: str, sort_spec=None) -> None:
    """Child body: merge arriving bucket payloads under a byte budget,
    spill sorted runs past it, k-way-merge runs into one output file per
    owned bucket."""
    os.environ["DLS_NATIVE_THREADS"] = "1"
    shms: dict[int, shared_memory.SharedMemory] = {}
    # keyed mode: bucket -> {key: [kb, acc]}; sort mode: bucket -> [entry]
    stores: dict[int, Any] = {}
    runs: dict[int, list[str]] = {}
    meter = _ByteMeter()
    done = set()
    stats = {"spills": 0, "spill_bytes": 0, "bucket_rows": {}, "merge_s": 0.0}

    def notify(msg) -> None:
        try:
            ctrl_q.put(msg, timeout=_POLL_S)
        except queue_lib.Full:
            pass

    def payload_of(rec) -> bytes:
        kind, mid = rec[0], rec[1]
        if kind == "pkl":
            return rec[3]
        _, _, _bucket, alloc_id, off, size = rec
        if mid not in shms:
            shms[mid] = shared_memory.SharedMemory(name=shm_names[mid])
        data = bytes(shms[mid].buf[off:off + size])
        try:  # copy taken — release the mapper's arena slot immediately
            free_qs[mid].put_nowait(alloc_id)
        except Exception:  # noqa: BLE001 — mapper may be gone at teardown
            pass
        return data

    def spill_largest() -> None:
        if not stores:
            return
        bucket = max(stores, key=lambda b: len(stores[b]))
        if sort_spec is not None:
            items = sorted(stores.pop(bucket), key=sort_spec[0],
                           reverse=sort_spec[1])
        else:
            items = sorted(
                ((e[0], key, e[1]) for key, e in stores.pop(bucket).items()),
                key=lambda t: t[0])
        path = _spill_path(spill_dir, rid, bucket,
                           len(runs.setdefault(bucket, [])))
        nbytes = _write_run(path, items)
        runs[bucket].append(path)
        stats["spills"] += 1
        stats["spill_bytes"] += nbytes
        # rebase surviving buckets at the meter's OWN rolling per-item
        # estimate — a flat constant here would under-charge fat values
        # (group lists) and let residency creep past the budget share
        meter.value = (sum(len(s) for s in stores.values())
                       * (meter._est + 100))
        notify(("spill", rid, bucket, len(items), nbytes))

    def merge_bucket(bucket: int) -> None:
        """Stream the bucket's runs + memory into its final output file."""
        t0 = time.perf_counter()
        rows = 0
        streams = [_iter_run(p) for p in runs.get(bucket, [])]
        if sort_spec is not None:
            mem = sorted(stores.pop(bucket, []), key=sort_spec[0],
                         reverse=sort_spec[1])
            merged = heapq.merge(*streams, mem, key=sort_spec[0],
                                 reverse=sort_spec[1])
            with open(out_path(spill_dir, bucket), "wb") as f:
                p = pickle.Pickler(f, protocol=_PICKLE_PROTO)
                for _kv, _part, _j, elem in merged:
                    p.dump(elem)
                    rows += 1
        else:
            mem = sorted(
                ((e[0], key, e[1])
                 for key, e in stores.pop(bucket, {}).items()),
                key=lambda t: t[0])
            merged = heapq.merge(*streams, mem, key=lambda t: t[0])
            with open(out_path(spill_dir, bucket), "wb") as f:
                p = pickle.Pickler(f, protocol=_PICKLE_PROTO)
                cur_kb = cur_key = cur_acc = None
                for kb, key, acc in merged:
                    if cur_kb is not None and kb == cur_kb:
                        cur_acc = spec.merge(cur_acc, acc)
                        continue
                    if cur_kb is not None:
                        p.dump(spec.final(cur_key, cur_acc))
                        rows += 1
                    cur_kb, cur_key, cur_acc = kb, key, acc
                if cur_kb is not None:
                    p.dump(spec.final(cur_key, cur_acc))
                    rows += 1
        for p_ in runs.pop(bucket, []):
            try:
                os.remove(p_)
            except OSError:
                pass
        stats["bucket_rows"][bucket] = rows
        stats["merge_s"] += time.perf_counter() - t0

    try:
        while len(done) < M:
            if stop_evt.is_set():
                return
            try:
                rec = in_q.get(timeout=_POLL_S)
            except queue_lib.Empty:
                continue
            if rec[0] == "done":
                done.add(rec[1])
                continue
            bucket = rec[2]
            items = pickle.loads(payload_of(rec))
            if sort_spec is not None:
                lst = stores.setdefault(bucket, [])
                lst.extend(items)
                for e in items:
                    meter.add(e[3], 64)
            else:
                st = stores.setdefault(bucket, {})
                for kb, key, acc in items:
                    ent = st.get(key)
                    if ent is None:
                        st[key] = [kb, acc]
                        meter.add(acc, len(kb) + 100)
                    else:
                        ent[1] = spec.merge(ent[1], acc)
                        meter.add(acc)
            while meter.value >= cap_bytes and stores:
                spill_largest()
        for bucket in range(rid, n_out, R):
            if stop_evt.is_set():
                return
            merge_bucket(bucket)
        notify(("reducer-done", rid, stats))
    except BaseException:  # noqa: BLE001
        notify(("err", ("reducer", rid), traceback.format_exc()))
    finally:
        for s in shms.values():
            try:
                s.close()
            except Exception:  # noqa: BLE001
                pass


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


class ShuffleResult:
    """Per-bucket output files + the stats the telemetry summary carried.
    Holds the spill directory alive; it is removed when this object (and
    every dataset partition referencing it) is garbage-collected."""

    def __init__(self, spill_dir: str, n_out: int, stats: dict,
                 keep_dir: bool):
        self.spill_dir = spill_dir
        self.n_out = n_out
        self.stats = stats
        self._fin = (weakref.finalize(self, _rm_dir, spill_dir)
                     if not keep_dir else None)

    def iter_bucket(self, bucket: int) -> Iterator:
        # generator METHOD on purpose: the running frame holds ``self``, so
        # the spill directory cannot be finalized out from under a consumer
        # whose dataset reference was dropped mid-iteration
        path = out_path(self.spill_dir, bucket)
        if os.path.exists(path):
            yield from _iter_run(path)


def _rm_dir(path: str) -> None:
    shutil.rmtree(path, ignore_errors=True)


def _assignments(P: int, M: int) -> list[list[tuple[int, int, int]]]:
    """Mapper → [(partition, slot, k)]: whole partitions round-robin onto
    mappers while ``M <= P``; past that, the mappers co-assigned to one
    partition split it by element residue (slot of k) — the WorkerPool
    discipline, so a single-partition source still scales."""
    if M <= P:
        whole: list[list[tuple[int, int, int]]] = [[] for _ in range(M)]
        for p in range(P):
            whole[p % M].append((p, 0, 1))
        return whole
    per_part: list[list[int]] = [[] for _ in range(P)]
    for m in range(M):
        per_part[m % P].append(m)
    out: list[list[tuple[int, int, int]]] = [[] for _ in range(M)]
    for p, ms in enumerate(per_part):
        for slot, m in enumerate(ms):
            out[m].append((p, slot, len(ms)))
    return out


def run_exchange(parts: Sequence[Callable[[], Any]], *, num_workers: int,
                 n_out: int, spec: _Spec | None, label: str,
                 sort_route=None, sort_spec=None,
                 mem_mb: float | None = None) -> ShuffleResult:
    """Execute one shuffle: spawn mappers + reducers, stream the exchange,
    return the per-bucket output. Raises :class:`WorkerCrashed` (cleaning
    up every child, shm segment, and spill file) when any child raises or
    dies."""
    P = len(parts)
    M = max(1, int(num_workers))
    R = max(1, min(M, n_out))
    budget = mem_budget_bytes(mem_mb)
    arena_bytes = max(_MIN_ARENA, budget // (4 * M))
    map_cap = max(_MIN_CAP, budget // (4 * M))
    red_cap = max(_MIN_CAP, budget // (2 * R))
    base = os.environ.get(SPILL_DIR_ENV) or None
    if base:
        os.makedirs(base, exist_ok=True)
    spill_dir = tempfile.mkdtemp(prefix="dlsx-", dir=base)
    ctx = mp.get_context("fork")
    stop = ctx.Event()
    ctrl_q = ctx.Queue()
    out_qs = [ctx.Queue(maxsize=_QUEUE_AHEAD) for _ in range(R)]
    free_qs = [ctx.Queue() for _ in range(M)]
    shms = [shared_memory.SharedMemory(
        create=True, size=arena_bytes,
        name=f"dlsx-{os.getpid()}-{uuid.uuid4().hex[:8]}-m{m}")
        for m in range(M)]
    shm_names = [s.name for s in shms]
    assign = _assignments(P, M)
    mappers = [ctx.Process(
        target=_mapper_loop, daemon=True, name=f"dlsx-map-{m}",
        args=(m, list(parts), assign[m], spec, n_out, shms[m], out_qs,
              free_qs[m], ctrl_q, stop, map_cap, sort_route))
        for m in range(M)]
    reducers = [ctx.Process(
        target=_reducer_loop, daemon=True, name=f"dlsx-red-{r}",
        args=(r, M, R, n_out, spec, out_qs[r], free_qs, shm_names, ctrl_q,
              stop, red_cap, spill_dir, sort_spec))
        for r in range(R)]
    procs = mappers + reducers
    with warnings.catch_warnings():
        # children run pure numpy/pickle, never JAX — same rationale as
        # WorkerPool's fork-under-JAX warning filter
        warnings.filterwarnings(
            "ignore", message=r".*os\.fork\(\) was called.*",
            category=RuntimeWarning)
        for p in procs:
            p.start()
    finalizer = weakref.finalize(
        run_exchange, _exchange_cleanup, stop, list(procs), list(shms))

    t_start = time.perf_counter()
    map_done: dict[int, dict] = {}
    red_done: dict[int, dict] = {}
    spills = 0
    spill_bytes = 0
    map_end: float | None = None
    telemetry.emit("phase", name="shuffle-map", edge="begin", op=label)
    try:
        # wait for BOTH roles: a reducer can observe the out_q "done"
        # sentinels and finish before the mapper's ctrl "mapper-done"
        # lands (two queues, two feeder threads — no cross-queue order);
        # exiting on reducers alone would drop that mapper's stats and
        # leave the shuffle-map phase open
        while len(red_done) < R or len(map_done) < M:
            try:
                msg = ctrl_q.get(timeout=_POLL_S)
            except queue_lib.Empty:
                for i, p in enumerate(procs):
                    is_map = i < M
                    wid = i if is_map else i - M
                    finished = (wid in map_done) if is_map else (wid in red_done)
                    if not finished and not p.is_alive():
                        # drain race: its last message may be in flight
                        try:
                            msg = ctrl_q.get(timeout=_POLL_S)
                            break
                        except queue_lib.Empty:
                            pass
                        role = "mapper" if is_map else "reducer"
                        raise WorkerCrashed(
                            f"shuffle {role} {wid} died (exit code "
                            f"{p.exitcode}) mid-exchange — killed (OOM/"
                            f"SIGKILL) or crashed in native code",
                            worker=wid, exitcode=p.exitcode)
                else:
                    continue
            kind = msg[0]
            if kind == "err":
                role, wid = msg[1]
                raise WorkerCrashed(
                    f"shuffle {role} {wid} raised:\n{msg[2]}", worker=wid)
            if kind == "mapper-done":
                map_done[msg[1]] = msg[2]
                if len(map_done) == M and map_end is None:
                    map_end = time.perf_counter()
                    telemetry.emit("phase", name="shuffle-map", edge="end",
                                   dur_s=map_end - t_start, op=label)
                    telemetry.emit("phase", name="shuffle-merge",
                                   edge="begin", op=label)
            elif kind == "reducer-done":
                red_done[msg[1]] = msg[2]
            elif kind == "spill":
                spills += 1
                spill_bytes += msg[4]
                telemetry.emit("shuffle", edge="spill", op=label,
                               reducer=msg[1], bucket=msg[2], rows=msg[3],
                               bytes=msg[4])
        merge_s = time.perf_counter() - (map_end or t_start)
        telemetry.emit("phase", name="shuffle-merge", edge="end",
                       dur_s=merge_s, op=label)
    except BaseException:
        # failed exchange: nothing must leak — children, shm, spill files.
        # End whichever phase is OPEN (map, or merge once map ended) so a
        # crashed shuffle never pins a stale open phase onto every later
        # heartbeat's hang localization
        stop.set()
        telemetry.emit(
            "phase", edge="end", op=label, aborted=True,
            name="shuffle-map" if map_end is None else "shuffle-merge")
        _exchange_cleanup(stop, procs, shms)
        finalizer.detach()
        _rm_dir(spill_dir)
        raise
    finalizer.detach()
    _exchange_cleanup(stop, procs, shms)

    bucket_rows: dict[int, int] = {}
    for st in red_done.values():
        bucket_rows.update(st["bucket_rows"])
    rows_list = [bucket_rows.get(b, 0) for b in range(n_out)]
    stats = {
        "op": label,
        "workers": M,
        "reducers": R,
        "buckets": n_out,
        "elems_in": sum(st["elems"] for st in map_done.values()),
        "pairs_in": sum(st["pairs"] for st in map_done.values()),
        "rows_out": sum(rows_list),
        "bytes_moved": sum(st["bytes_moved"] for st in map_done.values()),
        "overflow": sum(st["overflow"] for st in map_done.values()),
        "spills": spills,
        "spill_bytes": spill_bytes,
        "map_s": round((map_end or t_start) - t_start, 3),
        "merge_s": round(time.perf_counter() - (map_end or t_start), 3),
        "bucket_rows": rows_list,
        "mem_budget_mb": round(budget / (1 << 20), 1),
    }
    telemetry.emit("shuffle", edge="done", **stats)
    return ShuffleResult(spill_dir, n_out, stats, keep_dir=False)


def _exchange_cleanup(stop, procs, shms) -> None:
    """Idempotent teardown (finalize/atexit-safe): stop, reap, unlink."""
    stop.set()
    for p in procs:
        p.join(timeout=1.0)
    for p in procs:
        if p.is_alive():
            p.terminate()
            p.join(timeout=1.0)
    for s in shms:
        try:
            s.unlink()
        except FileNotFoundError:
            pass
        try:
            s.close()
        except BufferError:  # pragma: no cover - defensive
            s._buf = None
            s._mmap = None


# ---------------------------------------------------------------------------
# dataset-level entry points (used by rdd.py / data/dataframe.py)
# ---------------------------------------------------------------------------


def _lazy_exchange_dataset(parts, *, num_workers: int, n_out: int,
                           spec: _Spec | None, label: str,
                           prepare=None, sort_spec=None):
    """A PartitionedDataset whose partitions stream the exchange's bucket
    files; the exchange itself runs once, on first iteration (the lazy +
    memoized contract every wide op in rdd.py keeps). ``prepare`` (also
    deferred to first iteration) returns the ``sort_route`` pair for sort
    mode — it may walk the source (boundary sampling)."""
    from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

    memo: dict = {}

    def result() -> ShuffleResult:
        if "r" not in memo:
            memo["r"] = run_exchange(
                parts, num_workers=num_workers, n_out=n_out, spec=spec,
                label=label,
                sort_route=prepare() if prepare is not None else None,
                sort_spec=sort_spec)
        return memo["r"]

    def make(bucket: int):
        return lambda: result().iter_bucket(bucket)

    return PartitionedDataset([make(b) for b in range(n_out)])


def reduce_by_key(dataset, f, n_out: int, num_workers: int):
    return _lazy_exchange_dataset(
        dataset._parts, num_workers=num_workers, n_out=n_out,
        spec=_reduce_spec(f), label="reduce_by_key")


def group_by_key(dataset, n_out: int, num_workers: int):
    return _lazy_exchange_dataset(
        dataset._parts, num_workers=num_workers, n_out=n_out,
        spec=_group_spec(), label="group_by_key")


def distinct(dataset, num_workers: int):
    return _lazy_exchange_dataset(
        dataset._parts, num_workers=num_workers,
        n_out=dataset.num_partitions, spec=_distinct_spec(),
        label="distinct")


def _sample_boundaries(parts, key_fn, n_out: int) -> list:
    """Range-partition boundaries for sort_by: a deterministic stride-
    thinned sample of the key stream (every s-th key, s doubling once the
    sample would exceed 8192 entries), quantiled into ``n_out - 1`` cut
    points. One serial pre-pass over the source — cheap next to the sort
    itself, and data-derived, so boundaries are identical at any worker
    count."""
    sample: list = []
    stride, phase = 1, 0
    for p in parts:
        for x in p():
            if phase % stride == 0:
                sample.append(key_fn(x))
                if len(sample) >= 8192:
                    sample = sample[::2]
                    stride *= 2
            phase += 1
    if not sample:
        return []
    sample.sort()
    return [sample[(i + 1) * len(sample) // n_out]
            for i in range(n_out - 1)]


def sort_by(dataset, key_fn, *, ascending: bool, n_out: int,
            num_workers: int):
    """Range-partitioned external sort: sample boundaries, route elements
    to range buckets, external-sort each bucket by ``(key, position)`` so
    equal keys keep encounter order — the same total order the serial
    stable sort emits (partition boundaries fall on sample quantiles
    rather than exact equal splits)."""
    import bisect

    parts = dataset._parts

    def prepare():
        boundaries = ([] if n_out == 1
                      else _sample_boundaries(parts, key_fn, n_out))

        def route(kv) -> int:
            b = bisect.bisect_right(boundaries, kv)
            return (n_out - 1 - b) if not ascending else b

        return (key_fn, route)

    if ascending:
        sort_key = lambda e: (e[0], (e[1], e[2]))  # noqa: E731
    else:
        # reverse=True flips both: key DESC, (-part, -idx) DESC = pos ASC,
        # matching the serial stable sort's equal-key encounter order
        sort_key = lambda e: (e[0], (-e[1], -e[2]))  # noqa: E731
    return _lazy_exchange_dataset(
        dataset._parts, num_workers=num_workers, n_out=n_out, spec=None,
        label="sort_by", prepare=prepare,
        sort_spec=(sort_key, not ascending))


def serial_refusal(op: str, limit: int, what: str = "distinct keys") -> str:
    """The serial-path loud failure, with remediations in priority order:
    the exchange first (the fix that scales), then key bounding, then the
    ceiling knob."""
    return (
        f"{op} exceeded max_groups={limit} {what} on the serial driver-side "
        f"path. Set DLS_DATA_WORKERS=N (or pass num_workers=) to route "
        f"through the distributed shuffle exchange (data/exchange.py), "
        f"which spills to disk under DLS_SHUFFLE_MEM_MB instead of growing "
        f"a driver dict; or hash_bucket/pre-bucket the key to bound the "
        f"result; or raise {MAX_GROUPS_ENV} if the result genuinely fits "
        f"the driver.")
