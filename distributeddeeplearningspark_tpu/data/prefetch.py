"""Device-side prefetch: keep HBM fed while the current step runs.

BASELINE.json's north star names this explicitly: "Spark RDD/DataFrame
partitions stream into HBM via a device-side prefetch iterator". JAX dispatch
is asynchronous, so the recipe is a small look-ahead ring: transfer the next
``buffer_size`` batches to device *before* the consumer asks for them. The
``device_put`` for batch N+1 overlaps the device executing step N; a separate
host thread does the (possibly expensive) host-side assembly (decode /
augment / stack) so Python never blocks the dispatch path.
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Callable, Iterator

import jax
from jax.sharding import Mesh

from distributeddeeplearningspark_tpu.data.feed import put_global

_SENTINEL = object()


def prefetch_to_device(
    host_iter: Iterator[dict[str, Any]],
    mesh: Mesh,
    *,
    buffer_size: int = 2,
    put: Callable[[dict[str, Any], Mesh], Any] = put_global,
    background: bool = True,
) -> Iterator[Any]:
    """Wrap a host-batch iterator into a double-buffered device iterator.

    ``buffer_size=2`` (double buffering) is enough to hide transfer latency
    when host assembly keeps up; raise it for bursty sources.
    """
    if background:
        host_iter = _background(host_iter, maxsize=buffer_size + 1)

    buf: collections.deque = collections.deque()
    for hb in host_iter:
        buf.append(put(hb, mesh))
        if len(buf) >= buffer_size:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


def _background(it: Iterator, *, maxsize: int) -> Iterator:
    """Run an iterator in a daemon thread through a bounded queue."""
    q: queue.Queue = queue.Queue(maxsize=maxsize)
    err: list[BaseException] = []

    def worker() -> None:
        try:
            for x in it:
                q.put(x)
        except BaseException as e:  # propagate into consumer
            err.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=worker, daemon=True, name="dls-prefetch")
    t.start()
    while True:
        x = q.get()
        if x is _SENTINEL:
            if err:
                raise err[0]
            return
        yield x
