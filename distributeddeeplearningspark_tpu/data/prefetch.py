"""Device-side prefetch: keep HBM fed while the current step runs.

BASELINE.json's north star names this explicitly: "Spark RDD/DataFrame
partitions stream into HBM via a device-side prefetch iterator". JAX dispatch
is asynchronous, so the recipe is a small look-ahead ring: transfer the next
``buffer_size`` batches to device *before* the consumer asks for them. The
``device_put`` for batch N+1 overlaps the device executing step N; a separate
host thread does the (possibly expensive) host-side assembly (decode /
augment / stack) so Python never blocks the dispatch path.

**Starvation probe.** An input-bound step and a compute-bound step look
identical in wall-clock; the difference is whether the *consumer* had to
block waiting for the next host batch. :class:`StarvationProbe` measures
exactly that (plus prefetch queue depth and host assembly time), the Trainer
snapshots it per metrics lap into the telemetry stream, and the goodput
accountant reports the total as ``input_starved_s`` — see
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Callable, Iterator

import jax
from jax.sharding import Mesh

from distributeddeeplearningspark_tpu.data.feed import put_global

_SENTINEL = object()


class StarvationProbe:
    """Thread-safe counters for "how long did training wait on input?".

    Three signals, all cheap:

    - ``record_wait`` — consumer-side block: the training loop asked for the
      next batch and the prefetch ring had nothing ready. This is the
      starvation signal proper (sums into ``input_starved_s``).
    - ``record_depth`` — prefetch queue depth sampled at each consumer get;
      a ring that is persistently empty (min 0, mean ≈ 0) is input-bound,
      one that hovers full is compute-bound.
    - ``record_assembly`` — producer-side cost of building one host batch
      (decode/augment/stack), measured in the background thread; tells you
      WHY the ring ran dry.

    ``clock`` is injectable so tests measure deterministic fake seconds.
    ``snapshot(reset=True)`` returns-and-clears, giving per-lap gauges.
    """

    def __init__(self, clock=time.perf_counter):
        self.clock = clock
        self._lock = threading.Lock()
        self._zero()

    def _zero(self) -> None:
        self._wait_s = 0.0
        self._waits = 0
        self._wait_max = 0.0
        self._assembly_s = 0.0
        self._assemblies = 0
        self._depth_sum = 0
        self._depth_n = 0
        self._depth_min: int | None = None

    def record_wait(self, dt: float) -> None:
        with self._lock:
            self._wait_s += dt
            self._waits += 1
            self._wait_max = max(self._wait_max, dt)

    def record_assembly(self, dt: float) -> None:
        with self._lock:
            self._assembly_s += dt
            self._assemblies += 1

    def record_depth(self, depth: int) -> None:
        with self._lock:
            self._depth_sum += depth
            self._depth_n += 1
            self._depth_min = (depth if self._depth_min is None
                               else min(self._depth_min, depth))

    def timed(self, it, record=None) -> Iterator:
        """Wrap an iterable so each blocking ``next()`` is timed into
        ``record`` (default: :meth:`record_wait`)."""
        record = record or self.record_wait
        it = iter(it)  # accept plain iterables, same as a for-loop would
        while True:
            t0 = self.clock()
            try:
                x = next(it)
            except StopIteration:
                return
            record(self.clock() - t0)
            yield x

    def snapshot(self, *, reset: bool = True) -> dict[str, float]:
        """Gauges since the last snapshot, keyed for the telemetry record.

        When a :mod:`~distributeddeeplearningspark_tpu.data.workers` pool is
        live, the per-worker utilization/queue-depth rollup rides along
        (``input_workers``, ``worker_util_mean/min``, ``worker_items``,
        ``worker_overflow``, ``worker_ahead_mean``, ``worker_ring_used_mb``)
        so ``dlstatus`` can tell pool-bound (util ≈ 1 while the consumer
        still waits) from consumer-bound (util low, waits low) input.
        Worker utilizations are pool-lifetime fractions (pools restart per
        epoch); the wait/assembly keys stay per-lap as before.
        """
        with self._lock:
            out = {
                "input_wait_s": self._wait_s,
                "input_waits": self._waits,
                "input_wait_max_s": self._wait_max,
                "input_assembly_s": self._assembly_s,
            }
            if self._depth_n:
                out["prefetch_depth_mean"] = self._depth_sum / self._depth_n
                out["prefetch_depth_min"] = self._depth_min
            if reset:
                self._zero()
        try:
            from distributeddeeplearningspark_tpu.data import workers

            out.update(workers.pool_gauges())
        except Exception:  # noqa: BLE001 — gauges must never fail a lap
            pass
        return out


def prefetch_to_device(
    host_iter: Iterator[dict[str, Any]],
    mesh: Mesh,
    *,
    buffer_size: int = 2,
    put: Callable[[dict[str, Any], Mesh], Any] = put_global,
    background: bool = True,
    probe: StarvationProbe | None = None,
) -> Iterator[Any]:
    """Wrap a host-batch iterator into a double-buffered device iterator.

    ``buffer_size=2`` (double buffering) is enough to hide transfer latency
    when host assembly keeps up; raise it for bursty sources. ``probe``
    times the consumer-blocked fetch of each host batch and samples the
    ring's queue depth (see :class:`StarvationProbe`).
    """
    if background:
        host_iter = _background(host_iter, maxsize=buffer_size + 1,
                                probe=probe)
    if probe is not None:
        # times the blocking pull of the NEXT host batch: with background=
        # True that's the q.get() wait (assembly ran behind), without it the
        # synchronous assembly itself — either way, time training stood still
        host_iter = probe.timed(host_iter)

    buf: collections.deque = collections.deque()
    for hb in host_iter:
        buf.append(put(hb, mesh))
        if len(buf) >= buffer_size:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


def _background(it: Iterator, *, maxsize: int,
                probe: StarvationProbe | None = None) -> Iterator:
    """Run an iterator in a daemon thread through a bounded queue."""
    q: queue.Queue = queue.Queue(maxsize=maxsize)
    err: list[BaseException] = []

    def worker() -> None:
        try:
            if probe is not None:
                for x in probe.timed(it, probe.record_assembly):
                    q.put(x)
            else:
                for x in it:
                    q.put(x)
        except BaseException as e:  # propagate into consumer
            err.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=worker, daemon=True, name="dls-prefetch")
    t.start()
    while True:
        if probe is not None:
            probe.record_depth(q.qsize())
        x = q.get()
        if x is _SENTINEL:
            if err:
                raise err[0]
            return
        yield x
