"""``dlsubmit`` — the spark-submit-shaped CLI entrypoint.

The reference is launched as ``spark-submit train_script.py --conf k=v ...``
(SURVEY.md §1 L7). ``dlsubmit`` keeps that surface: it parses the same launch
flags, materializes them as session conf (so the driver script's plain
``Session.builder.getOrCreate()`` picks them up), then runs the script
in-process — there is no JVM to spawn; one OS process per TPU host *is* the
executor model, provisioned outside this CLI (GKE/TPU VM tooling), and
multi-host rendezvous is handled by ``Session.initialize_distributed`` via the
DLS_COORDINATOR env (set per host by the launcher).

Usage::

    dlsubmit [--master local[2]] [--name app] [--conf k=v ...] script.py [args...]

With ``--cluster ROOT`` the same surface submits to the shared-cluster
scheduler instead of running in-process — the spark-submit-on-YARN shape.
The script is enqueued in the ledger under the tenant/priority given and
launched by the scheduler's control loop once gang-aware placement grants
it hosts (``{workdir}``/``{ckpt}`` in the script args expand to the job's
run directory at launch)::

    dlsubmit --cluster /pool --tenant research --priority 10 --hosts 2 \\
        --min-hosts 1 train.py --ckpt-dir '{ckpt}'
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dlsubmit",
        description="Launch a driver script with session conf (spark-submit-shaped).",
    )
    p.add_argument("--master", default=None, help="local[N] | local[*] | tpu | auto")
    p.add_argument("--name", "--app-name", dest="name", default=None)
    p.add_argument(
        "--conf", action="append", default=[], metavar="KEY=VALUE",
        help="session conf entry (repeatable); spark.* keys are mapped",
    )
    p.add_argument(
        "--num-executors", type=int, default=None,
        help="alias for --conf spark.executor.instances=N",
    )
    p.add_argument(
        "--workdir", default=None,
        help="run directory: telemetry events append to <workdir>/telemetry "
             "and `dlstatus <workdir>` reads the run report",
    )
    p.add_argument(
        "--tenant", default=None,
        help="tenant this run belongs to: exported as DLS_TENANT, stamped "
             "on every telemetry record, and folded by `dlstatus --cluster` "
             "into the per-tenant goodput/occupancy rollup",
    )
    p.add_argument(
        "--priority", type=int, default=None,
        help="scheduling priority (integer, higher wins): exported as "
             "DLS_PRIORITY and stamped on every telemetry record like "
             "--tenant; under --cluster it orders the queue and arms "
             "preemption of lower-priority jobs",
    )
    p.add_argument(
        "--cluster", metavar="ROOT", default=None,
        help="submit to the shared-cluster scheduler's ledger under ROOT "
             "instead of running in-process; the control loop launches the "
             "job once placement grants it hosts",
    )
    p.add_argument(
        "--hosts", type=int, default=1,
        help="--cluster: hosts the job's gang needs (whole-or-not-at-all)",
    )
    p.add_argument(
        "--gangs", default=None, metavar="N,M,...",
        help="--cluster: multi-gang shape (e.g. MPMD stages '2,2'); "
             "overrides --hosts; every gang places whole-or-not-at-all",
    )
    p.add_argument(
        "--min-hosts", type=int, default=None,
        help="--cluster: elastic floor — preemption may shrink the job "
             "down to this many hosts (default: rigid, = total hosts)",
    )
    p.add_argument(
        "--kind", default="train", choices=["train", "serve", "mpmd",
                                            "shuffle"],
        help="--cluster: workload kind recorded in the ledger",
    )
    p.add_argument("script", help="driver script to run")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


#: env var prefix used to pass conf from dlsubmit to Session.builder defaults.
CONF_ENV_PREFIX = "DLS_CONF_"


def conf_from_env() -> dict[str, str]:
    """Conf entries exported by dlsubmit for the in-process driver script."""
    out = {}
    for k, v in os.environ.items():
        if k.startswith(CONF_ENV_PREFIX):
            out[k[len(CONF_ENV_PREFIX):].replace("__", ".")] = v
    return out


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    conf: dict[str, str] = {}
    for entry in args.conf:
        if "=" not in entry:
            raise SystemExit(f"--conf expects KEY=VALUE, got {entry!r}")
        k, _, v = entry.partition("=")
        conf[k] = v
    if args.master:
        conf["spark.master"] = args.master
    if args.name:
        conf["spark.app.name"] = args.name
    if args.num_executors is not None:
        conf["spark.executor.instances"] = str(args.num_executors)

    if not os.path.exists(args.script):
        raise SystemExit(f"dlsubmit: script not found: {args.script}")

    if args.cluster:
        # A cluster submission must not mutate the submitter's own process
        # env: conf rides in the job env in the ledger, and the runner sets
        # DLS_TENANT/DLS_PRIORITY/DLS_PREEMPT_NOTICE at launch.
        return _cluster_submit(args, conf)

    # Hand conf to the driver script through the env so its plain
    # Session.builder.getOrCreate() sees the launch configuration.
    for k, v in conf.items():
        os.environ[CONF_ENV_PREFIX + k.replace(".", "__")] = v
    if args.workdir:
        # same contract the supervisor uses: the Trainer binds its telemetry
        # stream to this dir
        from distributeddeeplearningspark_tpu import telemetry

        os.environ[telemetry.WORKDIR_ENV] = os.path.abspath(args.workdir)
    if args.tenant:
        from distributeddeeplearningspark_tpu import telemetry

        os.environ[telemetry.TENANT_ENV] = args.tenant
    if args.priority is not None:
        from distributeddeeplearningspark_tpu import telemetry

        os.environ[telemetry.PRIORITY_ENV] = str(args.priority)

    sys.argv = [args.script] + args.script_args
    runpy.run_path(args.script, run_name="__main__")
    return 0


def _cluster_submit(args: argparse.Namespace, conf: dict[str, str]) -> int:
    """Enqueue the script in the cluster ledger instead of running it.

    The submitted command re-enters the script through the same driver
    env contract (conf is carried as DLS_CONF_* entries in the job env),
    so a script that works under plain ``dlsubmit`` works unchanged when
    placed by the scheduler.
    """
    from distributeddeeplearningspark_tpu.scheduler import Scheduler

    gangs: list[int] | int
    if args.gangs:
        gangs = [int(g) for g in args.gangs.split(",") if g.strip()]
    else:
        gangs = args.hosts
    env = {CONF_ENV_PREFIX + k.replace(".", "__"): v for k, v in conf.items()}
    sched = Scheduler(os.path.abspath(args.cluster))
    try:
        job_id = sched.submit(
            [sys.executable, os.path.abspath(args.script)] + args.script_args,
            tenant=args.tenant or "default",
            priority=args.priority or 0,
            gangs=gangs,
            min_hosts=args.min_hosts,
            name=args.name or os.path.basename(args.script),
            kind=args.kind,
            env=env,
        )
    finally:
        sched.close()
    print(job_id)
    return 0


if __name__ == "__main__":
    sys.exit(main())
