"""``dlsubmit`` — the spark-submit-shaped CLI entrypoint.

The reference is launched as ``spark-submit train_script.py --conf k=v ...``
(SURVEY.md §1 L7). ``dlsubmit`` keeps that surface: it parses the same launch
flags, materializes them as session conf (so the driver script's plain
``Session.builder.getOrCreate()`` picks them up), then runs the script
in-process — there is no JVM to spawn; one OS process per TPU host *is* the
executor model, provisioned outside this CLI (GKE/TPU VM tooling), and
multi-host rendezvous is handled by ``Session.initialize_distributed`` via the
DLS_COORDINATOR env (set per host by the launcher).

Usage::

    dlsubmit [--master local[2]] [--name app] [--conf k=v ...] script.py [args...]
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dlsubmit",
        description="Launch a driver script with session conf (spark-submit-shaped).",
    )
    p.add_argument("--master", default=None, help="local[N] | local[*] | tpu | auto")
    p.add_argument("--name", "--app-name", dest="name", default=None)
    p.add_argument(
        "--conf", action="append", default=[], metavar="KEY=VALUE",
        help="session conf entry (repeatable); spark.* keys are mapped",
    )
    p.add_argument(
        "--num-executors", type=int, default=None,
        help="alias for --conf spark.executor.instances=N",
    )
    p.add_argument(
        "--workdir", default=None,
        help="run directory: telemetry events append to <workdir>/telemetry "
             "and `dlstatus <workdir>` reads the run report",
    )
    p.add_argument(
        "--tenant", default=None,
        help="tenant this run belongs to: exported as DLS_TENANT, stamped "
             "on every telemetry record, and folded by `dlstatus --cluster` "
             "into the per-tenant goodput/occupancy rollup",
    )
    p.add_argument("script", help="driver script to run")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


#: env var prefix used to pass conf from dlsubmit to Session.builder defaults.
CONF_ENV_PREFIX = "DLS_CONF_"


def conf_from_env() -> dict[str, str]:
    """Conf entries exported by dlsubmit for the in-process driver script."""
    out = {}
    for k, v in os.environ.items():
        if k.startswith(CONF_ENV_PREFIX):
            out[k[len(CONF_ENV_PREFIX):].replace("__", ".")] = v
    return out


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    conf: dict[str, str] = {}
    for entry in args.conf:
        if "=" not in entry:
            raise SystemExit(f"--conf expects KEY=VALUE, got {entry!r}")
        k, _, v = entry.partition("=")
        conf[k] = v
    if args.master:
        conf["spark.master"] = args.master
    if args.name:
        conf["spark.app.name"] = args.name
    if args.num_executors is not None:
        conf["spark.executor.instances"] = str(args.num_executors)

    # Hand conf to the driver script through the env so its plain
    # Session.builder.getOrCreate() sees the launch configuration.
    for k, v in conf.items():
        os.environ[CONF_ENV_PREFIX + k.replace(".", "__")] = v
    if args.workdir:
        # same contract the supervisor uses: the Trainer binds its telemetry
        # stream to this dir
        from distributeddeeplearningspark_tpu import telemetry

        os.environ[telemetry.WORKDIR_ENV] = os.path.abspath(args.workdir)
    if args.tenant:
        from distributeddeeplearningspark_tpu import telemetry

        os.environ[telemetry.TENANT_ENV] = args.tenant

    if not os.path.exists(args.script):
        raise SystemExit(f"dlsubmit: script not found: {args.script}")

    sys.argv = [args.script] + args.script_args
    runpy.run_path(args.script, run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
