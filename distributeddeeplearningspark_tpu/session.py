"""Session lifecycle — the SparkSession surface over a JAX device mesh.

The reference's user lifecycle (SURVEY.md §1 L6, §3.1) is::

    spark = SparkSession.builder.master("local[2]").appName("mnist").getOrCreate()
    rdd = spark.sparkContext.parallelize(data, numSlices=2)
    ... train ...
    spark.stop()

BASELINE.json's north star requires that this lifecycle "stay unchanged", so
the same builder API is kept verbatim — but ``getOrCreate`` provisions a
:class:`jax.sharding.Mesh` (and, on multi-host TPU pods, runs
``jax.distributed.initialize``) instead of spawning JVM executors. The
"executor count" maps to the number of data shards of the mesh.

Master URL forms:

- ``local[N]``  — N-way data parallelism over the first N local devices
  (the reference's 2-local-executor PR1 config is ``local[2]``);
- ``local[*]`` / ``local`` — all local devices, pure DP;
- ``tpu`` / ``auto`` — all devices with a mesh shaped by ``MeshSpec`` conf
  keys (see below); on a multi-host pod, call
  :func:`Session.initialize_distributed` first (done automatically when the
  standard TPU pod env vars are present).

Recognized ``.config()`` keys (Spark names kept where they exist):

- ``spark.executor.instances``  → data-parallel degree (mesh ``data`` axis)
- ``spark.app.name``            → app name
- ``mesh.data`` / ``mesh.fsdp`` / ``mesh.pipe`` / ``mesh.tensor`` /
  ``mesh.seq`` / ``mesh.expert`` → mesh axis sizes (one may be -1 = wildcard;
                                ``spark.executor.instances`` overrides ``mesh.data``)
- ``spark.jax.compilationCache.dir`` → persistent XLA compilation cache
                                directory for the session's lifetime
                                (restored on ``stop()``)
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Any, Iterable, Sequence

import jax

from distributeddeeplearningspark_tpu.parallel.mesh import MeshSpec, num_data_shards
from distributeddeeplearningspark_tpu.rdd import PartitionedDataset

logger = logging.getLogger("distributeddeeplearningspark_tpu")

_LOCK = threading.Lock()


class Session:
    """An active training session bound to a device mesh.

    Construct via ``Session.builder`` (SparkSession-style); direct
    construction is for tests.
    """

    _active: "Session | None" = None

    def __init__(self, app_name: str, conf: dict[str, str], mesh, spec: MeshSpec):
        self.app_name = app_name
        self.conf = dict(conf)
        self.mesh = mesh
        self.spec = spec
        self._stopped = False
        # Persistent XLA compilation cache: spark-submit-shaped jobs re-run
        # the same step graphs constantly and a TPU compile is tens of
        # seconds — the reference relies on the warm JVM across rounds, the
        # cache file plays that role here. Opt-in; prior value restored on
        # stop() so one session's job-scoped dir can't leak into the next.
        self._prev_cache_dir = None
        self._apply_cache_conf()

    def _apply_cache_conf(self) -> None:
        """Point jax at ``spark.jax.compilationCache.dir`` if configured
        (idempotent; also called when conf is merged into a live session)."""
        cache_dir = self.conf.get("spark.jax.compilationCache.dir")
        if cache_dir and jax.config.jax_compilation_cache_dir != cache_dir:
            if self._prev_cache_dir is None:
                self._prev_cache_dir = (jax.config.jax_compilation_cache_dir, )
            jax.config.update("jax_compilation_cache_dir", cache_dir)

    # -- SparkSession-shaped surface ----------------------------------------

    class Builder:
        def __init__(self) -> None:
            self._conf: dict[str, str] = {}

        def appName(self, name: str) -> "Session.Builder":
            self._conf["spark.app.name"] = name
            return self

        def master(self, master: str) -> "Session.Builder":
            self._conf["spark.master"] = master
            return self

        def config(self, key: str | None = None, value: Any = None, *, map: dict | None = None) -> "Session.Builder":
            if map is not None:
                self._conf.update({k: str(v) for k, v in map.items()})
            if key is not None:
                self._conf[key] = str(value)
            return self

        # snake_case aliases for non-Spark users
        app_name = appName

        def getOrCreate(self) -> "Session":
            from distributeddeeplearningspark_tpu.cli import conf_from_env

            with _LOCK:
                if Session._active is not None and not Session._active._stopped:
                    Session._active.conf.update(self._conf)
                    # conf merged into a live session must still take effect
                    # where it can (the cache key otherwise silently lands in
                    # .conf without ever reaching jax.config)
                    Session._active._apply_cache_conf()
                    return Session._active
                # dlsubmit launch flags arrive via env and lose to explicit
                # .config()/.master() calls in the driver script.
                conf = {**conf_from_env(), **self._conf}
                sess = _create_session(conf)
                Session._active = sess
                return sess

        get_or_create = getOrCreate

    # ``Session.builder`` must yield a fresh Builder per access, like pyspark.
    class _BuilderDescriptor:
        def __get__(self, obj, objtype=None) -> "Session.Builder":
            return Session.Builder()

    builder = _BuilderDescriptor()

    @classmethod
    def active(cls) -> "Session":
        if cls._active is None or cls._active._stopped:
            raise RuntimeError("no active Session; use Session.builder.getOrCreate()")
        return cls._active

    @classmethod
    def get_or_default(cls) -> "Session":
        """Active session, or a default all-device DP session."""
        if cls._active is not None and not cls._active._stopped:
            return cls._active
        return cls.Builder().getOrCreate()

    # -- data plane ---------------------------------------------------------

    @property
    def sparkContext(self) -> "Session":
        """The reference reaches ``parallelize`` via ``spark.sparkContext``;
        session and context are one object here, so this returns ``self``."""
        return self

    spark_context = sparkContext

    def parallelize(self, data: Sequence | Iterable, numSlices: int | None = None) -> PartitionedDataset:
        n = numSlices if numSlices is not None else self.default_parallelism
        return PartitionedDataset.parallelize(data, n)

    def range(self, n: int, numSlices: int | None = None) -> PartitionedDataset:
        return self.parallelize(range(n), numSlices)

    @property
    def read(self):
        """``spark.read`` — the DataFrame reader surface (config 4's
        feature-engineering entry point): ``spark.read.option("sep", "\\t")
        .schema([...]).csv(path)``."""
        from .data.dataframe import DataFrameReader

        return DataFrameReader(default_parallelism=self.default_parallelism)

    def createDataFrame(self, rows, numSlices: int | None = None):
        """Columnarize driver-side rows into a :class:`DataFrame`."""
        from .data.dataframe import from_rows

        n = numSlices if numSlices is not None else self.default_parallelism
        return from_rows(rows, num_partitions=n)

    create_dataframe = createDataFrame

    @property
    def default_parallelism(self) -> int:
        return num_data_shards(self.mesh)

    defaultParallelism = default_parallelism

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    # -- lifecycle ----------------------------------------------------------

    def stop(self) -> None:
        self._stopped = True
        if self._prev_cache_dir is not None:
            jax.config.update("jax_compilation_cache_dir", self._prev_cache_dir[0])
            self._prev_cache_dir = None
        if Session._active is self:
            Session._active = None

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def __repr__(self) -> str:
        return (
            f"Session(app={self.app_name!r}, devices={self.num_devices}, "
            f"mesh={dict(self.mesh.shape)})"
        )

    # -- multi-host ---------------------------------------------------------

    _distributed_initialized = False

    @classmethod
    def initialize_distributed(
        cls,
        coordinator_address: str | None = None,
        num_processes: int | None = None,
        process_id: int | None = None,
    ) -> None:
        """Join the multi-host coordination service (Spark driver↔executor RPC
        control plane ≙ jax.distributed's coordinator; SURVEY.md §5)."""
        if cls._distributed_initialized:
            return
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        cls._distributed_initialized = True
        atexit.register(jax.distributed.shutdown)


def _local_n(master: str | None) -> int | None:
    """N from 'local[N]' master URLs; None for wildcard/other forms."""
    if master and master.startswith("local[") and master.endswith("]"):
        inner = master[len("local["):-1]
        if inner.isdigit():
            return int(inner)
    return None


def _parse_master(master: str | None, conf: dict[str, str]) -> tuple[list[jax.Device] | None, MeshSpec]:
    """Resolve a master URL + conf into (device subset, MeshSpec)."""
    fsdp = int(conf.get("mesh.fsdp", 1))
    pipe = int(conf.get("mesh.pipe", 1))
    tensor = int(conf.get("mesh.tensor", 1))
    seq = int(conf.get("mesh.seq", 1))
    expert = int(conf.get("mesh.expert", 1))
    executors = conf.get("spark.executor.instances")

    devices: list[jax.Device] | None = None
    data: int = -1

    if master is None or master in ("auto", "tpu", "local[*]", "local"):
        pass
    elif _local_n(master) is not None:
        n = _local_n(master)
        # a -1 (wildcard) axis contributes ×1 here: local[N] then means "N
        # workers total", and the wildcard axis absorbs them in MeshSpec
        n_dev = (n * max(fsdp, 1) * max(pipe, 1) * max(tensor, 1)
                 * max(seq, 1) * max(expert, 1))
        all_dev = jax.devices()
        if n_dev > len(all_dev):
            raise ValueError(
                f"master {master!r} needs {n_dev} devices, only {len(all_dev)} available"
            )
        devices = all_dev[:n_dev]
        data = n
    else:
        raise ValueError(f"unrecognized master URL: {master!r}")

    if "mesh.data" in conf:
        # explicit data-axis size; lets another axis (e.g. mesh.fsdp=-1) be
        # the wildcard for FSDP-dominant layouts like config 5
        data = int(conf["mesh.data"])
    if executors is not None:
        data = int(executors)
        if devices is None:
            n_dev = (data * max(fsdp, 1) * max(pipe, 1) * max(tensor, 1)
                     * max(seq, 1) * max(expert, 1))
            all_dev = jax.devices()
            if n_dev > len(all_dev):
                raise ValueError(
                    f"spark.executor.instances={data} needs {n_dev} devices, "
                    f"only {len(all_dev)} available"
                )
            devices = all_dev[:n_dev]

    spec = MeshSpec(data=data, fsdp=fsdp, pipe=pipe, tensor=tensor, seq=seq, expert=expert)
    return devices, spec


def _create_session(conf: dict[str, str]) -> Session:
    from distributeddeeplearningspark_tpu.utils.env import apply_env_platform_config

    # Env platform intent (JAX_PLATFORMS / XLA_FLAGS) can be pre-empted by
    # site-level PJRT plugin registration; re-assert it while it still can win.
    apply_env_platform_config(min_cpu_devices=_local_n(conf.get("spark.master")))
    # Auto-join a pod if the driver environment provides coordination info.
    if os.environ.get("DLS_COORDINATOR") and not Session._distributed_initialized:
        Session.initialize_distributed(
            coordinator_address=os.environ["DLS_COORDINATOR"],
            num_processes=int(os.environ.get("DLS_NUM_PROCESSES", "1")),
            process_id=int(os.environ.get("DLS_PROCESS_ID", "0")),
        )
    master = conf.get("spark.master")
    devices, spec = _parse_master(master, conf)
    mesh = spec.build(devices)
    app = conf.get("spark.app.name", "dls-tpu")
    sess = Session(app, conf, mesh, spec)
    logger.info("session %s: mesh %s over %d %s device(s)", app, dict(mesh.shape),
                mesh.devices.size, mesh.devices.flat[0].platform)
    return sess
