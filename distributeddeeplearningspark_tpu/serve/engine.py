"""Dynamic micro-batching request engine — the serving hot loop.

The training side's throughput lever is one jitted SPMD step over a large
batch; serving gets the same lever by *coalescing*: concurrent requests
wait in a queue for at most ``max_wait_ms`` (or until ``max_batch`` are
waiting), are stacked into one host batch, padded up to a fixed **bucket**
size, and run through a single jit-compiled forward. Two properties carry
the whole design:

- **Bounded compile set.** XLA compiles one program per input shape, and a
  recompile mid-traffic is a multi-second stall. Batches therefore never
  run at their natural size: they pad to the smallest member of a fixed
  ladder of bucket sizes (powers of two up to ``max_batch`` by default),
  so steady-state traffic reuses a handful of compiled programs no matter
  how request counts fluctuate. ``stats()["compiled_batch_shapes"]``
  exposes the jit cache size so tests (and operators) can pin this.
- **Params are an argument, not a constant.** The forward is jitted as
  ``f(params, batch)``; hot-reload (:mod:`.reload`) swaps the param tree
  between batches without touching the compiled program, and the batch
  already in flight keeps the params it was dispatched with (jax arrays
  are immutable) — zero dropped requests across a swap.

Admission control is a bounded queue: when ``max_queue`` requests are
already waiting, :meth:`InferenceEngine.submit` fails fast with a typed
:class:`OverloadedError` (the backpressure contract — docs/SERVING.md)
instead of letting latency grow without bound. Every request leaves a
``request`` telemetry event; ``dlstatus`` rolls them into p50/p99.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import numpy as np

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.telemetry import anatomy as anatomy_lib
from distributeddeeplearningspark_tpu.telemetry import trace as trace_lib

logger = logging.getLogger("distributeddeeplearningspark_tpu.serve")


class OverloadedError(RuntimeError):
    """Load-shed rejection: the admission queue is full.

    Typed (not a bare RuntimeError) so callers can branch on it — retry
    with backoff, spill to another replica, or return HTTP 429 — and
    carries the queue evidence for the decision."""

    def __init__(self, queue_depth: int, max_queue: int):
        super().__init__(
            f"engine overloaded: {queue_depth} requests already queued "
            f"(max_queue={max_queue}) — shed, retry with backoff")
        self.queue_depth = queue_depth
        self.max_queue = max_queue


class EngineStoppedError(RuntimeError):
    """The engine is not accepting requests (stopped or never started)."""


@dataclass
class _Request:
    rid: int
    example: dict[str, np.ndarray]
    future: Future = field(default_factory=Future)
    t_submit: float = 0.0
    ts_submit: float = 0.0                 # wall-clock twin (span t0)
    trace: dict | None = None              # upstream trace context


def default_buckets(max_batch: int, *, multiple_of: int = 1) -> tuple[int, ...]:
    """The bucket ladder: powers of two up to ``max_batch``, each rounded up
    to ``multiple_of`` (the mesh's data-shard count — GSPMD needs the batch
    to divide evenly), deduplicated and capped at ``max_batch``."""
    sizes: set[int] = set()
    b = 1
    while b < max_batch:
        sizes.add(min(max_batch, -(-b // multiple_of) * multiple_of))
        b *= 2
    sizes.add(max_batch)
    return tuple(sorted(sizes))


class InferenceEngine:
    """Coalesce concurrent single-example requests into jitted batches.

    Parameters
    ----------
    forward:
        ``(params, batch) -> outputs`` — the raw forward (this class jits
        it). ``batch`` is a dict of stacked arrays; outputs may be any
        pytree whose leaves have a leading batch axis (rows are split back
        per request). Use :meth:`for_model` for the flax-module common case.
    params:
        The parameter pytree passed as the forward's first argument. Kept
        swappable (:meth:`swap_params`) for checkpoint hot-reload.
    mesh:
        Optional :class:`jax.sharding.Mesh`: batches are placed with the
        training feed's batch sharding (``put_global``) so the same GSPMD
        layout that trains the model serves it. ``None`` = default device.
    max_batch / max_wait_ms:
        Coalescing knobs: a batch dispatches when ``max_batch`` requests
        are waiting or the oldest has waited ``max_wait_ms``, whichever
        comes first (a lone request never waits longer than the deadline).
    max_queue:
        Admission bound: requests beyond this many waiting are shed with
        :class:`OverloadedError`.
    batch_sizes:
        Explicit bucket ladder; defaults to :func:`default_buckets`.
    workdir:
        When set, binds the process-wide telemetry stream here and emits
        one ``request`` event per request into it. When unset the engine
        is telemetry-silent — deliberate, so a side-by-side comparison
        engine (dlserve --compare-sequential) can share a process without
        blending its events into the run's serving rollup.
    """

    def __init__(
        self,
        forward: Callable[[Any, dict[str, Any]], Any],
        params: Any,
        *,
        mesh=None,
        max_batch: int = 32,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        batch_sizes: Sequence[int] | None = None,
        workdir: str | None = None,
        name: str = "engine",
    ):
        import jax

        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._jax = jax
        self.mesh = mesh
        self.name = name
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue = int(max_queue)
        shards = 1
        if mesh is not None:
            from distributeddeeplearningspark_tpu.parallel.mesh import (
                num_data_shards,
            )

            shards = num_data_shards(mesh)
            if self.max_batch % shards:
                raise ValueError(
                    f"max_batch {max_batch} must divide by the mesh's "
                    f"{shards} data shards")
        self.batch_sizes = tuple(sorted(
            batch_sizes if batch_sizes is not None
            else default_buckets(self.max_batch, multiple_of=shards)))
        if self.batch_sizes[-1] < self.max_batch:
            raise ValueError(
                f"largest bucket {self.batch_sizes[-1]} is smaller than "
                f"max_batch {self.max_batch} — a full batch would have no "
                f"shape to run at")
        self._tele = telemetry.configure(workdir) if workdir else None

        def _engine_forward(params, batch):
            # a fresh closure per engine: jax shares the jit cache between
            # wrappers of the SAME function object, so two engines over one
            # forward would otherwise count (and share) each other's
            # compiles — stats()["compiled_batch_shapes"] must be this
            # engine's own compile set
            return forward(params, batch)

        # the compile ledger (telemetry/anatomy.py) owns this forward's
        # lower→compile path: every bucket compile — warmup's precompiles
        # included — emits a `compile` phase span plus a cost-analyzed
        # `compile` event, so goodput and the request traces account
        # warmup/bucket-miss seconds instead of silently misattributing
        # them; any compile beyond the pinned bucket ladder flags as a
        # recompile in `dlstatus --anatomy`
        self._forward = anatomy_lib.instrument(
            jax.jit(_engine_forward), name=f"serve-{name}",
            expected_signatures=len(self.batch_sizes))
        self._params = params
        self.params_version: int | str = 0
        self._queue: list[_Request] = []
        self._cond = threading.Condition()
        # accepting from construction (requests queue up; nothing runs until
        # start() spawns the worker — lets callers pre-fill deterministically)
        self._stopped = False
        self._thread: threading.Thread | None = None
        self._rid = itertools.count()
        self._stats = {"requests": 0, "shed": 0, "errors": 0, "batches": 0,
                       "rows": 0, "reloads": 0}
        self._bucket_counts: dict[int, int] = {}
        self._last_hb = 0.0
        self.heartbeat_interval_s = 1.0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "InferenceEngine":
        with self._cond:
            if self._thread is not None:
                return self
            self._stopped = False
            self._thread = threading.Thread(
                target=self._loop, name=f"dlserve-{self.name}", daemon=True)
            self._thread.start()
        return self

    def stop(self, *, drain: bool = True) -> None:
        """Stop accepting requests; by default finish everything queued.

        ``drain=False`` fails queued (not yet dispatched) requests with
        :class:`EngineStoppedError` instead of running them. A never-
        started engine with queued requests starts its worker just to
        drain them — drain=True must never strand a future unresolved."""
        if drain and self._thread is None and self._queue:
            self.start()
        with self._cond:
            if self._stopped and self._thread is None:
                return
            self._stopped = True
            if not drain:
                for req in self._queue:
                    req.future.set_exception(
                        EngineStoppedError("engine stopped before dispatch"))
                    if self._tele is not None:
                        self._tele.clear_span(("req", req.rid))
                self._queue.clear()
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join()
        self._thread = None

    def __enter__(self) -> "InferenceEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client surface ------------------------------------------------------

    def submit(self, example: dict[str, Any], *,
               trace: dict | None = None) -> Future:
        """Enqueue one example; returns a Future resolving to its output row.

        ``trace`` is an upstream trace context (``{"trace_id",
        "parent_id"}`` — the router hands it across the replica socket);
        the request's ``queue``/``infer`` stage spans then join that trace.
        Without one (and with a workdir bound) the engine roots a fresh
        trace per request, so a bare engine is traceable too.

        Raises :class:`OverloadedError` immediately when the queue is full
        (load shed — the caller owns the retry policy) and
        :class:`EngineStoppedError` when the engine isn't running."""
        req = _Request(rid=next(self._rid),
                       example={k: np.asarray(v) for k, v in example.items()},
                       trace=(trace if isinstance(trace, dict)
                              and trace.get("trace_id") else None))
        req.t_submit = time.monotonic()
        req.ts_submit = time.time()
        with self._cond:
            if self._stopped:
                raise EngineStoppedError("engine is stopped")
            if len(self._queue) >= self.max_queue:
                self._stats["shed"] += 1
                if self._tele is not None:
                    self._tele.emit("request", engine=self.name, id=req.rid,
                                    outcome="shed",
                                    queue_depth=len(self._queue),
                                    **({"trace": req.trace["trace_id"]}
                                       if req.trace else {}))
                raise OverloadedError(len(self._queue), self.max_queue)
            self._queue.append(req)
            self._stats["requests"] += 1
            if self._tele is not None:
                # liveness note only (no write): heartbeats name the
                # oldest in-flight request so a wedged batch localizes
                # like a wedged restore. MUST happen under the lock —
                # once it drops, the dispatcher can complete the request
                # and clear_span BEFORE a late note re-inserts it, which
                # would leave a forever-open "request" on every heartbeat
                self._tele.note_span(("req", req.rid), "request")
            self._cond.notify_all()
        return req.future

    def infer(self, example: dict[str, Any], *, timeout: float | None = 30.0):
        """Blocking convenience: ``submit`` + ``result``."""
        return self.submit(example).result(timeout=timeout)

    def warmup(self, example: dict[str, Any]) -> int:
        """Compile every batch bucket up front (returns bucket count).

        XLA compiles lazily per shape; without this the first request to
        hit each bucket pays a multi-second stall *inside its latency*.
        Serving processes should warm at startup — bucket ladder compiles
        are a deploy cost, not a request cost. ``example`` is one request
        payload (row 0 is broadcast to every bucket size)."""
        row = {k: np.asarray(v)[None] for k, v in example.items()}
        for b in self.batch_sizes:
            batch = {k: np.repeat(v, b, axis=0) for k, v in row.items()}
            self._jax.block_until_ready(
                self._forward(self._params, self._place(batch)))
        return len(self.batch_sizes)

    def stats(self) -> dict[str, Any]:
        with self._cond:
            out = dict(self._stats)
            out["queue_depth"] = len(self._queue)
            out["bucket_counts"] = dict(self._bucket_counts)
        out["params_version"] = self.params_version
        try:
            out["compiled_batch_shapes"] = self._forward._cache_size()
        except Exception:  # jit cache introspection is best-effort
            out["compiled_batch_shapes"] = None
        return out

    # -- hot reload ----------------------------------------------------------

    def swap_params(self, params: Any, *, version: int | str | None = None) -> None:
        """Replace the serving params between batches (checkpoint hot-reload).

        The swap is a reference assignment under the queue lock: the worker
        reads ``self._params`` once per batch, so a batch already dispatched
        finishes on the params it started with and the next batch picks up
        the new tree — no request is ever dropped or torn across trees.
        When the current params are sharded jax arrays, the new tree is
        placed with the same shardings (serving topology preserved)."""
        jax = self._jax
        old = self._params
        try:
            shardings = jax.tree.map(lambda a: a.sharding, old)
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, s), params, shardings)
        except (AttributeError, ValueError, TypeError):
            # host-side / mismatched trees: let the jit placement handle it
            pass
        with self._cond:
            self._params = params
            self._stats["reloads"] += 1
            if version is not None:
                self.params_version = version
            elif isinstance(self.params_version, int):
                self.params_version += 1

    def export_params(self) -> tuple[Any, int | str]:
        """Host-side snapshot of the serving params, ``(tree, version)``.

        The peer-warm-up export: a relaunched replica imports this via
        :meth:`swap_params` instead of walking back to the checkpoint
        directory. Snapshot taken under the queue lock so the tree and its
        version are from the same swap; leaves come back as numpy (they
        must cross a process boundary by pickle)."""
        with self._cond:
            params = self._params
            version = self.params_version
        jax = self._jax
        return jax.tree.map(lambda a: np.asarray(jax.device_get(a)),
                            params), version

    # -- worker --------------------------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self.batch_sizes:
            if b >= n:
                return b
        return self.batch_sizes[-1]

    def _collect(self) -> tuple[list[_Request], Any] | None:
        """Block until a batch is ready (coalescing window) or engine stops.

        Returns (requests, params) — params snapshotted under the same lock
        acquisition that claims the requests, so one batch = one tree."""
        with self._cond:
            while not self._queue:
                if self._stopped:
                    return None
                self._cond.wait(0.1)
            deadline = self._queue[0].t_submit + self.max_wait_s
            while (len(self._queue) < self.max_batch
                   and not self._stopped):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch = self._queue[:self.max_batch]
            del self._queue[:self.max_batch]
            return batch, self._params

    def _place(self, batch: dict[str, np.ndarray]):
        if self.mesh is not None:
            from distributeddeeplearningspark_tpu.data.feed import put_global

            return put_global(batch, self.mesh)
        return batch  # jit's default placement

    def _maybe_heartbeat(self) -> None:
        """A liveness stamp per batch (rate-limited): its open-span
        enrichment is what lets a replica wedged INSIDE a forward be
        localized — the heartbeat before the dispatch is the stream's
        last record, and it names the oldest in-flight request."""
        if self._tele is None:
            return
        now = time.monotonic()
        if now - self._last_hb < self.heartbeat_interval_s:
            return
        self._last_hb = now
        self._tele.heartbeat()

    def _emit_spans(self, reqs: list[_Request], wts0: float, wts1: float,
                    *, n: int, bucket: int | None, outcome: str,
                    error: str | None = None) -> None:
        """The per-request span trees of one batch, ONE emit_many flush:
        ``queue`` (submit → batch collect) + ``infer`` (the jitted
        forward), children of the upstream trace context when the request
        carried one (router/fleet path) or of a fresh per-request root
        span otherwise."""
        if self._tele is None:
            return
        recs: list[dict] = []
        for r in reqs:
            buf = trace_lib.SpanBuffer.from_context(r.trace)
            parent = buf.parent_id
            if not buf.joined:
                parent = buf.add("request", r.ts_submit, wts1,
                                 engine=self.name, outcome=outcome,
                                 **({"error": error} if error else {}))
            # queue starts at the ROUTER's accept time when the context
            # carries one: socket transit + dispatch bookkeeping are
            # queueing from the request's point of view, not lost coverage
            buf.add("queue", trace_lib.SpanBuffer.upstream_t0(
                r.trace, r.ts_submit), wts0, parent_id=parent)
            buf.add("infer", wts0, wts1, parent_id=parent,
                    batch_size=n,
                    **({"bucket": bucket} if bucket is not None else {}),
                    **({"error": error} if error else {}))
            recs.extend(buf.records)
        self._tele.emit_many("span", recs)

    def _loop(self) -> None:
        jax = self._jax
        while True:
            got = self._collect()
            if got is None:
                return
            reqs, params = got
            n = len(reqs)
            bucket = self._bucket(n)
            self._maybe_heartbeat()
            t0 = time.monotonic()
            wts0 = time.time()
            try:
                stacked = {
                    k: np.stack([r.example[k] for r in reqs])
                    for k in reqs[0].example
                }
                if bucket > n:
                    # pad rows are copies of row 0: shape-stable, numerics
                    # can't overflow, and the rows are sliced off below
                    stacked = {
                        k: np.concatenate(
                            [v, np.repeat(v[:1], bucket - n, axis=0)])
                        for k, v in stacked.items()
                    }
                out = self._forward(params, self._place(stacked))
                host = jax.device_get(out)
                infer_s = time.monotonic() - t0
            except Exception as e:  # noqa: BLE001 — one bad batch must not
                # kill the serving loop; every member learns the real error
                logger.exception("serve batch failed (%d requests)", n)
                for r in reqs:
                    if not r.future.set_running_or_notify_cancel():
                        continue
                    r.future.set_exception(e)
                with self._cond:
                    self._stats["errors"] += n
                # one event PER request (the schema dlstatus counts by),
                # not one per batch — an error's blast radius is its batch
                if self._tele is not None:
                    err = f"{type(e).__name__}: {e}"
                    self._tele.emit_many("request", [
                        dict(engine=self.name, id=r.rid, outcome="error",
                             batch_size=n, error=err,
                             **({"trace": r.trace["trace_id"]}
                                if r.trace else {}))
                        for r in reqs])
                    self._emit_spans(reqs, wts0, time.time(), n=n,
                                     bucket=bucket, outcome="error",
                                     error=err)
                    for r in reqs:
                        self._tele.clear_span(("req", r.rid))
                continue
            done_ts = time.monotonic()
            with self._cond:
                self._stats["batches"] += 1
                self._stats["rows"] += n
                self._bucket_counts[bucket] = (
                    self._bucket_counts.get(bucket, 0) + 1)
            # results first (clients unblock and overlap the reporting),
            # then ONE batched telemetry append for the whole batch
            for i, r in enumerate(reqs):
                if not r.future.set_running_or_notify_cancel():
                    continue  # caller cancelled while queued
                r.future.set_result(jax.tree.map(lambda a: a[i], host))
            if self._tele is not None:
                self._tele.emit_many("request", [
                    dict(engine=self.name, id=r.rid, outcome="ok",
                         queue_wait_s=round(t0 - r.t_submit, 6),
                         infer_s=round(infer_s, 6),
                         latency_s=round(done_ts - r.t_submit, 6),
                         batch_size=n, bucket=bucket,
                         **({"trace": r.trace["trace_id"]}
                            if r.trace else {}))
                    for r in reqs])
                self._emit_spans(reqs, wts0, time.time(), n=n,
                                 bucket=bucket, outcome="ok")
                for r in reqs:
                    self._tele.clear_span(("req", r.rid))

    # -- construction helpers ------------------------------------------------

    @classmethod
    def for_model(cls, model, variables: dict[str, Any], **kw) -> "InferenceEngine":
        """Engine over a flax module's inference forward.

        ``variables`` is the full variable dict (``{"params": ...}`` plus
        any mutable collections like ``batch_stats``) — the whole tree is
        the swappable unit, so a hot-reload can refresh running statistics
        along with the weights."""

        def forward(variables, batch):
            return model.apply(variables, batch, train=False)

        return cls(forward, variables, **kw)
