"""Request router — queue-depth/latency-aware dispatch over engine replicas.

One :class:`~.engine.InferenceEngine` is one process on one host; "heavy
traffic from millions of users" is N of them behind a dispatcher. The
router is deliberately thin — replicas already own batching, admission,
and telemetry — and adds exactly three policies:

- **Placement by least expected wait.** Each request goes to the live,
  non-draining replica minimizing ``(outstanding + 1) × recent_p99`` —
  outstanding counts requests this router dispatched and not yet resolved
  (its own queue-depth view, no stats round-trip on the hot path), and
  recent p99 is folded from the last ``p99_window`` completions. A replica
  that slows down (compile stall, noisy neighbor, dying host) organically
  sheds load to its peers *before* any health check fires.
- **Per-tenant load-shed budgets.** Global admission control (each
  replica's ``max_queue``) cannot stop one tenant from starving the rest.
  Each tenant gets an outstanding-request budget (``tenant_budgets`` /
  ``default_tenant_budget``); beyond it the router sheds with the same
  typed :class:`~.engine.OverloadedError` contract the engine uses, and
  emits a ``request`` telemetry event (``outcome="shed"``, with the
  tenant) so ``dlstatus`` accounting stays exact.
- **Routing around failure.** A replica whose transport dies mid-request
  fails over: the request is re-dispatched once to the surviving replicas
  (inference is idempotent — retrying cannot double-apply anything), and
  the dead replica stops being a candidate until the fleet restarts it.

Draining (``drain``/``undrain``) is the rolling-hot-reload primitive
(:meth:`~.fleet.ServingFleet.rolling_reload`): a draining replica gets no
new requests but keeps its in-flight ones, so a fleet of N reloads one at
a time with N−1 always serving.

Replica handles only need the small protocol of
:class:`~.fleet.LocalReplica` / :class:`~.fleet.ReplicaHandle`:
``submit(payload, op) -> Future``, ``alive``, ``name``.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.serve.engine import OverloadedError
from distributeddeeplearningspark_tpu.telemetry import trace as trace_lib
from distributeddeeplearningspark_tpu.telemetry.fleet import _percentile

logger = logging.getLogger("distributeddeeplearningspark_tpu.serve")


class NoReplicaError(RuntimeError):
    """No live, non-draining replica to dispatch to."""


class ReplicaDiedError(RuntimeError):
    """The replica's process/transport died with this request in flight.

    The router retries such requests on a surviving replica; this escapes
    to the caller only when every candidate died."""


class Router:
    """Dispatch requests across replicas; see the module docstring.

    Parameters
    ----------
    replicas:
        Handles implementing ``submit(payload, op) -> Future`` / ``alive``
        / ``name`` — in-process :class:`~.fleet.LocalReplica` adapters or
        :class:`~.fleet.ReplicaHandle` process clients, freely mixed.
    default_tenant_budget:
        Max outstanding requests per tenant (None = unlimited). Overridden
        per tenant by ``tenant_budgets``.
    p99_window:
        Completions per replica folded into the recent-p99 estimate.
    workdir:
        Emit ``request`` shed events for tenant-budget rejections into this
        run directory (replica-side outcomes are emitted by the replicas
        themselves — the router never double-counts them). The router
        writes as a dedicated non-host process (``events-router.jsonl``,
        ``host=None``) so its stream never collides with replica 0's and
        stays out of the host table, like the supervisor's.
    """

    def __init__(
        self,
        replicas: list,
        *,
        default_tenant_budget: int | None = None,
        tenant_budgets: dict[str, int] | None = None,
        p99_window: int = 128,
        workdir: str | None = None,
        name: str = "router",
    ):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.name = name
        self._replicas: dict[str, Any] = {r.name: r for r in replicas}
        if len(self._replicas) != len(replicas):
            raise ValueError("replica names must be unique")
        self.default_tenant_budget = default_tenant_budget
        self.tenant_budgets = dict(tenant_budgets or {})
        self.p99_window = int(p99_window)
        self._tele = (telemetry.EventWriter(workdir, process=name, host=None)
                      if workdir else None)
        self._lock = threading.Lock()
        self._outstanding: dict[str, int] = {n: 0 for n in self._replicas}
        self._lat: dict[str, deque] = {
            n: deque(maxlen=self.p99_window) for n in self._replicas}
        self._draining: set[str] = set()
        self._rid = 0
        self._stats = {"dispatched": 0, "completed": 0, "shed_tenant": 0,
                       "failovers": 0, "errors": 0}
        self._dispatched_to: dict[str, int] = {n: 0 for n in self._replicas}
        self._tenant_out: dict[str, int] = {}

    # -- replica set ---------------------------------------------------------

    def replace(self, replica) -> None:
        """Swap in a (re)started replica under an existing name — the
        fleet's restart path. Outstanding counts reset (the old process's
        in-flight work died with it and was failed over already)."""
        with self._lock:
            self._replicas[replica.name] = replica
            self._outstanding[replica.name] = 0
            self._lat.setdefault(replica.name,
                                 deque(maxlen=self.p99_window))
            self._dispatched_to.setdefault(replica.name, 0)

    def drain(self, name: str) -> None:
        """Stop dispatching to ``name`` (in-flight requests unaffected)."""
        with self._lock:
            if name not in self._replicas:
                raise KeyError(name)
            if len(self._candidates_locked()) <= 1 \
                    and name not in self._draining:
                raise RuntimeError(
                    f"draining {name!r} would leave zero serving replicas")
            self._draining.add(name)

    def undrain(self, name: str) -> None:
        with self._lock:
            self._draining.discard(name)

    def inflight(self, name: str) -> int:
        """Requests dispatched to ``name`` and not yet resolved."""
        with self._lock:
            return self._outstanding.get(name, 0)

    def _candidates_locked(self) -> list[str]:
        return [n for n, r in self._replicas.items()
                if r.alive and n not in self._draining]

    # -- placement -----------------------------------------------------------

    def _recent_p99_locked(self, name: str) -> float:
        lat = self._lat[name]
        if not lat:
            return 1e-3  # optimistic prior: a cold replica attracts load
        return _percentile(sorted(lat), 0.99)

    def _pick(self, exclude: set[str]) -> str:
        with self._lock:
            cands = [n for n in self._candidates_locked()
                     if n not in exclude]
            if not cands:
                raise NoReplicaError(
                    f"no live replica (draining={sorted(self._draining)}, "
                    f"excluded={sorted(exclude)})")
            # least expected wait: queue depth × per-request latency
            name = min(cands, key=lambda n: (
                (self._outstanding[n] + 1) * self._recent_p99_locked(n)))
            self._outstanding[name] += 1
            self._dispatched_to[name] += 1
            self._stats["dispatched"] += 1
            return name

    # -- dispatch ------------------------------------------------------------

    def submit(self, payload: dict[str, Any], *, op: str = "infer",
               tenant: str = "default") -> Future:
        """Route one request; Future resolves to the replica's result.

        ``op`` is the replica-side operation (``"infer"`` for engine
        replicas, ``"generate"`` for continuous-decode replicas); payload
        fields are the op's kwargs. Raises :class:`~.engine.OverloadedError`
        when the tenant's budget is spent (the typed shed contract) and
        :class:`NoReplicaError` when nothing can serve.

        With a workdir bound the router is the **trace root**: it mints
        the request's ``trace_id``, stamps the trace context
        (``{"trace_id", "parent_id"}``) into the payload so the replica's
        stage spans join the same tree across the socket, and at
        resolution emits the root ``request`` span (tenant/outcome/hops)
        plus its own ``place``/``failover`` children. Tenant-budget sheds
        are rejected before any dispatch and carry no trace — their
        evidence is the ``request`` event."""
        budget = self.tenant_budgets.get(tenant, self.default_tenant_budget)
        with self._lock:
            out = self._tenant_out.get(tenant, 0)
            if budget is not None and out >= budget:
                self._stats["shed_tenant"] += 1
                if self._tele is not None:
                    self._tele.emit("request", engine=self.name,
                                    outcome="shed", tenant=tenant,
                                    queue_depth=out)
                raise OverloadedError(out, budget)
            self._tenant_out[tenant] = out + 1
            self._rid += 1
        fut: Future = Future()
        t0 = time.monotonic()
        ctx = None
        if self._tele is not None:
            ctx = {"buf": trace_lib.SpanBuffer(),
                   "root_sid": trace_lib.new_span_id(),
                   "ts0": time.time(), "hops": 0, "tenant": tenant,
                   "done": False}
        try:
            self._dispatch(payload, op, tenant, t0, fut, set(), ctx)
        except BaseException as e:
            with self._lock:
                self._tenant_out[tenant] -= 1
            self._finish_trace(ctx, "error", error=f"{type(e).__name__}: {e}")
            raise
        return fut

    def _finish_trace(self, ctx, outcome: str, error: str | None = None,
                      end_ts: float | None = None) -> None:
        """Close the request's root span and flush the router's whole
        span buffer (root + place/failover children) in ONE emit_many.
        ``end_ts`` lets the caller share ONE timestamp between the last
        stage span and the root's close — two adjacent ``time.time()``
        calls can drift ms apart under GIL contention, and that drift
        would read as unexplained latency in the anatomy's coverage."""
        if ctx is None or ctx["done"] or self._tele is None:
            return
        ctx["done"] = True
        buf = ctx["buf"]
        buf.add("request", ctx["ts0"],
                time.time() if end_ts is None else end_ts,
                span_id=ctx["root_sid"], engine=self.name,
                tenant=ctx["tenant"], outcome=outcome, hops=ctx["hops"],
                **({"error": error} if error else {}))
        buf.flush(self._tele)

    def _dispatch(self, payload, op, tenant, t0, fut: Future,
                  tried: set[str], ctx=None) -> None:
        tp0 = time.time() if ctx is not None else 0.0
        name = self._pick(tried)
        if ctx is not None:
            # t0 = when the ROUTER accepted the request: the replica's
            # queue span starts there, so socket transit + dispatch
            # bookkeeping are accounted as queueing, not lost coverage
            payload = {**payload,
                       "trace": {**ctx["buf"].context(ctx["root_sid"]),
                                 "t0": ctx["ts0"]}}
        try:
            inner = self._replicas[name].submit(payload, op)
        except Exception as e:  # noqa: BLE001 — a handle that can't even
            # accept the request counts as a dead dispatch: fail over
            self._settle(name, None, t0)
            self._failover(payload, op, tenant, t0, fut, tried | {name}, e,
                           ctx, failed=name)
            return
        if ctx is not None:
            ctx["buf"].add("place", tp0, time.time(),
                           parent_id=ctx["root_sid"], replica=name)
        inner.add_done_callback(
            lambda f: self._on_done(f, name, payload, op, tenant, t0, fut,
                                    tried, ctx))

    def _failover(self, payload, op, tenant, t0, fut, tried, exc,
                  ctx=None, failed: str | None = None) -> None:
        with self._lock:
            self._stats["failovers"] += 1
        if ctx is not None:
            ctx["hops"] += 1
            now = time.time()
            # a point span marking the hop: the re-dispatch's own `place`
            # child carries where the request went next
            ctx["buf"].add("failover", now, now, parent_id=ctx["root_sid"],
                           from_replica=failed,
                           error=f"{type(exc).__name__}: {exc}")
        logger.warning("router: replica failed mid-request (%s); "
                       "failing over", exc)
        try:
            self._dispatch(payload, op, tenant, t0, fut, tried, ctx)
        except NoReplicaError:
            self._settle(None, tenant, t0)
            # every replica refused: when the refusal was the typed shed
            # (in-process engines raise OverloadedError from submit), the
            # root must say shed — overload reads as capacity, not a bug
            self._finish_trace(ctx,
                               "shed" if isinstance(exc, OverloadedError)
                               else "error",
                               error=f"{type(exc).__name__}: {exc}")
            fut.set_exception(exc)

    def _settle(self, name: str | None, tenant: str | None, t0: float,
                latency: float | None = None) -> None:
        with self._lock:
            if name is not None:
                # floor at 0: replace() resets a restarted replica's count
                # while the dead process's futures may still be settling
                # on the reader thread — going negative would make
                # (outstanding+1)×p99 vanish and magnetize all traffic
                self._outstanding[name] = max(0, self._outstanding[name] - 1)
                if latency is not None:
                    self._lat[name].append(latency)
            if tenant is not None:
                self._tenant_out[tenant] -= 1

    def _on_done(self, inner: Future, name, payload, op, tenant, t0,
                 fut: Future, tried: set[str], ctx=None) -> None:
        exc = inner.exception()
        if isinstance(exc, ReplicaDiedError):
            # the replica died with this request in flight: inference is
            # idempotent, so retry once per surviving replica
            self._settle(name, None, t0)
            self._failover(payload, op, tenant, t0, fut, tried | {name},
                           exc, ctx, failed=name)
            return
        self._settle(name, tenant, t0,
                     latency=(time.monotonic() - t0) if exc is None else None)
        with self._lock:
            self._stats["completed" if exc is None else "errors"] += 1
        if exc is not None:
            # a replica-side OverloadedError is the typed shed contract,
            # not a failure: the tenant folds (serving_fleet, slo_report)
            # branch on shed vs error, and overload must read as capacity
            self._finish_trace(ctx,
                               "shed" if isinstance(exc, OverloadedError)
                               else "error",
                               error=f"{type(exc).__name__}: {exc}")
            fut.set_exception(exc)
        else:
            now = None
            if ctx is not None:
                # return hop: the replica stamped when its reply left
                # (ReplicaHandle stashes it on the future) — socket
                # transit back + reader dispatch is stream time from the
                # request's point of view, the last stage-sum piece
                rts = getattr(inner, "dls_reply_ts", None)
                if rts is not None:
                    now = time.time()
                    ctx["buf"].add("stream", min(float(rts), now), now,
                                   parent_id=ctx["root_sid"], leg="return")
            self._finish_trace(ctx, "ok", end_ts=now)
            fut.set_result(inner.result())

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                **dict(self._stats),
                "replicas": {
                    n: {"alive": r.alive,
                        "draining": n in self._draining,
                        "outstanding": self._outstanding[n],
                        "dispatched": self._dispatched_to[n],
                        "recent_p99_ms": round(
                            self._recent_p99_locked(n) * 1e3, 3)}
                    for n, r in self._replicas.items()},
                "tenants": {t: o for t, o in self._tenant_out.items() if o},
            }
