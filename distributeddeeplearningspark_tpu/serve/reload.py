"""Checkpoint hot-reload — swap serving params without dropping a request.

The serving engine and the training run meet at the checkpoint directory:
training keeps committing steps (async orbax + the PR 1 integrity
manifests), and the reloader watches that directory from the serving
side. Each time a step newer than the one being served appears it is

1. **verified** against its integrity manifest
   (:func:`~..checkpoint.verify_step_dir` — the same walk restore uses),
2. **loaded** (by default params-only via
   :meth:`~..checkpoint.Checkpointer.restore_params`, so the server never
   materializes optimizer state), and
3. **swapped** into the engine between batches
   (:meth:`~.engine.InferenceEngine.swap_params`) — the batch in flight
   finishes on the old tree; nothing is dropped.

A candidate that fails verification (torn write, killed finalize) is
*rejected and remembered*: the previous params keep serving — that IS the
rollback — a ``recovery`` telemetry event records the rejection, and the
walk falls back to the next-newest unverified step so a single bad commit
can't wedge reloading forever. A step that verifies but fails to load
(orbax error) is treated the same way.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable

from distributeddeeplearningspark_tpu import checkpoint as ckpt_lib
from distributeddeeplearningspark_tpu import telemetry

logger = logging.getLogger("distributeddeeplearningspark_tpu.serve")


def checkpoint_params_loader(
    directory: str | os.PathLike, *, wrap_in_variables: bool = False,
) -> Callable[[int], Any]:
    """A ``(step) -> params`` loader over a checkpoint directory.

    Params-only (``Checkpointer.restore_params`` — the serving process
    never materializes optimizer state and needs no knowledge of which
    optimizer trained the run). ``wrap_in_variables=True`` returns
    ``{"params": ...}`` — the swappable unit of
    :meth:`~.engine.InferenceEngine.for_model` engines. The loader carries
    a ``close()`` for the private Checkpointer it holds; a
    :class:`HotReloader` given this loader closes it on :meth:`~HotReloader.stop`.
    """
    ck = ckpt_lib.Checkpointer(directory, async_save=False)

    def load(step: int):
        params, _ = ck.restore_params(step=step)
        return {"params": params} if wrap_in_variables else params

    load.close = ck.close  # type: ignore[attr-defined]
    return load


class HotReloader:
    """Watch a checkpoint directory and hot-swap verified new steps.

    Parameters
    ----------
    engine:
        Anything with ``swap_params(params, version=...)`` — the batch
        engine, the continuous generator, or a test double.
    directory:
        The checkpoint root the training run writes (numbered step dirs).
    load_params:
        ``(step) -> params`` loader. Default: a params-only orbax restore
        through a private :class:`~..checkpoint.Checkpointer` (no
        optimizer state materialized; see ``Checkpointer.restore_params``).
    current_step:
        The step already being served (new steps must be strictly newer);
        ``None`` serves whatever appears first.
    interval_s:
        Poll period of the background thread (:meth:`start`). Directory
        mtime is checked first, so an idle poll is two stat calls.
    """

    def __init__(
        self,
        engine,
        directory: str | os.PathLike,
        *,
        load_params: Callable[[int], Any] | None = None,
        current_step: int | None = None,
        interval_s: float = 5.0,
        clock=time.monotonic,
    ):
        self.engine = engine
        self.directory = os.path.abspath(os.fspath(directory))
        self.interval_s = float(interval_s)
        self.current_step = current_step
        self._clock = clock
        self._rejected: set[int] = set()
        # transient-capable failures (orbax read races a step still landing
        # on NFS/GCS-fuse) get a small retry budget before the permanent
        # verdict; manifest CONTRADICTIONS are deterministic and permanent
        self._load_failures: dict[int, int] = {}
        self.max_load_retries = 3
        if load_params is None:
            load_params = checkpoint_params_loader(self.directory)
        self.load_params = load_params
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- one check -----------------------------------------------------------

    def _candidates(self) -> list[int]:
        """Unseen steps newer than current, newest first."""
        try:
            steps = [int(d) for d in os.listdir(self.directory)
                     if d.isdigit()
                     and os.path.isdir(os.path.join(self.directory, d))]
        except OSError:
            return []
        floor = self.current_step if self.current_step is not None else -1
        return sorted((s for s in steps
                       if s > floor and s not in self._rejected),
                      reverse=True)

    def poll(self) -> dict | None:
        """Check once; swap if a newer verified step exists.

        Returns an action record (``{"step", "action": "reloaded" |
        "rejected", ...}`` — the newest candidate's outcome) or None when
        nothing new was found. Walks newest → oldest so a corrupt latest
        step falls back to the next-newest verified one."""
        result: dict | None = None
        for step in self._candidates():
            step_dir = os.path.join(self.directory, str(step))
            ok, reason = ckpt_lib.verify_step_dir(step_dir)
            if ok:
                try:
                    params = self.load_params(step)
                except Exception as e:  # noqa: BLE001 — a broken load must
                    # leave the old params serving, like a failed verify —
                    # but unlike a manifest contradiction it may be a read
                    # racing a step still landing on a network filesystem,
                    # so it gets max_load_retries polls before the
                    # permanent verdict
                    ok = False
                    reason = f"load failed: {type(e).__name__}: {e}"
                    n = self._load_failures.get(step, 0) + 1
                    self._load_failures[step] = n
                    if n < self.max_load_retries:
                        logger.warning(
                            "hot-reload of step %d failed (%s); retry "
                            "%d/%d at the next poll", step, reason, n,
                            self.max_load_retries)
                        result = result or {"step": step, "action": "retry",
                                            "reason": reason}
                        continue
            if not ok:
                self._rejected.add(step)
                logger.error(
                    "hot-reload REJECTED checkpoint step %d (%s); previous "
                    "params keep serving", step, reason)
                telemetry.emit("recovery", step=int(step),
                               event="reload-rejected", reason=reason,
                               directory=self.directory,
                               serving_step=self.current_step)
                result = result or {"step": step, "action": "rejected",
                                    "reason": reason}
                continue  # fall back: maybe an older unseen step verifies
            self.engine.swap_params(params, version=step)
            previous = self.current_step
            self.current_step = step
            logger.info("hot-reloaded checkpoint step %d (was %s)",
                        step, previous)
            telemetry.emit("recovery", step=int(step), event="reload",
                           previous_step=previous, directory=self.directory)
            return {"step": step, "action": "reloaded",
                    "previous_step": previous,
                    **({"fell_back_past": result["step"]} if result else {})}
        return result

    # -- background watcher --------------------------------------------------

    def start(self) -> "HotReloader":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, name="dlserve-reload", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        close = getattr(self.load_params, "close", None)
        if close is not None:
            close()

    def _watch(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception:  # noqa: BLE001 — the watcher must outlive any
                # one poll's surprise; the next interval retries
                logger.exception("hot-reload poll failed")
            self._stop.wait(self.interval_s)

    def __enter__(self) -> "HotReloader":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
