"""``dlserve`` — stand up the serving engine (or a replica fleet) under load.

The serving sibling of ``dlsubmit``/``dlstatus``: builds an
:class:`~.engine.InferenceEngine` over a model (params from a checkpoint
directory when given, verified via the integrity manifests; fresh init
otherwise), drives it with N closed-loop synthetic clients, and prints
ONE JSON line with the latency/throughput evidence (the bench.py house
convention). With ``--compare-sequential`` the same request count runs
single-request-at-a-time through the identical jitted forward, so the
line carries the dynamic-batching speedup measured, not assumed. With
``--watch`` a :class:`~.reload.HotReloader` polls the checkpoint
directory for newer verified steps for the whole run — a training job
committing checkpoints mid-load exercises hot reload under traffic.

``--replicas N`` engages the fleet path (:mod:`.fleet`): N engine
replicas as separate processes behind the queue-depth/p99 router,
optionally with one ``--rolling-reload`` mid-traffic (zero dropped
in-flight requests — the record carries the count) and a
``--compare-single-replica`` arm that reruns the load through one
replica for the measured scaling factor. ``--model tinyllama`` serves
continuous decode over the paged KV arena with prefix caching; its
synthetic clients share a system prompt (``--prefix-tokens``), so the
record also carries the prefix-cache hit rate and prompt tokens saved.

::

    dlserve --model lenet --clients 64 --requests-per-client 4 \
            --compare-sequential
    dlserve --model lenet --checkpoint-dir /ckpt/run17 --watch \
            --workdir /ckpt/run17
    dlserve --model tinyllama --replicas 2 --rolling-reload \
            --compare-single-replica --workdir /tmp/fleet

Per-request ``request`` telemetry events land in ``--workdir`` (or the
checkpoint dir); ``dlstatus <workdir>`` renders the p50/p99 rollup and
``dlstatus <workdir> --fleet-serve`` the per-replica table.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import threading
import time

# the ONE percentile definition (nearest-rank, jax-free) — the CLI's
# printed p50/p99 must never drift from the dlstatus rollup of the same run
from distributeddeeplearningspark_tpu.status import _percentile as _pct


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dlserve",
        description="Serve a model with dynamic batching; measure it under "
                    "synthetic concurrent load.")
    p.add_argument("--model", default="lenet", choices=["lenet", "tinyllama"],
                   help="served model (synthetic request generator included); "
                        "tinyllama = continuous decode over the paged KV "
                        "arena, fleet mode only")
    p.add_argument("--checkpoint-dir", default=None,
                   help="load params from this checkpoint root (newest "
                        "verified step); fresh-init when unset")
    p.add_argument("--workdir", default=None,
                   help="telemetry dir for request events (default: the "
                        "checkpoint dir, when given; fleet mode makes a "
                        "tmp dir so the rollup always has a home)")
    p.add_argument("--watch", action="store_true",
                   help="hot-reload newer verified checkpoints during the "
                        "run (requires --checkpoint-dir)")
    p.add_argument("--watch-interval-s", type=float, default=2.0)
    p.add_argument("--clients", type=int, default=16,
                   help="concurrent closed-loop synthetic clients")
    p.add_argument("--requests-per-client", type=int, default=8)
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--max-queue", type=int, default=1024)
    p.add_argument("--compare-sequential", action="store_true",
                   help="also run the same request count one-by-one through "
                        "the identical forward and report the speedup")
    p.add_argument("--seed", type=int, default=0)
    # -- fleet mode -----------------------------------------------------------
    p.add_argument("--replicas", type=int, default=0,
                   help="serve through N replica PROCESSES behind the "
                        "router (0 = classic in-process single engine)")
    p.add_argument("--rolling-reload", action="store_true",
                   help="fleet mode: one rolling hot-reload mid-traffic "
                        "(drain → swap → undrain, one replica at a time)")
    p.add_argument("--compare-single-replica", action="store_true",
                   help="fleet mode: rerun the load through ONE replica and "
                        "report the measured scaling factor")
    p.add_argument("--tenant-budget", type=int, default=None,
                   help="fleet mode: per-tenant outstanding-request budget "
                        "(None = unlimited)")
    p.add_argument("--tenants", type=int, default=1,
                   help="fleet mode: spread clients across this many tenants")
    p.add_argument("--pin-cores", action="store_true",
                   help="fleet mode: pin each replica process to one CPU "
                        "core (the CPU stand-in for one-replica-per-chip — "
                        "without it one replica's XLA threadpool saturates "
                        "the whole box and 1->N scaling measures thread "
                        "contention, not replica capacity)")
    p.add_argument("--slots", type=int, default=4,
                   help="tinyllama: KV slots per replica")
    p.add_argument("--page-size", type=int, default=16,
                   help="tinyllama: KV page size (tokens)")
    p.add_argument("--max-cache-len", type=int, default=128)
    p.add_argument("--prefix-tokens", type=int, default=32,
                   help="tinyllama: shared system-prompt length (the "
                        "prefix-cache workload knob)")
    p.add_argument("--suffix-tokens", type=int, default=8,
                   help="tinyllama: per-request unique prompt tail")
    p.add_argument("--max-new-tokens", type=int, default=8)
    p.add_argument("--fault-sleep-ms", type=float, default=0.0,
                   help="fleet mode drill: make ONE replica slow by "
                        "sleeping this long before every decode step — "
                        "the deterministic fault the SLO sentinel smoke "
                        "injects (dlstatus --slo flips its verdict, "
                        "--traces names the slow replica's decode stage)")
    p.add_argument("--fault-replica", type=int, default=0,
                   help="which replica --fault-sleep-ms slows (default 0)")
    return p


def _lenet_setup(args):
    """(variables, example_fn) for the LeNet workload."""
    import jax
    import numpy as np

    from distributeddeeplearningspark_tpu.models import LeNet5

    model = LeNet5()
    rng = np.random.default_rng(args.seed)

    def example(i: int):
        return {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32)}

    if args.checkpoint_dir:
        from distributeddeeplearningspark_tpu import Checkpointer

        with Checkpointer(args.checkpoint_dir, async_save=False) as ck:
            params, step = ck.restore_params()
        print(f"dlserve: serving checkpoint step {step} from "
              f"{args.checkpoint_dir}", file=sys.stderr)
    else:
        params = model.init(
            jax.random.PRNGKey(args.seed),
            {"image": np.zeros((1, 28, 28, 1), np.float32)},
            train=False)["params"]
        step = None
        print("dlserve: no --checkpoint-dir, serving fresh-init params",
              file=sys.stderr)
    return model, {"params": params}, example, step


def run_load(engine, example_fn, *, clients: int, requests_per_client: int):
    """Pipelined concurrent load: every client submits its whole request
    stream, then collects the results (HTTP/2-style pipelining — the
    client-side Python cost of a resubmit never serializes the server,
    so the measurement sees the engine's throughput, not the GIL's).

    Returns (latencies_sorted, shed_count, wall_s). A shed request counts
    in ``shed`` and contributes no latency sample."""
    from distributeddeeplearningspark_tpu.serve.engine import OverloadedError

    lat: list[float] = []
    shed = [0]
    lock = threading.Lock()
    # payloads are built BEFORE the clock starts: generating request bodies
    # is client work, not serving work, and doing it inside the timed loop
    # would serialize every arm on the GIL identically — measuring python,
    # not the engine
    payloads = [[example_fn(c * requests_per_client + j)
                 for j in range(requests_per_client)]
                for c in range(clients)]
    barrier = threading.Barrier(clients + 1)

    def client(cid: int):
        barrier.wait()
        pending = []
        for ex in payloads[cid]:
            t0 = time.monotonic()
            try:
                pending.append((t0, engine.submit(ex)))
            except OverloadedError:
                with lock:
                    shed[0] += 1
        for t0, fut in pending:
            fut.result(timeout=120.0)
            with lock:
                lat.append(time.monotonic() - t0)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    return sorted(lat), shed[0], time.monotonic() - t0


def run_router_load(router, payload_fn, *, clients: int,
                    requests_per_client: int, op: str = "infer",
                    tenants: int = 1, timeout: float = 300.0):
    """The fleet twin of :func:`run_load`, dispatching through the router.

    Returns (latencies_sorted, shed_count, failed_count, wall_s) — a
    failed request (replica died with no survivor to fail over to) is the
    one thing the zero-drop assertion counts; sheds are intentional."""
    from distributeddeeplearningspark_tpu.serve.engine import OverloadedError

    lat: list[float] = []
    shed = [0]
    failed = [0]
    lock = threading.Lock()
    payloads = [[payload_fn(c * requests_per_client + j)
                 for j in range(requests_per_client)]
                for c in range(clients)]
    barrier = threading.Barrier(clients + 1)

    def client(cid: int):
        tenant = f"tenant{cid % max(1, tenants)}"
        barrier.wait()
        pending = []
        for payload in payloads[cid]:
            t0 = time.monotonic()
            try:
                pending.append((t0, router.submit(payload, op=op,
                                                  tenant=tenant)))
            except OverloadedError:
                with lock:
                    shed[0] += 1
        for t0, fut in pending:
            try:
                fut.result(timeout=timeout)
            except OverloadedError:
                # a replica-side shed (engine queue full) rides the
                # future — it is the intentional typed backpressure, not
                # a dropped request, and must not trip the zero-drop gate
                with lock:
                    shed[0] += 1
                continue
            except Exception:  # noqa: BLE001 — counted, not raised: the
                with lock:     # record must carry the drop evidence
                    failed[0] += 1
                continue
            with lock:
                lat.append(time.monotonic() - t0)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    return sorted(lat), shed[0], failed[0], time.monotonic() - t0


# -- fleet mode ---------------------------------------------------------------


def _fleet_payload_fn(args):
    """(payload_fn, op) for the fleet workload. tinyllama clients share a
    system prompt (the prefix-cache case); suffixes are per-request."""
    import numpy as np

    rng = np.random.default_rng(args.seed)
    if args.model == "lenet":
        def payload(i: int):
            return {"example": {
                "image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32)}}

        return payload, "infer"
    vocab = 256
    system = rng.integers(1, vocab, (args.prefix_tokens,)).astype(np.int32)

    def payload(i: int):
        suffix = rng.integers(1, vocab,
                              (args.suffix_tokens,)).astype(np.int32)
        return {"prompt": np.concatenate([system, suffix]),
                "max_new_tokens": args.max_new_tokens}

    return payload, "generate"


def fleet_main(args) -> int:
    from distributeddeeplearningspark_tpu.serve.fleet import ServingFleet

    workdir = (args.workdir or args.checkpoint_dir
               or tempfile.mkdtemp(prefix="dlserve_fleet_"))
    spec = {
        "model": args.model,
        "seed": args.seed,
        "checkpoint_dir": args.checkpoint_dir,
        "max_batch": args.max_batch,
        "max_wait_ms": args.max_wait_ms,
        "max_queue": args.max_queue,
        "slots": args.slots,
        "max_cache_len": args.max_cache_len,
        "page_size": args.page_size,
        "gauge_interval_s": 0.5,
        "pin_cores": args.pin_cores,
        **({"step_delay_ms": {str(args.fault_replica): args.fault_sleep_ms}}
           if args.fault_sleep_ms else {}),
    }
    payload_fn, op = _fleet_payload_fn(args)
    print(f"dlserve: launching {args.replicas} {args.model} replica(s), "
          f"workdir={workdir}", file=sys.stderr)
    reload_evidence: list[dict] = []
    with ServingFleet(spec, replicas=args.replicas,
                      workdir=workdir) as fleet:
        router = fleet.router(default_tenant_budget=args.tenant_budget)

        # warm every replica with the REAL payload shape before timing:
        # the replica's own warmup can't know the client prompt length, and
        # an untimed pair per replica compiles both the miss-path prompt
        # bucket and the hit-path remainder window (XLA compiles are a
        # deploy cost, not a request cost — same rule as the single path)
        for h in fleet.handles:
            for j in range(2):
                h.submit(payload_fn(-1 - j), op).result(timeout=600.0)

        reload_thread = None
        if args.rolling_reload:
            def reload_when_traffic_flows():
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    if router.stats()["dispatched"] >= args.replicas:
                        break
                    time.sleep(0.002)
                reload_evidence.extend(fleet.rolling_reload(router))

            reload_thread = threading.Thread(target=reload_when_traffic_flows)
            reload_thread.start()
        lat, shed, failed, wall = run_router_load(
            router, payload_fn, clients=args.clients,
            requests_per_client=args.requests_per_client, op=op,
            tenants=args.tenants)
        if reload_thread is not None:
            reload_thread.join()
        rstats = router.stats()
        replica_stats = {h.name: h.call("stats") for h in fleet.handles}

        single = None
        if args.compare_single_replica and args.replicas > 1:
            # same load, one replica: the others drain (stay alive — the
            # arm measures one engine's throughput under the identical
            # router/transport costs, isolating the replica scaling)
            for h in fleet.handles[1:]:
                router.drain(h.name)
            s_lat, s_shed, s_failed, s_wall = run_router_load(
                router, payload_fn, clients=args.clients,
                requests_per_client=args.requests_per_client, op=op,
                tenants=args.tenants)
            for h in fleet.handles[1:]:
                router.undrain(h.name)
            single = {"requests_ok": len(s_lat), "shed": s_shed,
                      "failed": s_failed, "wall_s": round(s_wall, 3),
                      "requests_per_sec": round(len(s_lat) / s_wall, 1)
                      if s_wall > 0 else 0.0}

    expected = args.clients * args.requests_per_client
    prefix_hits = sum(s.get("prefix_hits", 0) or 0
                      for s in replica_stats.values())
    prefix_misses = sum(s.get("prefix_misses", 0) or 0
                        for s in replica_stats.values())
    rec = {
        "metric": "dlserve_fleet_requests_per_sec",
        "value": round(len(lat) / wall, 1) if wall > 0 else 0.0,
        "unit": "req/s",
        "extra": {
            "model": args.model,
            "op": op,
            "replicas": args.replicas,
            "clients": args.clients,
            "requests_expected": expected,
            "requests_ok": len(lat),
            "requests_shed": shed,
            "requests_failed": failed,
            "requests_dropped": expected - len(lat) - shed - failed,
            "latency_p50_ms": (round(_pct(lat, 0.5) * 1e3, 2)
                               if lat else None),
            "latency_p99_ms": (round(_pct(lat, 0.99) * 1e3, 2)
                               if lat else None),
            "wall_s": round(wall, 3),
            "router": rstats,
            "per_replica": replica_stats,
            "rolling_reload": {
                "performed": bool(reload_evidence),
                "replicas_reloaded": len(reload_evidence),
                "evidence": reload_evidence,
            },
            "prefix": {
                "hits": prefix_hits,
                "misses": prefix_misses,
                "hit_rate": (round(prefix_hits / (prefix_hits + prefix_misses),
                                   4) if prefix_hits + prefix_misses else None),
                "tokens_saved": sum(s.get("prefix_tokens_saved", 0) or 0
                                    for s in replica_stats.values()),
            },
            "kv_page_occupancy": {
                n: s.get("kv_page_occupancy")
                for n, s in replica_stats.items()
                if s.get("kv_page_occupancy") is not None} or None,
            "tenants": args.tenants,
            "tenant_budget": args.tenant_budget,
            "workdir": workdir,
        },
    }
    if single is not None:
        rec["extra"]["single_replica"] = single
        if single["requests_per_sec"] > 0:
            rec["extra"]["replica_scaling"] = round(
                rec["value"] / single["requests_per_sec"], 2)
    print(json.dumps(rec))
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.watch and not args.checkpoint_dir:
        build_parser().error("--watch requires --checkpoint-dir")
    if args.replicas < 0:
        build_parser().error("--replicas must be >= 0")
    if args.model == "tinyllama" and not args.replicas:
        build_parser().error("--model tinyllama runs in fleet mode "
                             "(--replicas N)")
    fleet_flags = args.rolling_reload or args.compare_single_replica \
        or args.pin_cores or args.tenant_budget is not None \
        or args.fault_sleep_ms
    if fleet_flags and not args.replicas:
        build_parser().error("--rolling-reload/--compare-single-replica/"
                             "--pin-cores/--tenant-budget/--fault-sleep-ms "
                             "need --replicas N")
    if args.fault_sleep_ms < 0:
        # a negative sleep would reach time.sleep() inside the replica's
        # decode loop and kill its serving thread with a ValueError
        build_parser().error("--fault-sleep-ms must be >= 0")
    if args.fault_sleep_ms and not (0 <= args.fault_replica < args.replicas):
        # an out-of-range id would make the drill a silent no-op: every
        # replica healthy, the SLO verdict GOOD, and the operator
        # concluding the sentinel tolerates a fault that never ran
        build_parser().error(
            f"--fault-replica {args.fault_replica} is out of range for "
            f"--replicas {args.replicas}")
    if args.replicas:
        if args.watch or args.compare_sequential:
            build_parser().error("--watch/--compare-sequential are the "
                                 "single-engine harness; fleet mode has "
                                 "--rolling-reload/--compare-single-replica")
        return fleet_main(args)

    workdir = args.workdir or args.checkpoint_dir
    import jax  # noqa: F401 — heavy import AFTER argparse (bench.py house rule)

    from distributeddeeplearningspark_tpu.serve import (
        HotReloader,
        InferenceEngine,
    )

    model, variables, example_fn, ckpt_step = _lenet_setup(args)
    engine = InferenceEngine.for_model(
        model, variables, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        workdir=workdir, name=args.model)
    reloader = None
    if args.watch:
        from distributeddeeplearningspark_tpu.serve.reload import (
            checkpoint_params_loader,
        )

        reloader = HotReloader(
            engine, args.checkpoint_dir, current_step=ckpt_step,
            interval_s=args.watch_interval_s,
            load_params=checkpoint_params_loader(
                args.checkpoint_dir, wrap_in_variables=True))

    with engine:
        # compile the whole bucket ladder before timing: XLA compiles are a
        # deploy cost, not a per-request latency fact
        n_warm = engine.warmup(example_fn(0))
        print(f"dlserve: warmed {n_warm} batch bucket(s) "
              f"{engine.batch_sizes}", file=sys.stderr)
        if reloader is not None:
            reloader.start()
        lat, shed, wall = run_load(
            engine, example_fn, clients=args.clients,
            requests_per_client=args.requests_per_client)
        stats = engine.stats()
        if reloader is not None:
            reloader.stop()

    rec = {
        "metric": "dlserve_requests_per_sec",
        "value": round(len(lat) / wall, 1) if wall > 0 else 0.0,
        "unit": "req/s",
        "extra": {
            "model": args.model,
            "clients": args.clients,
            "requests_ok": len(lat),
            "requests_shed": shed,
            "latency_p50_ms": (round(_pct(lat, 0.5) * 1e3, 2)
                               if lat else None),
            "latency_p99_ms": (round(_pct(lat, 0.99) * 1e3, 2)
                               if lat else None),
            "wall_s": round(wall, 3),
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "bucket_counts": stats["bucket_counts"],
            "compiled_batch_shapes": stats["compiled_batch_shapes"],
            "params_version": stats["params_version"],
            "reloads": stats["reloads"],
            "checkpoint_step": ckpt_step,
            "workdir": workdir,
        },
    }

    if args.compare_sequential:
        # the same closed-loop load through an engine that answers ONE
        # request per forward (max_batch=1, no coalescing window): both
        # arms pay identical queue/future/telemetry costs, so the ratio
        # isolates exactly what dynamic batching buys
        # NO workdir: the comparison arm is local evidence for this JSON
        # line — its request events in the run's stream would blend two
        # engines' latencies into one dlstatus rollup and deflate the
        # span-based throughput with the idle gap between the phases
        seq = InferenceEngine.for_model(
            model, variables, max_batch=1, max_wait_ms=0.0,
            max_queue=args.max_queue, batch_sizes=(1,),
            name=f"{args.model}-seq")
        with seq:
            seq.warmup(example_fn(0))
            seq_lat, _, seq_wall = run_load(
                seq, example_fn, clients=args.clients,
                requests_per_client=args.requests_per_client)
        seq_rps = len(seq_lat) / seq_wall if seq_wall > 0 else 0.0
        rec["extra"]["sequential_requests_per_sec"] = round(seq_rps, 1)
        rec["extra"]["sequential_latency_p50_ms"] = (
            round(_pct(seq_lat, 0.5) * 1e3, 2) if seq_lat else None)
        rec["extra"]["batching_speedup"] = (
            round(rec["value"] / seq_rps, 2) if seq_rps > 0 else None)

    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
