"""``dlserve`` — stand up the serving engine and measure it under load.

The serving sibling of ``dlsubmit``/``dlstatus``: builds an
:class:`~.engine.InferenceEngine` over a model (params from a checkpoint
directory when given, verified via the integrity manifests; fresh init
otherwise), drives it with N closed-loop synthetic clients, and prints
ONE JSON line with the latency/throughput evidence (the bench.py house
convention). With ``--compare-sequential`` the same request count runs
single-request-at-a-time through the identical jitted forward, so the
line carries the dynamic-batching speedup measured, not assumed. With
``--watch`` a :class:`~.reload.HotReloader` polls the checkpoint
directory for newer verified steps for the whole run — a training job
committing checkpoints mid-load exercises hot reload under traffic.

::

    dlserve --model lenet --clients 64 --requests-per-client 4 \
            --compare-sequential
    dlserve --model lenet --checkpoint-dir /ckpt/run17 --watch \
            --workdir /ckpt/run17

Per-request ``request`` telemetry events land in ``--workdir`` (or the
checkpoint dir); ``dlstatus <workdir>`` renders the p50/p99 rollup.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

# the ONE percentile definition (status.py's nearest-rank, jax-free) — the
# CLI's printed p50/p99 must never drift from the dlstatus rollup of the
# same run
from distributeddeeplearningspark_tpu.status import _percentile as _pct


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dlserve",
        description="Serve a model with dynamic batching; measure it under "
                    "synthetic concurrent load.")
    p.add_argument("--model", default="lenet", choices=["lenet"],
                   help="served model (synthetic request generator included)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="load params from this checkpoint root (newest "
                        "verified step); fresh-init when unset")
    p.add_argument("--workdir", default=None,
                   help="telemetry dir for request events (default: the "
                        "checkpoint dir, when given)")
    p.add_argument("--watch", action="store_true",
                   help="hot-reload newer verified checkpoints during the "
                        "run (requires --checkpoint-dir)")
    p.add_argument("--watch-interval-s", type=float, default=2.0)
    p.add_argument("--clients", type=int, default=16,
                   help="concurrent closed-loop synthetic clients")
    p.add_argument("--requests-per-client", type=int, default=8)
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--max-queue", type=int, default=1024)
    p.add_argument("--compare-sequential", action="store_true",
                   help="also run the same request count one-by-one through "
                        "the identical forward and report the speedup")
    p.add_argument("--seed", type=int, default=0)
    return p


def _lenet_setup(args):
    """(variables, example_fn) for the LeNet workload."""
    import jax
    import numpy as np

    from distributeddeeplearningspark_tpu.models import LeNet5

    model = LeNet5()
    rng = np.random.default_rng(args.seed)

    def example(i: int):
        return {"image": rng.normal(0, 1, (28, 28, 1)).astype(np.float32)}

    if args.checkpoint_dir:
        from distributeddeeplearningspark_tpu import Checkpointer

        with Checkpointer(args.checkpoint_dir, async_save=False) as ck:
            params, step = ck.restore_params()
        print(f"dlserve: serving checkpoint step {step} from "
              f"{args.checkpoint_dir}", file=sys.stderr)
    else:
        params = model.init(
            jax.random.PRNGKey(args.seed),
            {"image": np.zeros((1, 28, 28, 1), np.float32)},
            train=False)["params"]
        step = None
        print("dlserve: no --checkpoint-dir, serving fresh-init params",
              file=sys.stderr)
    return model, {"params": params}, example, step


def run_load(engine, example_fn, *, clients: int, requests_per_client: int):
    """Pipelined concurrent load: every client submits its whole request
    stream, then collects the results (HTTP/2-style pipelining — the
    client-side Python cost of a resubmit never serializes the server,
    so the measurement sees the engine's throughput, not the GIL's).

    Returns (latencies_sorted, shed_count, wall_s). A shed request counts
    in ``shed`` and contributes no latency sample."""
    from distributeddeeplearningspark_tpu.serve.engine import OverloadedError

    lat: list[float] = []
    shed = [0]
    lock = threading.Lock()
    # payloads are built BEFORE the clock starts: generating request bodies
    # is client work, not serving work, and doing it inside the timed loop
    # would serialize every arm on the GIL identically — measuring python,
    # not the engine
    payloads = [[example_fn(c * requests_per_client + j)
                 for j in range(requests_per_client)]
                for c in range(clients)]
    barrier = threading.Barrier(clients + 1)

    def client(cid: int):
        barrier.wait()
        pending = []
        for ex in payloads[cid]:
            t0 = time.monotonic()
            try:
                pending.append((t0, engine.submit(ex)))
            except OverloadedError:
                with lock:
                    shed[0] += 1
        for t0, fut in pending:
            fut.result(timeout=120.0)
            with lock:
                lat.append(time.monotonic() - t0)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    return sorted(lat), shed[0], time.monotonic() - t0




def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.watch and not args.checkpoint_dir:
        build_parser().error("--watch requires --checkpoint-dir")

    workdir = args.workdir or args.checkpoint_dir
    import jax  # heavy import AFTER argparse (bench.py house rule)

    from distributeddeeplearningspark_tpu.serve import (
        HotReloader,
        InferenceEngine,
    )

    model, variables, example_fn, ckpt_step = _lenet_setup(args)
    engine = InferenceEngine.for_model(
        model, variables, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        workdir=workdir, name=args.model)
    reloader = None
    if args.watch:
        from distributeddeeplearningspark_tpu.serve.reload import (
            checkpoint_params_loader,
        )

        reloader = HotReloader(
            engine, args.checkpoint_dir, current_step=ckpt_step,
            interval_s=args.watch_interval_s,
            load_params=checkpoint_params_loader(
                args.checkpoint_dir, wrap_in_variables=True))

    with engine:
        # compile the whole bucket ladder before timing: XLA compiles are a
        # deploy cost, not a per-request latency fact
        n_warm = engine.warmup(example_fn(0))
        print(f"dlserve: warmed {n_warm} batch bucket(s) "
              f"{engine.batch_sizes}", file=sys.stderr)
        if reloader is not None:
            reloader.start()
        lat, shed, wall = run_load(
            engine, example_fn, clients=args.clients,
            requests_per_client=args.requests_per_client)
        stats = engine.stats()
        if reloader is not None:
            reloader.stop()

    rec = {
        "metric": "dlserve_requests_per_sec",
        "value": round(len(lat) / wall, 1) if wall > 0 else 0.0,
        "unit": "req/s",
        "extra": {
            "model": args.model,
            "clients": args.clients,
            "requests_ok": len(lat),
            "requests_shed": shed,
            "latency_p50_ms": (round(_pct(lat, 0.5) * 1e3, 2)
                               if lat else None),
            "latency_p99_ms": (round(_pct(lat, 0.99) * 1e3, 2)
                               if lat else None),
            "wall_s": round(wall, 3),
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "bucket_counts": stats["bucket_counts"],
            "compiled_batch_shapes": stats["compiled_batch_shapes"],
            "params_version": stats["params_version"],
            "reloads": stats["reloads"],
            "checkpoint_step": ckpt_step,
            "workdir": workdir,
        },
    }

    if args.compare_sequential:
        # the same closed-loop load through an engine that answers ONE
        # request per forward (max_batch=1, no coalescing window): both
        # arms pay identical queue/future/telemetry costs, so the ratio
        # isolates exactly what dynamic batching buys
        # NO workdir: the comparison arm is local evidence for this JSON
        # line — its request events in the run's stream would blend two
        # engines' latencies into one dlstatus rollup and deflate the
        # span-based throughput with the idle gap between the phases
        seq = InferenceEngine.for_model(
            model, variables, max_batch=1, max_wait_ms=0.0,
            max_queue=args.max_queue, batch_sizes=(1,),
            name=f"{args.model}-seq")
        with seq:
            seq.warmup(example_fn(0))
            seq_lat, _, seq_wall = run_load(
                seq, example_fn, clients=args.clients,
                requests_per_client=args.requests_per_client)
        seq_rps = len(seq_lat) / seq_wall if seq_wall > 0 else 0.0
        rec["extra"]["sequential_requests_per_sec"] = round(seq_rps, 1)
        rec["extra"]["sequential_latency_p50_ms"] = (
            round(_pct(seq_lat, 0.5) * 1e3, 2) if seq_lat else None)
        rec["extra"]["batching_speedup"] = (
            round(rec["value"] / seq_rps, 2) if seq_rps > 0 else None)

    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
