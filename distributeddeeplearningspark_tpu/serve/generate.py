"""Continuous batched decode for the Llama family — KV-cache slot serving.

Plain :func:`~..models.llama_gen.generate` is a batch call: every row
starts together and the call returns when the slowest row finishes, so a
server built on it would stall short requests behind long ones and leave
the chip idle while the batch drains. Continuous batching fixes both with
a fixed pool of **KV-cache slots** stepped together forever:

- the decode loop is ONE jitted single-token step over all ``slots`` rows
  (per-row cache indices — ``LlamaAttention._decode_attend`` keys each
  row at its own sequence position);
- when a sequence completes, its slot frees and the next queued request
  **joins mid-flight**: its prompt is prefilled in a separate bucketed
  ``[1, bucket]`` call (a bounded compile set, like the engine's batch
  buckets) and its cache row is inserted into the pool while the
  neighboring slots are hundreds of tokens into their own sequences;
- each sampled token is pushed through the request's optional streaming
  callback the step it is produced — time-to-first-token is one prefill,
  not one full batch.

Params are read once per step, so :meth:`ContinuousGenerator.swap_params`
(checkpoint hot-reload) takes effect at the next token without dropping
or restarting in-flight sequences. Admission is the same bounded-queue /
typed-shed contract as :mod:`.engine`.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import numpy as np

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.serve.engine import (
    EngineStoppedError,
    OverloadedError,
)

logger = logging.getLogger("distributeddeeplearningspark_tpu.serve")


def default_prompt_buckets(max_cache_len: int) -> tuple[int, ...]:
    """Powers of two up to ``max_cache_len`` (8 at minimum): each distinct
    bucket is one prefill compile, so the ladder is short by construction."""
    sizes = []
    b = 8
    while b < max_cache_len:
        sizes.append(b)
        b *= 2
    sizes.append(max_cache_len)
    return tuple(sizes)


@dataclass
class _GenRequest:
    rid: int
    prompt: np.ndarray                      # [T] int32
    max_new_tokens: int
    stream: Callable[[int], None] | None
    future: Future = field(default_factory=Future)
    t_submit: float = 0.0
    t_admit: float = 0.0
    tokens: list[int] = field(default_factory=list)


class ContinuousGenerator:
    """A slot-pool decode server over one Llama param tree.

    Parameters
    ----------
    cfg:
        The model's :class:`~..models.llama.LlamaConfig` (training config;
        the decode twin is derived here via
        :func:`~..models.llama_gen.decode_model`).
    params:
        Param tree (same tree the training step holds).
    slots:
        KV-cache pool size — the decode step's fixed batch. Memory scales
        linearly (``slots × max_cache_len`` K/V per layer).
    max_cache_len:
        Cache length per slot; every request needs
        ``len(prompt) + max_new_tokens <= max_cache_len``.
    temperature / top_k / top_p / eos_id / pad_id / seed:
        Sampling configuration (engine-wide), semantics of
        :func:`~..models.llama_gen.generate`.
    prompt_buckets:
        Prefill pad ladder; right-padded with ``pad_id`` (pads sit at
        positions AFTER the real tokens, so causal attention never lets a
        real token see one, and decode overwrites each pad's K/V before
        that position is ever attended).
    max_queue:
        Admission bound; beyond it :meth:`submit` sheds with
        :class:`~.engine.OverloadedError`.
    """

    def __init__(
        self,
        cfg,
        params: Any,
        *,
        slots: int = 4,
        max_cache_len: int | None = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_id: int | None = None,
        pad_id: int = 0,
        seed: int = 0,
        prompt_buckets: Sequence[int] | None = None,
        max_queue: int = 256,
        workdir: str | None = None,
        name: str = "generate",
    ):
        import jax
        import jax.numpy as jnp

        from distributeddeeplearningspark_tpu.models.llama_gen import (
            _sample,
            decode_model,
        )

        self._jax, self._jnp = jax, jnp
        self.cfg = cfg
        self.slots = int(slots)
        self.max_cache_len = int(max_cache_len or cfg.max_position)
        if self.max_cache_len > cfg.max_position:
            raise ValueError(
                f"max_cache_len {self.max_cache_len} exceeds max_position "
                f"{cfg.max_position}")
        self.name = name
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        self.max_queue = int(max_queue)
        self.prompt_buckets = tuple(sorted(
            prompt_buckets if prompt_buckets is not None
            else default_prompt_buckets(self.max_cache_len)))
        if self.prompt_buckets[-1] > self.max_cache_len:
            raise ValueError(
                f"largest prompt bucket {self.prompt_buckets[-1]} exceeds "
                f"max_cache_len {self.max_cache_len}")
        # same contract as InferenceEngine: request events only when a
        # workdir is given (telemetry-silent otherwise)
        self._tele = telemetry.configure(workdir) if workdir else None

        self._model = decode_model(cfg, self.max_cache_len)
        self._params = params
        self.params_version: int | str = 0
        self._key = jax.random.PRNGKey(seed)

        sample = lambda logits, key: _sample(  # noqa: E731 — one-liner bind
            logits.astype(jnp.float32), key,
            temperature=temperature, top_k=top_k, top_p=top_p)

        def prefill(params, ids, true_len, key):
            """[1, bucket] prompt → (cache row at index true_len, first tok)."""
            logits, mut = self._model.apply(
                {"params": params}, {"input_ids": ids},
                train=False, mutable=["cache"])
            # pads were written into the cache beyond true_len; reset every
            # index leaf (the only int32 cache leaves) so decode resumes at
            # the REAL end of prompt — stale pad K/V beyond it is masked
            # until overwritten (llama.py _decode_attend docstring)
            cache = jax.tree.map(
                lambda x: jnp.full_like(x, true_len)
                if x.dtype == jnp.int32 else x,
                mut["cache"])
            tok = sample(logits[jnp.arange(1), true_len - 1], key)
            return cache, tok

        def step(params, cache, tok, key):
            """One decode token for every slot at once."""
            logits, mut = self._model.apply(
                {"params": params, "cache": cache},
                {"input_ids": tok[:, None]}, train=False, mutable=["cache"])
            return mut["cache"], sample(logits[:, -1], key)

        def insert(cache, row, slot):
            """Write a prefilled [1, ...] cache row into pool slot ``slot``.

            The slot axis is identified per leaf as the one where the pool
            and row shapes differ (pool ``slots`` vs row 1) — robust to the
            scanned-layer stacking that prepends a layer axis."""

            def ins(c, r):
                if c.shape == r.shape:
                    return r
                starts = tuple(
                    slot if cs != rs else 0
                    for cs, rs in zip(c.shape, r.shape))
                return jax.lax.dynamic_update_slice(c, r, starts)

            return jax.tree.map(ins, cache, row)

        self._prefill = jax.jit(prefill, static_argnames=())
        self._step = jax.jit(step)
        self._insert = jax.jit(insert)

        # empty slot pool: cache structure from an abstract eval (free), zeros
        abstract = jax.eval_shape(
            lambda p: self._model.apply(
                {"params": p},
                {"input_ids": jnp.zeros((self.slots, 1), jnp.int32)},
                train=False, mutable=["cache"])[1]["cache"],
            params)
        self._cache = jax.tree.map(
            lambda a: jnp.zeros(a.shape, a.dtype), abstract)
        self._cur_tok = np.zeros((self.slots,), np.int32)

        self._queue: list[_GenRequest] = []
        self._active: list[_GenRequest | None] = [None] * self.slots
        self._cond = threading.Condition()
        # accepting from construction, like InferenceEngine: requests queue
        # up; decoding begins when start() spawns the serving thread
        self._stopped = False
        self._thread: threading.Thread | None = None
        self._rid = itertools.count()
        self._stats = {"requests": 0, "shed": 0, "completed": 0, "steps": 0,
                       "admitted": 0, "reloads": 0, "max_active": 0,
                       "tokens": 0}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ContinuousGenerator":
        with self._cond:
            if self._thread is not None:
                return self
            self._stopped = False
            self._thread = threading.Thread(
                target=self._loop, name=f"dlserve-{self.name}", daemon=True)
            self._thread.start()
        return self

    def stop(self, *, drain: bool = True) -> None:
        """Stop admission; by default finish queued + in-flight sequences.

        A never-started generator with queued requests starts the serving
        thread just to drain them — drain=True must never strand a future."""
        if drain and self._thread is None and self._queue:
            self.start()
        with self._cond:
            if self._stopped and self._thread is None:
                return
            self._stopped = True
            if not drain:
                for req in self._queue:
                    req.future.set_exception(
                        EngineStoppedError("generator stopped before admission"))
                self._queue.clear()
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join()
        self._thread = None

    def __enter__(self) -> "ContinuousGenerator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client surface ------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               stream: Callable[[int], None] | None = None) -> Future:
        """Enqueue a prompt; Future resolves to the np.int32 token array.

        ``stream`` is called with each token id the step it is sampled
        (from the serving thread — keep it cheap/non-blocking)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt.size + max_new_tokens > self.max_cache_len:
            raise ValueError(
                f"prompt {prompt.size} + new {max_new_tokens} exceeds "
                f"max_cache_len {self.max_cache_len}")
        if prompt.size > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt {prompt.size} exceeds largest prompt bucket "
                f"{self.prompt_buckets[-1]}")
        req = _GenRequest(rid=next(self._rid), prompt=prompt,
                          max_new_tokens=int(max_new_tokens), stream=stream)
        req.t_submit = time.monotonic()
        with self._cond:
            if self._stopped:
                raise EngineStoppedError("generator is stopped")
            if len(self._queue) >= self.max_queue:
                self._stats["shed"] += 1
                if self._tele is not None:
                    self._tele.emit("request", engine=self.name, id=req.rid,
                                    outcome="shed",
                                    queue_depth=len(self._queue))
                raise OverloadedError(len(self._queue), self.max_queue)
            self._queue.append(req)
            self._stats["requests"] += 1
            self._cond.notify_all()
        return req.future

    def generate(self, prompt, max_new_tokens: int, *,
                 timeout: float | None = 120.0,
                 stream: Callable[[int], None] | None = None) -> np.ndarray:
        """Blocking convenience: ``submit`` + ``result``."""
        return self.submit(prompt, max_new_tokens, stream=stream).result(
            timeout=timeout)

    def stats(self) -> dict[str, Any]:
        with self._cond:
            out = dict(self._stats)
            out["queue_depth"] = len(self._queue)
            out["active"] = sum(r is not None for r in self._active)
        out["params_version"] = self.params_version
        return out

    # -- hot reload ----------------------------------------------------------

    def swap_params(self, params: Any, *, version: int | str | None = None) -> None:
        """Swap the param tree between decode steps: in-flight sequences
        keep their KV cache and continue on the new params at the next
        token — nothing is dropped or restarted."""
        jax = self._jax
        old = self._params
        try:
            shardings = jax.tree.map(lambda a: a.sharding, old)
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, s), params, shardings)
        except (AttributeError, ValueError, TypeError):
            pass
        with self._cond:
            self._params = params
            self._stats["reloads"] += 1
            if version is not None:
                self.params_version = version
            elif isinstance(self.params_version, int):
                self.params_version += 1

    # -- serving loop --------------------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return self.prompt_buckets[-1]

    def _split_key(self):
        self._key, sub = self._jax.random.split(self._key)
        return sub

    def _finish(self, req: _GenRequest, *, n_active: int) -> None:
        done = time.monotonic()
        req.future.set_result(np.asarray(req.tokens, np.int32))
        with self._cond:
            self._stats["completed"] += 1
            self._stats["tokens"] += len(req.tokens)
        if self._tele is not None:
            self._tele.emit(
                "request", engine=self.name, id=req.rid, outcome="ok",
                tokens=len(req.tokens),
                queue_wait_s=round(req.t_admit - req.t_submit, 6),
                latency_s=round(done - req.t_submit, 6),
                batch_size=n_active)

    def _emit_token(self, req: _GenRequest, tok: int) -> bool:
        """Record one sampled token; True when the sequence is complete."""
        req.tokens.append(tok)
        if req.stream is not None:
            try:
                req.stream(tok)
            except Exception:  # noqa: BLE001 — a client callback must not
                logger.exception("stream callback failed (request %d)", req.rid)
        return (len(req.tokens) >= req.max_new_tokens
                or (self.eos_id is not None and tok == self.eos_id))

    def _admit(self, req: _GenRequest, slot: int, params) -> None:
        """Prefill ``req`` and insert its cache row into ``slot``."""
        jax = self._jax
        req.t_admit = time.monotonic()
        bucket = self._bucket(req.prompt.size)
        ids = np.full((1, bucket), self.pad_id, np.int32)
        ids[0, :req.prompt.size] = req.prompt
        row, tok = self._prefill(params, ids,
                                 np.int32(req.prompt.size), self._split_key())
        tok = int(jax.device_get(tok)[0])
        with self._cond:
            self._stats["admitted"] += 1
        n_active = sum(r is not None for r in self._active) + 1
        if self._emit_token(req, tok):
            # one-token request (or instant eos): never occupies the slot
            self._finish(req, n_active=n_active)
            return
        self._cache = self._insert(self._cache, row, np.int32(slot))
        self._cur_tok[slot] = tok
        self._active[slot] = req
        with self._cond:
            self._stats["max_active"] = max(self._stats["max_active"],
                                            n_active)

    def _loop(self) -> None:
        jax = self._jax
        while True:
            with self._cond:
                idle = (not self._queue
                        and all(r is None for r in self._active))
                if idle:
                    if self._stopped:
                        return
                    self._cond.wait(0.05)
                    continue
                params = self._params
                admissions: list[tuple[_GenRequest, int]] = []
                for slot in range(self.slots):
                    if self._active[slot] is None and self._queue:
                        admissions.append((self._queue.pop(0), slot))
            for req, slot in admissions:
                try:
                    self._admit(req, slot, params)
                except Exception as e:  # noqa: BLE001 — a poisoned prompt
                    # fails ITS future; the pool keeps serving the rest
                    logger.exception("prefill failed (request %d)", req.rid)
                    req.future.set_exception(e)
                    if self._tele is not None:
                        self._tele.emit("request", engine=self.name,
                                        id=req.rid, outcome="error",
                                        error=f"{type(e).__name__}: {e}")
            if all(r is None for r in self._active):
                continue
            self._cache, nxt = self._step(
                params, self._cache, self._cur_tok, self._split_key())
            nxt = np.asarray(jax.device_get(nxt))
            with self._cond:
                self._stats["steps"] += 1
            n_active = sum(r is not None for r in self._active)
            for slot, req in enumerate(self._active):
                if req is None:
                    continue
                tok = int(nxt[slot])
                if self._emit_token(req, tok):
                    self._active[slot] = None       # frees the slot: the
                    self._finish(req, n_active=n_active)  # next queued request
                    continue                        # joins mid-flight
                self._cur_tok[slot] = tok
