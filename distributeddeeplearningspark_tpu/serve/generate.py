"""Continuous batched decode for the Llama family — KV-cache slot serving.

Plain :func:`~..models.llama_gen.generate` is a batch call: every row
starts together and the call returns when the slowest row finishes, so a
server built on it would stall short requests behind long ones and leave
the chip idle while the batch drains. Continuous batching fixes both with
a fixed pool of **KV-cache slots** stepped together forever:

- the decode loop is ONE jitted single-token step over all ``slots`` rows
  (per-row cache indices — ``LlamaAttention._decode_attend`` keys each
  row at its own sequence position);
- when a sequence completes, its slot frees and the next queued request
  **joins mid-flight**: its prompt is prefilled in a separate bucketed
  ``[1, bucket]`` call (a bounded compile set, like the engine's batch
  buckets) and its cache row is inserted into the pool while the
  neighboring slots are hundreds of tokens into their own sequences;
- each sampled token is pushed through the request's optional streaming
  callback the step it is produced — time-to-first-token is one prefill,
  not one full batch.

**Paged KV arena** (``page_size=N``): instead of a private
``[max_cache_len]`` slab per slot, KV storage becomes a pool of fixed-size
pages with a block table per slot (:mod:`.kv` — the same out-of-order
first-fit discipline ``data/workers.py`` proved for shm planes). Each
jitted call gathers a slot's dense cache view from its pages and scatters
the updated view back, so the attention math — and therefore every
sampled token — is identical to the fixed-slot pool; what changes is the
memory discipline: pages reclaim out of order on eos, and **prefix
caching** lets requests sharing a page-aligned prompt prefix (the
system-prompt case) reference the same prefilled pages and prefill only
their remainder. (On TPU the gather/scatter is the XLA-portable
formulation; a paged attention kernel that indexes pages in place is the
chip-path successor — docs/SERVING.md "Paged KV sizing".)

Params are read once per step, so :meth:`ContinuousGenerator.swap_params`
(checkpoint hot-reload) takes effect at the next token without dropping
or restarting in-flight sequences; a swap also invalidates the prefix
cache (its K/V was computed under the old tree). Admission is the same
bounded-queue / typed-shed contract as :mod:`.engine`.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import numpy as np

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.serve.engine import (
    EngineStoppedError,
    OverloadedError,
)
from distributeddeeplearningspark_tpu.serve.kv import PagedKVArena, PrefixCache
from distributeddeeplearningspark_tpu.telemetry import anatomy as anatomy_lib
from distributeddeeplearningspark_tpu.telemetry import trace as trace_lib

logger = logging.getLogger("distributeddeeplearningspark_tpu.serve")


def default_prompt_buckets(max_cache_len: int) -> tuple[int, ...]:
    """Powers of two up to ``max_cache_len`` (8 at minimum): each distinct
    bucket is one prefill compile, so the ladder is short by construction."""
    sizes = []
    b = 8
    while b < max_cache_len:
        sizes.append(b)
        b *= 2
    sizes.append(max_cache_len)
    return tuple(sizes)


@dataclass
class _GenRequest:
    rid: int
    prompt: np.ndarray                      # [T] int32
    max_new_tokens: int
    stream: Callable[[int], None] | None
    future: Future = field(default_factory=Future)
    t_submit: float = 0.0
    t_admit: float = 0.0
    tokens: list[int] = field(default_factory=list)
    prefix_hit: bool = False                # admission reused cached pages
    prefix_tokens: int = 0                  # prompt tokens NOT re-prefilled
    # wall-clock stage marks for the request's span tree (trace_lib):
    # queue [ts_submit, ts_pick] → admission [ts_pick, ts_prefill0] →
    # prefill [ts_prefill0, ts_prefill1] → decode [ts_prefill1, last
    # token] → stream [last token, done]. token_ts records each sampled
    # token's wall time (the per-token decode timeline).
    trace: dict | None = None               # upstream trace context
    ts_submit: float = 0.0
    ts_pick: float | None = None            # first admission attempt
    ts_prefill0: float | None = None
    ts_prefill1: float | None = None
    token_ts: list[float] = field(default_factory=list)
    deferred: int = 0                       # page-pressure admission waits
    bucket: int = 0                         # prefill pad bucket used


class ContinuousGenerator:
    """A slot-pool decode server over one Llama param tree.

    Parameters
    ----------
    cfg:
        The model's :class:`~..models.llama.LlamaConfig` (training config;
        the decode twin is derived here via
        :func:`~..models.llama_gen.decode_model`).
    params:
        Param tree (same tree the training step holds).
    slots:
        KV-cache pool size — the decode step's fixed batch. Memory scales
        linearly (``slots × max_cache_len`` K/V per layer).
    max_cache_len:
        Cache length per slot; every request needs
        ``len(prompt) + max_new_tokens <= max_cache_len``.
    temperature / top_k / top_p / eos_id / pad_id / seed:
        Sampling configuration (engine-wide), semantics of
        :func:`~..models.llama_gen.generate`.
    prompt_buckets:
        Prefill pad ladder; right-padded with ``pad_id`` (pads sit at
        positions AFTER the real tokens, so causal attention never lets a
        real token see one, and decode overwrites each pad's K/V before
        that position is ever attended).
    max_queue:
        Admission bound; beyond it :meth:`submit` sheds with
        :class:`~.engine.OverloadedError`.
    page_size:
        None (default) = the PR 4 fixed-slot pool. An int switches KV
        storage to the paged arena: must divide ``max_cache_len`` and
        every prompt bucket. Token output is identical either way (pinned
        by tests) — paging changes memory discipline, not math.
    kv_pages:
        Paged mode's pool size in pages (page 0 is the reserved trash
        page). Default ``slots × pages_per_slot + pages_per_slot + 1`` —
        every slot full plus one sequence's worth of headroom for
        prefix-cache retention.
    prefix_cache:
        Paged mode only: share page-aligned prompt-prefix K/V between
        requests (hash-keyed map; hits skip re-prefilling the shared
        pages). Invalidated on :meth:`swap_params`.
    gauge_interval_s:
        Cadence of the ``serve`` telemetry gauge (KV occupancy, prefix
        hit rate, active slots) when a ``workdir`` is bound. A liveness
        heartbeat rides the same cadence, enriched with the oldest open
        request span so a wedged decode localizes in ``dlstatus --hosts``.
    step_delay_s:
        Debug/drill knob: sleep this long before every decode step — the
        deterministic "one replica got slow" fault the SLO sentinel smoke
        injects (``dlserve --fault-sleep-ms``). 0 (default) = off.
    """

    def __init__(
        self,
        cfg,
        params: Any,
        *,
        slots: int = 4,
        max_cache_len: int | None = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        eos_id: int | None = None,
        pad_id: int = 0,
        seed: int = 0,
        prompt_buckets: Sequence[int] | None = None,
        max_queue: int = 256,
        page_size: int | None = None,
        kv_pages: int | None = None,
        prefix_cache: bool = True,
        gauge_interval_s: float = 5.0,
        step_delay_s: float = 0.0,
        workdir: str | None = None,
        name: str = "generate",
    ):
        import jax
        import jax.numpy as jnp

        from distributeddeeplearningspark_tpu.models.llama_gen import (
            _sample,
            decode_model,
        )

        self._jax, self._jnp = jax, jnp
        self.cfg = cfg
        self.slots = int(slots)
        self.max_cache_len = int(max_cache_len or cfg.max_position)
        if self.max_cache_len > cfg.max_position:
            raise ValueError(
                f"max_cache_len {self.max_cache_len} exceeds max_position "
                f"{cfg.max_position}")
        self.name = name
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        self.max_queue = int(max_queue)
        if prompt_buckets is None:
            prompt_buckets = default_prompt_buckets(self.max_cache_len)
            if page_size:
                # paged prefill scatters whole pages, so the DEFAULT ladder
                # self-aligns: each bucket rounds up to a page multiple
                # (explicitly passed buckets are validated, not rewritten)
                prompt_buckets = {
                    min(self.max_cache_len,
                        -(-b // int(page_size)) * int(page_size))
                    for b in prompt_buckets}
        self.prompt_buckets = tuple(sorted(set(prompt_buckets)))
        if self.prompt_buckets[-1] > self.max_cache_len:
            raise ValueError(
                f"largest prompt bucket {self.prompt_buckets[-1]} exceeds "
                f"max_cache_len {self.max_cache_len}")
        # same contract as InferenceEngine: request events only when a
        # workdir is given (telemetry-silent otherwise)
        self._tele = telemetry.configure(workdir) if workdir else None
        self.gauge_interval_s = float(gauge_interval_s)
        self._last_gauge = 0.0
        # floored: a negative delay would kill the serving thread the
        # first time the loop hands it to time.sleep()
        self.step_delay_s = max(0.0, float(step_delay_s))

        self._model = decode_model(cfg, self.max_cache_len)
        self._params = params
        self.params_version: int | str = 0
        self._key = jax.random.PRNGKey(seed)

        sample = lambda logits, key: _sample(  # noqa: E731 — one-liner bind
            logits.astype(jnp.float32), key,
            temperature=temperature, top_k=top_k, top_p=top_p)

        def prefill(params, ids, true_len, key):
            """[1, bucket] prompt → (cache row at index true_len, first tok)."""
            logits, mut = self._model.apply(
                {"params": params}, {"input_ids": ids},
                train=False, mutable=["cache"])
            # pads were written into the cache beyond true_len; reset every
            # index leaf (the only int32 cache leaves) so decode resumes at
            # the REAL end of prompt — stale pad K/V beyond it is masked
            # until overwritten (llama.py _decode_attend docstring)
            cache = jax.tree.map(
                lambda x: jnp.full_like(x, true_len)
                if x.dtype == jnp.int32 else x,
                mut["cache"])
            tok = sample(logits[jnp.arange(1), true_len - 1], key)
            return cache, tok

        def step(params, cache, tok, key):
            """One decode token for every slot at once."""
            logits, mut = self._model.apply(
                {"params": params, "cache": cache},
                {"input_ids": tok[:, None]}, train=False, mutable=["cache"])
            return mut["cache"], sample(logits[:, -1], key)

        def insert(cache, row, slot):
            """Write a prefilled [1, ...] cache row into pool slot ``slot``.

            The slot axis is identified per leaf as the one where the pool
            and row shapes differ (pool ``slots`` vs row 1) — robust to the
            scanned-layer stacking that prepends a layer axis."""

            def ins(c, r):
                if c.shape == r.shape:
                    return r
                starts = tuple(
                    slot if cs != rs else 0
                    for cs, rs in zip(c.shape, r.shape))
                return jax.lax.dynamic_update_slice(c, r, starts)

            return jax.tree.map(ins, cache, row)

        # compile-ledgered (telemetry/anatomy.py): warmup and bucket-miss
        # compiles emit `compile` phase spans + cost-analyzed events, so a
        # replica's startup seconds stop misattributing to serving time;
        # prefill's pinned compile set is the prompt-bucket ladder, the
        # single-token step and the row insert compile exactly once
        self._prefill = anatomy_lib.instrument(
            jax.jit(prefill, static_argnames=()), name="decode-prefill",
            expected_signatures=len(self.prompt_buckets))
        self._step = anatomy_lib.instrument(
            jax.jit(step), name="decode-step")
        self._insert = anatomy_lib.instrument(
            jax.jit(insert), name="decode-insert")

        # cache structure from an abstract eval (free)
        def abstract_cache(batch, cache_len):
            m = decode_model(cfg, cache_len) if cache_len != self.max_cache_len \
                else self._model
            return jax.eval_shape(
                lambda p: m.apply(
                    {"params": p},
                    {"input_ids": jnp.zeros((batch, 1), jnp.int32)},
                    train=False, mutable=["cache"])[1]["cache"],
                params)

        abstract = abstract_cache(self.slots, self.max_cache_len)
        self.page_size = int(page_size) if page_size is not None else None
        if self.page_size is None:
            self._arena = None
            self._prefix = None
            # dense fixed-slot pool: zeros
            self._cache = jax.tree.map(
                lambda a: jnp.zeros(a.shape, a.dtype), abstract)
        else:
            self._init_paged(abstract, abstract_cache, kv_pages,
                             prefix_cache, sample)
        self._cur_tok = np.zeros((self.slots,), np.int32)

        self._queue: list[_GenRequest] = []
        self._active: list[_GenRequest | None] = [None] * self.slots
        self._cond = threading.Condition()
        # accepting from construction, like InferenceEngine: requests queue
        # up; decoding begins when start() spawns the serving thread
        self._stopped = False
        self._thread: threading.Thread | None = None
        self._rid = itertools.count()
        self._stats = {"requests": 0, "shed": 0, "completed": 0, "steps": 0,
                       "admitted": 0, "reloads": 0, "max_active": 0,
                       "tokens": 0, "deferred": 0}

    # -- paged KV arena setup ------------------------------------------------

    def _init_paged(self, abstract, abstract_cache, kv_pages, prefix_cache,
                    sample) -> None:
        """Build the page pool, block tables, and the paged jit twins.

        Per-leaf axis identification is structural, not positional: the
        slot (batch) axis is the one that moves when the abstract cache is
        re-evaluated at ``slots+1``, the length axis the one that moves at
        ``max_cache_len + page_size`` — robust to scanned-layer stacking
        and any future cache leaves. Leaves with no length axis are the
        int32 per-row indices; they have no pool storage (positions live
        host-side in ``self._pos``) and are rebuilt at assemble time."""
        jax, jnp = self._jax, self._jnp
        page = self.page_size
        if self.max_cache_len % page:
            raise ValueError(
                f"page_size {page} must divide max_cache_len "
                f"{self.max_cache_len}")
        bad = [b for b in self.prompt_buckets if b % page]
        if bad:
            raise ValueError(
                f"page_size {page} must divide every prompt bucket "
                f"(violating: {bad}) — prefill scatters whole pages")
        self._pps = self.max_cache_len // page
        num_pages = (int(kv_pages) if kv_pages is not None
                     else self.slots * self._pps + self._pps + 1)
        if num_pages < self._pps + 1:
            raise ValueError(
                f"kv_pages {num_pages} cannot back one full sequence "
                f"({self._pps} pages + the trash page)")
        self._arena = PagedKVArena(num_pages, page)
        self._prefix = PrefixCache(self._arena) if prefix_cache else None
        self._prefix_version = self.params_version

        leaves0, treedef = jax.tree.flatten(abstract)
        leavesB = jax.tree.leaves(abstract_cache(self.slots + 1,
                                                 self.max_cache_len))
        leavesL = jax.tree.leaves(abstract_cache(self.slots,
                                                 self.max_cache_len + page))
        self._cache_treedef = treedef
        self._leaf_meta: list[tuple] = []
        pool = []
        for s0, sb, sl in zip(leaves0, leavesB, leavesL):
            slot_ax = [i for i, (a, b) in enumerate(zip(s0.shape, sb.shape))
                       if a != b]
            len_ax = [i for i, (a, b) in enumerate(zip(s0.shape, sl.shape))
                      if a != b]
            if not len_ax:
                # per-row index leaf: int32, slot axis last by construction
                assert s0.dtype == jnp.int32 and slot_ax == [len(s0.shape) - 1], \
                    (s0.shape, s0.dtype, slot_ax)
                self._leaf_meta.append(("idx", s0.shape, s0.dtype))
                continue
            assert len(slot_ax) == 1 and len_ax == [slot_ax[0] + 1], \
                (s0.shape, slot_ax, len_ax)
            sa = slot_ax[0]
            pool_shape = (s0.shape[:sa] + (num_pages, page)
                          + s0.shape[sa + 2:])
            pool.append(jnp.zeros(pool_shape, s0.dtype))
            self._leaf_meta.append(("kv", sa, s0.dtype))
        self._pool = pool
        self._tables = np.zeros((self.slots, self._pps), np.int32)
        self._slot_pages: list[list[int]] = [[] for _ in range(self.slots)]
        self._pos = np.zeros((self.slots,), np.int32)

        def assemble(pool, tables, pos):
            """Dense cache view: per KV leaf, gather the block-table pages
            and merge (pages, page_size) back into the length axis; index
            leaves broadcast from ``pos``."""
            dense, it = [], iter(pool)
            for meta in self._leaf_meta:
                if meta[0] == "kv":
                    _, sa, _ = meta
                    g = jnp.take(next(it), tables, axis=sa)
                    dense.append(g.reshape(
                        g.shape[:sa]
                        + (g.shape[sa], g.shape[sa + 1] * g.shape[sa + 2])
                        + g.shape[sa + 3:]))
                else:
                    _, shape, dtype = meta
                    dense.append(jnp.broadcast_to(
                        pos.astype(dtype), shape[:-1] + (tables.shape[0],)))
            return jax.tree.unflatten(self._cache_treedef, dense)

        def scatter(pool, cache, tables):
            """Write a dense cache view back to its pages. Duplicate page
            ids across the table (shared prefix pages, the trash page)
            scatter in arbitrary order — shared pages always receive
            identical values (decode never writes below prompt_len), and
            the trash page is garbage by contract."""
            out, pi = [], 0
            flat = tables.reshape(-1)
            for meta, leaf in zip(self._leaf_meta, jax.tree.leaves(cache)):
                if meta[0] != "kv":
                    continue
                _, sa, _ = meta
                s, length = leaf.shape[sa], leaf.shape[sa + 1]
                d = leaf.reshape(
                    leaf.shape[:sa] + (s * (length // page), page)
                    + leaf.shape[sa + 2:])
                idx = (slice(None),) * sa + (flat,)
                out.append(pool[pi].at[idx].set(d))
                pi += 1
            return out

        def paged_step(params, pool, tables, pos, tok, key):
            cache = assemble(pool, tables, pos)
            logits, mut = self._model.apply(
                {"params": params, "cache": cache},
                {"input_ids": tok[:, None]}, train=False, mutable=["cache"])
            return scatter(pool, mut["cache"], tables), sample(
                logits[:, -1], key)

        def paged_prefill(params, pool, row_tables, start, ids, true_end, key):
            """Prefill ``ids`` (window at cache position ``start``) into the
            row backed by ``row_tables`` — ``start=0`` is a full prefill,
            ``start>0`` continues from cached prefix pages. Index leaves
            reset to ``true_end`` (pads beyond the prompt were written but
            stay masked until decode overwrites them)."""
            row = assemble(pool, row_tables,
                           jnp.full((1,), start, jnp.int32))
            logits, mut = self._model.apply(
                {"params": params, "cache": row}, {"input_ids": ids},
                train=False, mutable=["cache"])
            cache = jax.tree.map(
                lambda x: jnp.full_like(x, true_end)
                if x.dtype == jnp.int32 else x,
                mut["cache"])
            tok = sample(logits[jnp.arange(1), true_end - start - 1], key)
            return scatter(pool, cache, row_tables), tok

        # same ledger discipline as the dense twins: step compiles once,
        # prefill's pinned set is the (page-aligned) prompt-bucket ladder
        self._paged_step = anatomy_lib.instrument(
            jax.jit(paged_step), name="decode-step")
        self._paged_prefill = anatomy_lib.instrument(
            jax.jit(paged_prefill), name="decode-prefill",
            expected_signatures=len(self.prompt_buckets))

    @property
    def paged(self) -> bool:
        return self.page_size is not None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ContinuousGenerator":
        with self._cond:
            if self._thread is not None:
                return self
            self._stopped = False
            self._thread = threading.Thread(
                target=self._loop, name=f"dlserve-{self.name}", daemon=True)
            self._thread.start()
        return self

    def stop(self, *, drain: bool = True) -> None:
        """Stop admission; by default finish queued + in-flight sequences.

        A never-started generator with queued requests starts the serving
        thread just to drain them — drain=True must never strand a future."""
        if drain and self._thread is None and self._queue:
            self.start()
        with self._cond:
            if self._stopped and self._thread is None:
                return
            self._stopped = True
            if not drain:
                for req in self._queue:
                    req.future.set_exception(
                        EngineStoppedError("generator stopped before admission"))
                    if self._tele is not None:
                        self._tele.clear_span(("gen", req.rid))
                self._queue.clear()
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join()
        self._thread = None

    def __enter__(self) -> "ContinuousGenerator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- client surface ------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               stream: Callable[[int], None] | None = None,
               trace: dict | None = None) -> Future:
        """Enqueue a prompt; Future resolves to the np.int32 token array.

        ``stream`` is called with each token id the step it is sampled
        (from the serving thread — keep it cheap/non-blocking). ``trace``
        is an upstream trace context (``{"trace_id", "parent_id"}``); the
        request's stage spans — queue, admission, prefill, decode,
        stream — then join that trace instead of rooting a fresh one."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt.size + max_new_tokens > self.max_cache_len:
            raise ValueError(
                f"prompt {prompt.size} + new {max_new_tokens} exceeds "
                f"max_cache_len {self.max_cache_len}")
        if prompt.size > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt {prompt.size} exceeds largest prompt bucket "
                f"{self.prompt_buckets[-1]}")
        req = _GenRequest(rid=next(self._rid), prompt=prompt,
                          max_new_tokens=int(max_new_tokens), stream=stream,
                          trace=(trace if isinstance(trace, dict)
                                 and trace.get("trace_id") else None))
        req.t_submit = time.monotonic()
        req.ts_submit = time.time()
        with self._cond:
            if self._stopped:
                raise EngineStoppedError("generator is stopped")
            if len(self._queue) >= self.max_queue:
                self._stats["shed"] += 1
                if self._tele is not None:
                    self._tele.emit("request", engine=self.name, id=req.rid,
                                    outcome="shed",
                                    queue_depth=len(self._queue),
                                    **({"trace": req.trace["trace_id"]}
                                       if req.trace else {}))
                raise OverloadedError(len(self._queue), self.max_queue)
            self._queue.append(req)
            self._stats["requests"] += 1
            if self._tele is not None:
                # liveness note (no write): heartbeats carry the oldest
                # open request so a wedged decode localizes like a wedged
                # restore. MUST happen under the lock — once it drops, the
                # serving loop can finish the request and clear_span
                # BEFORE a late note re-inserts it, leaving a forever-
                # open "request" on every heartbeat
                self._tele.note_span(("gen", req.rid), "request")
            self._cond.notify_all()
        return req.future

    def generate(self, prompt, max_new_tokens: int, *,
                 timeout: float | None = 120.0,
                 stream: Callable[[int], None] | None = None) -> np.ndarray:
        """Blocking convenience: ``submit`` + ``result``."""
        return self.submit(prompt, max_new_tokens, stream=stream).result(
            timeout=timeout)

    def stats(self) -> dict[str, Any]:
        with self._cond:
            out = dict(self._stats)
            out["queue_depth"] = len(self._queue)
            out["active"] = sum(r is not None for r in self._active)
        out["params_version"] = self.params_version
        if self._arena is not None:
            out.update(self._arena.stats())
        if self._prefix is not None:
            out.update(self._prefix.stats())
        return out

    # -- hot reload ----------------------------------------------------------

    def swap_params(self, params: Any, *, version: int | str | None = None) -> None:
        """Swap the param tree between decode steps: in-flight sequences
        keep their KV cache and continue on the new params at the next
        token — nothing is dropped or restarted. The prefix cache is
        invalidated (its pages hold K/V computed under the old tree); the
        serving thread flushes it before the next admission."""
        jax = self._jax
        old = self._params
        try:
            shardings = jax.tree.map(lambda a: a.sharding, old)
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, s), params, shardings)
        except (AttributeError, ValueError, TypeError):
            pass
        with self._cond:
            self._params = params
            self._stats["reloads"] += 1
            if version is not None:
                self.params_version = version
            elif isinstance(self.params_version, int):
                self.params_version += 1

    def export_params(self) -> tuple[Any, int | str]:
        """Host-side snapshot of the serving params, ``(tree, version)`` —
        same contract as :meth:`.engine.InferenceEngine.export_params`
        (peer warm-up export; numpy leaves, version from the same swap)."""
        with self._cond:
            params = self._params
            version = self.params_version
        jax = self._jax
        return jax.tree.map(lambda a: np.asarray(jax.device_get(a)),
                            params), version

    # -- serving loop --------------------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return self.prompt_buckets[-1]

    def _split_key(self):
        self._key, sub = self._jax.random.split(self._key)
        return sub

    def _finish(self, req: _GenRequest, *, n_active: int) -> None:
        done = time.monotonic()
        req.future.set_result(np.asarray(req.tokens, np.int32))
        ts_done = time.time()
        with self._cond:
            self._stats["completed"] += 1
            self._stats["tokens"] += len(req.tokens)
        if self._tele is not None:
            self._tele.emit(
                "request", engine=self.name, id=req.rid, outcome="ok",
                tokens=len(req.tokens),
                queue_wait_s=round(req.t_admit - req.t_submit, 6),
                latency_s=round(done - req.t_submit, 6),
                batch_size=n_active,
                **({"trace": req.trace["trace_id"]} if req.trace else {}),
                **({"prefix_hit": req.prefix_hit,
                    "prefix_tokens": req.prefix_tokens}
                   if self.paged and self._prefix is not None else {}))
            self._emit_request_spans(req, ts_done, outcome="ok")
            self._tele.clear_span(("gen", req.rid))

    def _emit_request_spans(self, req: _GenRequest, ts_done: float, *,
                            outcome: str, error: str | None = None) -> None:
        """The request's whole causal stage tree, ONE emit_many flush at
        completion: queue → admission → prefill → decode → stream. The
        stages tile [submit, done] by construction, so the latency
        anatomy's coverage acceptance (Σ stages ≈ e2e) holds for every
        request the decode pool serves."""
        buf = trace_lib.SpanBuffer.from_context(req.trace)
        parent = buf.parent_id
        if not buf.joined:
            parent = buf.add("request", req.ts_submit, ts_done,
                             engine=self.name, outcome=outcome,
                             **({"error": error} if error else {}))
        ts_pick = req.ts_pick if req.ts_pick is not None else ts_done
        # queue starts at the ROUTER's accept time when the context
        # carries one: socket transit + dispatch bookkeeping are queueing
        # from the request's point of view, not lost coverage
        buf.add("queue", trace_lib.SpanBuffer.upstream_t0(
            req.trace, req.ts_submit), ts_pick, parent_id=parent)
        if req.ts_prefill0 is not None:
            buf.add("admission", ts_pick, req.ts_prefill0, parent_id=parent,
                    deferred=req.deferred, prefix_hit=req.prefix_hit,
                    prefix_tokens=req.prefix_tokens)
            if req.ts_prefill1 is None and error is not None:
                # prefill itself raised: its whole elapsed time IS the
                # prefill stage — booked anywhere else (it used to land
                # in `stream`) the anatomy sends the operator chasing a
                # ghost stage
                buf.add("prefill", req.ts_prefill0, ts_done,
                        parent_id=parent,
                        prompt_tokens=int(req.prompt.size),
                        bucket=req.bucket, error=error)
                buf.flush(self._tele)
                return
            ts_p1 = (req.ts_prefill1 if req.ts_prefill1 is not None
                     else req.ts_prefill0)
            buf.add("prefill", req.ts_prefill0, ts_p1, parent_id=parent,
                    prompt_tokens=int(req.prompt.size), bucket=req.bucket,
                    prefix_tokens=req.prefix_tokens)
            last_tok = req.token_ts[-1] if req.token_ts else ts_p1
            timeline = req.token_ts[:trace_lib.MAX_TOKEN_TIMELINE]
            buf.add("decode", ts_p1, last_tok, parent_id=parent,
                    tokens=len(req.tokens),
                    first_token_s=(round(req.token_ts[0] - req.ts_submit, 6)
                                   if req.token_ts else None),
                    token_ms=[round((t - ts_p1) * 1e3, 2) for t in timeline])
            buf.add("stream", last_tok, ts_done, parent_id=parent)
        elif error is not None:
            # died before prefill: the queue span plus the error evidence
            buf.add("admission", ts_pick, ts_done, parent_id=parent,
                    deferred=req.deferred, error=error)
        buf.flush(self._tele)

    def _emit_token(self, req: _GenRequest, tok: int) -> bool:
        """Record one sampled token; True when the sequence is complete."""
        req.tokens.append(tok)
        req.token_ts.append(time.time())
        if req.stream is not None:
            try:
                req.stream(tok)
            except Exception:  # noqa: BLE001 — a client callback must not
                logger.exception("stream callback failed (request %d)", req.rid)
        return (len(req.tokens) >= req.max_new_tokens
                or (self.eos_id is not None and tok == self.eos_id))

    def _admit(self, req: _GenRequest, slot: int, params, version) -> bool:
        """Prefill ``req`` and insert its cache row into ``slot``.

        ``version`` is the caller's snapshot taken with ``params`` under
        one lock hold — prefix-cache entries must be keyed by the version
        of the tree that actually computed them, and reading
        ``self.params_version`` here would race a concurrent swap (old
        params registered under the new version = stale K/V surviving the
        post-swap flush). Returns False when admission must wait (paged
        mode, arena out of pages until a completion frees some) — the
        caller re-queues the request at the front. The dense path always
        admits."""
        if self.paged:
            return self._admit_paged(req, slot, params, version)
        jax = self._jax
        req.t_admit = time.monotonic()
        bucket = self._bucket(req.prompt.size)
        req.bucket = bucket
        ids = np.full((1, bucket), self.pad_id, np.int32)
        ids[0, :req.prompt.size] = req.prompt
        req.ts_prefill0 = time.time()
        row, tok = self._prefill(params, ids,
                                 np.int32(req.prompt.size), self._split_key())
        tok = int(jax.device_get(tok)[0])
        req.ts_prefill1 = time.time()
        with self._cond:
            self._stats["admitted"] += 1
        n_active = sum(r is not None for r in self._active) + 1
        if self._emit_token(req, tok):
            # one-token request (or instant eos): never occupies the slot
            self._finish(req, n_active=n_active)
            return True
        self._cache = self._insert(self._cache, row, np.int32(slot))
        self._cur_tok[slot] = tok
        self._active[slot] = req
        with self._cond:
            self._stats["max_active"] = max(self._stats["max_active"],
                                            n_active)
        return True

    # -- paged admission -----------------------------------------------------

    def _admit_paged(self, req: _GenRequest, slot: int, params,
                     version) -> bool:
        jax, page = self._jax, self.page_size
        plen = int(req.prompt.size)
        total = plen + req.max_new_tokens

        # longest cached prefix, shrunk until a remainder bucket fits the
        # cache (near-full prompts may need a shallower reuse depth)
        n_shared, shared = (self._prefix.lookup(req.prompt, version)
                            if self._prefix is not None else (0, []))
        while True:
            start = n_shared * page
            rem = plen - start
            rb = next((b for b in self.prompt_buckets
                       if b >= rem and start + b <= self.max_cache_len), None)
            if rb is not None:
                break
            # submit() guarantees plen fits the largest bucket, so the
            # loop terminates at n_shared == 0 at the latest
            self._arena.release([shared.pop()])
            n_shared -= 1
        hit = n_shared > 0

        # back every position prefill or decode will touch
        cover = -(-max(total, start + rb) // page)
        owned = self._arena.alloc(cover - n_shared)
        if owned is None and self._prefix is not None:
            # the cache is a scavenger of free pages, never a reason to
            # refuse admission: LRU-evict until the allocation fits
            self._prefix.evict_until(cover - n_shared)
            owned = self._arena.alloc(cover - n_shared)
        if owned is None:
            # pages are held by in-flight slots; a completion will free
            # them. Re-queue (caller) — progress is guaranteed because a
            # full sequence always fits an empty arena (ctor invariant).
            if shared:
                self._arena.release(shared)
            with self._cond:
                self._stats["deferred"] += 1
            req.deferred += 1
            return False

        pages = shared + owned
        self._slot_pages[slot] = pages
        self._tables[slot, :] = 0
        self._tables[slot, :len(pages)] = pages

        req.t_admit = time.monotonic()
        req.prefix_hit, req.prefix_tokens = hit, start
        req.bucket = rb
        ids = np.full((1, rb), self.pad_id, np.int32)
        ids[0, :rem] = req.prompt[start:]
        req.ts_prefill0 = time.time()
        try:
            self._pool, tok = self._paged_prefill(
                params, self._pool, self._tables[slot:slot + 1],
                np.int32(start), ids, np.int32(plen), self._split_key())
            tok = int(jax.device_get(tok)[0])
            req.ts_prefill1 = time.time()
        except BaseException:
            # a poisoned prompt fails ITS future in _loop — but the pages
            # just allocated/retained must go back, or every such failure
            # leaks `cover` pages until the arena wedges shut
            self._release_slot(slot)
            raise
        if self._prefix is not None:
            self._prefix.record(start if hit else 0)
            # register every page-aligned depth of THIS prompt (retains the
            # pages) — done before any release so an instant finish can't
            # reclaim pages the cache wants
            self._prefix.register(req.prompt, pages[:plen // page], version)
        with self._cond:
            self._stats["admitted"] += 1
        n_active = sum(r is not None for r in self._active) + 1
        if self._emit_token(req, tok):
            self._release_slot(slot)
            self._finish(req, n_active=n_active)
            return True
        self._pos[slot] = plen
        self._cur_tok[slot] = tok
        self._active[slot] = req
        with self._cond:
            self._stats["max_active"] = max(self._stats["max_active"],
                                            n_active)
        return True

    def _release_slot(self, slot: int) -> None:
        """Return a slot's pages to the arena (pages the prefix cache
        retained survive at lower refcount) and reset its table row."""
        if self._slot_pages[slot]:
            self._arena.release(self._slot_pages[slot])
            self._slot_pages[slot] = []
        self._tables[slot, :] = 0
        self._pos[slot] = 0

    # -- telemetry gauges ----------------------------------------------------

    def _maybe_gauge(self, *, force: bool = False) -> None:
        if self._tele is None:
            return
        now = time.monotonic()
        if not force and now - self._last_gauge < self.gauge_interval_s:
            return
        self._last_gauge = now
        # liveness stamp on the gauge cadence: when the decode loop later
        # wedges inside a step, this heartbeat is the stream's last record
        # and names the oldest in-flight request (note_span enrichment)
        self._tele.heartbeat()
        fields: dict[str, Any] = {
            "engine": self.name,
            "active": sum(r is not None for r in self._active),
            "queue_depth": len(self._queue),
            "completed": self._stats["completed"],
            "params_version": self.params_version,
        }
        if self._arena is not None:
            fields.update(self._arena.stats())
        if self._prefix is not None:
            fields.update(self._prefix.stats())
        self._tele.emit("serve", **fields)

    # -- the loop ------------------------------------------------------------

    def _loop(self) -> None:
        jax = self._jax
        while True:
            with self._cond:
                idle = (not self._queue
                        and all(r is None for r in self._active))
                if idle:
                    if self._stopped:
                        self._maybe_gauge(force=True)
                        return
                    self._cond.wait(0.05)
                    continue
                # one lock hold: the version must be THE version of this
                # params snapshot (admissions key prefix-cache entries by it)
                params = self._params
                version = self.params_version
            # a params swap stales every cached prefix K/V — flush before
            # any admission could hit one (serving thread owns the cache)
            if self._prefix is not None and self._prefix_version != version:
                self._prefix.flush()
                self._prefix_version = version
            while True:
                with self._cond:
                    free = next((s for s in range(self.slots)
                                 if self._active[s] is None), None)
                    if free is None or not self._queue:
                        break
                    req = self._queue.pop(0)
                if req.ts_pick is None:   # first admission attempt only —
                    req.ts_pick = time.time()  # re-queues keep queue=wait
                try:
                    admitted = self._admit(req, free, params, version)
                except Exception as e:  # noqa: BLE001 — a poisoned prompt
                    # fails ITS future; the pool keeps serving the rest
                    logger.exception("prefill failed (request %d)", req.rid)
                    req.future.set_exception(e)
                    if self._tele is not None:
                        err = f"{type(e).__name__}: {e}"
                        self._tele.emit("request", engine=self.name,
                                        id=req.rid, outcome="error",
                                        error=err,
                                        **({"trace": req.trace["trace_id"]}
                                           if req.trace else {}))
                        self._emit_request_spans(req, time.time(),
                                                 outcome="error", error=err)
                        self._tele.clear_span(("gen", req.rid))
                    continue
                if not admitted:
                    # arena full: the request keeps its queue position and
                    # waits for a completion to free pages
                    with self._cond:
                        self._queue.insert(0, req)
                    break
            self._maybe_gauge()
            if all(r is None for r in self._active):
                continue
            if self.step_delay_s:
                time.sleep(self.step_delay_s)
            if self.paged:
                self._pool, nxt = self._paged_step(
                    params, self._pool, self._tables, self._pos,
                    self._cur_tok, self._split_key())
                nxt = np.asarray(jax.device_get(nxt))
                # advance positions only AFTER the step has executed
                # (device_get above): jax's CPU backend zero-copies
                # aligned numpy arguments, so mutating self._pos while
                # the dispatched step is still in flight races the
                # execution — the step sometimes reads the incremented
                # position and decodes one slot ahead (flaky token
                # divergence vs the dense pool)
                for slot, req in enumerate(self._active):
                    if req is not None:
                        self._pos[slot] += 1
            else:
                self._cache, nxt = self._step(
                    params, self._cache, self._cur_tok, self._split_key())
                nxt = np.asarray(jax.device_get(nxt))
            with self._cond:
                self._stats["steps"] += 1
            n_active = sum(r is not None for r in self._active)
            for slot, req in enumerate(self._active):
                if req is None:
                    continue
                tok = int(nxt[slot])
                if self._emit_token(req, tok):
                    self._active[slot] = None       # frees the slot: the
                    if self.paged:                  # next queued request
                        self._release_slot(slot)    # joins mid-flight
                    self._finish(req, n_active=n_active)
                    continue
                self._cur_tok[slot] = tok
