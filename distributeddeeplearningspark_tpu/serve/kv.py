"""Paged KV arena + prefix cache — the memory layer under continuous decode.

The PR 4 slot pool gives every sequence a private ``[max_cache_len]`` KV
allocation for its whole life: a 12-token chat turn holds the same cache
bytes as a 2k-token document, and two requests sharing a 500-token system
prompt prefill it twice. Paging fixes both with the discipline
``data/workers.py`` already proved for shm planes — a pool of fixed-size
blocks, allocated first-fit and reclaimed out of order:

- the device cache becomes a **page pool** (``[num_pages, page_size, ...]``
  per KV leaf) instead of a per-slot slab; a **block table** maps each slot
  to the ordered list of page ids backing its positions;
- :class:`PagedKVArena` is the host-side allocator: first-fit (lowest free
  page id — holes from out-of-order eos reclaim are refilled immediately,
  exactly like the workers' interval list), refcounted so a page can back
  several readers at once;
- :class:`PrefixCache` is the sharing map: page-aligned prompt prefixes are
  keyed by their token content (sha1) and mapped to the already-prefilled
  pages, so a request whose prompt starts with a known system prompt
  *references* those pages and prefills only its remainder. Sharing is safe
  by construction: decode writes land only at positions ``>= prompt_len``,
  and a shared page covers positions ``< n*page_size <= prompt_len`` — no
  writer ever touches a shared page (the vLLM full-page-sharing rule; the
  partial last page of a prefix is never shared).

Pure host bookkeeping — no jax here. The device-side gather/scatter that
materializes a slot's dense cache view from its pages lives in
:mod:`.generate` (the only consumer), keyed by the tables this module
hands out. Page 0 is reserved as the **trash page**: unallocated block-
table entries point at it, so gathers stay shape-static (garbage beyond a
slot's length is masked by attention and overwritten before it is ever
attended — the same argument the dense pool already relies on).
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import itertools
from typing import Any

import numpy as np

#: Block-table entries that back no allocated page point here. Never
#: allocated; holds garbage by design.
TRASH_PAGE = 0


class PagedKVArena:
    """First-fit page allocator with refcounts over ``num_pages`` pages.

    ``alloc`` hands out the lowest-numbered free pages (first-fit: a hole
    opened by an out-of-order ``release`` is refilled by the very next
    allocation — pool occupancy stays dense at the low ids, and the free
    structure is a heap, O(log P) per page). Pages are refcounted:
    :class:`PrefixCache` retains pages a finished slot released, so "free"
    means "no slot AND no cache entry references it".

    Sizing: a page holds ``page_size`` token positions of K+V for every
    layer; ``num_pages`` must cover at least one full-length sequence
    (``max_cache_len / page_size`` pages) plus the trash page.
    """

    def __init__(self, num_pages: int, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the reserved trash "
                f"page), got {num_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._free: list[int] = list(range(1, num_pages))
        heapq.heapify(self._free)
        self._ref: dict[int, int] = {}
        self.allocs = 0          # pages handed out, lifetime
        self.alloc_failures = 0  # alloc() calls that returned None

    @property
    def pages_total(self) -> int:
        """Allocatable pages (the trash page is not one)."""
        return self.num_pages - 1

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_used(self) -> int:
        return self.pages_total - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.pages_used / max(1, self.pages_total)

    def alloc(self, n: int) -> list[int] | None:
        """``n`` pages at refcount 1 (lowest free ids), or None if the pool
        can't supply them — the caller decides whether to evict cache
        entries and retry, or defer admission."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            self.alloc_failures += 1
            return None
        pages = [heapq.heappop(self._free) for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        self.allocs += n
        return pages

    def retain(self, pages: list[int]) -> None:
        """Add one reference to each page (sharing — pages must be live)."""
        for p in pages:
            if p not in self._ref:
                raise ValueError(f"retain of unallocated page {p}")
            self._ref[p] += 1

    def release(self, pages: list[int]) -> int:
        """Drop one reference per page; pages reaching zero return to the
        free pool (out-of-order — this is the reclaim path eos takes).
        Returns how many pages actually freed."""
        freed = 0
        for p in pages:
            r = self._ref.get(p)
            if r is None:
                raise ValueError(f"release of unallocated page {p}")
            if r > 1:
                self._ref[p] = r - 1
            else:
                del self._ref[p]
                heapq.heappush(self._free, p)
                freed += 1
        return freed

    def stats(self) -> dict[str, Any]:
        return {
            "kv_page_size": self.page_size,
            "kv_pages_total": self.pages_total,
            "kv_pages_used": self.pages_used,
            "kv_pages_free": self.pages_free,
            "kv_page_occupancy": round(self.occupancy, 4),
            "kv_page_allocs": self.allocs,
            "kv_alloc_failures": self.alloc_failures,
        }


@dataclasses.dataclass
class _PrefixEntry:
    pages: list[int]      # the n pages backing tokens [0, n*page_size)
    tokens: int           # n * page_size
    version: Any          # params version the K/V was computed under
    last_used: int        # LRU tick


class PrefixCache:
    """Hash-keyed page-sharing map: token prefix → already-prefilled pages.

    Keys are sha1 over the raw int32 token bytes of page-ALIGNED prompt
    prefixes. Registration stores every aligned depth of a prompt (depth n
    retains ``pages[:n]``), because two prompts sharing a system prompt
    diverge at an arbitrary depth — a hit must be possible at exactly the
    shared depth, not only at the registering prompt's full depth.
    ``lookup`` walks longest-first and caps the match at ``len(prompt)-1``
    tokens: at least one real token must remain to prefill, since sampling
    the first output token needs that position's logits.

    Entries are invalidated by params version (a hot-reload makes every
    cached K/V stale — :meth:`flush` drops them) and evicted LRU when the
    arena runs dry (:meth:`evict_until` — the cache is a scavenger of free
    memory, never a reason to refuse admission).
    """

    def __init__(self, arena: PagedKVArena, *, max_entries: int = 512):
        self.arena = arena
        self.max_entries = int(max_entries)
        self._entries: dict[bytes, _PrefixEntry] = {}
        self._tick = itertools.count()
        self.hits = 0
        self.misses = 0
        self.tokens_saved = 0

    def __len__(self) -> int:
        return len(self._entries)

    @staticmethod
    def _key(tokens: np.ndarray) -> bytes:
        return hashlib.sha1(
            np.ascontiguousarray(tokens, np.int32).tobytes()).digest()

    def lookup(self, prompt: np.ndarray, version: Any
               ) -> tuple[int, list[int]]:
        """Longest registered page-aligned prefix of ``prompt`` under
        ``version`` → ``(n_pages, pages)``; ``(0, [])`` on miss.

        A hit retains the pages for the caller (caller releases them with
        the slot's other pages on completion). Hit/miss accounting is NOT
        done here — the caller may still defer the admission (arena full)
        and retry, so it reports the outcome once, via :meth:`record`."""
        page = self.arena.page_size
        for n in range((len(prompt) - 1) // page, 0, -1):
            e = self._entries.get(self._key(prompt[:n * page]))
            if e is None or e.version != version:
                continue
            e.last_used = next(self._tick)
            self.arena.retain(e.pages)
            return n, list(e.pages)
        return 0, []

    def record(self, tokens_reused: int) -> None:
        """Count one completed admission: ``tokens_reused`` prompt tokens
        were served from cached pages (0 = a miss)."""
        if tokens_reused:
            self.hits += 1
            self.tokens_saved += int(tokens_reused)
        else:
            self.misses += 1

    def register(self, prompt: np.ndarray, pages: list[int],
                 version: Any) -> int:
        """Register every page-aligned depth of ``prompt`` whose pages are
        fully prefilled (``pages`` backs positions [0, len(pages)*page)).
        Returns how many new entries were created. Existing keys are kept
        (their K/V is identical by construction)."""
        page = self.arena.page_size
        created = 0
        for n in range(1, min(len(prompt) // page, len(pages)) + 1):
            k = self._key(prompt[:n * page])
            if k in self._entries:
                continue
            self.arena.retain(pages[:n])
            self._entries[k] = _PrefixEntry(
                pages=list(pages[:n]), tokens=n * page, version=version,
                last_used=next(self._tick))
            created += 1
        while len(self._entries) > self.max_entries:
            self._evict_one()
        return created

    def _evict_one(self) -> bool:
        if not self._entries:
            return False
        k = min(self._entries, key=lambda k: self._entries[k].last_used)
        self.arena.release(self._entries.pop(k).pages)
        return True

    def evict_until(self, pages_free: int) -> int:
        """LRU-evict entries until the arena has ``pages_free`` free pages
        (or the cache is empty — pages held by live slots can't be freed
        here). Returns entries evicted."""
        evicted = 0
        while self.arena.pages_free < pages_free and self._evict_one():
            evicted += 1
        return evicted

    def flush(self) -> int:
        """Drop every entry (params swapped: all cached K/V is stale)."""
        n = len(self._entries)
        for e in self._entries.values():
            self.arena.release(e.pages)
        self._entries.clear()
        return n

    def stats(self) -> dict[str, Any]:
        total = self.hits + self.misses
        return {
            "prefix_entries": len(self._entries),
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "prefix_hit_rate": round(self.hits / total, 4) if total else None,
            "prefix_tokens_saved": self.tokens_saved,
        }
