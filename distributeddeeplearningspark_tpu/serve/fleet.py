"""Serving fleet — N engine replicas as separate processes, one command.

The MPMD shape from PAPERS.md 2412.14374 — multiple independent programs,
each with its own devices and code, coordinated by a controller — applied
to serving replicas instead of pipeline stages. Each replica is its own
OS process running one :class:`~.engine.InferenceEngine` (or
:class:`~.generate.ContinuousGenerator`), launched with the supervisor's
gang idiom: a fresh port per process and the ``DLS_*`` env contract
(``DLS_PROCESS_ID`` = replica index, ``DLS_NUM_PROCESSES``,
``DLS_TELEMETRY_DIR`` — so every replica's ``request`` events land in ONE
run directory under its own process identity, and ``dlstatus
--fleet-serve`` attributes them without parsing anything).

Control + data plane is a single ``multiprocessing.connection`` socket
per replica (stdlib, authkey-authenticated, pickles numpy cleanly): the
parent sends ``{"id", "op", ...}`` requests, a reader thread resolves the
matching futures as responses arrive out of order. The transport is the
failure detector — a replica that dies tears the socket, every pending
future fails with :class:`~.router.ReplicaDiedError`, the
:class:`~.router.Router` retries those requests on the survivors and
stops picking the corpse, and :meth:`ServingFleet.restart_dead` (or the
:meth:`ServingFleet.watch` thread) relaunches it with a bumped
``DLS_RESTART`` ordinal (docs/POD_PLAYBOOK.md "A serving replica died").

**Rolling hot-reload** (:meth:`ServingFleet.rolling_reload`): one replica
at a time is drained (router stops feeding it, in-flight requests finish),
told to reload, and undrained — N−1 replicas serve throughout, so the
fleet never has zero capacity and no request is dropped. The per-replica
primitive is PR 4's params-as-argument swap; the fleet adds only ordering.

This module is both library and replica entry point:
``python -m distributeddeeplearningspark_tpu.serve.fleet`` (no args) runs
:func:`replica_main`, entirely env-configured — exactly how the
supervisor's workers boot.
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from typing import Any

from distributeddeeplearningspark_tpu import telemetry as telemetry_lib
from distributeddeeplearningspark_tpu.serve.engine import (
    EngineStoppedError,
    OverloadedError,
)
from distributeddeeplearningspark_tpu.serve.router import (
    ReplicaDiedError,
    Router,
)
from distributeddeeplearningspark_tpu.supervisor import free_port

logger = logging.getLogger("distributeddeeplearningspark_tpu.serve")

ENV_SPEC = "DLS_SERVE_SPEC"
ENV_PORT = "DLS_SERVE_PORT"
ENV_AUTHKEY = "DLS_SERVE_AUTHKEY"

#: Exceptions a replica may raise that the client reconstructs typed (the
#: load-shed/stop contract must survive the process boundary — a caller
#: branching on OverloadedError can't branch on a stringly RuntimeError).
_TYPED_ERRORS = {
    "OverloadedError": lambda m, f: OverloadedError(
        f.get("queue_depth", -1), f.get("max_queue", -1)),
    "EngineStoppedError": lambda m, f: EngineStoppedError(m),
    "ValueError": lambda m, f: ValueError(m),
}


# -- replica side (child process) ---------------------------------------------


def _tiny_llama_cfg(spec: dict):
    """The fleet's built-in CPU-serveable Llama geometry (tests/CI — real
    checkpoints come via ``checkpoint_dir`` + the standard restore path)."""
    import jax.numpy as jnp

    from distributeddeeplearningspark_tpu.models import LlamaConfig

    return LlamaConfig(
        vocab_size=int(spec.get("vocab_size", 256)), hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, intermediate_size=128,
        max_position=int(spec.get("max_cache_len", 128)), dtype=jnp.float32)


def _build_replica(spec: dict, replica_id: int, workdir: str | None):
    """(engine, reload_fn, warm_fn) for the spec'd model.

    ``reload_fn(step)`` performs one hot-reload and returns evidence:
    checkpoint-backed replicas poll the directory for a newer verified
    step (the PR 4 :class:`~.reload.HotReloader` walk, manifests and
    all); checkpoint-less ones re-init deterministically from a bumped
    seed — the drill path the CI smoke uses."""
    import jax
    import numpy as np

    seed = int(spec.get("seed", 0))
    model_name = spec.get("model", "lenet")
    ckpt_dir = spec.get("checkpoint_dir")

    if model_name == "lenet":
        from distributeddeeplearningspark_tpu.models import LeNet5
        from distributeddeeplearningspark_tpu.serve.engine import (
            InferenceEngine,
        )

        model = LeNet5()

        def init_variables(s: int):
            return {"params": model.init(
                jax.random.PRNGKey(s),
                {"image": np.zeros((1, 28, 28, 1), np.float32)},
                train=False)["params"]}

        step0 = None
        if ckpt_dir:
            from distributeddeeplearningspark_tpu import Checkpointer

            with Checkpointer(ckpt_dir, async_save=False) as ck:
                params, step0 = ck.restore_params()
            variables = {"params": params}
        else:
            variables = init_variables(seed)
        engine = InferenceEngine.for_model(
            model, variables,
            max_batch=int(spec.get("max_batch", 32)),
            max_wait_ms=float(spec.get("max_wait_ms", 5.0)),
            max_queue=int(spec.get("max_queue", 1024)),
            workdir=workdir, name=model_name)

        def warm():
            engine.warmup(
                {"image": np.zeros((28, 28, 1), np.float32)})

        swap = engine.swap_params
        new_params = init_variables
    elif model_name == "tinyllama":
        from distributeddeeplearningspark_tpu.models import LlamaForCausalLM
        from distributeddeeplearningspark_tpu.serve.generate import (
            ContinuousGenerator,
        )

        cfg = _tiny_llama_cfg(spec)
        model = LlamaForCausalLM(cfg)

        def new_params(s: int):
            return model.init(
                jax.random.PRNGKey(s),
                {"input_ids": np.zeros((1, 8), np.int32)},
                train=False)["params"]

        step0 = None
        if ckpt_dir:
            from distributeddeeplearningspark_tpu import Checkpointer

            with Checkpointer(ckpt_dir, async_save=False) as ck:
                params, step0 = ck.restore_params()
        else:
            params = new_params(seed)
        # deterministic "this replica got slow" fault (the SLO sentinel
        # drill): spec maps replica id (str — JSON keys) → per-step sleep ms
        delay_ms = (spec.get("step_delay_ms") or {}).get(str(replica_id), 0)
        engine = ContinuousGenerator(
            cfg, params,
            slots=int(spec.get("slots", 4)),
            max_cache_len=int(spec.get("max_cache_len", 128)),
            page_size=spec.get("page_size", 16),
            prefix_cache=bool(spec.get("prefix_cache", True)),
            max_queue=int(spec.get("max_queue", 1024)),
            gauge_interval_s=float(spec.get("gauge_interval_s", 1.0)),
            step_delay_s=float(delay_ms) / 1e3,
            workdir=workdir, name=model_name)

        def warm():
            engine.generate(np.arange(1, 5, dtype=np.int32), 2,
                            timeout=300.0)

        swap = engine.swap_params
    else:
        raise ValueError(f"unknown fleet model {model_name!r}")

    reloads = [0]
    reloader = None
    if ckpt_dir:
        from distributeddeeplearningspark_tpu.serve.reload import (
            HotReloader,
            checkpoint_params_loader,
        )

        reloader = HotReloader(
            engine, ckpt_dir, current_step=step0,
            load_params=checkpoint_params_loader(
                ckpt_dir, wrap_in_variables=(model_name == "lenet")))

    def reload_fn(step=None):
        if reloader is not None:
            act = reloader.poll()
            return {"action": act,
                    "params_version": engine.params_version}
        # drill path: deterministic re-init from a bumped seed
        reloads[0] += 1
        swap(new_params(seed + 1000 * reloads[0]),
             version=reloads[0])
        telemetry_lib.emit("recovery", event="serve-reload",
                           replica=replica_id,
                           params_version=engine.params_version)
        return {"action": {"action": "reinit", "seed_bump": reloads[0]},
                "params_version": engine.params_version}

    return engine, reload_fn, warm


def replica_main() -> int:
    """One serving replica, entirely env-configured (the worker half of
    the gang contract): build the engine, warm it, listen, serve ops
    until shutdown or the parent's socket dies."""
    from multiprocessing.connection import Listener

    from distributeddeeplearningspark_tpu.utils.env import (
        apply_env_platform_config,
    )

    logging.basicConfig(level=logging.INFO, format="%(message)s")
    apply_env_platform_config()
    spec = json.loads(os.environ[ENV_SPEC])
    if spec.get("pin_cores"):
        # one replica ↔ one core, the CPU stand-in for one-replica-per-chip:
        # without it XLA's per-process threadpool spans every host core, so
        # replica 0 alone saturates the box and 1→2 scaling measures thread
        # contention, not replica capacity. Affinity must land BEFORE jax
        # initializes its threadpool (first jax import below).
        try:
            cores = sorted(os.sched_getaffinity(0))
            mine = cores[int(os.environ.get("DLS_PROCESS_ID", "0"))
                         % len(cores)]
            os.sched_setaffinity(0, {mine})
        except (AttributeError, OSError):
            pass  # non-Linux: serve unpinned rather than not at all
    port = int(os.environ[ENV_PORT])
    authkey = bytes.fromhex(os.environ[ENV_AUTHKEY])
    replica_id = int(os.environ.get("DLS_PROCESS_ID", "0"))
    workdir = os.environ.get(telemetry_lib.WORKDIR_ENV) or None

    engine, reload_fn, warm = _build_replica(spec, replica_id, workdir)
    engine.start()
    if spec.get("warmup", True):
        warm()
    logger.info("replica %d: serving %s on port %d", replica_id,
                spec.get("model"), port)

    send_lock = threading.Lock()

    with Listener(("127.0.0.1", port), authkey=authkey) as listener, \
            listener.accept() as conn:

        def reply(mid, **fields):
            with send_lock:
                try:
                    conn.send({"id": mid, **fields})
                except (OSError, ValueError):
                    pass  # parent gone; the recv loop will see EOF too

        def reply_err(mid, e: BaseException):
            extra = {}
            if isinstance(e, OverloadedError):
                extra = {"queue_depth": e.queue_depth,
                         "max_queue": e.max_queue}
            reply(mid, ok=False, etype=type(e).__name__,
                  error=str(e), **extra)

        def on_future(mid, fut: Future):
            e = fut.exception()
            if e is not None:
                reply_err(mid, e)
            else:
                # ts = when the reply left the replica: the parent stamps
                # it on the resolved future so the router can account the
                # return hop as a trace stage (stream leg=return) — the
                # last piece of the e2e latency the stage sum must cover
                reply(mid, ok=True, result=fut.result(), ts=time.time())

        try:
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    logger.info("replica %d: parent gone, stopping",
                                replica_id)
                    break
                mid, op = msg.get("id"), msg.get("op")
                try:
                    if op == "ping":
                        reply(mid, ok=True,
                              result={"replica": replica_id, "pid": os.getpid(),
                                      "model": spec.get("model")})
                    elif op == "stats":
                        reply(mid, ok=True, result=engine.stats())
                    elif op == "infer":
                        # trace context crosses the socket as a plain
                        # payload field: the replica's stage spans join
                        # the router's tree (telemetry.trace)
                        fut = engine.submit(msg["example"],
                                            trace=msg.get("trace"))
                        fut.add_done_callback(
                            lambda f, mid=mid: on_future(mid, f))
                    elif op == "generate":
                        fut = engine.submit(msg["prompt"],
                                            msg["max_new_tokens"],
                                            trace=msg.get("trace"))
                        fut.add_done_callback(
                            lambda f, mid=mid: on_future(mid, f))
                    elif op == "reload":
                        reply(mid, ok=True, result=reload_fn(msg.get("step")))
                    elif op == "export_params":
                        # peer warm-up export: the serving weights leave as
                        # numpy + a digest so the importer can prove the
                        # transfer landed intact (docs/POD_PLAYBOOK.md)
                        from distributeddeeplearningspark_tpu.parallel import (
                            live_reshard,
                        )

                        params, version = engine.export_params()
                        reply(mid, ok=True, result={
                            "params": params, "version": version,
                            "digest": live_reshard.tree_digest(params)})
                    elif op == "import_params":
                        from distributeddeeplearningspark_tpu.parallel import (
                            live_reshard,
                        )

                        got = live_reshard.tree_digest(msg["params"])
                        want = msg.get("digest")
                        if want is not None and got != want:
                            raise ValueError(
                                f"import_params digest mismatch: donor sent "
                                f"{want}, received tree hashes to {got} — "
                                f"refusing to serve corrupted weights; "
                                f"reload from the checkpoint instead")
                        engine.swap_params(msg["params"],
                                           version=msg.get("version"))
                        telemetry_lib.emit(
                            "recovery", event="replica-warmup",
                            replica=replica_id, digest=got,
                            params_version=engine.params_version)
                        reply(mid, ok=True, result={
                            "params_version": engine.params_version,
                            "digest": got})
                    elif op == "shutdown":
                        reply(mid, ok=True, result=engine.stats())
                        break
                    else:
                        reply(mid, ok=False, etype="ValueError",
                              error=f"unknown op {op!r}")
                except Exception as e:  # noqa: BLE001 — one bad op must not
                    # kill the replica; the caller learns the real error
                    reply_err(mid, e)
        finally:
            engine.stop()
    return 0


# -- parent side --------------------------------------------------------------


class ReplicaHandle:
    """Client for one replica process: request/response correlation over
    the authenticated socket, a reader thread resolving futures, and
    death detection (socket EOF or process exit fails every pending
    future with :class:`~.router.ReplicaDiedError` — the router's cue to
    fail over)."""

    def __init__(self, name: str, proc: subprocess.Popen, conn):
        self.name = name
        self.proc = proc
        self._conn = conn
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: dict[int, Future] = {}
        self._mid = 0
        self._dead = False
        self._reader = threading.Thread(
            target=self._read_loop, name=f"dlserve-{name}-reader",
            daemon=True)
        self._reader.start()

    @property
    def alive(self) -> bool:
        return not self._dead and self.proc.poll() is None

    def submit(self, payload: dict[str, Any], op: str = "infer") -> Future:
        fut: Future = Future()
        with self._lock:
            if self._dead:
                raise ReplicaDiedError(f"replica {self.name} is dead")
            self._mid += 1
            mid = self._mid
            self._pending[mid] = fut
        try:
            with self._send_lock:
                self._conn.send({"id": mid, "op": op, **payload})
        except (OSError, ValueError, BrokenPipeError) as e:
            with self._lock:
                self._pending.pop(mid, None)
            self._mark_dead()
            raise ReplicaDiedError(
                f"replica {self.name}: send failed ({e})") from e
        return fut

    def call(self, op: str, *, timeout: float | None = 60.0,
             **payload) -> Any:
        """Blocking convenience for control ops (ping/stats/reload)."""
        return self.submit(payload, op).result(timeout=timeout)

    def _read_loop(self) -> None:
        while True:
            try:
                msg = self._conn.recv()
            except (EOFError, OSError):
                self._mark_dead()
                return
            with self._lock:
                fut = self._pending.pop(msg.get("id"), None)
            if fut is None:
                continue
            if msg.get("ok"):
                if msg.get("ts") is not None:
                    fut.dls_reply_ts = msg["ts"]  # replica send time
                fut.set_result(msg.get("result"))
            else:
                make = _TYPED_ERRORS.get(msg.get("etype"))
                err = (make(msg.get("error", ""), msg) if make
                       else RuntimeError(
                           f"{msg.get('etype')}: {msg.get('error')}"))
                fut.set_exception(err)

    def _mark_dead(self) -> None:
        with self._lock:
            if self._dead:
                return
            self._dead = True
            pending = list(self._pending.values())
            self._pending.clear()
        for fut in pending:
            if not fut.done():
                fut.set_exception(ReplicaDiedError(
                    f"replica {self.name} died with the request in flight"))

    def stop(self, timeout: float = 15.0) -> None:
        try:
            if self.alive:
                self.call("shutdown", timeout=timeout)
        except Exception:  # noqa: BLE001 — best-effort; escalate below
            pass
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        try:
            self._conn.close()
        except OSError:
            pass


class LocalReplica:
    """In-process handle over an engine/generator — same protocol as
    :class:`ReplicaHandle`, no process. For tests, and for composing a
    router over engines that share one process (e.g. two meshes)."""

    def __init__(self, name: str, engine, *, reload_fn=None):
        self.name = name
        self.engine = engine
        self.alive = True
        self._reload_fn = reload_fn
        self._reloads = 0

    def submit(self, payload: dict[str, Any], op: str = "infer") -> Future:
        if not self.alive:
            raise ReplicaDiedError(f"replica {self.name} is dead")
        if op == "infer":
            return self.engine.submit(payload["example"],
                                      trace=payload.get("trace"))
        if op == "generate":
            return self.engine.submit(payload["prompt"],
                                      payload["max_new_tokens"],
                                      trace=payload.get("trace"))
        fut: Future = Future()
        try:
            if op in ("stats", "ping"):
                fut.set_result(self.engine.stats())
            elif op == "reload":
                if self._reload_fn is None:
                    raise ValueError(f"replica {self.name} has no reload_fn")
                self._reloads += 1
                self.engine.swap_params(self._reload_fn(self._reloads))
                fut.set_result(
                    {"params_version": self.engine.params_version})
            elif op == "export_params":
                from distributeddeeplearningspark_tpu.parallel import (
                    live_reshard,
                )

                params, version = self.engine.export_params()
                fut.set_result({
                    "params": params, "version": version,
                    "digest": live_reshard.tree_digest(params)})
            elif op == "import_params":
                from distributeddeeplearningspark_tpu.parallel import (
                    live_reshard,
                )

                got = live_reshard.tree_digest(payload["params"])
                want = payload.get("digest")
                if want is not None and got != want:
                    raise ValueError(
                        f"import_params digest mismatch: donor sent {want}, "
                        f"received tree hashes to {got} — refusing to serve "
                        f"corrupted weights; reload from the checkpoint "
                        f"instead")
                self.engine.swap_params(payload["params"],
                                        version=payload.get("version"))
                fut.set_result({
                    "params_version": self.engine.params_version,
                    "digest": got})
            else:
                raise ValueError(f"unknown op {op!r}")
        except Exception as e:  # noqa: BLE001 — protocol parity with the
            fut.set_exception(e)  # process handle: errors ride the future
        return fut

    def call(self, op: str, *, timeout: float | None = 60.0,
             **payload) -> Any:
        return self.submit(payload, op).result(timeout=timeout)

    def stop(self, timeout: float = 15.0) -> None:
        self.engine.stop()


def _proc_of(replica_name: str) -> str | None:
    """Fleet handle name ("r<idx>") -> the replica's telemetry process
    name ("p<idx>" — the fleet exports ``DLS_PROCESS_ID=idx``), so
    recovery events can be joined against per-process serving rows and
    health-alert evidence without knowing the naming convention."""
    if replica_name.startswith("r") and replica_name[1:].isdigit():
        return "p" + replica_name[1:]
    return None


class ServingFleet:
    """Launch and manage N replica processes (the serving gang).

    ``spec`` is the replica build recipe (model, checkpoint_dir, engine
    knobs — see :func:`_build_replica`), shipped to each child via
    ``DLS_SERVE_SPEC``. Replicas inherit the parent env plus the gang
    contract; ``workdir`` binds every replica's telemetry into one run
    directory (``dlstatus --fleet-serve`` reads it back).
    """

    def __init__(self, spec: dict, *, replicas: int = 2,
                 workdir: str | None = None,
                 startup_timeout_s: float = 240.0,
                 env: dict[str, str] | None = None):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.spec = dict(spec)
        self.num_replicas = int(replicas)
        self.workdir = workdir
        self.startup_timeout_s = float(startup_timeout_s)
        self.env = dict(env or {})
        self.handles: list[ReplicaHandle] = []
        self._ordinals: dict[int, int] = {}
        self._watch_stop = threading.Event()
        self._watch_thread: threading.Thread | None = None
        self._tele = (telemetry_lib.EventWriter(
            workdir, process="fleet", host=None) if workdir else None)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingFleet":
        t0 = time.monotonic()
        # Popen everything first (compiles overlap), then connect each
        launches = [self._spawn(i) for i in range(self.num_replicas)]
        handles: list[ReplicaHandle] = []
        try:
            for i, (proc, port, key) in enumerate(launches):
                handles.append(self._connect(i, proc, port, key))
            for h in handles:
                h.call("ping", timeout=self.startup_timeout_s)
        except BaseException:
            # one replica failing to come up must not leak the rest:
            # connected ones stop cleanly; never-connected ones would
            # block in accept() forever waiting for a parent that gave up
            for h in handles:
                try:
                    h.stop(timeout=2.0)
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass
            for proc, _, _ in launches[len(handles):]:
                if proc.poll() is None:
                    proc.terminate()
            raise
        self.handles = handles
        logger.info("fleet: %d replica(s) serving after %.1fs",
                    len(self.handles), time.monotonic() - t0)
        return self

    def _spawn(self, idx: int) -> tuple[subprocess.Popen, int, str]:
        port = free_port()
        key = secrets.token_hex(16)
        ordinal = self._ordinals.get(idx, 0)
        env = {
            **os.environ,
            **self.env,
            "DLS_PROCESS_ID": str(idx),
            "DLS_NUM_PROCESSES": str(self.num_replicas),
            "DLS_RESTART": str(ordinal),
            ENV_PORT: str(port),
            ENV_AUTHKEY: key,
            ENV_SPEC: json.dumps(self.spec),
        }
        if self.workdir:
            env[telemetry_lib.WORKDIR_ENV] = self.workdir
        # -c, not -m: running the module under runpy while the package's
        # __init__ also imports it would double-execute it (runpy warns)
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "import sys; from distributeddeeplearningspark_tpu.serve."
             "fleet import replica_main; sys.exit(replica_main())"],
            env=env)
        return proc, port, key

    def _connect(self, idx: int, proc, port: int, key: str) -> ReplicaHandle:
        from multiprocessing.connection import Client

        deadline = time.monotonic() + self.startup_timeout_s
        while True:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"replica {idx} exited rc={proc.returncode} before "
                    f"accepting its control socket")
            try:
                conn = Client(("127.0.0.1", port),
                              authkey=bytes.fromhex(key))
                break
            except (ConnectionRefusedError, OSError):
                if time.monotonic() > deadline:
                    proc.terminate()
                    raise RuntimeError(
                        f"replica {idx} did not listen within "
                        f"{self.startup_timeout_s:.0f}s")
                time.sleep(0.1)
        return ReplicaHandle(f"r{idx}", proc, conn)

    def router(self, **kw) -> Router:
        kw.setdefault("workdir", self.workdir)
        return Router(list(self.handles), **kw)

    def stop(self) -> None:
        self._watch_stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join()
            self._watch_thread = None
        for h in self.handles:
            h.stop()
        if self._tele is not None:
            self._tele.close()

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- rolling hot-reload --------------------------------------------------

    def rolling_reload(self, router: Router, *, step: int | None = None,
                       drain_timeout_s: float = 120.0,
                       reload_timeout_s: float = 300.0) -> list[dict]:
        """Reload every replica, one at a time, with zero global downtime:
        drain (router stops feeding it) → wait for its in-flight requests
        to finish → reload → undrain. N−1 replicas serve at every moment;
        the router's drain guard refuses to take the last one offline.

        Returns one evidence record per replica."""
        results = []
        for h in self.handles:
            router.drain(h.name)
            try:
                deadline = time.monotonic() + drain_timeout_s
                while router.inflight(h.name) > 0:
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"{h.name}: {router.inflight(h.name)} requests "
                            f"still in flight after {drain_timeout_s:.0f}s "
                            f"drain")
                    time.sleep(0.002)
                rec = h.call("reload", step=step, timeout=reload_timeout_s)
                results.append({"replica": h.name, **(rec or {})})
                if self._tele is not None:
                    self._tele.recovery(None, "rolling-reload",
                                        replica=h.name,
                                        replica_process=_proc_of(h.name),
                                        params_version=(rec or {}).get(
                                            "params_version"))
            finally:
                router.undrain(h.name)
        return results

    # -- failure handling ----------------------------------------------------

    def _warm_from_peer(self, nh) -> dict | None:
        """Warm a relaunched replica's weights from an alive peer instead of
        disk: export the donor's serving params (numpy + digest over the
        socket), import them into the newcomer, which re-hashes before
        swapping. The relaunch already serves *something* (spec seed or
        whatever the checkpoint dir holds); this replaces it with the exact
        tree the survivors are serving — no stale-version window, no
        checkpoint round trip. Returns the warm-up record, or None when no
        donor is alive or the transfer failed (the replica then keeps its
        disk/seed params — degraded, not down)."""
        donor = next(
            (h for h in self.handles if h is not nh and h.alive), None)
        if donor is None:
            return None
        try:
            t0 = time.monotonic()
            exported = donor.call("export_params",
                                  timeout=self.startup_timeout_s)
            rec = nh.call("import_params", params=exported["params"],
                          version=exported["version"],
                          digest=exported["digest"],
                          timeout=self.startup_timeout_s)
            return {"donor": donor.name,
                    "wall_s": round(time.monotonic() - t0, 6),
                    **(rec or {})}
        except Exception:  # noqa: BLE001 — warm-up is best-effort: a failed
            # transfer must not turn one dead replica into two
            logger.exception("fleet: warm-up of %s from peer failed; "
                             "serving its own restore", nh.name)
            return None

    def restart_dead(self, router: Router | None = None) -> list[str]:
        """Relaunch every dead replica (bumped ``DLS_RESTART`` ordinal),
        warm its weights from an alive peer (:meth:`_warm_from_peer`), and
        swap the new handle into the router. Returns restarted names."""
        restarted = []
        for i, h in enumerate(self.handles):
            if h.alive:
                continue
            rc = h.proc.poll()
            self._ordinals[i] = self._ordinals.get(i, 0) + 1
            logger.warning("fleet: replica %s died (rc=%s); restarting "
                           "(ordinal %d)", h.name, rc, self._ordinals[i])
            h.stop(timeout=1.0)
            proc, port, key = self._spawn(i)
            nh = self._connect(i, proc, port, key)
            nh.call("ping", timeout=self.startup_timeout_s)
            warm = self._warm_from_peer(nh)
            self.handles[i] = nh
            if router is not None:
                router.replace(nh)
            if self._tele is not None:
                # replica_process is the incident-correlation stamp: the
                # health engine's alert evidence names replicas by their
                # telemetry stream ("p0"), the fleet by handle ("r0") —
                # both on the event lets the timeline join them
                self._tele.recovery(None, "replica-restart",
                                    replica=nh.name,
                                    replica_process=_proc_of(nh.name),
                                    returncode=rc,
                                    ordinal=self._ordinals[i],
                                    warmed_from=(warm or {}).get("donor"))
                if warm is not None:
                    self._tele.recovery(
                        None, "replica-warmup", replica=nh.name,
                        replica_process=_proc_of(nh.name),
                        donor=warm["donor"], wall_s=warm["wall_s"],
                        digest=warm.get("digest"),
                        params_version=warm.get("params_version"))
            restarted.append(nh.name)
        return restarted

    def watch(self, router: Router, *, interval_s: float = 1.0) -> None:
        """Background liveness watcher: restart dead replicas while the
        router keeps routing around them. Stopped by :meth:`stop`."""
        if self._watch_thread is not None:
            return

        def loop():
            while not self._watch_stop.wait(interval_s):
                try:
                    self.restart_dead(router)
                except Exception:  # noqa: BLE001 — the watcher must outlive
                    logger.exception("fleet watch: restart failed")

        self._watch_thread = threading.Thread(
            target=loop, name="dlserve-fleet-watch", daemon=True)
        self._watch_thread.start()


if __name__ == "__main__":
    sys.exit(replica_main())
