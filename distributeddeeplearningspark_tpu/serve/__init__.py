"""``dlserve`` — the inference-serving subsystem.

The Spark-shaped lifecycle this repo preserves (``dlsubmit`` trains,
``dlsupervise`` keeps the gang alive, ``dlstatus`` reads the telemetry)
had no serving analogue: after three PRs the framework could train,
recover, and observe a run but not answer a single request. This package
closes the loop, reusing the layers the previous PRs hardened instead of
growing a parallel stack:

- :mod:`.engine` — a thread-safe request queue feeding a dynamic
  micro-batcher: waiting requests coalesce up to ``max_batch`` /
  ``max_wait_ms``, pad to a fixed set of jit-compiled batch buckets (no
  recompile per request), and run one jitted forward on the existing
  mesh/sharding layer. Admission control is a bounded queue with a typed
  load-shed rejection (:class:`~.engine.OverloadedError`).
- :mod:`.generate` — continuous batched decode for
  :mod:`..models.llama_gen`: a fixed set of KV-cache slots, bucketed
  prefill, join-mid-flight admission the moment a sequence completes, and
  per-token streaming callbacks.
- :mod:`.reload` — checkpoint hot-reload: watch the training run's
  checkpoint directory, verify each new step against its PR 1 integrity
  manifest, swap params between batches without dropping an in-flight
  request, and keep the previous params serving when a candidate fails
  verification.
- :mod:`.kv` — the paged KV arena: first-fit page allocator with
  refcounts plus the hash-keyed prefix cache that lets requests sharing
  a system prompt reference the same prefilled pages.
- :mod:`.fleet` + :mod:`.router` — the pod-scale layer: N engine
  replicas as separate processes (supervisor gang idiom, ``DLS_*`` env
  contract) behind a queue-depth/p99-aware router with per-tenant
  load-shed budgets, rolling hot-reload with zero global downtime, and
  route-around + restart on replica death.
- :mod:`.cli` — the ``dlserve`` console entry point (synthetic-load
  harness + latency report; ``--replicas N`` drives the whole fleet from
  one command; see docs/SERVING.md).

Every request leaves a ``request`` telemetry event (queue wait, batch
size, inference time) in the same JSONL stream the training side writes,
and ``dlstatus`` folds them into p50/p99 latency rollups
(docs/OBSERVABILITY.md).
"""

from distributeddeeplearningspark_tpu.serve.engine import (  # noqa: F401
    EngineStoppedError,
    InferenceEngine,
    OverloadedError,
)
from distributeddeeplearningspark_tpu.serve.fleet import (  # noqa: F401
    LocalReplica,
    ReplicaHandle,
    ServingFleet,
)
from distributeddeeplearningspark_tpu.serve.generate import (  # noqa: F401
    ContinuousGenerator,
)
from distributeddeeplearningspark_tpu.serve.kv import (  # noqa: F401
    PagedKVArena,
    PrefixCache,
)
from distributeddeeplearningspark_tpu.serve.reload import (  # noqa: F401
    HotReloader,
)
from distributeddeeplearningspark_tpu.serve.router import (  # noqa: F401
    NoReplicaError,
    ReplicaDiedError,
    Router,
)

__all__ = [
    "InferenceEngine",
    "ContinuousGenerator",
    "HotReloader",
    "OverloadedError",
    "EngineStoppedError",
    "PagedKVArena",
    "PrefixCache",
    "Router",
    "ServingFleet",
    "ReplicaHandle",
    "LocalReplica",
    "NoReplicaError",
    "ReplicaDiedError",
]
