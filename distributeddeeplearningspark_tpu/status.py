"""``dlstatus`` — render a run report from a run directory's telemetry alone.

The terminal counterpart of the Spark UI's job page, sibling of
``dlprofile`` (which answers "where did the *device* time go" from a trace;
this answers "where did the *wall-clock* go" from the JSONL event stream —
see docs/OBSERVABILITY.md). It needs nothing but the files: a crashed or
still-running run reports exactly as well as a finished one, which is the
point — the first question after an incident is "what fraction of the run
was productive, and what ate the rest".

::

    dlstatus <workdir>                # goodput table, attempts, recovery
    dlstatus <workdir> --json         # machine-readable report
    dlstatus <workdir> --hosts        # + per-host fleet table, skew, verdicts
    dlstatus <workdir> --fleet-serve  # + per-replica serving table
    dlstatus <workdir> --traces       # + request latency anatomy (trace fold)
    dlstatus <workdir> --slo 0.25     # + SLO sentinel: p99 target, burn rate
    dlstatus <workdir> --anatomy      # + compile ledger, device/host/input
                                      #   split, MFU, memory watermarks
    dlstatus <workdir> --health       # + rule-evaluated health verdicts
                                      #   (rewrites <workdir>/health.json)
    dlstatus <workdir> --incidents    # + the ordered incident timeline
                                      #   (alert edges + recovery + attempts)
    dlstatus --cluster ROOT           # every workdir under ROOT: per-tenant
                                      #   goodput/occupancy, worst alert
    dlstatus <workdir> --watch        # live-follow: re-render on an interval
    dlstatus <workdir> --export-trace out.json  # Chrome/Perfetto trace_event

A workdir that served traffic (:mod:`..serve` — ``request`` events in the
stream) additionally gets the serving rollup: request counts by outcome
(ok/shed/error), p50/p99/max latency, queue-wait percentiles, mean batch
size, and throughput.

``--hosts`` adds the pod-level view (:mod:`..telemetry.fleet`): one row per
host with last step / heartbeat age / current phase / comms wait / goodput,
the step-skew timeline, and — when the evidence supports one — a straggler
or hang verdict naming the culprit host. Like the rest of the report it is
a pure fold over the JSONL streams, so it works on crashed and partial
streams (a silent host is exactly what it localizes).

``--fleet-serve`` adds the serving-fleet view
(:func:`..telemetry.fleet.serving_fleet`): one row per replica process
with request counts, p50/p99, shed rate, KV page occupancy, and
prefix-cache hit rate — the table that names which replica is shedding,
paging-pressured, or dead-silent (docs/POD_PLAYBOOK.md "A serving replica
died").
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.telemetry import anatomy as anatomy_lib
from distributeddeeplearningspark_tpu.telemetry import fleet as fleet_lib
from distributeddeeplearningspark_tpu.telemetry import health as health_lib
from distributeddeeplearningspark_tpu.telemetry import series as series_lib

#: goodput components rendered in the breakdown table, in display order.
_COMPONENTS = telemetry.GOODPUT_COMPONENTS


def attempts_from(events: list[dict]) -> list[dict]:
    """Fold ``attempt`` records into one row per gang launch.

    Rows carry ``(session, ordinal)``: a second supervisor invocation on
    the same workdir restarts ordinals at 0, and the earlier session's
    history must stay in the timeline, not be overwritten — a repeated
    ``begin`` for an ordinal already begun starts a new session. A crashed
    supervisor can leave a begin with no end — the row then reports
    ``end_ts: None`` and no classification, which is itself diagnostic
    (the supervisor died mid-attempt). A row with a backoff but NO begin
    means the supervisor was killed during the backoff sleep — that
    attempt never launched (render says so, so nobody hunts for a gang
    that never existed)."""
    rows: list[dict] = []
    current: dict[int, dict] = {}
    session = 0

    def flush() -> None:
        rows.extend(current[k] for k in sorted(current))
        current.clear()

    for e in events:
        if e.get("kind") != "attempt":
            continue
        ordinal = int(e.get("ordinal", -1))
        edge = e.get("edge")
        if (edge == "begin" and ordinal in current
                and current[ordinal]["begin_ts"] is not None):
            # the same ordinal launching again = a fresh supervisor session
            flush()
            session += 1
        row = current.setdefault(ordinal, {
            "session": session, "ordinal": ordinal, "begin_ts": None,
            "end_ts": None, "duration_s": None, "returncodes": None,
            "classification": None, "made_progress": None, "backoff_s": None,
            "num_processes": None, "dead_host": None,
        })
        if edge == "begin":
            row["begin_ts"] = float(e["ts"])
            if "num_processes" in e:
                row["num_processes"] = e["num_processes"]
        elif edge == "end":
            row["end_ts"] = float(e["ts"])
            for k in ("duration_s", "returncodes", "classification",
                      "made_progress", "num_processes", "dead_host"):
                if k in e:
                    row[k] = e[k]
        elif edge == "backoff":
            row["backoff_s"] = e.get("delay_s")
    flush()
    return rows


# the ONE percentile definition (nearest-rank, jax-free) now lives beside
# the serving-fleet rollup that also needs it; re-exported here because
# dlserve and the tests import it as status._percentile
_percentile = fleet_lib._percentile


def serving_from(events: list[dict]) -> dict | None:
    """Fold ``request`` events (:mod:`..serve`) into the latency rollup.

    None when the run served nothing. Latency percentiles cover completed
    requests only; shed/error counts ride alongside so a load-shedding
    incident can't hide inside a pretty p50 (the shed requests never got a
    latency to report)."""
    reqs = [e for e in events if e.get("kind") == "request"]
    if not reqs:
        return None
    ok = [e for e in reqs if e.get("outcome") == "ok"]
    lat = sorted(float(e["latency_s"]) for e in ok
                 if e.get("latency_s") is not None)
    queue = sorted(float(e["queue_wait_s"]) for e in ok
                   if e.get("queue_wait_s") is not None)
    sizes = [float(e["batch_size"]) for e in ok if e.get("batch_size")]
    span = float(reqs[-1]["ts"]) - float(reqs[0]["ts"])
    return {
        "requests": len(reqs),
        "ok": len(ok),
        "shed": sum(e.get("outcome") == "shed" for e in reqs),
        "errors": sum(e.get("outcome") == "error" for e in reqs),
        "engines": sorted({str(e["engine"]) for e in reqs
                           if e.get("engine") is not None}),
        "latency_p50_s": _percentile(lat, 0.50),
        "latency_p99_s": _percentile(lat, 0.99),
        "latency_max_s": lat[-1] if lat else None,
        "queue_wait_p50_s": _percentile(queue, 0.50),
        "queue_wait_p99_s": _percentile(queue, 0.99),
        "mean_batch_size": (sum(sizes) / len(sizes)) if sizes else None,
        "requests_per_s": (len(ok) / span) if span > 0 else None,
    }


#: worker-pool gauge keys a step_metrics event may carry (emitted by
#: StarvationProbe.snapshot when a data/workers.py pool is live).
_WORKER_KEYS = ("input_workers", "worker_util_mean", "worker_util_min",
                "worker_items", "worker_overflow", "worker_ahead_mean",
                "worker_ring_used_mb")


def input_workers_from(events: list[dict]) -> dict | None:
    """The newest input-worker-pool gauge set, or None when the run never
    used a pool. The latest snapshot (not an average) is what answers "is
    the pool or the consumer the bottleneck *now*" — utilizations are
    pool-lifetime fractions already."""
    for e in reversed(events):
        if e.get("kind") == "step_metrics" and e.get("input_workers"):
            return {k: e[k] for k in _WORKER_KEYS if e.get(k) is not None}
    return None


def shuffle_from(events: list[dict]) -> dict | None:
    """Fold ``shuffle`` events (:mod:`..data.exchange`) into the shuffle
    block, or None when the run never shuffled. Totals sum every exchange
    in the stream; ``last`` keeps the newest summary whole (its per-bucket
    row counts are what skew is judged from)."""
    done = [e for e in events
            if e.get("kind") == "shuffle" and e.get("edge") == "done"]
    spill_events = sum(e.get("kind") == "shuffle"
                       and e.get("edge") == "spill" for e in events)
    retry_events = [e for e in events if e.get("kind") == "shuffle"
                    and e.get("edge") == "retry"]
    spec_events = sum(e.get("kind") == "shuffle"
                      and e.get("edge") == "speculate" for e in events)
    bl_events = sum(e.get("kind") == "shuffle"
                    and e.get("edge") == "blacklist" for e in events)
    if not done:
        return None
    last = done[-1]
    rows = [int(r) for r in (last.get("bucket_rows") or [])]
    mean_rows = (sum(rows) / len(rows)) if rows else 0.0
    max_rows = max(rows) if rows else 0
    skew = (max_rows / mean_rows) if mean_rows > 0 else None
    if skew is None:
        verdict = "no rows"
    elif skew < 2.0:
        verdict = f"balanced (max/mean {skew:.2f}x)"
    else:
        verdict = (f"SKEWED — bucket {rows.index(max_rows)} holds "
                   f"{skew:.1f}x the mean; pre-bucket or salt the hot key")
    def _fmt_total(key: str) -> int:
        return sum(int(e.get(key, 0) or 0) for e in done)

    # per-format split (ISSUE 12): which bytes/keys rode which transport.
    # Pre-columnar events carry no per-format fields — their pairs/bytes
    # fold under "tuple" (which is what they were) so totals still tie out
    formats = {
        "columnar": {
            "pairs": _fmt_total("columnar_pairs"),
            "bytes": _fmt_total("columnar_bytes"),
            "buckets": _fmt_total("columnar_buckets"),
        },
        "tuple": {
            "pairs": sum(
                int(e.get("tuple_pairs",
                          e.get("pairs_in", 0)) or 0) for e in done),
            "bytes": sum(
                int(e.get("tuple_bytes",
                          e.get("bytes_moved", 0)) or 0) for e in done),
            "buckets": _fmt_total("tuple_buckets"),
        },
    }
    return {
        "ops": len(done),
        "pairs_in": _fmt_total("pairs_in"),
        "rows_out": _fmt_total("rows_out"),
        "bytes_moved": _fmt_total("bytes_moved"),
        "spills": _fmt_total("spills"),
        "spill_events": spill_events,
        "overflow": _fmt_total("overflow"),
        "formats": formats,
        # self-healing rollup (ISSUE 14): every retry/speculation/
        # blacklist decision the exchanges took, folded from their edges
        "recovery": {
            "retries": len(retry_events),
            "mapper_retries": sum(
                e.get("role") == "mapper" for e in retry_events),
            "reducer_retries": sum(
                e.get("role") == "reducer" for e in retry_events),
            "speculations": spec_events,
            "blacklists": bl_events,
        },
        "last": {
            "op": last.get("op"),
            "workers": last.get("workers"),
            "buckets": last.get("buckets"),
            "map_s": last.get("map_s"),
            "merge_s": last.get("merge_s"),
            "spills": last.get("spills"),
            "mem_budget_mb": last.get("mem_budget_mb"),
            "transport": last.get("transport", "tuple"),
            "bucket_rows_max": max_rows,
            "bucket_rows_mean": round(mean_rows, 1),
            "skew": round(skew, 3) if skew is not None else None,
            "verdict": verdict,
        },
    }


def reshard_from(events: list[dict]) -> dict | None:
    """Fold ``reshard`` recovery events into one block, or None when the
    run never resharded. The split the operator cares about is transport:
    ``collectives``/``handoff`` moves are checkpoint-free (the run kept its
    current step), ``checkpoint`` moves are restore-time walk-backs. Totals
    sum every move; ``last`` keeps the newest move whole."""
    moves = [e for e in events if e.get("kind") == "recovery"
             and e.get("event") == "reshard"]
    if not moves:
        return None
    live = [e for e in moves if not e.get("walk_back")]
    last = moves[-1]
    return {
        "moves": len(moves),
        "live_moves": len(live),
        "walk_back_moves": len(moves) - len(live),
        "bytes_moved": sum(int(e.get("bytes_moved", 0) or 0) for e in moves),
        "by_transport": {
            t: sum(e.get("transport") == t for e in moves)
            for t in ("collectives", "handoff", "checkpoint")},
        "last": {
            "step": last.get("step"),
            "transport": last.get("transport"),
            "walk_back": bool(last.get("walk_back")),
            "reason": last.get("reason"),
            "bytes_moved": last.get("bytes_moved"),
            "rounds": last.get("rounds"),
            "peak_inflight_bytes": last.get("peak_inflight_bytes"),
            "mem_budget_mb": last.get("mem_budget_mb"),
            "wall_s": last.get("wall_s"),
            "leaves_moved": last.get("leaves_moved"),
            "verified": last.get("verified"),
        },
    }


def report(workdir: str, *, now: float | None = None,
           hosts: bool = False, fleet_serve: bool = False,
           traces: bool = False, slo_target: float | None = None,
           slo_budget: float = 0.01, anatomy: bool = False,
           events: list[dict] | None = None) -> dict:
    """The full run report as a plain dict (what ``--json`` prints).
    ``hosts=True`` adds the ``fleet`` key (per-host table, skew, verdicts);
    ``fleet_serve=True`` adds ``fleet_serve`` (per-replica serving table);
    ``traces=True`` adds ``traces`` (the per-stage latency anatomy);
    ``slo_target`` (p99 seconds) adds ``slo`` (per-tenant burn rates and
    GOOD/BURNING/EXHAUSTED verdicts against ``slo_budget``);
    ``anatomy=True`` adds ``anatomy`` (compile ledger, device/host/input
    split, MFU, memory watermarks — :func:`..telemetry.anatomy
    .anatomy_report`); ``events`` skips the stream read when the caller
    already holds it."""
    if events is None:
        events = telemetry.read_events(workdir)
    heartbeats = [e for e in events if e.get("kind") == "heartbeat"]
    # the MOST RECENT step-bearing event, not the max step: a divergence
    # rollback legitimately rewinds the step counter, and the honest "where
    # is the run now" after one is the rewound position
    stepped = [e for e in events
               if e.get("kind") in ("step_metrics", "heartbeat")
               and e.get("step") is not None]
    last_hb = float(heartbeats[-1]["ts"]) if heartbeats else None
    # fleet ages anchor on the STREAM's end (now=None), not wall-clock: the
    # table must read the same on a live run and a week-old post-mortem
    # copy — who fell silent first, and by how much, is stream-relative
    rep_fleet = fleet_lib.fleet_report(events, now=now) if hosts else None
    return {
        **({"fleet": rep_fleet} if hosts else {}),
        **({"fleet_serve": fleet_lib.serving_fleet(events)}
           if fleet_serve else {}),
        **({"traces": fleet_lib.latency_anatomy(events)} if traces else {}),
        **({"pipeline": fleet_lib.pipeline_anatomy(events)}
           if traces else {}),
        **({"slo": fleet_lib.slo_report(events, target_p99_s=slo_target,
                                        budget=slo_budget)}
           if slo_target is not None else {}),
        **({"anatomy": anatomy_lib.anatomy_report(events)}
           if anatomy else {}),
        "workdir": workdir,
        "event_files": telemetry.event_files(workdir),
        "num_events": len(events),
        "first_ts": float(events[0]["ts"]) if events else None,
        "last_ts": float(events[-1]["ts"]) if events else None,
        "last_step": int(stepped[-1]["step"]) if stepped else None,
        "last_heartbeat_ts": last_hb,
        "last_heartbeat_age_s": (
            ((now if now is not None else time.time()) - last_hb)
            if last_hb is not None else None),
        "goodput": telemetry.goodput(events),
        "input_workers": input_workers_from(events),
        "shuffle": shuffle_from(events),
        "reshard": reshard_from(events),
        "serving": serving_from(events),
        "attempts": attempts_from(events),
        "recovery_events": [e for e in events if e.get("kind") == "recovery"],
    }


def _json_safe(obj):
    """Replace non-finite floats with None so ``--json`` output is STRICT
    JSON. Divergence incidents put real NaNs in the stream (a skip event's
    ``nonfinite={'loss': nan}``); python's json would pass them through as
    bare ``NaN`` literals, breaking every spec-compliant consumer (jq,
    browsers) exactly in the incident case this tool exists for."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def _fmt_s(v: float | None) -> str:
    return "-" if v is None else f"{v:.1f}s"


def render_fleet(fl: dict) -> list[str]:
    """The ``--hosts`` section: host table, skew, verdict lines."""
    lines: list[str] = []
    lines.append(
        f"fleet: {fl['num_hosts']}/{fl['expected_hosts'] or fl['num_hosts']} "
        f"host(s) reporting"
        + (f"; MISSING hosts {fl['missing_hosts']}"
           if fl["missing_hosts"] else ""))
    header = (f"  {'host':>4}  {'last step':>9}  {'hb age':>8}  "
              f"{'phase':<18} {'comms':>8}  {'goodput':>7}")
    lines.append(header)
    for r in fl["hosts"]:
        hb = (f"{r['heartbeat_age_s']:.1f}s"
              if r["heartbeat_age_s"] is not None else "-")
        step = r["last_step"] if r["last_step"] is not None else "-"
        phase = r["phase"] or "-"
        lines.append(
            f"  {r['host']:>4}  {step:>9}  {hb:>8}  {phase:<18} "
            f"{r['comms_wait_s']:>7.2f}s  {r['goodput']['goodput_frac']:>7.3f}")
    sk = fl["skew"]
    if sk["per_step"]:
        lines.append(
            f"  step skew: max {sk['max_skew_s']:.2f}s / median "
            f"{sk['median_skew_s']:.2f}s over {len(sk['per_step'])} common "
            f"step window(s), last common step {sk['last_common_step']}, "
            f"step lag {sk['step_lag']}")
        tail = sk["per_step"][-8:]
        lines.append("  skew timeline (last windows): " + "  ".join(
            f"s{w['step']}:{w['skew_s']:.2f}s(h{w['slowest_host']})"
            for w in tail))
    elif sk["step_lag"]:
        lines.append(f"  step lag: {sk['step_lag']} (no common step windows)")
    if fl["straggler"]:
        lines.append(f"  straggler: {fl['straggler']['verdict']}")
    if fl["hang"]:
        lines.append(f"  hang: {fl['hang']['verdict']}")
    return lines


def _fmt_pct(v: float | None) -> str:
    return "-" if v is None else f"{100.0 * v:.0f}%"


def render_fleet_serve(fs: dict) -> list[str]:
    """The ``--fleet-serve`` section: one serving row per replica process."""
    lines: list[str] = []
    t = fs["totals"]
    lines.append(
        f"serving fleet: {len(fs['replicas'])} process(es), "
        f"{t['ok']}/{t['requests']} requests ok"
        + (f"  prefix hit rate {_fmt_pct(t['prefix_hit_rate'])}"
           f" ({t['prefix_tokens_saved']} prompt tokens saved)"
           if t["prefix_hit_rate"] is not None else "")
        + (f"  failovers={t['failovers']}" if t.get("failovers") else ""))
    if t.get("tenants"):
        for name, row in t["tenants"].items():
            lines.append(
                f"  tenant {name}: {row['requests']} request(s), "
                f"shed rate {_fmt_pct(row['shed_rate'])} "
                f"({row['shed']} shed, {row['errors']} error(s))")
    lines.append(
        f"  {'replica':<8}  {'ok':>6}  {'shed':>5}  {'err':>4}  "
        f"{'p50':>8}  {'p99':>8}  {'shed%':>6}  {'kv occ':>6}  {'prefix':>6}")
    for r in fs["replicas"]:
        p50 = (f"{r['latency_p50_s'] * 1e3:.1f}ms"
               if r["latency_p50_s"] is not None else "-")
        p99 = (f"{r['latency_p99_s'] * 1e3:.1f}ms"
               if r["latency_p99_s"] is not None else "-")
        lines.append(
            f"  {r['process']:<8}  {r['ok']:>6}  {r['shed']:>5}  "
            f"{r['errors']:>4}  {p50:>8}  {p99:>8}  "
            f"{_fmt_pct(r['shed_rate']):>6}  "
            f"{_fmt_pct(r.get('kv_page_occupancy')):>6}  "
            f"{_fmt_pct(r.get('prefix_hit_rate')):>6}")
    return lines


def _fmt_ms(v: float | None) -> str:
    return "-" if v is None else f"{v * 1e3:.1f}ms"


def render_traces(tr: dict) -> list[str]:
    """The ``--traces`` section: per-stage latency anatomy + exemplars."""
    lines: list[str] = []
    lines.append(
        f"request traces: {tr['requests']} ({tr['complete']} complete, "
        f"{tr['incomplete']} incomplete)  e2e p50={_fmt_ms(tr['e2e_p50_s'])} "
        f"p99={_fmt_ms(tr['e2e_p99_s'])}"
        + (f"  stage coverage {_fmt_pct(tr['coverage_median'])} of e2e"
           if tr["coverage_median"] is not None else ""))
    if tr["stages"]:
        lines.append(f"  {'stage':<12} {'count':>6}  {'p50':>9}  {'p99':>9}  "
                     f"{'total':>9}")
        for name, s in tr["stages"].items():
            lines.append(
                f"  {name:<12} {s['count']:>6}  {_fmt_ms(s['p50_s']):>9}  "
                f"{_fmt_ms(s['p99_s']):>9}  {s['total_s']:>8.2f}s")
    for p, stages in (tr.get("per_process") or {}).items():
        decomp = "  ".join(f"{n}={_fmt_ms(s['p99_s'])}"
                           for n, s in stages.items())
        lines.append(f"  [{p}] p99 by stage: {decomp}")
    if tr["slowest"]:
        lines.append("  slowest requests:")
        for r in tr["slowest"]:
            chain = " > ".join(
                f"{s['name']} {_fmt_ms(s['dur_s'])}"
                for s in sorted(r["stage_spans"], key=lambda s: s["t0"]))
            where = f" [{r['process']}]" if r.get("process") else ""
            lines.append(
                f"    {r['trace_id']}{where} e2e={_fmt_ms(r['e2e_s'])}"
                + (f" hops={r['hops']}" if r.get("hops") else "")
                + f": {chain}")
    return lines


def _fmt_bytes(v: float | None) -> str:
    if v is None:
        return "-"
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024.0 or unit == "GiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024.0
    return "-"


def render_anatomy(an: dict) -> list[str]:
    """The ``--anatomy`` section: device/host/input split, MFU, compile
    ledger + recompile verdict, memory watermarks."""
    lines: list[str] = []
    st = an.get("steps")
    if st:
        lines.append(
            f"device anatomy: {st['laps']} lap(s) / {st['steps']} step(s), "
            f"lap wall {st['wall_s']:.2f}s")
        fr = st["fractions"]

        def pct(k):
            f = fr.get(k)
            return f"{100.0 * f:5.1f}%" if f is not None else "     -"

        lines.append(
            f"  device       {st['device_s']:10.2f}s  {pct('device')}  "
            f"(dispatch {st['device_dispatch_s']:.2f}s + drain "
            f"{st['device_drain_s']:.2f}s)")
        lines.append(f"  host         {st['host_s']:10.2f}s  {pct('host')}")
        lines.append(
            f"  input-wait   {st['input_wait_s']:10.2f}s  "
            f"{pct('input_wait')}")
        lines.append(
            f"  compile      {st['compile_s']:10.2f}s  {pct('compile')}  "
            f"(in-lap)")
        if an["verdicts"].get("bound"):
            lines.append(f"  verdict: {an['verdicts']['bound']}")
    mfu = an.get("mfu")
    if mfu and mfu.get("mfu") is not None:
        lines.append(
            f"  MFU {100.0 * mfu['mfu']:.3f}%"
            + (f" (last lap {100.0 * mfu['mfu_last_lap']:.3f}%)"
               if mfu.get("mfu_last_lap") is not None else "")
            + (f" — {mfu['flops_per_step']:.2e} flops/step"
               if mfu.get("flops_per_step") else "")
            + f" over {mfu.get('num_chips') or 1} chip(s), peak "
              f"{mfu['peak_flops_per_chip']:.2e}/chip "
              f"[{mfu.get('peak_source')}]")
    cl = an.get("compile_ledger")
    if cl and cl["compiles"]:
        lines.append(
            f"compile ledger: {cl['compiles']} compile(s), "
            f"{cl['distinct_signatures']} signature(s), "
            f"{cl['total_compile_s']:.2f}s total — "
            f"{an['verdicts']['recompile']}")
        for fn, row in sorted(cl["by_fn"].items()):
            lines.append(
                f"  {fn:<16} {row['compiles']:>3} compile(s)  "
                f"{row['signatures']:>3} sig(s)  {row['compile_s']:8.2f}s"
                + (f"  flops={row['flops']:.2e}" if row.get("flops") else "")
                + (f"  plan={row['plan']}[{row.get('plan_sig') or '?'}]"
                   if row.get("plan") else "")
                + (f"  RECOMPILES={row['flagged_recompiles']}"
                   if row["flagged_recompiles"] else ""))
    mem = an.get("memory")
    if mem:
        if mem["source"] == "memory_stats":
            lines.append(
                f"memory (memory_stats): in use "
                f"{_fmt_bytes(mem.get('bytes_in_use_max'))}  peak "
                f"{_fmt_bytes(mem.get('peak_bytes_in_use_max'))}  limit "
                f"{_fmt_bytes(mem.get('bytes_limit_min'))}  headroom "
                f"{_fmt_bytes(mem.get('headroom_bytes'))}")
        else:
            lines.append(
                f"memory (live-buffers): "
                f"{_fmt_bytes(mem.get('live_bytes'))} in live arrays "
                f"(backend exposes no allocator stats)")
    return lines


def render_pipeline(pl: dict) -> list[str]:
    """The ``--traces`` pipeline block: per-stage span anatomy + measured
    bubble fraction vs the (P−1)/(M+P−1) theoretical bound."""
    lines: list[str] = []
    meas, theo = pl["measured_bubble_frac"], pl["theoretical_bubble_frac"]
    verdict = ""
    if meas is not None and theo is not None:
        verdict = (" — within bound" if meas <= theo + 0.10
                   else " — ABOVE bound+10%: transport or stage imbalance "
                        "is eating the overlap")
    lines.append(
        f"pipeline: {pl['p'] or '?'} stage(s) x {pl['m'] or '?'} "
        f"microbatch(es) [{pl.get('schedule') or '?'}], "
        f"{pl['steps_judged']}/{pl['steps']} step(s) judged"
        + (f", {pl['microbatch_traces']} cross-stage microbatch trace(s)"
           if pl.get("microbatch_traces") else ""))
    if meas is not None:
        lines.append(
            f"  bubble fraction: measured {meas:.3f} vs theoretical "
            f"(P-1)/(M+P-1) = {theo if theo is not None else float('nan'):.3f}"
            f"{verdict}")
    lines.append(
        f"  {'stage':>5}  {'steps':>5}  {'fwd':>8}  {'bwd':>8}  "
        f"{'loss+opt':>8}  {'recv-wait':>9}  {'send-wait':>9}  {'bubble':>6}")
    for stage, r in pl["stages"].items():
        bub = f"{r['bubble_frac']:.3f}" if r["bubble_frac"] is not None else "-"
        lines.append(
            f"  {stage:>5}  {r['steps']:>5}  {_fmt_s(r['fwd_s']):>8}  "
            f"{_fmt_s(r['bwd_s']):>8}  {_fmt_s(r['loss_s']):>8}  "
            f"{_fmt_s(r['recv_wait_s']):>9}  {_fmt_s(r['send_wait_s']):>9}  "
            f"{bub:>6}")
    return lines


def render_slo(s: dict) -> list[str]:
    """The ``--slo`` section: per-tenant burn rate and verdict."""
    lines: list[str] = []
    lines.append(
        f"SLO: p99 target {_fmt_ms(s['target_p99_s'])}, error budget "
        f"{100.0 * s['budget']:.1f}% of requests")
    lines.append(
        f"  {'tenant':<10} {'req':>6} {'ok':>6} {'shed':>5} {'err':>4} "
        f"{'slow':>5}  {'viol%':>6}  {'burn':>6}  {'p99':>9}  verdict")
    rows = list(s["tenants"].items()) + [("TOTAL", s["totals"])]
    for name, r in rows:
        lines.append(
            f"  {name:<10} {r['requests']:>6} {r['ok']:>6} {r['shed']:>5} "
            f"{r['errors']:>4} {r['slow']:>5}  "
            f"{100.0 * r['violation_frac']:>5.1f}%  {r['burn_rate']:>5.1f}x  "
            f"{_fmt_ms(r['p99_s']):>9}  {r['verdict']}")
    return lines


def render_health(h: dict) -> list[str]:
    """The ``--health`` section: worst-severity rollup, per-rule verdicts,
    active (damped) alerts."""
    lines: list[str] = []
    st = h.get("stream") or {}
    lines.append(
        f"health: {h['worst_severity']}  "
        f"(schema v{h['schema']}, evaluation {h.get('evaluations', 1)})"
        + ("  DEGRADED STREAM" if st.get("degraded") else ""))
    for name, r in h["rules"].items():
        if not r["verdicts"]:
            continue
        for v in r["verdicts"]:
            lines.append(f"  [{v['severity']:<4}] {v['key']}: {v['summary']}")
    if all(not r["verdicts"] for r in h["rules"].values()):
        lines.append("  all rules OK")
    for a in h.get("alerts_active") or []:
        lines.append(
            f"  active alert {a['key']} [{a['severity']}] since "
            f"t={a['since_ts']:.1f} (held {a['held']} eval(s))")
    return lines


def render_incidents(rows: list[dict], first_ts: float | None) -> list[str]:
    """The ``--incidents`` section: the ordered timeline, one line each."""
    lines = [f"incident timeline: {len(rows)} event(s)"]
    t0 = first_ts if first_ts is not None else (rows[0]["ts"] if rows else 0.0)
    for r in rows:
        sev = f" [{r['severity']}]" if r.get("severity") else ""
        who = f" <{r['who']}>" if r.get("who") else ""
        step = f" step={r['step']}" if r.get("step") is not None else ""
        lines.append(
            f"  t+{r['ts'] - t0:8.1f}s  {r['type']:<12}{sev}{who}"
            f"{step}  {r['summary']}")
    return lines


_TREND_ARROWS = {"rising": "↗", "falling": "↘", "flat": "→"}


def _trend_arrow(t: dict | str | None) -> str:
    """Cell for a trend verdict (or a workdir's trend dict; '-' when the
    workdir has no series store)."""
    if not t:
        return "-"
    verdict = t if isinstance(t, str) else t.get("trend")
    return _TREND_ARROWS.get(verdict, "?")


def _parse_duration(raw: str) -> float:
    """``90s`` / ``10m`` / ``2h`` / ``1d`` / bare seconds -> seconds."""
    raw = str(raw).strip()
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}.get(
        raw[-1:].lower())
    if mult is not None:
        return float(raw[:-1]) * mult
    return float(raw)


def _fmt_sig(v: float | None) -> str:
    if v is None:
        return "-"
    return f"{v:.4g}"


def render_history(hist: dict) -> str:
    """The ``--history`` view: one sparkline row per series with
    min/mean/max/last and the fitted trend verdict."""
    lines = [
        f"history: {hist['workdir']}  resolution {hist['resolution_s']:g}s "
        f"over last {hist['since_s']:g}s  ({len(hist['series'])} series)"]
    for r in hist["series"]:
        lines.append(
            f"  {r['key']:<34} {r['spark']}  "
            f"min {_fmt_sig(r['min'])}  mean {_fmt_sig(r['mean'])}  "
            f"max {_fmt_sig(r['max'])}  last {_fmt_sig(r['last'])}  "
            f"{_trend_arrow(r['trend'])} {r['trend']}")
    if not hist["series"]:
        lines.append("  (no buckets in range — is the health engine "
                     "recording? try a longer --since)")
    return "\n".join(lines)


def render_cluster(c: dict) -> str:
    """The ``--cluster`` table: one row per discovered workdir + the
    per-tenant rollup."""
    lines: list[str] = []
    lines.append(
        f"cluster: {len(c['workdirs'])} workdir(s) under {c['root']}  "
        f"worst={c['worst_severity']}")
    lines.append(
        f"  {'workdir':<32} {'kind':<6} {'tenants':<16} {'goodput':>7} "
        f"{'trend':>5}  {'occ':>5}  {'hb age':>7}  {'step':>7}  worst alert")
    for r in c["workdirs"]:
        wd = r["workdir"]
        if len(wd) > 32:
            wd = "…" + wd[-31:]
        worst = (f"[{r['worst_alert']['severity']}] "
                 f"{r['worst_alert']['key']}" if r["worst_alert"] else "-")
        if r["degraded"]:
            worst += " (degraded stream)"
        lines.append(
            f"  {wd:<32} {r['kind']:<6} {','.join(r['tenants']):<16} "
            f"{r['goodput_frac']:>7.3f} {_trend_arrow(r.get('trend')):>5}  "
            f"{_fmt_pct(r['occupancy']):>5}  "
            f"{_fmt_s(r['last_heartbeat_age_s']):>7}  "
            f"{r['last_step'] if r['last_step'] is not None else '-':>7}  "
            f"{worst}")
    sched = c.get("sched")
    if c["tenants"]:
        lines.append("  per-tenant rollup:")
        sched_tenants = (sched or {}).get("tenants") or {}
        for t, agg in sorted(c["tenants"].items()):
            good = (f"{agg['goodput_frac']:.3f}"
                    if agg.get("goodput_frac") is not None else "-")
            quota = ""
            if t in sched_tenants:
                st = sched_tenants[t]
                cap = st["quota"] if st.get("quota") is not None else "∞"
                quota = f"  hosts={st['used']}/{cap}"
            lines.append(
                f"    {t:<14} workdirs={agg['workdirs']} "
                f"(train {agg['train_workdirs']}, serve "
                f"{agg['serve_workdirs']})  goodput={good}  "
                f"requests={agg['requests']} shed={agg['shed']}  "
                f"worst={agg['worst_severity']}{quota}")
    if sched:
        lines.extend(render_sched(sched))
    return "\n".join(lines)


def render_sched(sched: dict) -> list[str]:
    """The scheduler section of ``--cluster``: queue + inventory from the
    ledger fold (``cluster_report``'s ``sched`` block)."""
    if sched.get("error"):
        return [f"  scheduler: (unreadable: {sched['error']})"]
    hosts = sched.get("hosts") or {}
    lines = [f"  scheduler: hosts {hosts.get('free', '?')}/"
             f"{hosts.get('total', '?')} free"]
    tenants = sched.get("tenants") or {}
    if tenants:
        used = ", ".join(
            f"{t}={row['used']}/{row['quota'] if row.get('quota') is not None else '∞'}"
            for t, row in sorted(tenants.items()))
        lines.append(f"    quota (used/limit): {used}")
    jobs = sched.get("jobs") or []
    live = [j for j in jobs if j["status"] not in
            ("COMPLETED", "FAILED", "CANCELLED")]
    done = len(jobs) - len(live)
    if not jobs:
        lines.append("    (no jobs submitted)")
        return lines
    lines.append(
        f"    {'job':<6} {'name':<18} {'tenant':<12} {'pri':>4} "
        f"{'status':<8} {'hosts':<14} {'min':>4}  note")
    for j in live:
        name = j["name"] or "-"
        if len(name) > 18:
            name = name[:17] + "…"
        held = ",".join(j["hosts"]) if j["hosts"] else "-"
        if len(held) > 14:
            held = held[:13] + "…"
        note_bits = []
        if j.get("draining") is not None:
            note_bits.append(f"draining g{j['draining']}")
        if j.get("requeues"):
            note_bits.append(f"requeues={j['requeues']}")
        if j.get("reason"):
            note_bits.append(str(j["reason"]))
        lines.append(
            f"    {j['job']:<6} {name:<18} {j['tenant']:<12} "
            f"{j['priority']:>4} {j['status']:<8} {held:<14} "
            f"{j['min_hosts']:>4}  {' '.join(note_bits) or '-'}")
    if done:
        lines.append(f"    (+{done} terminal job(s))")
    return lines


def render(rep: dict) -> str:
    """Human-readable report (the default output)."""
    lines: list[str] = []
    g = rep["goodput"]
    lines.append(f"run report: {rep['workdir']}")
    lines.append(
        f"  {rep['num_events']} events from {len(rep['event_files'])} "
        f"process file(s); wall-clock {_fmt_s(g['wall_s'])}"
        + (f"; last step {rep['last_step']}"
           if rep["last_step"] is not None else ""))
    if rep["last_heartbeat_ts"] is not None:
        lines.append(
            f"  last heartbeat: {_fmt_s(rep['last_heartbeat_age_s'])} ago")
    if rep.get("health"):
        lines.append("")
        lines.extend(render_health(rep["health"]))
    if rep.get("fleet"):
        lines.append("")
        lines.extend(render_fleet(rep["fleet"]))
    if rep.get("fleet_serve"):
        lines.append("")
        lines.extend(render_fleet_serve(rep["fleet_serve"]))
    if rep.get("traces"):
        lines.append("")
        lines.extend(render_traces(rep["traces"]))
    if rep.get("pipeline"):
        lines.append("")
        lines.extend(render_pipeline(rep["pipeline"]))
    if rep.get("slo"):
        lines.append("")
        lines.extend(render_slo(rep["slo"]))
    if rep.get("anatomy"):
        lines.append("")
        lines.extend(render_anatomy(rep["anatomy"]))
    lines.append("")
    lines.append("goodput breakdown")
    wall = g["wall_s"] or float("inf")
    for comp in _COMPONENTS:
        lines.append(f"  {comp:<20} {g[comp]:10.2f}s  "
                     f"{100.0 * g[comp] / wall:6.1f}%")
    lines.append(f"  goodput_frac         {g['goodput_frac']:10.3f}")
    iw = rep.get("input_workers")
    if iw:
        starved = (g.get("input_starved_s") or 0.0) > 0.05 * (g["wall_s"] or 1)
        util = iw.get("worker_util_mean", 0.0)
        if util >= 0.85 and starved:
            verdict = "pool-bound — workers saturated; add workers/cores"
        elif starved:
            verdict = ("source-bound — training waits but workers idle; "
                       "the raw source (IO) is the limit")
        else:
            verdict = "keeping up — consumer/device is the bottleneck"
        lines.append("")
        lines.append(
            f"input workers: {iw['input_workers']} process(es)  "
            f"util mean={util:.2f}"
            + (f" min={iw['worker_util_min']:.2f}"
               if iw.get("worker_util_min") is not None else "")
            + f"  items={iw.get('worker_items', 0)}"
            + f"  ahead={iw.get('worker_ahead_mean', 0.0):.1f}"
            + (f"  OVERFLOW={iw['worker_overflow']} (raise "
               f"DLS_DATA_WORKER_RING_MB)" if iw.get("worker_overflow")
               else ""))
        lines.append(f"  verdict: {verdict}")
    sh = rep.get("shuffle")
    if sh:
        last = sh["last"]
        lines.append("")
        lines.append(
            f"shuffle: {sh['ops']} op(s)  pairs={sh['pairs_in']}  "
            f"rows out={sh['rows_out']}  "
            f"moved={sh['bytes_moved'] / 1e6:.1f}MB  "
            f"spills={sh['spills']}"
            + (f"  OVERFLOW={sh['overflow']} (raise DLS_SHUFFLE_MEM_MB)"
               if sh.get("overflow") else ""))
        fmts = sh.get("formats") or {}
        fmt_bits = [
            f"{name}: keys={f['pairs']} moved={f['bytes'] / 1e6:.1f}MB"
            + (f" buckets={f['buckets']}" if f.get("buckets") else "")
            for name, f in fmts.items() if f.get("pairs")]
        if fmt_bits:
            lines.append("  by format  " + "   ".join(fmt_bits))
        rec = sh.get("recovery") or {}
        if any(rec.values()):
            lines.append(
                f"  recovery: retries={rec['retries']} "
                f"(mapper {rec['mapper_retries']}, "
                f"reducer {rec['reducer_retries']})  "
                f"speculations={rec['speculations']}  "
                f"blacklisted={rec['blacklists']} — self-healed; "
                f"escalations would have raised WorkerCrashed instead")
        lines.append(
            f"  last op {last['op']}: transport={last.get('transport')} "
            f"workers={last['workers']} "
            f"buckets={last['buckets']} map={_fmt_s(last['map_s'])} "
            f"merge={_fmt_s(last['merge_s'])} spills={last['spills']}"
            + (f" budget={last['mem_budget_mb']}MB"
               if last.get("mem_budget_mb") is not None else ""))
        lines.append(
            f"  bucket rows max={last['bucket_rows_max']} "
            f"mean={last['bucket_rows_mean']}  verdict: {last['verdict']}")
    rs = rep.get("reshard")
    if rs:
        last = rs["last"]
        lines.append("")
        lines.append(
            f"resharding: {rs['moves']} move(s)  "
            f"live={rs['live_moves']}  walk-back={rs['walk_back_moves']}  "
            f"moved={rs['bytes_moved'] / 1e6:.1f}MB")
        mode = ("walk-back (checkpoint)" if last["walk_back"]
                else "checkpoint-free (live)")
        lines.append(
            f"  last move: {mode} transport={last.get('transport')} "
            f"step={last.get('step', '-')}"
            + (f" reason={last['reason']}" if last.get("reason") else "")
            + (f" moved={last['bytes_moved'] / 1e6:.1f}MB"
               if last.get("bytes_moved") is not None else "")
            + (f" rounds={last['rounds']}"
               if last.get("rounds") is not None else "")
            + (f" peak={last['peak_inflight_bytes'] / 1e6:.1f}MB"
               f"/{last['mem_budget_mb']:.0f}MB budget"
               if last.get("peak_inflight_bytes") is not None
               and last.get("mem_budget_mb") is not None else "")
            + (f" wall={_fmt_s(last['wall_s'])}"
               if last.get("wall_s") is not None else "")
            + ("" if last.get("verified") is None
               else f" verified={str(bool(last['verified'])).lower()}"))
    sv = rep.get("serving")
    if sv:
        lines.append("")
        lines.append("serving"
                     + (f" ({', '.join(sv['engines'])})"
                        if sv["engines"] else ""))
        lines.append(
            f"  {sv['ok']}/{sv['requests']} requests ok"
            f"  shed={sv['shed']}  errors={sv['errors']}"
            + (f"  throughput={sv['requests_per_s']:.1f} req/s"
               if sv["requests_per_s"] is not None else ""))
        if sv["latency_p50_s"] is not None:
            lines.append(
                f"  latency p50={sv['latency_p50_s'] * 1e3:.1f}ms "
                f"p99={sv['latency_p99_s'] * 1e3:.1f}ms "
                f"max={sv['latency_max_s'] * 1e3:.1f}ms"
                + (f"  queue p50={sv['queue_wait_p50_s'] * 1e3:.1f}ms "
                   f"p99={sv['queue_wait_p99_s'] * 1e3:.1f}ms"
                   if sv["queue_wait_p50_s"] is not None else ""))
        if sv["mean_batch_size"] is not None:
            lines.append(f"  mean batch size {sv['mean_batch_size']:.1f}")
    if rep["attempts"]:
        lines.append("")
        lines.append("attempts")
        multi_session = any(a["session"] for a in rep["attempts"])
        for a in rep["attempts"]:
            codes = a["returncodes"]
            if a["begin_ts"] is None and a["end_ts"] is None:
                # backoff recorded, launch never happened: the supervisor
                # died during the backoff sleep
                state = "never launched (supervisor died in backoff)"
            else:
                state = a["classification"] or "in-flight"
            tag = (f"s{a['session']}#{a['ordinal']}" if multi_session
                   else f"#{a['ordinal']}")
            lines.append(
                f"  {tag}: {state}"
                f"  dur={_fmt_s(a['duration_s'])}"
                f"  codes={codes if codes is not None else '-'}"
                + (f"  np={a['num_processes']}"
                   if a.get("num_processes") is not None else "")
                + (f"  dead_host={a['dead_host']}"
                   if a.get("dead_host") is not None else "")
                + (f"  backoff={_fmt_s(a['backoff_s'])}"
                   if a["backoff_s"] is not None else ""))
        # an elastic run's shrinks, summarized where the operator looks
        # first: one line per geometry change, between the attempt rows
        # it separates (the events also appear in the recovery list below)
        drains = [e for e in rep["recovery_events"]
                  if e.get("event") == "graceful_shutdown"]
        for e in drains:
            lines.append(
                f"  graceful shutdown: host {e.get('dead_host')} drained at "
                f"step {e.get('step', '-')} (attempt "
                f"#{e.get('ordinal', '-')}) — handed off live, no backoff")
        geo = [e for e in rep["recovery_events"]
               if e.get("event") == "geometry_change"]
        for e in geo:
            lines.append(
                f"  geometry change: {e.get('from_processes')} -> "
                f"{e.get('to_processes')} host(s) after "
                f"{e.get('evidence_attempts')} attempt(s) blamed host "
                f"{e.get('dead_host')}; survivors {e.get('hosts')}, "
                f"resume step {e.get('step', '-')} "
                f"({e.get('resume', 'checkpoint')}), batch "
                f"{e.get('batch_policy')}")
    if rep["recovery_events"]:
        lines.append("")
        lines.append("recovery events")
        for e in rep["recovery_events"]:
            extra = {k: v for k, v in e.items()
                     if k not in ("ts", "kind", "process", "event", "step")}
            lines.append(
                f"  t+{float(e['ts']) - rep['first_ts']:.1f}s "
                f"[{e.get('process')}] {e.get('event')} "
                f"step={e.get('step', '-')}"
                + (f" {json.dumps(extra, default=str)}" if extra else ""))
    if rep.get("incidents") is not None:
        lines.append("")
        lines.extend(render_incidents(rep["incidents"], rep["first_ts"]))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dlstatus",
        description="Inspect a run's telemetry: goodput, attempts, recovery.")
    ap.add_argument("workdir", nargs="?", default=None,
                    help="run directory (holds telemetry/) or the "
                         "telemetry directory itself (optional with "
                         "--cluster)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--hosts", action="store_true",
                    help="per-host fleet table, step skew, and straggler/"
                         "hang verdicts (multi-host runs)")
    ap.add_argument("--fleet-serve", action="store_true",
                    help="per-replica serving table: p50/p99, shed rate, "
                         "KV page occupancy, prefix-cache hit rate")
    ap.add_argument("--traces", action="store_true",
                    help="request latency anatomy from span traces: "
                         "per-stage p50/p99 and the slowest exemplars")
    ap.add_argument("--slo", type=float, metavar="P99_S", default=None,
                    help="judge served traffic against this p99 target "
                         "(seconds): per-tenant burn rate and "
                         "GOOD/BURNING/EXHAUSTED verdicts")
    ap.add_argument("--slo-budget", type=float, default=0.01,
                    help="violation fraction the SLO tolerates "
                         "(default 0.01 = 99%% of requests in target)")
    ap.add_argument("--anatomy", action="store_true",
                    help="device-side anatomy: compile ledger + recompile "
                         "verdict, device/host/input lap split, MFU, "
                         "memory watermarks")
    ap.add_argument("--health", action="store_true",
                    help="evaluate the health ruleset (telemetry.health): "
                         "per-rule OK/WARN/CRIT verdicts, worst-severity "
                         "rollup — and rewrite <workdir>/health.json, the "
                         "machine contract")
    ap.add_argument("--incidents", action="store_true",
                    help="ordered incident timeline: alert raise/clear "
                         "edges + recovery events + failed attempts, "
                         "attributed to host/replica/stage/tenant")
    ap.add_argument("--cluster", metavar="ROOT", default=None,
                    help="discover every workdir under ROOT and render the "
                         "cluster table: per-tenant goodput/occupancy, "
                         "worst alert, heartbeat age (composes with "
                         "--json/--watch; --slo arms the SLO rule)")
    ap.add_argument("--history", nargs="?", const="*", metavar="KEY",
                    default=None,
                    help="render the downsampled series history as "
                         "sparklines with min/mean/max/trend verdicts "
                         "(all series, or one KEY like "
                         "'queue_depth{replica=p0}' or a bare name); "
                         "composes with --json (pinned schema) and "
                         "--since")
    ap.add_argument("--since", type=_parse_duration, default="1h",
                    metavar="DUR",
                    help="--history span: 90s / 10m / 2h / 1d or bare "
                         "seconds (default 1h); picks the finest "
                         "resolution whose ring covers it")
    ap.add_argument("--resolution", type=float, default=None, metavar="S",
                    help="--history: force a bucket width in seconds "
                         "instead of auto-picking from --since")
    ap.add_argument("--serve-metrics", type=int, metavar="PORT",
                    default=None,
                    help="serve an OpenMetrics/Prometheus text exposition "
                         "of the newest series buckets + health.json "
                         "verdicts on http://127.0.0.1:PORT/metrics "
                         "(0 = ephemeral port, printed to stderr; "
                         "--watch-count N answers N scrapes then exits)")
    ap.add_argument("--export-trace", metavar="OUT.json", default=None,
                    help="write the run's spans (serve requests + train "
                         "phases) as Chrome/Perfetto trace_event JSON")
    ap.add_argument("--watch", action="store_true",
                    help="live-follow mode: re-read the JSONL stream and "
                         "re-render every --interval seconds (works on an "
                         "in-progress run; ctrl-C to stop)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--watch refresh period in seconds (default 2)")
    ap.add_argument("--watch-count", type=int, default=0,
                    help="--watch: stop after N renders (0 = until ctrl-C; "
                         "mainly for tests/scripts)")
    args = ap.parse_args(argv)
    if args.watch and args.export_trace:
        ap.error("--watch and --export-trace are mutually exclusive "
                 "(export reads one finished stream)")
    if args.cluster is None and args.workdir is None:
        ap.error("a workdir is required (or --cluster ROOT)")
    if args.cluster is not None:
        return _cluster_main(args)
    if args.serve_metrics is not None:
        return _serve_metrics_main(args)
    if args.history is not None:
        return _history_main(args)

    # --health runs through ONE engine for the whole invocation: a watch's
    # successive evaluations share its incremental cursor and its flap-
    # damping state (damping=1 one-shot: the report reflects the stream
    # NOW; continuous damping belongs to a long-lived --watch/daemon).
    # write_alerts=False — an inspector must not append to the stream it
    # inspects; health.json is its only write.
    engine = None
    if args.health:
        engine = health_lib.HealthEngine(
            args.workdir, damping=(None if args.watch else 1),
            slo_target_s=args.slo, slo_budget=args.slo_budget,
            write_alerts=False)

    def build(events: list[dict]) -> dict:
        rep = report(args.workdir, hosts=args.hosts,
                     fleet_serve=args.fleet_serve, traces=args.traces,
                     slo_target=args.slo, slo_budget=args.slo_budget,
                     anatomy=args.anatomy, events=events)
        if engine is not None:
            rep["health"] = {k: v for k, v in engine.evaluate().items()
                             if not k.startswith("_")}
        if args.incidents:
            rep["incidents"] = health_lib.incident_timeline(events)
        return rep

    def emit_one(rep: dict) -> None:
        if args.json:
            print(json.dumps(_json_safe(rep), default=str))
        else:
            print(render(rep))

    if args.watch:
        return _watch(args, build, emit_one)
    # ONE stream read shared between the report and the exporter — a
    # rotation-capped long-lived fleet's segments are a real parse cost
    events = telemetry.read_events(args.workdir)
    rep = build(events)
    if not rep["num_events"]:
        if rep["event_files"]:
            # parseable-but-degraded: the files say a run was here (a
            # crashed run's partial segment mid-rotation) — report that,
            # don't die. The health rule says the same thing.
            print(f"dlstatus: {len(rep['event_files'])} event file(s) under "
                  f"{args.workdir} but no parseable events — degraded "
                  f"stream (crashed run's partial segment?)",
                  file=sys.stderr)
            emit_one(rep)
            return 0
        print(f"dlstatus: no telemetry events under {args.workdir} "
              f"(looked in {telemetry.telemetry_dir(args.workdir)})",
              file=sys.stderr)
        return 1
    if args.export_trace:
        from distributeddeeplearningspark_tpu.telemetry import (
            trace as trace_lib,
        )

        ladder = series_lib.list_resolutions(args.workdir)
        series_buckets = (
            series_lib.read_buckets(args.workdir, ladder[0][0])
            if ladder else None)
        data = trace_lib.chrome_trace(events, series_buckets=series_buckets)
        with open(args.export_trace, "w") as f:
            json.dump(_json_safe(data), f)
        n = sum(e.get("ph") in ("X", "B") for e in data["traceEvents"])
        print(f"dlstatus: wrote {n} span(s) to {args.export_trace} "
              f"(open in ui.perfetto.dev or chrome://tracing)",
              file=sys.stderr)
    emit_one(rep)
    return 0


def _history_main(args) -> int:
    """``--history [KEY]``: the series-store view. Reads ONLY the
    downsampled store (never the event stream) — answering "is it
    getting worse?" costs the ring size, not the run length."""
    hist = series_lib.history_report(
        args.workdir, key=(None if args.history == "*" else args.history),
        since_s=args.since, resolution_s=args.resolution)
    if hist is None:
        print(f"dlstatus: no series store under {args.workdir} — history "
              f"is recorded by the health engine (run "
              f"`dlstatus {args.workdir} --health` or a --watch daemon)",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(_json_safe(hist), default=str))
    else:
        print(render_history(hist))
    return 0


def _serve_metrics_main(args) -> int:
    """``--serve-metrics PORT``: stdlib-http OpenMetrics exposition.

    Every GET re-reads health.json + the newest series buckets from disk,
    so the endpoint pairs with whatever is producing them (a ``--health
    --watch`` daemon, a supervised run's engine) without sharing a
    process. Binds loopback; PORT 0 picks an ephemeral port — the chosen
    one is printed to stderr. ``--watch-count N`` answers N requests and
    exits (tests/CI); default serves until ctrl-C."""
    import http.server

    workdir = args.workdir

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib handler contract)
            if self.path.partition("?")[0] not in ("/", "/metrics"):
                self.send_error(404)
                return
            body = series_lib.openmetrics_exposition(workdir).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type",
                             series_lib.OPENMETRICS_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *fmt_args):
            pass  # scrape logs belong to the scraper, not stderr

    srv = http.server.HTTPServer(("127.0.0.1", args.serve_metrics), Handler)
    host, port = srv.server_address[0], srv.server_address[1]
    print(f"dlstatus: serving OpenMetrics on http://{host}:{port}/metrics "
          f"for {workdir}", file=sys.stderr, flush=True)
    try:
        if args.watch_count:
            for _ in range(args.watch_count):
                srv.handle_request()
        else:
            srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return 0


def _cluster_main(args) -> int:
    """``--cluster ROOT``: the multi-workdir fold, composing with
    ``--json`` (one report per line) and ``--watch`` (which holds one
    :class:`~.telemetry.EventCursor` per workdir, so each tick parses
    only the fleet's appends, not every stream from byte 0)."""
    cursors: dict | None = {} if args.watch else None

    def build() -> dict:
        return health_lib.cluster_report(
            args.cluster, slo_target_s=args.slo, slo_budget=args.slo_budget,
            cursors=cursors)

    def emit_one(c: dict) -> None:
        if args.json:
            print(json.dumps(_json_safe(c), default=str))
        else:
            print(render_cluster(c))

    if not args.watch:
        c = build()
        if not c["workdirs"]:
            print(f"dlstatus: no telemetry workdirs under {args.cluster}",
                  file=sys.stderr)
            return 1
        emit_one(c)
        return 0
    renders = 0
    try:
        while True:
            if not args.json:
                if sys.stdout.isatty():
                    print("\x1b[2J\x1b[H", end="")
                elif renders:
                    print("\n" + "=" * 72)
            emit_one(build())
            renders += 1
            if args.watch_count and renders >= args.watch_count:
                return 0
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0
    except BrokenPipeError:
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


def _watch(args, build, emit_one) -> int:
    """``--watch``: tail the stream, re-render on an interval.

    Incremental per tick: an :class:`~..telemetry.EventCursor` keeps one
    byte offset per segment file, so each tick parses only what was
    appended since the last one — a long run's watch tick stops being
    O(total events). The cursor's glob still follows segment rotation and
    newly appearing process files, and a torn mid-append tail is held
    back until its newline lands, so following an in-progress run needs
    no writer cooperation. A workdir whose files hold no parseable events
    (a crashed run's partial segment) renders as a degraded stream and
    keeps following — it does not die. Human mode clears the screen
    between renders on a TTY (a separator line otherwise); ``--json``
    emits one report line per tick, streamable into ``jq``."""
    renders = 0
    cursor = telemetry.EventCursor(args.workdir)
    try:
        while True:
            cursor.poll()
            events = cursor.events
            if not args.json:
                if sys.stdout.isatty():
                    print("\x1b[2J\x1b[H", end="")
                elif renders:
                    print("\n" + "=" * 72)
                print(f"dlstatus --watch {args.workdir}  "
                      f"(refresh {args.interval:g}s, render "
                      f"{renders + 1}"
                      + (f"/{args.watch_count}" if args.watch_count else "")
                      + ", ctrl-C to stop)")
            if events:
                emit_one(build(events))
            else:
                files = telemetry.event_files(args.workdir)
                if args.json:
                    print(json.dumps({"workdir": args.workdir,
                                      "num_events": 0,
                                      "degraded": bool(files)}))
                elif files:
                    print(f"  {len(files)} event file(s) but no parseable "
                          f"events under {args.workdir} — degraded stream "
                          f"(crashed run's partial segment?); waiting")
                else:
                    print(f"  no telemetry events yet under {args.workdir} "
                          f"(waiting)")
            renders += 1
            if args.watch_count and renders >= args.watch_count:
                return 0
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0
    except BrokenPipeError:
        # the downstream pager/head closed: a follow mode's normal exit.
        # Point fd 1 at devnull before returning — the interpreter's
        # shutdown flush of the buffered stdout would otherwise re-raise
        # and turn the clean rc 0 into exit 120 + "Exception ignored"
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
