"""Continuous health engine — one evaluated ruleset over the event stream.

Every report surface before this module (goodput accountant, per-host
fleet table, SLO sentinel, latency anatomy, compile/MFU/HBM anatomy,
shuffle recovery rollup, resharding, supervisor attempts) is a one-shot,
one-workdir, human-read verdict. Nothing watched the stream continuously,
nothing emitted a durable signal when a verdict *flipped*, and nothing saw
across workdirs — exactly what the SLO autoscaler, the multi-tenant
scheduler, and the online production loop need. This module closes that
gap in three layers, all pure folds over the same JSONL stream:

- :func:`evaluate_health` — run the RULES registry over an event stream
  once and assemble the machine-readable health report (per-rule raw
  verdicts, burn rate, per-replica queue depth, per-tenant rows,
  worst-severity rollup). One-shot, stateless: what ``dlstatus --health``
  and the cluster view call.
- :class:`HealthEngine` — the continuous wrapper: an incremental
  :class:`~.EventCursor` read per tick, **flap damping** (a rule must hold
  its new state for ``damping`` consecutive evaluations before the edge
  emits, so a jittery SLO doesn't storm the bus), ``alert`` telemetry
  events on every confirmed state *transition* (raise/clear edge, dedup
  key — one live alert per key, identical re-raises emit nothing), and an
  atomic rewrite of ``<workdir>/health.json`` (schema-versioned, the
  contract consumers parse instead of JSONL).
- :func:`incident_timeline` / :func:`cluster_report` — the fold of alert
  edges + ``recovery`` events + failed supervisor attempts into the
  ordered "what happened, attributed to whom" view (``dlstatus
  --incidents``), and the multi-workdir fold ``dlstatus --cluster``
  renders (per-tenant/per-job goodput, serve occupancy, worst alert,
  heartbeat age).

Severity is a three-rung ladder: ``OK`` < ``WARN`` < ``CRIT``. Rules wrap
the existing producers rather than re-deriving them — the SLO rule maps
the sentinel's GOOD/BURNING/EXHAUSTED ladder, the hang rule wraps
:func:`~.fleet.localize_hang`, the HBM rule reads the anatomy fold — so
there is ONE severity policy and the render surfaces stay byte-stable.

Rate-shaped rules (SLO burn, shed rate, restart storms, shuffle retries)
judge only the trailing ``window_s`` of *event time*, so a clean rerun
appended to a workdir genuinely clears the alert; structural rules (hang,
missing hosts, degraded stream, recompiles) judge the whole stream.

Like the rest of the reader side: no jax import, works on a crashed run's
partial stream, and a workdir whose only events are a torn mid-rotation
segment is reported as *parseable-but-degraded* (a WARN with evidence),
never a crash.
"""

from __future__ import annotations

import glob
import json
import math
import os
import time
from typing import Any, Callable, Iterable

from distributeddeeplearningspark_tpu import telemetry
from distributeddeeplearningspark_tpu.telemetry import anatomy as anatomy_lib
from distributeddeeplearningspark_tpu.telemetry import fleet as fleet_lib
from distributeddeeplearningspark_tpu.telemetry import series as series_lib

#: schema version stamped into every health.json — consumers MUST check it;
#: any key removal/rename bumps it (additions don't).
HEALTH_SCHEMA = 1

#: the machine contract file, rewritten atomically on every evaluation.
HEALTH_FILENAME = "health.json"

#: severity ladder (rollups take the max).
SEVERITIES = ("OK", "WARN", "CRIT")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

#: env knobs (read at evaluation time so a live engine retunes on restart).
DAMPING_ENV = "DLS_HEALTH_DAMPING"              # default 3 evaluations
WINDOW_ENV = "DLS_HEALTH_WINDOW_S"              # default 300s of event time
SLO_TARGET_ENV = "DLS_HEALTH_SLO_P99_S"         # no default: rule off unless set
HB_WARN_ENV = "DLS_HEALTH_HB_WARN_S"            # default 60s
HB_CRIT_ENV = "DLS_HEALTH_HB_CRIT_S"            # default 300s
QUEUE_WARN_ENV = "DLS_HEALTH_QUEUE_WARN"        # default 8 waiting requests
QUEUE_CRIT_ENV = "DLS_HEALTH_QUEUE_CRIT"        # default 32
SHED_WARN_ENV = "DLS_HEALTH_SHED_WARN"          # default 0.05
SHED_CRIT_ENV = "DLS_HEALTH_SHED_CRIT"          # default 0.25
GOODPUT_WARN_ENV = "DLS_HEALTH_GOODPUT_WARN"    # default 0.5 fraction
TREND_N_ENV = "DLS_HEALTH_TREND_N"              # default 3 consecutive moves
STEPS_DROP_ENV = "DLS_HEALTH_STEPS_DROP"        # default 0.15 below peak


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def worst_severity(severities: Iterable[str]) -> str:
    worst = "OK"
    for s in severities:
        if _SEV_RANK.get(s, 0) > _SEV_RANK[worst]:
            worst = s
    return worst


def _json_safe(obj):
    """Non-finite floats -> None (health.json must be strict JSON — the
    same NaN hazard :mod:`..status` documents: divergence incidents put
    real NaNs in evidence dicts)."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def _verdict(rule: str, key: str, severity: str, summary: str,
             **evidence: Any) -> dict[str, Any]:
    return {"rule": rule, "key": key, "severity": severity,
            "summary": summary, "evidence": evidence}


# -- the ruleset --------------------------------------------------------------
#
# Each rule is ``fn(ctx) -> list[verdict]`` where a verdict names its dedup
# ``key`` (one live alert per key: ``slo:tenant0``, ``hang:host2``), its
# ``severity``, a one-line operator ``summary``, and the measured
# ``evidence`` behind it. A rule that is healthy returns [] — the engine
# treats every key it doesn't mention as OK. ``ctx`` carries the stream and
# the producer folds computed ONCE per evaluation (see _build_ctx).


def _rule_stream(ctx: dict) -> list[dict]:
    """Parseable-but-degraded stream: event files exist but nothing in them
    parses (a crashed run's partial segment mid-rotation). WARN — the
    workdir is observable (the files say a run was here) but blind."""
    st = ctx["stream"]
    if st["files"] and not st["events"]:
        return [_verdict(
            "stream", "stream:degraded", "WARN",
            f"{st['files']} event file(s) but 0 parseable events — "
            f"degraded stream (crashed run's partial segment?)",
            files=st["files"], skipped_lines=st["skipped_lines"])]
    return []


def _rule_heartbeat(ctx: dict) -> list[dict]:
    """Stale heartbeat on a run that never closed its ``run`` phase.

    A finished run (every ``run`` span ended) stops heartbeating forever
    and must not alarm; an open run whose heartbeats age past the
    thresholds is dying or wedged. Age is measured against the
    evaluation's ``now`` anchor, so a stream-anchored post-mortem (age≈0
    at stream end) stays quiet and a wall-clock engine sees the dwell."""
    hbs = [e for e in ctx["events"] if e.get("kind") == "heartbeat"]
    if not hbs:
        return []
    open_runs = 0
    for e in ctx["events"]:
        if e.get("kind") == "phase" and e.get("name") == "run":
            open_runs += 1 if e.get("edge") == "begin" else -1
    if open_runs <= 0:
        return []
    age = ctx["now"] - float(hbs[-1]["ts"])
    warn = _env_float(HB_WARN_ENV, 60.0)
    crit = _env_float(HB_CRIT_ENV, 300.0)
    if age < warn:
        return []
    sev = "CRIT" if age >= crit else "WARN"
    return [_verdict(
        "heartbeat", "heartbeat:run", sev,
        f"last heartbeat {age:.0f}s ago with the run phase still open",
        age_s=round(age, 1), last_step=hbs[-1].get("step"),
        warn_s=warn, crit_s=crit)]


def _rule_hosts(ctx: dict) -> list[dict]:
    """A host the writers' own gang-size stamp expected never reported."""
    fl = ctx["fleet"]
    if not fl or not fl["missing_hosts"]:
        return []
    missing = fl["missing_hosts"]
    return [_verdict(
        "hosts", "hosts:missing", "CRIT",
        f"{len(missing)}/{fl['expected_hosts']} host(s) never reported: "
        f"{missing}",
        missing_hosts=missing, expected_hosts=fl["expected_hosts"],
        reporting=fl["num_hosts"])]


def _rule_hang(ctx: dict) -> list[dict]:
    """The fleet fold localized a hang to one host -> CRIT naming it."""
    fl = ctx["fleet"]
    hang = fl and fl.get("hang")
    if not hang:
        return []
    # the localizer's margin floors at seconds — right for the
    # supervisor's reap-time call (the gang is already dead) but a live
    # wall-clock engine must not page on a quiet-but-healthy stream, so
    # the dwell has to clear the heartbeat WARN threshold first
    if hang["stalled_for_s"] < _env_float(HB_WARN_ENV, 60.0):
        return []
    return [_verdict(
        "hang", f"hang:host{hang['host']}", "CRIT", hang["verdict"],
        host=hang["host"], process=hang["process"], phase=hang["phase"],
        stalled_for_s=round(hang["stalled_for_s"], 1),
        others_at_step=hang["others_at_step"])]


def _rule_straggler(ctx: dict) -> list[dict]:
    """A persistent slowest host (the gang runs at its pace) -> WARN."""
    fl = ctx["fleet"]
    st = fl and fl.get("straggler")
    if not st:
        return []
    return [_verdict(
        "straggler", f"straggler:host{st['host']}", "WARN", st["verdict"],
        host=st["host"], slow_windows=st["slow_windows"],
        windows=st["windows"], median_skew_s=st["median_skew_s"])]


def _rule_slo(ctx: dict) -> list[dict]:
    """Per-tenant SLO burn over the trailing window: the sentinel's ladder
    mapped onto severities (BURNING -> WARN, EXHAUSTED -> CRIT), with the
    worst replica named from per-replica windowed p99 so a CRIT is
    actionable without a second query."""
    slo = ctx["slo"]
    if not slo:
        return []
    out = []
    for tenant, row in slo["tenants"].items():
        if row["verdict"] == "GOOD":
            continue
        sev = "CRIT" if row["verdict"] == "EXHAUSTED" else "WARN"
        worst = ctx["worst_replica"]
        summary = (
            f"tenant {tenant} burning error budget at {row['burn_rate']}x "
            f"({row['violations']}/{row['requests']} violations, p99 "
            f"{row['p99_s']:.3f}s vs {slo['target_p99_s']:.3f}s target)"
            if row["p99_s"] is not None else
            f"tenant {tenant} burning error budget at {row['burn_rate']}x "
            f"({row['violations']}/{row['requests']} violations)")
        if worst:
            summary += (f"; worst replica {worst['process']} "
                        f"(p99 {worst['p99_s']:.3f}s)")
        out.append(_verdict(
            "slo", f"slo:{tenant}", sev, summary,
            tenant=tenant, burn_rate=row["burn_rate"],
            violation_frac=row["violation_frac"], p99_s=row["p99_s"],
            target_p99_s=slo["target_p99_s"], verdict=row["verdict"],
            worst_replica=(worst or {}).get("process"),
            worst_replica_p99_s=(worst or {}).get("p99_s")))
    return out


def _rule_queue(ctx: dict) -> list[dict]:
    """Per-replica queue depth from the newest ``serve`` gauge — the
    backlog signal the autoscaler scales on, alarmed here first."""
    warn = _env_float(QUEUE_WARN_ENV, 8.0)
    crit = _env_float(QUEUE_CRIT_ENV, 32.0)
    out = []
    for proc, depth in sorted(ctx["queue_depth"].items()):
        if depth is None or depth < warn:
            continue
        sev = "CRIT" if depth >= crit else "WARN"
        out.append(_verdict(
            "queue", f"queue:{proc}", sev,
            f"replica {proc} queue depth {depth:.0f} "
            f"(warn≥{warn:.0f}, crit≥{crit:.0f})",
            process=proc, queue_depth=depth, warn=warn, crit=crit))
    return out


def _rule_shed(ctx: dict) -> list[dict]:
    """Fleet-wide shed rate over the trailing window (per-tenant sheds are
    the SLO rule's job; this one catches an untenanted overload)."""
    reqs = [e for e in ctx["window_events"] if e.get("kind") == "request"]
    if not reqs:
        return []
    shed = sum(e.get("outcome") == "shed" for e in reqs)
    rate = shed / len(reqs)
    warn = _env_float(SHED_WARN_ENV, 0.05)
    crit = _env_float(SHED_CRIT_ENV, 0.25)
    if rate < warn:
        return []
    sev = "CRIT" if rate >= crit else "WARN"
    return [_verdict(
        "shed", "shed:fleet", sev,
        f"shedding {100.0 * rate:.1f}% of requests "
        f"({shed}/{len(reqs)} in window)",
        shed=shed, requests=len(reqs), shed_rate=round(rate, 4))]


def _rule_recompile(ctx: dict) -> list[dict]:
    """The compile ledger flagged recompiles (a signature compiled twice,
    or more signatures than the wrapper pinned) -> WARN naming the fns."""
    an = ctx["anatomy"]
    cl = an and an.get("compile_ledger")
    if not cl or not cl.get("flagged_recompiles"):
        return []
    fns = sorted(fn for fn, row in cl["by_fn"].items()
                 if row["flagged_recompiles"])
    return [_verdict(
        "recompile", "recompile:ledger", "WARN",
        f"{cl['flagged_recompiles']} flagged recompile(s) in {fns} "
        f"({cl['total_compile_s']:.1f}s total compile)",
        flagged_recompiles=cl["flagged_recompiles"], fns=fns,
        total_compile_s=cl["total_compile_s"])]


def _rule_hbm(ctx: dict) -> list[dict]:
    """HBM headroom from the allocator watermarks (memory_stats source
    only — the live-buffer CPU fallback has no limit to judge against)."""
    an = ctx["anatomy"]
    mem = an and an.get("memory")
    if not mem or mem.get("source") != "memory_stats":
        return []
    headroom = mem.get("headroom_bytes")
    limit = mem.get("bytes_limit_min")
    if headroom is None or not limit:
        return []
    frac = headroom / float(limit)
    if frac >= 0.10:
        return []
    sev = "CRIT" if frac < 0.05 else "WARN"
    return [_verdict(
        "hbm", "hbm:headroom", sev,
        f"HBM headroom {100.0 * frac:.1f}% of limit "
        f"({headroom / 2**30:.2f}GiB free)",
        headroom_bytes=headroom, bytes_limit_min=limit,
        headroom_frac=round(frac, 4))]


def _rule_restarts(ctx: dict) -> list[dict]:
    """A restart storm in the window: one restart is the supervisor doing
    its job; repeated ones mean the fault survives the remedy."""
    restarts = [e for e in ctx["window_events"]
                if e.get("kind") == "recovery"
                and e.get("event") in ("restart", "geometry_change")]
    if len(restarts) < 2:
        return []
    sev = "CRIT" if len(restarts) >= 4 else "WARN"
    classes = sorted({str(e.get("classification"))
                      for e in restarts if e.get("classification")})
    return [_verdict(
        "restarts", "restarts:storm", sev,
        f"{len(restarts)} restart/geometry event(s) in the last "
        f"{ctx['window_s']:.0f}s ({', '.join(classes) or 'unclassified'})",
        restarts=len(restarts), classifications=classes,
        window_s=ctx["window_s"])]


def _rule_shuffle(ctx: dict) -> list[dict]:
    """Shuffle self-healing churn in the window: retries/speculations are
    absorbed faults; a blacklist or a retry pile-up is worth a WARN
    before it escalates to WorkerCrashed."""
    retries = blacklists = 0
    for e in ctx["window_events"]:
        if e.get("kind") != "shuffle":
            continue
        if e.get("edge") == "retry":
            retries += 1
        elif e.get("edge") == "blacklist":
            blacklists += 1
    if blacklists == 0 and retries < 3:
        return []
    return [_verdict(
        "shuffle", "shuffle:recovery", "WARN",
        f"shuffle recovery churn: {retries} retry(ies), "
        f"{blacklists} blacklist(s) in window",
        retries=retries, blacklists=blacklists)]


def _rule_goodput(ctx: dict) -> list[dict]:
    """Whole-stream goodput floor, gated on enough wall-clock that the
    startup compile can't dominate the fraction."""
    g = ctx["goodput"]
    floor = _env_float(GOODPUT_WARN_ENV, 0.5)
    has_steps = any(e.get("kind") == "step_metrics" for e in ctx["events"])
    if not has_steps or g["wall_s"] < 120.0 or g["goodput_frac"] >= floor:
        return []
    overhead = {k: round(g[k], 1) for k in telemetry.GOODPUT_COMPONENTS
                if k != "productive_s" and g.get(k, 0.0) > 0.0}
    biggest = max(overhead, key=overhead.get, default=None)
    return [_verdict(
        "goodput", "goodput:run", "WARN",
        f"goodput {g['goodput_frac']:.2f} below {floor:.2f} floor"
        + (f" — biggest overhead {biggest} ({overhead[biggest]}s)"
           if biggest else ""),
        goodput_frac=round(g["goodput_frac"], 4), floor=floor,
        overhead=overhead)]


# -- predictive trend rules ---------------------------------------------------
#
# Level rules above fire when a threshold is ALREADY crossed; these fire
# when the recorded history says it is ABOUT to be — a WARN with the
# projection as evidence, strictly before the damped level CRIT. They read
# ``ctx["trend"]``: per-series (ts, value) tails the engine seeds from its
# :class:`~.series.SeriesStore` (history + this evaluation's sample). A
# stateless caller (one-shot --health, the cluster fold) has no history,
# so the trend rules simply return [] — prediction needs memory. Each rule
# is hysteretic twice over: the movement must repeat ``DLS_HEALTH_TREND_N``
# evaluations straight AND the projection must land inside the trailing
# window; and each goes quiet once the level it predicts has arrived (the
# level rule owns the incident from there).


def _trend_tail(ctx: dict, key: str) -> list[tuple[float, float]]:
    return list(ctx.get("trend", {}).get(key) or ())


def _moves(points: list[tuple[float, float]], n: int, sign: int) -> bool:
    """Did the series move strictly in ``sign`` direction for the last
    ``n`` consecutive deltas (needs n+1 points)?"""
    if n < 1 or len(points) < n + 1:
        return False
    vals = [v for _, v in points[-(n + 1):]]
    return all(sign * (b - a) > 0 for a, b in zip(vals, vals[1:]))


def _trend_n() -> int:
    return max(1, int(_env_float(TREND_N_ENV, 3.0)))


def _rule_trend_queue(ctx: dict) -> list[dict]:
    """Queue depth growing N evaluations straight and projected to cross
    the CRIT threshold within the window -> predictive WARN."""
    n = _trend_n()
    crit = _env_float(QUEUE_CRIT_ENV, 32.0)
    out = []
    for proc in sorted(ctx["queue_depth"]):
        key = series_lib.series_key(series_lib.QUEUE_SERIES, replica=proc)
        pts = _trend_tail(ctx, key)
        if not _moves(pts, n, +1):
            continue
        cur = pts[-1][1]
        if cur >= crit:
            continue  # already there: the level rule owns it
        fit = series_lib.linear_trend(pts[-(n + 1):])
        if not fit or fit["slope_per_s"] <= 0:
            continue
        eta = (crit - cur) / fit["slope_per_s"]
        if eta > ctx["window_s"]:
            continue
        out.append(_verdict(
            "trend_queue", f"trend:queue:{proc}", "WARN",
            f"replica {proc} queue depth rising {n} evaluations straight "
            f"({cur:.0f} now, projected ≥{crit:.0f} in ~{eta:.0f}s)",
            process=proc, queue_depth=cur,
            slope_per_s=round(fit["slope_per_s"], 6),
            projected_crit_in_s=round(eta, 1), crit=crit, consecutive=n))
    return out


def _rule_trend_slo(ctx: dict) -> list[dict]:
    """Burn-rate slope projecting EXHAUSTED within the window -> WARN
    before the level rule's damped CRIT."""
    slo = ctx["slo"]
    if not slo:
        return []
    n = _trend_n()
    exhaust = fleet_lib.SLO_EXHAUST_BURN
    out = []
    for tenant, row in slo["tenants"].items():
        if row["verdict"] == "EXHAUSTED":
            continue  # already there: the level rule owns it
        key = series_lib.series_key(series_lib.BURN_SERIES, tenant=tenant)
        pts = _trend_tail(ctx, key)
        if not _moves(pts, n, +1):
            continue
        cur = pts[-1][1]
        fit = series_lib.linear_trend(pts[-(n + 1):])
        if not fit or fit["slope_per_s"] <= 0:
            continue
        eta = max(0.0, (exhaust - cur) / fit["slope_per_s"])
        if eta > ctx["window_s"]:
            continue
        out.append(_verdict(
            "trend_slo", f"trend:slo:{tenant}", "WARN",
            f"tenant {tenant} burn rate rising {n} evaluations straight "
            f"({cur:.1f}x now, projecting EXHAUSTED ≥{exhaust:.0f}x "
            f"in ~{eta:.0f}s)",
            tenant=tenant, burn_rate=cur,
            slope_per_s=round(fit["slope_per_s"], 6),
            projected_exhausted_in_s=round(eta, 1),
            exhaust_burn=exhaust, consecutive=n))
    return out


def _rule_trend_hbm(ctx: dict) -> list[dict]:
    """HBM headroom trending to zero within the window -> WARN while the
    level rule still reads it as survivable (≥5%)."""
    n = _trend_n()
    pts = _trend_tail(ctx, series_lib.HBM_SERIES)
    if not _moves(pts, n, -1):
        return []
    cur = pts[-1][1]
    if cur < 0.05:
        return []  # already there: the level rule owns it
    fit = series_lib.linear_trend(pts[-(n + 1):])
    if not fit or fit["slope_per_s"] >= 0:
        return []
    eta = cur / -fit["slope_per_s"]
    if eta > ctx["window_s"]:
        return []
    return [_verdict(
        "trend_hbm", "trend:hbm", "WARN",
        f"HBM headroom falling {n} evaluations straight "
        f"({100.0 * cur:.1f}% now, projected exhausted in ~{eta:.0f}s)",
        headroom_frac=round(cur, 4),
        slope_per_s=round(fit["slope_per_s"], 8),
        projected_zero_in_s=round(eta, 1), consecutive=n)]


def _rule_trend_steps(ctx: dict) -> list[dict]:
    """In-run steps/sec decline: N straight drops AND the current rate a
    configurable fraction below the tail's peak (same judgment
    perf_guard --series makes post-hoc, raised live here)."""
    n = _trend_n()
    drop = _env_float(STEPS_DROP_ENV, 0.15)
    pts = _trend_tail(ctx, series_lib.STEPS_SERIES)
    if not _moves(pts, n, -1):
        return []
    vals = [v for _, v in pts]
    peak, cur = max(vals), vals[-1]
    if peak <= 0 or cur > (1.0 - drop) * peak:
        return []
    return [_verdict(
        "trend_steps", "trend:steps", "WARN",
        f"steps/sec declining {n} evaluations straight "
        f"({cur:.2f} now, {100.0 * (1.0 - cur / peak):.0f}% below "
        f"peak {peak:.2f})",
        steps_per_sec=round(cur, 4), peak_steps_per_sec=round(peak, 4),
        drop_frac=round(1.0 - cur / peak, 4), floor_frac=drop,
        consecutive=n)]


def _rule_trend_engine(ctx: dict) -> list[dict]:
    """The engine watching itself: unread backlog (cursor lag) growing N
    evaluations straight means evaluations are falling behind the
    writers' append rate — today a slow engine is invisible."""
    n = _trend_n()
    pts = _trend_tail(ctx, series_lib.ENGINE_LAG_SERIES)
    if not _moves(pts, n, +1):
        return []
    cur = pts[-1][1]
    if cur <= 0:
        return []
    return [_verdict(
        "trend_engine", "trend:engine", "WARN",
        f"health engine falling behind the append rate: unread backlog "
        f"grew {n} evaluations straight to {cur:.0f} bytes",
        lag_bytes=cur, consecutive=n)]


#: the registry, evaluation order = display order. Names are part of the
#: health.json contract (the ``rules`` map is keyed by them; additions
#: don't bump the schema).
RULES: tuple[tuple[str, Callable[[dict], list[dict]]], ...] = (
    ("stream", _rule_stream),
    ("heartbeat", _rule_heartbeat),
    ("hosts", _rule_hosts),
    ("hang", _rule_hang),
    ("straggler", _rule_straggler),
    ("slo", _rule_slo),
    ("queue", _rule_queue),
    ("shed", _rule_shed),
    ("recompile", _rule_recompile),
    ("hbm", _rule_hbm),
    ("restarts", _rule_restarts),
    ("shuffle", _rule_shuffle),
    ("goodput", _rule_goodput),
    ("trend_queue", _rule_trend_queue),
    ("trend_slo", _rule_trend_slo),
    ("trend_hbm", _rule_trend_hbm),
    ("trend_steps", _rule_trend_steps),
    ("trend_engine", _rule_trend_engine),
)


def _build_ctx(events: list[dict], *, now: float | None,
               window_s: float, slo_target_s: float | None,
               slo_budget: float, stream: dict | None) -> dict:
    """Compute every producer fold ONCE; rules read, never re-fold.

    ``now`` None anchors on the stream's end (the post-mortem-safe default
    the whole reader side uses); an explicit ``now`` also BOUNDS the
    stream to events at or before it, so an injected-clock engine
    replaying history evaluates each tick exactly as a live engine would
    have seen it (a live engine's poll can't return the future anyway —
    the bound only bites on replays). The engine's own ``alert`` events
    are excluded from the anchor and from rule inputs so the engine never
    reacts to itself."""
    events = [e for e in events if "ts" in e and e.get("kind") != "alert"]
    if now is not None:
        events = [e for e in events if float(e["ts"]) <= float(now)]
    anchor = (float(now) if now is not None
              else (float(events[-1]["ts"]) if events else 0.0))
    window_events = [e for e in events
                     if float(e["ts"]) >= anchor - window_s]
    replica_p99 = fleet_lib.replica_p99(window_events)
    worst = None
    for proc, row in replica_p99.items():
        if worst is None or row["p99_s"] > worst["p99_s"]:
            worst = {"process": proc, **row}
    serving = fleet_lib.serving_fleet(events)
    queue_depth: dict[str, Any] = {}
    if serving:
        for r in serving["replicas"]:
            if r.get("queue_depth") is not None:
                queue_depth[r["process"]] = r["queue_depth"]
    return {
        "events": events,
        "window_events": window_events,
        "now": anchor,
        "window_s": window_s,
        "stream": stream or {"files": 0, "events": len(events),
                             "skipped_lines": 0},
        "fleet": fleet_lib.fleet_report(events, now=now) if events else None,
        "serving": serving,
        "queue_depth": queue_depth,
        "replica_p99": replica_p99,
        "worst_replica": worst,
        "slo": (fleet_lib.slo_report(window_events,
                                     target_p99_s=slo_target_s,
                                     budget=slo_budget)
                if slo_target_s is not None else None),
        "anatomy": anatomy_lib.anatomy_report(events) if events else None,
        "goodput": telemetry.goodput(events),
    }


def _tenant_rows(ctx: dict) -> dict[str, dict]:
    """Per-tenant rows: serve tenants (requests/sheds, burn when the SLO
    rule is armed) + the env-stamped attribution tenants (``DLS_TENANT``
    -> every record), with the run's goodput attributed to the latter so a
    training workdir has a per-tenant row too."""
    rows: dict[str, dict] = {}

    def row(t: str) -> dict:
        return rows.setdefault(str(t), {})

    # bare engines stamp `tenant` on their own request events (no router
    # fold to read); count those first so a single-engine workdir still
    # gets requests/shed per tenant
    reqs = [e for e in ctx["events"] if e.get("kind") == "request"
            and e.get("tenant") is not None]
    for t in sorted({str(e["tenant"]) for e in reqs}):
        mine = [e for e in reqs if str(e["tenant"]) == t]
        shed = sum(e.get("outcome") == "shed" for e in mine)
        row(t).update(requests=len(mine), shed=shed,
                      shed_rate=round(shed / len(mine), 4))
    serving = ctx["serving"]
    if serving and serving["totals"].get("tenants"):
        for t, r in serving["totals"]["tenants"].items():
            row(t).update(requests=r["requests"], shed=r["shed"],
                          shed_rate=r["shed_rate"])
    if ctx["slo"]:
        for t, r in ctx["slo"]["tenants"].items():
            row(t).update(requests=r["requests"],
                          burn_rate=r["burn_rate"],
                          slo_verdict=r["verdict"])
    stamped = sorted({str(e["tenant"]) for e in ctx["events"]
                      if e.get("tenant") is not None})
    for t in stamped:
        row(t).setdefault("stamped", True)
        row(t).setdefault("goodput_frac",
                          round(ctx["goodput"]["goodput_frac"], 4))
    return rows


def _series_samples(ctx: dict) -> dict[str, float]:
    """The per-evaluation sample batch the engine records into its
    :class:`~.series.SeriesStore` — every value re-read from the folds
    the rules already consumed, so history costs nothing extra. Keys are
    the canonical series names (:mod:`.series`); a signal with no
    evidence this evaluation is simply absent (no phantom zeros)."""
    s: dict[str, float] = {}
    if ctx["events"]:
        s[series_lib.GOODPUT_SERIES] = ctx["goodput"]["goodput_frac"]
    laps = [e for e in ctx["window_events"]
            if e.get("kind") == "step_metrics" and e.get("lap_s")]
    lap_s = sum(float(e["lap_s"]) for e in laps)
    if lap_s > 0:
        s[series_lib.STEPS_SERIES] = (
            sum(int(e.get("steps", 0) or 0) for e in laps) / lap_s)
    an = ctx["anatomy"]
    if an:
        mfu_doc = an.get("mfu") or {}
        mfu = mfu_doc.get("mfu_last_lap")
        if mfu is None:
            mfu = mfu_doc.get("mfu")
        if mfu is not None:
            s[series_lib.MFU_SERIES] = float(mfu)
        mem = an.get("memory")
        if (mem and mem.get("source") == "memory_stats"
                and mem.get("headroom_bytes") is not None
                and mem.get("bytes_limit_min")):
            s[series_lib.HBM_SERIES] = (
                mem["headroom_bytes"] / float(mem["bytes_limit_min"]))
    hbs = [e for e in ctx["events"] if e.get("kind") == "heartbeat"]
    if hbs:
        s[series_lib.HEARTBEAT_SERIES] = ctx["now"] - float(hbs[-1]["ts"])
    reqs = [e for e in ctx["window_events"] if e.get("kind") == "request"]
    if reqs:
        s[series_lib.SHED_SERIES] = (
            sum(e.get("outcome") == "shed" for e in reqs) / len(reqs))
    if any(e.get("kind") == "shuffle" for e in ctx["events"]):
        spills = sum(1 for e in ctx["window_events"]
                     if e.get("kind") == "shuffle"
                     and e.get("edge") == "spill")
        s[series_lib.SPILL_SERIES] = spills / max(ctx["window_s"], 1e-9)
    for proc, depth in ctx["queue_depth"].items():
        if depth is not None:
            s[series_lib.series_key(series_lib.QUEUE_SERIES,
                                    replica=proc)] = float(depth)
    for proc, row in ctx["replica_p99"].items():
        s[series_lib.series_key(series_lib.P99_SERIES,
                                replica=proc)] = row["p99_s"]
    if ctx["slo"]:
        for tenant, row in ctx["slo"]["tenants"].items():
            s[series_lib.series_key(series_lib.BURN_SERIES,
                                    tenant=tenant)] = row["burn_rate"]
    return {k: float(v) for k, v in s.items()
            if v is not None and math.isfinite(float(v))}


def evaluate_health(events: list[dict], *, workdir: str | None = None,
                    now: float | None = None,
                    window_s: float | None = None,
                    slo_target_s: float | None = None,
                    slo_budget: float = 0.01,
                    stream: dict | None = None,
                    trend_tails: dict[str, list] | None = None) -> dict:
    """One stateless evaluation: the raw (undamped) health report.

    Returns the health.json body MINUS the engine-state keys
    (``evaluations``, ``alerts_active``, damped ``worst_severity``) — the
    engine adds those; one-shot callers (``--health`` with ``damping=1``,
    the cluster fold) use the raw verdicts directly. ``stream`` is the
    reader's file/skip accounting (``{files, events, skipped_lines}``)
    when the caller has it (the cursor tracks it; a bare events list
    can't know how many files it came from). ``trend_tails`` is the
    engine's per-series history ({key: [(ts, value), ...]}); the current
    evaluation's samples are appended before the predictive trend rules
    read them, and the batch is returned under ``_series_samples`` for
    the engine to record. None (the stateless default) disarms the trend
    rules — prediction needs memory."""
    if window_s is None:
        window_s = _env_float(WINDOW_ENV, 300.0)
    if slo_target_s is None:
        raw = os.environ.get(SLO_TARGET_ENV)
        slo_target_s = float(raw) if raw else None
    ctx = _build_ctx(events, now=now, window_s=window_s,
                     slo_target_s=slo_target_s, slo_budget=slo_budget,
                     stream=stream)
    samples = _series_samples(ctx)
    trend: dict[str, list] = {}
    if trend_tails is not None:
        trend = {k: list(v) for k, v in trend_tails.items()}
        for key, val in samples.items():
            trend.setdefault(key, []).append((ctx["now"], val))
    ctx["trend"] = trend
    rules: dict[str, dict] = {}
    verdicts: list[dict] = []
    for name, fn in RULES:
        vs = fn(ctx)
        verdicts.extend(vs)
        rules[name] = {
            "severity": worst_severity(v["severity"] for v in vs),
            "verdicts": vs,
        }
    hbs = [e for e in ctx["events"] if e.get("kind") == "heartbeat"]
    stepped = [e for e in ctx["events"]
               if e.get("kind") in ("step_metrics", "heartbeat")
               and e.get("step") is not None]
    st = dict(ctx["stream"])
    st["degraded"] = bool(st["files"] and not st["events"])
    return {
        "schema": HEALTH_SCHEMA,
        "generated_ts": ctx["now"],
        "workdir": workdir,
        "worst_severity": worst_severity(v["severity"] for v in verdicts),
        "rules": rules,
        "goodput": ctx["goodput"],
        "slo": ctx["slo"],
        "queue_depth": ctx["queue_depth"],
        "tenants": _tenant_rows(ctx),
        "last_step": int(stepped[-1]["step"]) if stepped else None,
        "last_heartbeat_age_s": (
            round(ctx["now"] - float(hbs[-1]["ts"]), 1) if hbs else None),
        "stream": st,
        "_verdicts": verdicts,  # engine-internal; stripped before writing
        "_series_samples": samples,  # engine-internal, recorded to series
    }


def write_health_json(report: dict, workdir: str | os.PathLike,
                      path: str | None = None) -> str:
    """Atomically rewrite ``<workdir>/health.json`` (temp + rename: a
    consumer polling the file never reads a torn JSON body)."""
    path = path or os.path.join(os.fspath(workdir), HEALTH_FILENAME)
    body = {k: v for k, v in report.items() if not k.startswith("_")}
    tmp = f"{path}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(_json_safe(body), f, default=str)
    os.replace(tmp, path)
    return path


class HealthEngine:
    """The continuous evaluator: incremental reads, flap damping, alert
    edges, atomic health.json.

    State machine per dedup ``key``: a *confirmed* severity (OK when the
    key is absent) plus at most one *pending* candidate. A raw verdict that
    differs from the confirmed state must repeat for ``damping``
    consecutive evaluations before the transition commits — at which point
    ONE ``alert`` event emits (``edge="raise"`` into WARN/CRIT with the
    held count as its receipt, ``edge="clear"`` back to OK carrying
    ``cleared_from``) and health.json flips. A raw state that flaps back
    before holding resets the candidate, so an oscillating rule emits
    nothing; a steady raised state re-evaluating raised emits nothing
    (dedup); a severity change on a live alert (WARN->CRIT) emits a raise
    with ``prev``. Clears always pair with their raise by ``key``.

    ``clock=None`` (default) anchors every evaluation on the stream's end
    — deterministic for tests and drills, and self-advancing on a live
    stream; pass ``time.time`` for wall-clock anchoring (ages measured to
    real now even when the stream stops — the live-daemon mode).
    ``write_alerts=False`` inspects without appending to the stream (the
    one-shot ``--health`` surface)."""

    def __init__(self, workdir: str | os.PathLike, *,
                 damping: int | None = None,
                 window_s: float | None = None,
                 slo_target_s: float | None = None,
                 slo_budget: float = 0.01,
                 clock: Callable[[], float] | None = None,
                 write_alerts: bool = True,
                 health_path: str | None = None):
        self.workdir = os.fspath(workdir)
        self.damping = max(1, int(damping if damping is not None
                                  else _env_float(DAMPING_ENV, 3.0)))
        self.window_s = window_s
        self.slo_target_s = slo_target_s
        self.slo_budget = slo_budget
        self._clock = clock
        self._write_alerts = write_alerts
        self._health_path = health_path
        self._cursor = telemetry.EventCursor(workdir)
        #: the history plane: one sample batch per evaluation, downsampled
        #: into multi-resolution buckets. Tails double as the memory the
        #: predictive trend rules fit their slope on.
        self.series = series_lib.SeriesStore(workdir)
        self._writer: telemetry.EventWriter | None = None
        # key -> confirmed non-OK state {rule, severity, summary, evidence,
        #                                since_ts, held}
        self._state: dict[str, dict] = {}
        # key -> pending candidate {severity, count, verdict}
        self._pending: dict[str, dict] = {}
        self.evaluations = 0

    # -- internals --

    def _emit_alert(self, fields: dict) -> None:
        if not self._write_alerts:
            return
        if self._writer is None:
            # host=None keeps the engine out of the fleet table, exactly
            # like the supervisor's stream
            self._writer = telemetry.EventWriter(
                self.workdir, process="health", host=None,
                clock=self._clock or time.time)
        self._writer.emit("alert", **fields)

    def _transition(self, key: str, verdict: dict | None, held: int,
                    now: float) -> None:
        prev = self._state.get(key)
        if verdict is None:  # -> OK: clear
            if prev is not None:
                self._emit_alert({
                    "edge": "clear", "rule": prev["rule"], "key": key,
                    "severity": "OK", "cleared_from": prev["severity"],
                    "summary": f"cleared: {prev['summary']}",
                    "held": held})
                del self._state[key]
            return
        edge = {
            "edge": "raise", "rule": verdict["rule"], "key": key,
            "severity": verdict["severity"], "summary": verdict["summary"],
            "evidence": verdict["evidence"], "held": held,
        }
        if prev is not None:
            edge["prev"] = prev["severity"]
        self._emit_alert(edge)
        self._state[key] = {
            "rule": verdict["rule"], "severity": verdict["severity"],
            "summary": verdict["summary"], "evidence": verdict["evidence"],
            "since_ts": now, "held": held,
        }

    def evaluate(self) -> dict:
        """One tick: poll appended events, run the rules, damp, emit edges,
        rewrite health.json. Returns the written report (plus the raw
        verdict list under ``_verdicts``)."""
        t_tick0 = time.perf_counter()
        self._cursor.poll()
        now = self._clock() if self._clock is not None else None
        # the engine's own alert stream must not count as "the workdir has
        # events": a degraded workdir would otherwise raise, append the
        # alert, then read its own edge as recovery and clear — forever
        stream = {"files": len(telemetry.event_files(self.workdir)),
                  "events": sum(e.get("kind") != "alert"
                                for e in self._cursor.events),
                  "skipped_lines": self._cursor.skipped_lines}
        report = evaluate_health(
            self._cursor.events, workdir=self.workdir, now=now,
            window_s=self.window_s, slo_target_s=self.slo_target_s,
            slo_budget=self.slo_budget, stream=stream,
            trend_tails=self.series.tails)
        self.evaluations += 1
        anchor = report["generated_ts"]
        raw = {v["key"]: v for v in report["_verdicts"]}
        for key in sorted(set(raw) | set(self._state) | set(self._pending)):
            verdict = raw.get(key)
            tgt = verdict["severity"] if verdict else "OK"
            cur = self._state.get(key, {}).get("severity", "OK")
            if tgt == cur:
                self._pending.pop(key, None)
                continue
            p = self._pending.get(key)
            if p is None or p["severity"] != tgt:
                p = {"severity": tgt, "count": 0, "verdict": verdict}
            p["count"] += 1
            p["verdict"] = verdict
            if p["count"] >= self.damping:
                self._pending.pop(key, None)
                self._transition(key, verdict, p["count"], anchor)
            else:
                self._pending[key] = p
        report["evaluations"] = self.evaluations
        report["worst_severity"] = worst_severity(
            s["severity"] for s in self._state.values())
        report["alerts_active"] = [
            {"key": key, **st} for key, st in sorted(self._state.items())]
        # self-telemetry (tick wall time is always real — even an
        # injected-clock drill wants the engine's actual cost), then the
        # whole batch lands in the series store; record() no-ops when the
        # anchor didn't advance, so a stalled stream records nothing twice
        tick_s = time.perf_counter() - t_tick0
        lag = self._cursor.lag_bytes()
        report["engine"] = {
            "tick_s": round(tick_s, 6), "lag_bytes": lag,
            "rules_evaluated": len(RULES),
            "bytes_read": self._cursor.bytes_read,
        }
        samples = dict(report.get("_series_samples") or {})
        samples[series_lib.ENGINE_TICK_SERIES] = tick_s
        samples[series_lib.ENGINE_LAG_SERIES] = float(lag)
        samples[series_lib.ENGINE_RULES_SERIES] = float(len(RULES))
        self.series.record(anchor, samples)
        write_health_json(report, self.workdir, self._health_path)
        return report

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


# -- incident timeline --------------------------------------------------------


def _who(e: dict) -> str | None:
    """Attribute an event to the host/replica/stage/tenant it names."""
    for field, fmt in (("culprit_host", "host{}"), ("dead_host", "host{}"),
                      ("replica", "replica {}"), ("stage", "stage {}"),
                      ("tenant", "tenant {}")):
        if e.get(field) is not None:
            return fmt.format(e[field])
    ev = e.get("evidence") or {}
    if isinstance(ev, dict):
        if ev.get("worst_replica") is not None:
            return f"replica {ev['worst_replica']}"
        if ev.get("host") is not None:
            return f"host{ev['host']}"
        if ev.get("process") is not None:
            return f"replica {ev['process']}"
        if ev.get("tenant") is not None:
            return f"tenant {ev['tenant']}"
    if e.get("host") is not None:
        return f"host{e['host']}"
    return None


def incident_timeline(events: list[dict]) -> list[dict]:
    """Fold alert edges + ``recovery`` events + failed supervisor attempt
    ends into one ts-ordered timeline: "what happened, in order, attributed
    to whom" (``dlstatus --incidents``)."""
    rows: list[dict] = []
    for e in events:
        kind, ts = e.get("kind"), e.get("ts")
        if ts is None:
            continue
        if kind == "alert":
            rows.append({
                "ts": float(ts),
                "type": f"alert-{e.get('edge', '?')}",
                "severity": e.get("severity"),
                "rule": e.get("rule"), "key": e.get("key"),
                "who": _who(e), "summary": e.get("summary"),
                "step": e.get("step"),
                "cleared_from": e.get("cleared_from"),
            })
        elif kind == "recovery":
            extra = {k: e[k] for k in ("classification", "transport",
                                       "reason", "ordinal", "replica")
                     if e.get(k) is not None}
            rows.append({
                "ts": float(ts), "type": "recovery",
                "severity": None, "rule": None,
                "key": e.get("event"), "who": _who(e),
                "summary": e.get("event", "") + (
                    " " + json.dumps(extra, default=str) if extra else ""),
                "step": e.get("step"), "cleared_from": None,
            })
        elif kind == "sched":
            edge = e.get("edge", "?")
            bits = [e.get("job") or "?"]
            if e.get("mode"):
                bits.append(e["mode"])
            if e.get("victim_of"):
                bits.append(f"for {e['victim_of']}")
            if e.get("reason"):
                bits.append(e["reason"])
            if e.get("hosts") is not None:
                bits.append(f"hosts={e['hosts']}")
            rows.append({
                "ts": float(ts), "type": f"sched-{edge}",
                "severity": ("WARN" if edge in ("preempt", "requeue", "fail")
                             else None),
                "rule": None, "key": e.get("job"),
                "who": _who(e), "summary": " ".join(str(b) for b in bits),
                "step": e.get("step"), "cleared_from": None,
            })
        elif (kind == "attempt" and e.get("edge") == "end"
              and e.get("classification") not in (None, "clean")):
            rows.append({
                "ts": float(ts), "type": "attempt-end",
                "severity": None, "rule": None,
                "key": f"attempt#{e.get('ordinal')}", "who": _who(e),
                "summary": (f"attempt {e.get('ordinal')} ended: "
                            f"{e.get('classification')} "
                            f"(codes {e.get('returncodes')})"),
                "step": None, "cleared_from": None,
            })
    rows.sort(key=lambda r: r["ts"])
    return rows


# -- cluster view -------------------------------------------------------------


def discover_workdirs(root: str | os.PathLike) -> list[str]:
    """Every workdir under ``root`` that holds telemetry (an
    ``events-*.jsonl`` anywhere below it, in a ``telemetry/`` subdir or
    bare). Returns run-directory paths, sorted."""
    root = os.fspath(root)
    hits: set[str] = set()
    for p in glob.glob(os.path.join(root, "**", "events-*.jsonl"),
                       recursive=True):
        d = os.path.dirname(p)
        if os.path.basename(d) == telemetry.TELEMETRY_DIRNAME:
            d = os.path.dirname(d)
        hits.add(d)
    return sorted(hits)


def _workdir_kind(events: list[dict]) -> str:
    kinds = {e.get("kind") for e in events}
    if "request" in kinds or "serve" in kinds:
        return "serve"
    if "step_metrics" in kinds or "attempt" in kinds or "phase" in kinds:
        return "train"
    if "sched" in kinds:
        return "sched"
    return "events" if events else "empty"


def workdir_trend(wd: str | os.PathLike,
                  key: str = series_lib.GOODPUT_SERIES) -> dict | None:
    """The cluster view's per-workdir trend cell: the finest-resolution
    series fitted over its whole ring. None when the workdir has no
    series store (no engine ever ran there) or no such series."""
    ladder = series_lib.list_resolutions(wd)
    if not ladder:
        return None
    bs = series_lib.read_buckets(wd, ladder[0][0], keys=[key]).get(key)
    if not bs:
        return None
    fit = series_lib.linear_trend([(b["t"], b["mean"]) for b in bs])
    return {"key": key, "trend": series_lib.trend_verdict(fit),
            "slope_per_s": (round(fit["slope_per_s"], 8) if fit else None),
            "last": bs[-1]["last"]}


def cluster_report(root: str | os.PathLike, *,
                   slo_target_s: float | None = None,
                   slo_budget: float = 0.01,
                   window_s: float | None = None,
                   cursors: dict[str, Any] | None = None) -> dict:
    """The multi-workdir fold ``dlstatus --cluster`` renders: one health
    evaluation per discovered workdir (raw verdicts — the cluster view is
    a poll, damping lives in each workdir's own engine) plus the
    per-tenant rollup across workdirs the scheduler item specifies.

    ``cursors`` (a caller-held ``{workdir: EventCursor}`` dict, mutated in
    place) switches the fold to incremental reads: each tick parses only
    what the fleet appended since the last one, so ``--cluster --watch``
    cost is bounded by the append rate, not the stream length."""
    rows: list[dict] = []
    tenants: dict[str, dict] = {}
    for wd in discover_workdirs(root):
        if cursors is None:
            events = telemetry.read_events(wd)
            skipped = 0
        else:
            cur = cursors.get(wd)
            if cur is None:
                cur = cursors[wd] = telemetry.EventCursor(wd)
            cur.poll()
            events = cur.events
            skipped = cur.skipped_lines
        files = len(telemetry.event_files(wd))
        rep = evaluate_health(
            events, workdir=wd, window_s=window_s,
            slo_target_s=slo_target_s, slo_budget=slo_budget,
            stream={"files": files,
                    "events": sum(e.get("kind") != "alert" for e in events),
                    "skipped_lines": skipped})
        serving = fleet_lib.serving_fleet(events)
        occupancy = (serving["totals"].get("kv_page_occupancy_max")
                     if serving else None)
        worst_alert = None
        for v in rep["_verdicts"]:
            if worst_alert is None or (_SEV_RANK[v["severity"]]
                                       > _SEV_RANK[worst_alert["severity"]]):
                worst_alert = {k: v[k] for k in ("rule", "key", "severity",
                                                 "summary")}
        row_tenants = sorted(rep["tenants"]) or ["-"]
        rows.append({
            "workdir": wd,
            "kind": _workdir_kind(events),
            "tenants": row_tenants,
            "num_events": len(events),
            "degraded": rep["stream"]["degraded"],
            "goodput_frac": round(rep["goodput"]["goodput_frac"], 4),
            "occupancy": occupancy,
            "worst_severity": rep["worst_severity"],
            "worst_alert": worst_alert,
            "last_step": rep["last_step"],
            "last_heartbeat_age_s": rep["last_heartbeat_age_s"],
            "trend": workdir_trend(wd),
        })
        for t, trow in rep["tenants"].items():
            agg = tenants.setdefault(t, {
                "workdirs": 0, "train_workdirs": 0, "serve_workdirs": 0,
                "requests": 0, "shed": 0, "goodput_fracs": [],
                "worst_severity": "OK"})
            agg["workdirs"] += 1
            agg[f"{rows[-1]['kind']}_workdirs"] = (
                agg.get(f"{rows[-1]['kind']}_workdirs", 0) + 1)
            agg["requests"] += int(trow.get("requests", 0) or 0)
            agg["shed"] += int(trow.get("shed", 0) or 0)
            if trow.get("goodput_frac") is not None:
                agg["goodput_fracs"].append(trow["goodput_frac"])
            agg["worst_severity"] = worst_severity(
                [agg["worst_severity"], rep["worst_severity"]])
    for agg in tenants.values():
        fracs = agg.pop("goodput_fracs")
        agg["goodput_frac"] = (round(sum(fracs) / len(fracs), 4)
                               if fracs else None)
    return {
        "root": os.fspath(root),
        "workdirs": rows,
        "tenants": tenants,
        "sched": _sched_report(root),
        "worst_severity": worst_severity(
            r["worst_severity"] for r in rows),
    }


def _sched_report(root: str | os.PathLike) -> dict | None:
    """The scheduler's queue + per-tenant used/quota accounting, when
    ``root`` is (or contains) a cluster state dir. None when no ledger
    exists — a plain fleet of workdirs renders exactly as before."""
    from distributeddeeplearningspark_tpu.scheduler import ledger as ledger_lib

    if not ledger_lib.has_ledger(root):
        return None
    try:
        return ledger_lib.load_state(root).to_report()
    except Exception as e:  # torn config / mid-write races: degrade, not die
        return {"error": f"{type(e).__name__}: {e}"}
