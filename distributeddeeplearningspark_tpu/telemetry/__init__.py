"""Run telemetry — a durable, typed JSONL event stream per run.

The reference's Spark UI leaves a per-stage account of where a job's time
went that survives the job; the rebuild's equivalents were fragmented —
``Meter`` laps lived in process memory, recovery events went to stderr, and
the supervisor's attempt history evaporated with the process. This module is
the single durable artifact: every process appends typed, timestamped
records to ``<workdir>/telemetry/events-<process>.jsonl`` and everything
downstream (the goodput accountant here, the ``dlstatus`` inspector in
:mod:`.status`) is a pure fold over those files — it works on a crashed
run's partial stream exactly as on a finished one.

Event kinds (one JSON object per line, ``ts``/``kind``/``process`` always
present):

- ``step_metrics`` — one metrics lap: ``step``, ``steps`` (in the lap),
  ``lap_s``, ``metrics`` (the device metrics), plus the input-starvation
  probe's snapshot (``input_wait_s``, ``prefetch_depth_min``, ...).
- ``phase`` — ``name`` + ``edge`` ("begin"/"end"; end carries ``dur_s``).
  Phase names the goodput accountant treats as overhead: ``compile``,
  ``restore``, ``checkpoint``/``checkpoint-wait``/``checkpoint-verify``,
  ``eval``. Other names (``run``, ``manifest``, ``profile-trace``) are
  informational.
- ``recovery`` — a recovery action fired: ``event`` ("skip", "rollback",
  "restart", "restore-fallback", "geometry_change", "graceful_shutdown",
  "reshard", ...) plus free-form evidence fields. ``geometry_change`` is
  the supervisor's elastic shrink (``dead_host``, ``evidence_attempts``,
  ``from_processes``/``to_processes``, surviving ``hosts``,
  ``batch_policy``; ``step`` is where the survivors resume and ``resume``
  says how — "checkpoint" walk-back or "live-handoff" continuation);
  ``graceful_shutdown`` is a drained preemption exit (``dead_host``,
  ``ordinal``, ``step`` = the drain step — no backoff slot burned);
  ``reshard`` is one state move across layouts. Every reshard carries
  ``transport`` ("checkpoint" = restore-time re-projection, "collectives"
  = live all-to-all between steps, "handoff" = ingest of a drained
  host's persisted live state) and ``walk_back`` (True only for the
  checkpoint path — the run rewound to a saved step). Live paths add the
  engine's measured evidence: ``bytes_moved``, ``rounds``,
  ``peak_inflight_bytes``, ``mem_budget_mb``, ``wall_s``,
  ``leaves_moved``, ``verified``; the checkpoint path keeps its
  topology record (``from_mesh``/``to_mesh``, ``from_devices``/
  ``to_devices``, ``from_processes``/``to_processes``).
- ``attempt`` — supervisor gang lifecycle: ``edge`` ("begin"/"end"/
  "backoff"), ``ordinal``, ``num_processes`` (+ ``hosts``, the surviving
  original host ordinals, on begin), and on end ``returncodes``/
  ``classification``/``duration_s`` (+ ``dead_host`` when the failure
  unambiguously names one).
- ``heartbeat`` — liveness stamp (``step``), the telemetry twin of the
  supervisor's ``DLS_HEARTBEAT_FILE`` mtime. The writer auto-enriches it
  with the innermost open ``phase`` so a stalled host is localizable from
  its last event alone (:mod:`.fleet`).
- ``collective`` — an opt-in comms probe sample (``op``, ``wait_s``) from
  :mod:`..parallel.collectives`; feeds the fleet table's comms-wait column.
- ``request`` — one served inference request (:mod:`..serve`): ``engine``,
  ``outcome`` ("ok"/"shed"/"error"), and for ok ``queue_wait_s``,
  ``infer_s``, ``latency_s``, ``batch_size`` (continuous decode adds
  ``prefix_hit``/``prefix_tokens``; router tenant sheds add ``tenant``).
  ``dlstatus`` folds these into the p50/p99 serving rollup; they never
  enter goodput accounting (serving wall-clock is not training overhead).
- ``serve`` — a serving-state gauge (:mod:`..serve.generate`): KV page
  occupancy, prefix-cache hit rate, active slots, queue depth. The
  newest one per process is a replica's "now" in ``dlstatus
  --fleet-serve`` (:func:`.fleet.serving_fleet`).
- ``shuffle`` — one distributed-exchange gauge (:mod:`..data.exchange`;
  the device agg path emits the same shape): ``edge="spill"`` marks one
  reducer spill (``reducer``/``bucket``/``rows``/``bytes``),
  ``edge="done"`` the whole-shuffle summary (``op``, ``workers``,
  ``buckets``, ``pairs_in``, ``rows_out``, ``bytes_moved``, ``spills``,
  ``overflow``, ``map_s``, ``merge_s``, ``bucket_rows``, plus the
  per-format split: ``transport`` (``tuple``/``columnar``/``mixed``/
  ``device``), ``columnar_pairs``/``columnar_bytes``/
  ``tuple_pairs``/``tuple_bytes`` summing to the totals, and
  ``columnar_buckets``/``tuple_buckets`` — how each non-empty bucket
  finalized). The shuffle's map/merge wall-clock additionally lands
  as ``shuffle-map``/``shuffle-merge`` ``phase`` spans (informational —
  not goodput overhead: a shuffle IS the productive work of an ETL step),
  which lower into the span model like any phase. ``dlstatus`` renders
  the newest summaries as the shuffle block (bytes moved, spill count,
  per-format rows, per-bucket skew, slowest-bucket verdict).
- ``compile`` — one executable built by the compile ledger
  (:mod:`.anatomy`): ``fn`` (the instrumented callable), ``sig`` /
  ``sig_hash`` (shape/dtype signature), ``compile_s``, ``flops`` /
  ``bytes_accessed`` (XLA cost analysis), ``argument_bytes`` /
  ``output_bytes`` / ``temp_bytes`` (memory analysis), and ``recompile``
  — True when the signature compiled more than once or the distinct-
  signature count exceeded the wrapper's pinned expectation (1 for a
  train step, the bucket ladder for the serve forwards). Every compile
  additionally spans a ``compile`` *phase* so goodput accounts the
  stall. ``dlstatus --anatomy`` renders the ledger and its recompile
  verdict.
- ``memory`` — a device-memory watermark sample (:mod:`.anatomy`), one
  per metrics lap: ``bytes_in_use_max`` / ``peak_bytes_in_use_max`` /
  ``bytes_limit_min`` / ``headroom_bytes`` from jax device
  ``memory_stats()`` where the backend exposes them
  (``source="memory_stats"``), or the live-buffer byte total
  (``source="live-buffers"``, CPU fallback). The Chrome exporter draws
  these as a counter track.
- ``span`` — one closed span of a request-level distributed trace
  (:mod:`.trace`): ``trace_id``/``span_id``/``parent_id``/``name``/
  ``t0``/``t1`` + free-form ``attrs``. Spans are buffered per request and
  appended with :meth:`EventWriter.emit_many` at completion (ONE flush per
  request, so the serve hot loop stays cheap); a crash mid-request leaves
  a partial trace the reader flags ``incomplete``, never throws on.
  ``dlstatus --traces`` folds them into the latency anatomy, ``dlstatus
  --export-trace`` exports them (plus train ``phase`` spans lowered into
  the same model) as Chrome ``trace_event`` JSON. MPMD pipeline stages
  (:mod:`..train.pipeline_trainer`) emit the same kind: per-step
  ``pipe-step``/``pipe-fwd``/``pipe-bwd``/``pipe-*-wait`` spans (attrs
  ``stage``/``step``/``mb``) plus one cross-process trace per microbatch
  whose context rides the transport frames — folded by
  :func:`.fleet.pipeline_anatomy` into the measured bubble fraction.
- ``alert`` — one health-rule state *transition* from the continuous
  health engine (:mod:`.health`): ``edge`` ("raise"/"clear"), ``rule``
  (which rule fired), ``key`` (the dedup identity, e.g. ``slo:tenant0``
  or ``hang:host2`` — one live alert per key, re-evaluations of an
  already-raised state emit nothing), ``severity`` ("WARN"/"CRIT"; a
  clear carries ``cleared_from``), ``summary`` (one operator-facing
  line), ``evidence`` (the rule's measured inputs at the edge), and
  ``held`` (evaluations the new state was held before the edge emitted
  — the flap-damping receipt). Alert edges + ``recovery`` events are
  the incident timeline ``dlstatus --incidents`` renders; the Chrome
  exporter draws them as instant events on an ``alerts`` row.
- ``sched`` — one cluster-scheduler lifecycle edge (:mod:`..scheduler`):
  ``edge`` ("submit"/"place"/"launch"/"preempt"/"shrink"/"requeue"/
  "complete"/"fail"/"cancel"), ``job`` (the ledger job id), ``tenant``/
  ``priority``, and per-edge evidence (``assignment`` host map on place,
  ``mode``/``victim_of``/``ordinal`` on preempt, ``reason`` on requeue,
  ``rc`` on complete/fail). The scheduler writes its own stream under
  ``<root>/sched`` and mirrors the edges that concern a job (place,
  preempt, requeue) into that job's workdir stream — so ``dlstatus
  <workdir> --incidents`` folds them into the job's timeline and the
  Chrome exporter draws them beside alert edges on the ``alerts`` row.

Worker-side events additionally carry ``host`` (the process index from the
``DLS_*`` env contract via :func:`~..utils.env.process_identity`, plus
``hosts`` when the gang has more than one) so the cross-host aggregator in
:mod:`.fleet` can attribute a multi-host run's streams without parsing file
names. Non-host processes (the supervisor, ``tpu_watch``) write with
``host=None`` and stay out of the fleet table.

Writers are append-only and line-buffered; a SIGKILL can at worst tear the
final line, which readers skip. No jax import here — the reader side must
stay cheap enough for a CLI pointed at a run directory.
"""

from __future__ import annotations

import contextlib
import glob
import json
import logging
import math
import os
import threading
import time
from typing import Any, Iterable

logger = logging.getLogger("distributeddeeplearningspark_tpu.telemetry")

#: Subdirectory of the workdir holding the per-process event files.
TELEMETRY_DIRNAME = "telemetry"

#: Env var carrying the run's workdir to every process (the supervisor
#: exports it; a bare `Trainer` falls back to its checkpointer directory).
WORKDIR_ENV = "DLS_TELEMETRY_DIR"

#: Env var capping one process's event file size in MB: past it the writer
#: rotates to ``events-<process>.<n>.jsonl`` segments (the reader merges
#: them transparently). Unset/invalid = unbounded (the training default —
#: runs are finite; long-lived serving fleets should cap).
MAX_MB_ENV = "DLS_TELEMETRY_MAX_MB"

#: Env var naming the tenant a run/fleet belongs to. When set (``dlsubmit
#: --tenant`` exports it; the supervisor and serve fleet pass their env to
#: children), every writer stamps ``tenant`` on its records — the attribution
#: key ``dlstatus --cluster`` and the multi-tenant scheduler fold on. An
#: explicit per-record ``tenant`` field (router tenant sheds, per-client
#: serving tenants) always wins over the env-level stamp.
TENANT_ENV = "DLS_TENANT"

#: Env var naming the run's scheduling priority (an integer; higher wins).
#: ``dlsubmit --priority`` exports it and the scheduler stamps it on every
#: job it launches; like the tenant stamp, every writer then carries
#: ``priority`` on its records so cluster views can attribute preemption
#: decisions without joining back to the ledger. An explicit per-record
#: ``priority`` always wins over the env-level stamp.
PRIORITY_ENV = "DLS_PRIORITY"


def _priority_from_env() -> int | None:
    raw = os.environ.get(PRIORITY_ENV)
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r", PRIORITY_ENV, raw)
        return None


def _max_bytes_from_env() -> int | None:
    raw = os.environ.get(MAX_MB_ENV)
    if not raw:
        return None
    try:
        mb = float(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r", MAX_MB_ENV, raw)
        return None
    return int(mb * 1024 * 1024) if mb > 0 else None

#: phase name -> goodput component it is accounted under. Blocking spans
#: only: async background work (orbax writes, manifest CRC threads) must
#: NOT be listed here — it overlaps training and steals no step time.
PHASE_CATEGORY = {
    "compile": "compile_s",
    "restore": "restore_s",
    "checkpoint": "checkpoint_s",
    "checkpoint-wait": "checkpoint_s",
    "checkpoint-verify": "checkpoint_s",
    "eval": "eval_s",
}

_INTERVAL_COMPONENTS = ("compile_s", "restore_s", "checkpoint_s", "eval_s",
                        "restart_overhead_s", "idle_s")

#: Every goodput component, in display order — the ONE list dlstatus renders
#: and the acceptance tests sum ("components sum to wall-clock"). Extending
#: PHASE_CATEGORY with a new overhead category means extending this too.
GOODPUT_COMPONENTS = ("productive_s", "compile_s", "restore_s",
                      "checkpoint_s", "eval_s", "input_starved_s",
                      "restart_overhead_s", "idle_s")


def _default_process() -> str:
    """``p<rank>`` from the supervisor's env contract (``DLS_PROCESS_ID``);
    a plain single-process run is p0."""
    return f"p{os.environ.get('DLS_PROCESS_ID', '0')}"


def telemetry_dir(workdir: str | os.PathLike) -> str:
    """The events directory for ``workdir`` (which may BE the events dir —
    ``dlstatus <workdir>`` and ``dlstatus <workdir>/telemetry`` both work)."""
    workdir = os.fspath(workdir)
    sub = os.path.join(workdir, TELEMETRY_DIRNAME)
    if os.path.isdir(sub):
        return sub
    if os.path.basename(os.path.normpath(workdir)) == TELEMETRY_DIRNAME:
        return workdir
    if glob.glob(os.path.join(workdir, "events-*.jsonl")):
        return workdir
    return sub


class EventWriter:
    """Appends typed events to ``<workdir>/telemetry/events-<process>.jsonl``.

    Best-effort by design: a full disk or read-only filesystem downgrades
    telemetry to a one-time warning, never a training failure. ``clock`` is
    injectable (epoch seconds) so accounting tests run on a fake clock.
    """

    _HOST_FROM_ENV = object()  # sentinel: resolve host identity from DLS_*

    def __init__(self, workdir: str | os.PathLike, *, process: str | None = None,
                 clock=time.time, host: int | None | object = _HOST_FROM_ENV,
                 hosts: int | None = None, max_mb: float | None = None,
                 tenant: str | None = None, priority: int | None = None):
        self.workdir = os.path.abspath(os.fspath(workdir))
        self.process = process or _default_process()
        self.tenant = tenant if tenant is not None else (
            os.environ.get(TENANT_ENV) or None)
        self.priority = (priority if priority is not None
                         else _priority_from_env())
        # size-capped segment rotation (long-lived serving fleets must not
        # grow one unbounded file per process): segment 0 is the classic
        # ``events-<process>.jsonl``, later ones ``events-<process>.<n>.jsonl``
        # — all matched by the reader's events-*.jsonl glob, merged by ts.
        self._max_bytes = (int(max_mb * 1024 * 1024)
                           if max_mb else _max_bytes_from_env())
        self._seg = 0
        self._bytes = 0
        self.path = self._seg_path(0)
        # host identity stamped on every event (fleet aggregation key).
        # Default: the DLS_* env contract. host=None opts a non-host process
        # (supervisor, tpu_watch, bench) out of the fleet table; an explicit
        # host should come with the gang size (``hosts``), which otherwise
        # falls back to the env contract's count.
        from distributeddeeplearningspark_tpu.utils.env import (
            process_identity,
        )

        env_host, env_hosts = process_identity()
        self.host = env_host if host is EventWriter._HOST_FROM_ENV else host
        self.hosts = hosts if hosts is not None else env_hosts
        if self.host is not None:
            self.hosts = max(self.hosts, self.host + 1)
        self._clock = clock
        self._lock = threading.Lock()
        self._f = None
        self._closed = False
        self._warned = False
        # innermost-open-phase tracking for heartbeat enrichment: a list,
        # not a set — nested identical names (restore inside restore) must
        # pop correctly
        self._open_phases: list[str] = []
        # open-span notes (serving request liveness): insertion-ordered, so
        # next(iter(...)) is the OLDEST in-flight request — the one a hang
        # verdict should name (see note_span)
        self._open_spans: dict[Any, tuple[str, float]] = {}

    def _seg_path(self, seg: int) -> str:
        name = (f"events-{self.process}.jsonl" if seg == 0
                else f"events-{self.process}.{seg}.jsonl")
        return os.path.join(self.workdir, TELEMETRY_DIRNAME, name)

    def _record(self, kind: str, fields: dict[str, Any]) -> dict[str, Any]:
        rec = {"ts": self._clock(), "kind": kind, "process": self.process,
               **fields}
        if self.host is not None:
            rec.setdefault("host", self.host)
            if self.hosts > 1:
                rec.setdefault("hosts", self.hosts)
        if self.tenant is not None:
            # setdefault: a record-level tenant (a router shed naming the
            # tenant it throttled) is evidence; the env stamp is attribution
            rec.setdefault("tenant", self.tenant)
        if self.priority is not None:
            # same discipline as the tenant stamp: a record-level priority
            # (a sched edge describing another job) wins over attribution
            rec.setdefault("priority", self.priority)
        return rec

    def _resume_segment(self) -> None:
        """Continue appending to the newest existing segment (a restarted
        process must extend its predecessor's rotation sequence, not
        overwrite segment 0 growth accounting)."""
        seg = 0
        for p in glob.glob(os.path.join(
                self.workdir, TELEMETRY_DIRNAME,
                f"events-{self.process}.*.jsonl")):
            tag = os.path.basename(p)[len(f"events-{self.process}."):-len(".jsonl")]
            if tag.isdigit():
                seg = max(seg, int(tag))
        self._seg = seg
        self.path = self._seg_path(seg)
        try:
            self._bytes = os.path.getsize(self.path)
        except OSError:
            self._bytes = 0

    def _write_lines(self, lines: list[str]) -> None:
        """Append + flush under the already-held lock (ONE flush per call
        — the single write path emit and emit_many share). Rotates to the
        next segment first when the append would push the current one past
        the size cap (a single oversized batch still lands whole — events
        are never split across segments)."""
        data = "\n".join(lines) + "\n"
        try:
            if self._f is None:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                self._resume_segment()
                self._f = open(self.path, "a")
            if (self._max_bytes is not None and self._bytes > 0
                    and self._bytes + len(data) > self._max_bytes):
                self._f.close()
                # None BEFORE the reopen: if it raises, a later emit must
                # retry the open path, not write to a closed handle (a
                # ValueError no handler catches — telemetry failures
                # degrade to a warning, never kill a serving thread)
                self._f = None
                self._seg += 1
                self._bytes = 0
                self.path = self._seg_path(self._seg)
                self._f = open(self.path, "a")
            self._f.write(data)
            self._f.flush()
            self._bytes += len(data)
        except OSError as e:
            if not self._warned:
                logger.warning("telemetry disabled (%s): %s", self.path, e)
                self._warned = True

    def emit(self, kind: str, **fields: Any) -> None:
        rec = self._record(kind, fields)
        with self._lock:
            if self._closed:
                # a stale reference held past configure()'s rebind (or any
                # close()) must NOT silently reopen the file and fork the
                # stream in two — late emits drop instead
                return
            if kind == "phase":
                name = fields.get("name")
                if name:
                    if fields.get("edge") == "begin":
                        self._open_phases.append(name)
                    elif fields.get("edge") == "end" and name in self._open_phases:
                        # remove the LAST occurrence (innermost of nested spans)
                        for i in range(len(self._open_phases) - 1, -1, -1):
                            if self._open_phases[i] == name:
                                del self._open_phases[i]
                                break
            elif kind == "heartbeat" and "phase" not in rec:
                # a heartbeat names where the process IS, not just that it
                # lives — the field hang localization reads when a host's
                # last event is a heartbeat. Open phases win (training);
                # otherwise the OLDEST open request span (serving) plays
                # the same role, so a wedged request localizes exactly
                # like a wedged restore.
                if self._open_phases:
                    rec["phase"] = self._open_phases[-1]
                elif self._open_spans:
                    name, t0 = next(iter(self._open_spans.values()))
                    rec["phase"] = name
                    rec["phase_t0"] = t0
            self._write_lines([json.dumps(rec, default=str)])

    def emit_many(self, kind: str, records: "list[dict[str, Any]]") -> None:
        """Append N same-kind events under ONE lock/flush.

        The serving engine emits one ``request`` event per request in a
        coalesced batch; flushing per event made telemetry ~45% of the
        serving hot loop's host time. One flush per *batch* keeps the
        durability granularity the engine actually has (a crash loses at
        most the batch that was being reported) at 1/N the cost.

        ``phase``/``heartbeat`` are rejected: those kinds carry the
        open-phase tracking/enrichment that only :meth:`emit` maintains,
        and silently skipping it would starve hang localization."""
        if kind in ("phase", "heartbeat"):
            raise ValueError(
                f"emit_many({kind!r}): phase/heartbeat events need emit()'s "
                f"open-phase tracking — batch-append would skip it")
        if not records:
            return
        with self._lock:
            if self._closed:
                return
            self._write_lines([json.dumps(self._record(kind, fields),
                                          default=str)
                               for fields in records])

    def note_span(self, key: Any, name: str) -> None:
        """Mark an in-flight request span open (serving liveness).

        Nothing is written: the note only enriches subsequent heartbeats —
        when no training phase is open, a heartbeat carries the oldest
        noted span's ``name`` as its ``phase`` plus ``phase_t0`` (when the
        request began), so hang localization can say "replica 1 stuck in
        request for 312s" from the stream's last record alone, exactly as
        it says "stuck in restore". ``key`` is any hashable request
        identity; :meth:`clear_span` removes it."""
        with self._lock:
            self._open_spans.pop(key, None)
            self._open_spans[key] = (name, self._clock())

    def clear_span(self, key: Any) -> None:
        with self._lock:
            self._open_spans.pop(key, None)

    @contextlib.contextmanager
    def phase(self, name: str, **fields: Any):
        """Span a blocking phase: begin/end records, end carries ``dur_s``.
        The begin record makes crashed runs honest — an unterminated begin
        is accounted up to the stream's last event."""
        t0 = self._clock()
        self.emit("phase", name=name, edge="begin", **fields)
        try:
            yield
        finally:
            self.emit("phase", name=name, edge="end",
                      dur_s=self._clock() - t0, **fields)

    # typed convenience emitters ------------------------------------------

    def step_metrics(self, step: int, *, steps: int, lap_s: float,
                     metrics: dict[str, float] | None = None,
                     **gauges: Any) -> None:
        self.emit("step_metrics", step=int(step), steps=int(steps),
                  lap_s=float(lap_s), metrics=dict(metrics or {}), **gauges)

    def recovery(self, step: int | None, event: str, **fields: Any) -> None:
        """``step=None`` when the emitter doesn't know the training step
        (e.g. the supervisor, which only sees process lifecycles) — a wrong
        guess would mislead the dlstatus timeline."""
        if step is None:
            self.emit("recovery", event=event, **fields)
        else:
            self.emit("recovery", step=int(step), event=event, **fields)

    def attempt(self, edge: str, ordinal: int, **fields: Any) -> None:
        self.emit("attempt", edge=edge, ordinal=int(ordinal), **fields)

    def heartbeat(self, **fields: Any) -> None:
        self.emit("heartbeat", **fields)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


# -- module singleton (for layers that can't thread a writer through) --------

_writer: EventWriter | None = None


def configure(workdir: str | os.PathLike, *, process: str | None = None,
              clock=time.time) -> EventWriter:
    """Bind the process-wide writer to ``workdir`` (idempotent per workdir).

    The Trainer calls this with the resolved run workdir; from then on
    layers without a writer reference (checkpoint.py, profiling.py) emit
    through :func:`emit`/:func:`phase`."""
    global _writer
    wd = os.path.abspath(os.fspath(workdir))
    if (_writer is not None and _writer.workdir == wd
            and (process is None or _writer.process == process)):
        return _writer
    if _writer is not None:
        _writer.close()
    _writer = EventWriter(wd, process=process, clock=clock)
    return _writer


def get() -> EventWriter | None:
    return _writer


def reset() -> None:
    """Drop the process-wide writer (tests; also ends a run's binding)."""
    global _writer
    if _writer is not None:
        _writer.close()
        _writer = None


def emit(kind: str, **fields: Any) -> None:
    """Emit through the process-wide writer; no-op when unconfigured."""
    if _writer is not None:
        _writer.emit(kind, **fields)


def emit_many(kind: str, records: "list[dict[str, Any]]") -> None:
    """Batched :func:`emit` through the process-wide writer (one flush)."""
    if _writer is not None:
        _writer.emit_many(kind, records)


def phase(name: str, **fields: Any):
    """Span context through the process-wide writer (no-op unconfigured)."""
    if _writer is not None:
        return _writer.phase(name, **fields)
    return contextlib.nullcontext()


# -- reader ------------------------------------------------------------------


def event_files(workdir: str | os.PathLike) -> list[str]:
    return sorted(glob.glob(os.path.join(telemetry_dir(workdir),
                                         "events-*.jsonl")))


def _parse_event_line(line: str) -> dict | None:
    """One JSONL line -> event dict, or None for torn/garbage lines.

    A record must be a JSON object carrying ``ts`` and ``kind`` — anything
    else (a half-written tail, an editor's stray newline, a non-event JSON
    value) is not an event."""
    line = line.strip()
    if not line:
        return None
    try:
        rec = json.loads(line)
    except ValueError:
        return None
    if isinstance(rec, dict) and "ts" in rec and "kind" in rec:
        return rec
    return None


def read_events(workdir: str | os.PathLike) -> list[dict]:
    """Merge every process's event file into one ts-ordered stream.

    Torn lines (a writer SIGKILLed mid-append) and non-JSON garbage are
    skipped — a crashed run's partial stream must parse. The sort is stable,
    so records with equal timestamps keep their per-file order (the
    multi-process merge contract the tests pin)."""
    events: list[dict] = []
    for path in event_files(workdir):
        try:
            with open(path) as f:
                for line in f:
                    rec = _parse_event_line(line)
                    if rec is not None:
                        events.append(rec)
        except OSError:
            continue
    events.sort(key=lambda e: float(e["ts"]))
    return events


class EventCursor:
    """Incremental :func:`read_events`: per-file byte offsets so each poll
    parses only what was appended since the last one.

    ``dlstatus --watch`` and the health engine re-evaluate every few
    seconds; re-parsing a long run's whole JSONL set each tick is O(total
    events) per tick and grows without bound. The cursor keeps one byte
    offset per segment file:

    - **New files/segments** (a rotation, a late-joining process) enter the
      glob on the next poll and are read from byte 0.
    - **Torn tails** — a writer mid-append when we poll — are held back:
      only complete (newline-terminated) lines are consumed, the offset
      stays at the line start, and the finished line parses next poll.
      A torn line is therefore *deferred*, never dropped (the one-shot
      reader, arriving after the crash, skips it instead).
    - **Truncated/replaced files** (offset beyond EOF) reset to 0.

    ``events`` is the accumulated ts-sorted merge (what :func:`read_events`
    would return, minus any still-torn tails); :meth:`poll` returns just the
    newly appended records. ``skipped_lines`` counts complete-but-garbage
    lines — the parseable-but-degraded signal the health engine reports
    when a crashed run's partial segment is all a workdir has."""

    def __init__(self, workdir: str | os.PathLike):
        self.workdir = os.fspath(workdir)
        self._offsets: dict[str, int] = {}
        self.events: list[dict] = []
        self.skipped_lines = 0
        #: total bytes consumed across every poll — the receipt that watch
        #: cost is bounded by the append rate (ci.sh history asserts it).
        self.bytes_read = 0

    @property
    def files(self) -> list[str]:
        """Every segment file seen so far (polled at least once)."""
        return sorted(self._offsets)

    def lag_bytes(self) -> int:
        """Bytes on disk the cursor has not consumed yet: appended-but-
        unpolled data plus still-torn tails (files the glob hasn't seen
        count in full). The health engine records this as its own
        falling-behind gauge."""
        lag = 0
        for path in event_files(self.workdir):
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            lag += max(0, size - self._offsets.get(path, 0))
        return lag

    def poll(self) -> list[dict]:
        """Read appended lines from every segment; return the new events
        (also merged, ts-stably, into :attr:`events`)."""
        new: list[dict] = []
        for path in event_files(self.workdir):
            off = self._offsets.setdefault(path, 0)
            try:
                size = os.path.getsize(path)
                if size < off:
                    off = self._offsets[path] = 0  # truncated/replaced
                if size == off:
                    continue
                with open(path, "rb") as f:
                    f.seek(off)
                    data = f.read()
            except OSError:
                continue
            end = data.rfind(b"\n")
            if end < 0:
                continue  # only a torn fragment so far — retry next poll
            self._offsets[path] = off + end + 1
            self.bytes_read += end + 1
            for raw in data[:end + 1].splitlines():
                rec = _parse_event_line(raw.decode("utf-8", errors="replace"))
                if rec is not None:
                    new.append(rec)
                elif raw.strip():
                    self.skipped_lines += 1
        if new:
            self.events.extend(new)
            self.events.sort(key=lambda e: float(e["ts"]))
        return new


# -- goodput accounting ------------------------------------------------------


def _union_seconds(intervals: list[tuple[float, float]]) -> float:
    """Total covered length of possibly-overlapping [t0, t1] intervals."""
    total = 0.0
    end = -math.inf
    for t0, t1 in sorted(intervals):
        if t1 <= end:
            continue
        total += t1 - max(t0, end)
        end = t1
    return total


def _subtract_intervals(
    iv: tuple[float, float], subs: list[tuple[float, float]],
) -> list[tuple[float, float]]:
    """``iv`` minus every interval in ``subs`` (may split it)."""
    out = [iv]
    for s0, s1 in subs:
        nxt: list[tuple[float, float]] = []
        for t0, t1 in out:
            if s1 <= t0 or t1 <= s0:
                nxt.append((t0, t1))
                continue
            if t0 < s0:
                nxt.append((t0, s0))
            if s1 < t1:
                nxt.append((s1, t1))
        out = nxt
    return out


def goodput(events: Iterable[dict]) -> dict[str, float]:
    """Fold an event stream into the run's time budget.

    Returns ``{wall_s, productive_s, compile_s, restore_s, checkpoint_s,
    eval_s, input_starved_s, restart_overhead_s, goodput_frac}``.

    Accounting model: wall-clock is the stream's [first ts, last ts] span.
    Overhead phases are intervals, merged by union — within a category so a
    double-instrumented span counts once, and across ALL categories for the
    productive residual, so a span nested in another is never subtracted
    twice. ``input_starved_s`` is a counter (the per-lap probe snapshots
    summed per process, then the MAX across processes — lockstep SPMD means
    the slowest host's wait is the gang's wait). ``restart_overhead_s``
    is the dead time between one attempt's end and the next one's begin
    (supervisor backoff + teardown). ``idle_s`` is the gap between one
    ``run`` span's end and the next one's begin — a stop-today/resume-
    tomorrow workdir accrues a day of idle, which must be neither
    "productive" nor a restart (gaps already covered by a supervisor
    restart interval are not double-counted). ``productive_s`` is the
    residual: wall − union(all overhead intervals) − input_starved. A
    crashed stream simply ends early — an unterminated phase begin is
    accounted up to the last event seen.
    """
    out = {"wall_s": 0.0, "productive_s": 0.0, "input_starved_s": 0.0,
           "goodput_frac": 0.0}
    for c in _INTERVAL_COMPONENTS:
        out[c] = 0.0
    # alert events are meta-observation (the health engine watching the
    # run), not run activity: a long-lived engine appending edges to a
    # finished workdir must not stretch its wall-clock span
    events = [e for e in events if "ts" in e and e.get("kind") != "alert"]
    if not events:
        return out
    events = sorted(events, key=lambda e: float(e["ts"]))
    t_lo, t_hi = float(events[0]["ts"]), float(events[-1]["ts"])
    wall = t_hi - t_lo
    out["wall_s"] = wall

    intervals: dict[str, list[tuple[float, float]]] = {
        c: [] for c in _INTERVAL_COMPONENTS}
    open_phases: dict[tuple, list[float]] = {}
    last_ts_by_process: dict[str | None, float] = {}
    attempt_ends: list[float] = []
    input_by_process: dict[str | None, float] = {}
    last_attempt_end: float | None = None
    last_end_ordinal = -2  # sentinel: nothing follows it
    last_run_end: float | None = None
    idle_candidates: list[tuple[float, float]] = []
    for e in events:
        kind, ts = e.get("kind"), float(e["ts"])
        proc = e.get("process")
        prev_proc_ts = last_ts_by_process.get(proc)
        last_ts_by_process[proc] = ts
        if kind == "phase":
            name = e.get("name", "")
            cat = PHASE_CATEGORY.get(name)
            key = (proc, name)
            if e.get("edge") == "begin":
                if name == "run":
                    starts = open_phases.get(key)
                    if starts:
                        # a NEW run span while this process's previous one
                        # never closed: that session crashed — it effectively
                        # ended at the process's last prior event, and the
                        # gap from there to this resume is idle, not
                        # productive residual
                        starts.clear()
                        if prev_proc_ts is not None and ts > prev_proc_ts:
                            idle_candidates.append((prev_proc_ts, ts))
                    elif last_run_end is not None and ts > last_run_end:
                        # gap since the previous run span closed cleanly =
                        # a stopped workdir sitting idle between sessions
                        idle_candidates.append((last_run_end, ts))
                    last_run_end = None
                open_phases.setdefault(key, []).append(ts)
            elif e.get("edge") == "end":
                starts = open_phases.get(key)
                t0 = starts.pop() if starts else ts - float(e.get("dur_s", 0.0))
                if cat:
                    intervals[cat].append((min(t0, ts), ts))
                if name == "run":
                    last_run_end = ts
        elif kind == "step_metrics":
            input_by_process[proc] = (input_by_process.get(proc, 0.0)
                                      + float(e.get("input_wait_s", 0.0) or 0.0))
        elif kind == "attempt":
            if e.get("edge") == "end":
                last_attempt_end = ts
                last_end_ordinal = int(e.get("ordinal", -1))
                attempt_ends.append(ts)
            elif e.get("edge") == "begin" and last_attempt_end is not None:
                # restart overhead only pairs WITHIN one supervisor session
                # (ordinals increment per relaunch); an ordinal that does
                # not follow the last end is a fresh supervisor invocation
                # on the same workdir — that gap is idle time between
                # sessions, not the price of a restart
                if (int(e.get("ordinal", -1)) == last_end_ordinal + 1
                        and ts > last_attempt_end):
                    intervals["restart_overhead_s"].append(
                        (last_attempt_end, ts))
                last_attempt_end = None
    # crash mid-phase: the begin is all we have. Do NOT extend it to the
    # whole stream's end — a relaunched attempt appends hours of events to
    # the same file set, and an orphaned span stretched across them would
    # swallow the relaunch's productive time. The honest bound is the first
    # supervisor attempt-end after the begin (when the death was reaped),
    # falling back to the opening process's own last event (when it went
    # silent) for unsupervised runs.
    for (proc, name), starts in open_phases.items():
        cat = PHASE_CATEGORY.get(name or "")
        if cat:
            proc_last = last_ts_by_process.get(proc, t_hi)
            for t0 in starts:
                reaped = [t for t in attempt_ends if t >= t0]
                t1 = min(reaped) if reaped else proc_last
                intervals[cat].append((t0, max(t0, t1)))

    # idle-between-runs, minus the sub-spans a supervisor restart interval
    # already accounts for (a relaunch IS a run-end→run-begin gap too).
    # SUBTRACTED, not dropped whole: a hang's dwell (worker silent long
    # before the watchdog reaped it) and the relaunch's startup tail extend
    # beyond the restart interval and must not fall back into "productive"
    restarts = intervals["restart_overhead_s"]
    intervals["idle_s"] = [
        piece for cand in idle_candidates
        for piece in _subtract_intervals(cand, restarts)]

    all_iv: list[tuple[float, float]] = []
    for cat, iv in intervals.items():
        out[cat] = _union_seconds(iv)
        all_iv.extend(iv)
    # gang-step SPMD runs in lockstep: the slowest host's input wait gates
    # every step, so the gang-level starvation is the MAX over processes —
    # summing would over-count N-fold exactly like un-unioned intervals
    input_starved = max(input_by_process.values(), default=0.0)
    out["input_starved_s"] = input_starved
    overhead = _union_seconds(all_iv) + input_starved
    out["productive_s"] = max(0.0, wall - overhead)
    out["goodput_frac"] = out["productive_s"] / wall if wall > 0 else 0.0
    return out
